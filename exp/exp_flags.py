"""Can TPU backend compiler options reach the remote compiler? Probe with a
tiny jit, then measure the ResNet window under candidate options."""
import functools, sys, time
import jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")

opts = {}
if len(sys.argv) > 1 and sys.argv[1] != "none":
    k, _, v = sys.argv[1].partition("=")
    opts[k] = v

f = jax.jit(lambda x: x @ x, compiler_options=opts or None)
print("probe ok:", f(jnp.ones((256, 256), jnp.bfloat16)).shape, opts, flush=True)

from exp_profile_resnet import build_window  # noqa: E402

window, carry = build_window(steps=20)
if opts:
    window = jax.jit(window.__wrapped__, donate_argnums=(0,),
                     compiler_options=opts)
carry, loss = window(carry); float(loss)
carry, loss = window(carry); float(loss)
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    carry, loss = window(carry); float(loss)
    best = min(best, time.perf_counter() - t0)
print(f"{best/20*1e3:.2f} ms/step under {opts}", flush=True)
