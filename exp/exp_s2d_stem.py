"""Round-5 experiment: space-to-depth stem (VERDICT r4 next-step #1).

Measures, on the real chip, fwd+bwd time of:
  1. the baseline 7x7/s2 stem conv on [N,224,224,3]
  2. the s2d-equivalent 4x4/s1 conv on [N,112,112,12] (s2d inside the graph)
  3. same but input pre-packed as [N,112,112,12] (s2d done by the data
     pipeline, as MLPerf submissions do)
  4. bandwidth probe: elementwise pass over [N,224,224,3] vs [N,112,112,12]
     vs [N,224,224,128] to expose physical lane padding of tiny-C tensors.

Protocol: jitted scan windows, device->host fenced, best-of-3 (ROOFLINE.md).
"""
import functools
import time

import jax
import jax.numpy as jnp

N = 384
STEPS = 20


def timeit(window, carry):
    carry, out = window(carry)
    float(out.ravel()[0])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        carry, out = window(carry)
        float(out.ravel()[0])
        best = min(best, time.perf_counter() - t0)
    return best / STEPS


def bench_fwd_bwd(f, params, x):
    """best-of-3 per-step time of value_and_grad(f)(params, x) in a scan."""
    def loss(p):
        return jnp.sum(f(p, x).astype(jnp.float32) * 1e-6)

    def step(p, _):
        l, g = jax.value_and_grad(loss)(p)
        p = jax.tree_util.tree_map(lambda a, b: a - 1e-9 * b, p, g)
        return p, l

    @jax.jit
    def window(p):
        p, ls = jax.lax.scan(step, p, None, length=STEPS)
        return p, ls[-1]

    return timeit(window, params)


def main():
    k = jax.random.PRNGKey(0)
    results = {}

    # -- bandwidth probes: one read+write pass over each tensor ------------
    for name, shape in [("copy_224x3", (N, 224, 224, 3)),
                        ("copy_112x12", (N, 112, 112, 12)),
                        ("copy_112x1344_packed", (N, 112, 1344)),
                        ("copy_56x64", (N, 56, 56, 64)),
                        ("copy_56x56x64_as_3584", (N, 56, 3584))]:
        x = jax.random.normal(k, shape, jnp.bfloat16)

        def step(c, _, x=x):
            return c, jnp.sum(x * c)

        @jax.jit
        def window(c, step=step):
            c, ls = jax.lax.scan(step, c, None, length=STEPS)
            return c, ls[-1]

        t = timeit(window, jnp.bfloat16(1.0))
        import numpy as np
        logical_gb = float(np.prod(shape)) * 2 / 1e9
        print(f"{name:28s} {t*1e3:8.3f} ms/step  "
              f"{logical_gb/t:7.0f} GB/s logical", flush=True)

    # -- stem variants -----------------------------------------------------
    import flax.linen as nn

    class Stem(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                           use_bias=False, dtype=jnp.bfloat16)(x)

    class S2dStem(nn.Module):
        pack: bool = False  # input already [N,112,112,12]

        @nn.compact
        def __call__(self, x):
            if not self.pack:
                n, h, w, c = x.shape
                x = x.reshape(n, h // 2, 2, w // 2, 2, c)
                x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                    n, h // 2, w // 2, 4 * c)
            return nn.Conv(64, (4, 4), (1, 1), padding=[(2, 1), (2, 1)],
                           use_bias=False, dtype=jnp.bfloat16)(x)

    x224 = jax.random.normal(k, (N, 224, 224, 3), jnp.bfloat16)
    x112 = jax.random.normal(k, (N, 112, 112, 12), jnp.bfloat16)

    m = Stem()
    p = jax.jit(m.init)(k, x224)
    print(f"{'stem_7x7':28s} {bench_fwd_bwd(m.apply, p, x224)*1e3:8.3f} "
          "ms/step", flush=True)

    m = S2dStem()
    p = jax.jit(m.init)(k, x224)
    print(f"{'stem_s2d_ingraph':28s} "
          f"{bench_fwd_bwd(m.apply, p, x224)*1e3:8.3f} ms/step", flush=True)

    m = S2dStem(pack=True)
    p = jax.jit(m.init)(k, x112)
    print(f"{'stem_s2d_packed':28s} "
          f"{bench_fwd_bwd(m.apply, p, x112)*1e3:8.3f} ms/step", flush=True)


if __name__ == "__main__":
    main()
