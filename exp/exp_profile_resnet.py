"""Capture a device trace of the ResNet bench step and print the top ops
by self time (round-5 evidence base for the conv-efficiency attack)."""
import functools
import glob
import gzip
import os
import sys
import time

import jax
import jax.numpy as jnp


def build_window(batch=384, image=224, steps=5, fused_bn=False, s2d=False):
    import optax
    from tony_tpu.models import get_model
    from tony_tpu import train as tr

    model = get_model("resnet50", fused_bn=fused_bn, **(
        {"s2d_stem": True} if s2d else {}))
    kx, ky, kinit = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, image, image, 3), jnp.bfloat16)
    y = jax.random.randint(ky, (batch,), 0, 1000)
    variables = jax.jit(lambda: model.init(kinit, x, train=False))()
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)

    def step(carry, _):
        params, opt_state, batch_stats = carry

        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return tr.cross_entropy_loss(logits, y), updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, new_stats), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window(carry):
        carry, losses = jax.lax.scan(step, carry, None, length=steps)
        return carry, losses[-1]

    return window, (params, opt_state, batch_stats)


def parse_xplane(logdir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not files:
        print("no xplane files under", logdir)
        return
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(sorted(files)[-1], "rb").read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "Device" not in plane.name:
            continue
        evmeta = {m.id: m.name for m in plane.event_metadata.values()}
        totals = {}
        for line in plane.lines:
            for ev in line.events:
                name = evmeta.get(ev.metadata_id, "?")
                totals[name] = totals.get(name, 0) + ev.duration_ps
        total = sum(totals.values())
        print(f"== plane {plane.name}: {total/1e12*1e3:.1f} ms total")
        for name, ps in sorted(totals.items(), key=lambda kv: -kv[1])[:40]:
            print(f"  {ps/1e9:9.3f} ms {100*ps/total:5.1f}%  {name[:110]}")


def main():
    steps = 5
    window, carry = build_window(steps=steps,
                                 s2d=os.environ.get("S2D", "0") == "1")
    carry, loss = window(carry)
    float(loss)
    carry, loss = window(carry)
    float(loss)
    logdir = os.path.abspath(os.environ.get("TRACE_DIR", "exp/trace_r5"))
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    t0 = time.perf_counter()
    carry, loss = window(carry)
    float(loss)
    dt = time.perf_counter() - t0
    jax.profiler.stop_trace()
    print(f"window: {dt*1e3:.1f} ms wall, {dt/steps*1e3:.1f} ms/step")
    parse_xplane(logdir)


if __name__ == "__main__":
    main()
