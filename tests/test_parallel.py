"""Compute-plane tests: mesh building, sharding rules, ring attention —
on the virtual 8-device CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu import parallel as par


# THE semantic spec (GQA repeat included) — not a local re-implementation,
# so a change to the canonical mapping fails these tests instead of
# silently diverging.
from tony_tpu.ops import reference_attention  # noqa: E402


def test_mesh_spec_fills_dp():
    mesh = par.make_mesh(tp=2, sp=2)
    assert mesh.shape["data"] == 2  # 8 / (2*2)
    assert mesh.shape["model"] == 2 and mesh.shape["seq"] == 2
    assert mesh.axis_names == par.AXES


def test_mesh_spec_rejects_bad_shape():
    with pytest.raises(ValueError):
        par.MeshSpec(dp=3, tp=2).build(jax.devices())  # 6 != 8


def test_logical_sharding_rules():
    mesh = par.make_mesh(fsdp=2, tp=4)
    s = par.logical_sharding(mesh, "embed", "ffn")
    assert s.spec == jax.sharding.PartitionSpec("fsdp", "model")
    s2 = par.logical_sharding(mesh, "batch", "act_seq", "act_embed")
    assert s2.spec == jax.sharding.PartitionSpec(
        ("slice", "data", "fsdp"), "seq", None)


def test_shard_logical_places_array():
    mesh = par.make_mesh(fsdp=2, tp=4)
    w = par.shard_logical(mesh, jnp.zeros((16, 32)), "embed", "ffn")
    assert w.sharding.spec == jax.sharding.PartitionSpec("fsdp", "model")


def test_logical_sharding_unknown_axis_raises():
    """A typo'd logical axis used to fall through to None and silently
    replicate the dim — it must raise, naming the bad axis."""
    mesh = par.make_mesh(fsdp=2, tp=4)
    with pytest.raises(ValueError, match="embde"):
        par.logical_sharding(mesh, "embde", "ffn")
    with pytest.raises(ValueError, match="allow_unknown"):
        par.constraint(jnp.zeros((4, 4)), mesh, "nope", None)


def test_logical_sharding_allow_unknown_escape_hatch():
    mesh = par.make_mesh(fsdp=2, tp=4)
    s = par.logical_sharding(mesh, "custom_axis", "ffn",
                             allow_unknown=True)
    assert s.spec == jax.sharding.PartitionSpec(None, "model")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = par.make_mesh(sp=8)
    b, h, t, d = 2, 4, 64, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, t, d), jnp.float32)
    out = par.ring_attention_sharded(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grad_flows():
    mesh = par.make_mesh(sp=4, tp=2)
    b, h, t, d = 1, 2, 32, 8
    q = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d))

    def loss(q):
        return par.ring_attention_sharded(q, q, q, mesh).sum()

    g = jax.grad(loss)(q)
    assert g.shape == q.shape
    assert bool(jnp.isfinite(g).all())


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gqa_matches_reference(causal):
    """Zero-copy GQA through the ring (r5): K/V carry fewer heads and the
    NARROW blocks rotate — values must match repeat-then-attend, and the
    group fold must keep per-head identity (h -> kv h//reps)."""
    mesh = par.make_mesh(sp=4)
    b, h, hkv, t, d = 2, 4, 2, 64, 16
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, t, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, t, d), jnp.float32)
    out = par.ring_attention_sharded(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)  # repeats internally
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_gqa_grads_flow():
    mesh = par.make_mesh(sp=4)
    b, h, hkv, t, d = 2, 4, 2, 32, 8
    q = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d))
    k = jax.random.normal(jax.random.PRNGKey(2), (b, hkv, t, d))
    v = jax.random.normal(jax.random.PRNGKey(3), (b, hkv, t, d))

    def loss(q, k, v):
        return par.ring_attention_sharded(q, k, v, mesh).sum()

    gq, gk, gv = jax.grad(loss, (0, 1, 2))(q, k, v)
    assert gq.shape == q.shape and gk.shape == k.shape and gv.shape == v.shape
    for g in (gq, gk, gv):
        assert bool(jnp.isfinite(g).all())


def test_ring_attention_gqa_rejects_ragged():
    mesh = par.make_mesh(sp=4)
    q = jnp.zeros((2, 4, 32, 8))
    kv = jnp.zeros((2, 3, 32, 8))
    with pytest.raises(ValueError, match="multiple"):
        par.ring_attention_sharded(q, kv, kv, mesh)


def test_ring_attention_gqa_tp_wider_than_kv_heads_falls_back():
    """kv heads that don't divide the model axis (kv=2 over tp=4) cannot
    stay narrow under shard_map — the wrapper must repeat K/V and still be
    exact (the pre-r5 behavior), not raise."""
    mesh = par.make_mesh(tp=4, sp=2)
    b, h, hkv, t, d = 2, 8, 2, 32, 8
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, h, t, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, hkv, t, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, hkv, t, d), jnp.float32)
    out = par.ring_attention_sharded(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
