"""Compute-plane tests: mesh building, sharding rules, ring attention —
on the virtual 8-device CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu import parallel as par


def reference_attention(q, k, v, causal=True):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * d ** -0.5
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


def test_mesh_spec_fills_dp():
    mesh = par.make_mesh(tp=2, sp=2)
    assert mesh.shape["data"] == 2  # 8 / (2*2)
    assert mesh.shape["model"] == 2 and mesh.shape["seq"] == 2
    assert mesh.axis_names == par.AXES


def test_mesh_spec_rejects_bad_shape():
    with pytest.raises(ValueError):
        par.MeshSpec(dp=3, tp=2).build(jax.devices())  # 6 != 8


def test_logical_sharding_rules():
    mesh = par.make_mesh(fsdp=2, tp=4)
    s = par.logical_sharding(mesh, "embed", "ffn")
    assert s.spec == jax.sharding.PartitionSpec("fsdp", "model")
    s2 = par.logical_sharding(mesh, "batch", "act_seq", "act_embed")
    assert s2.spec == jax.sharding.PartitionSpec(
        ("data", "fsdp"), "seq", None)


def test_shard_logical_places_array():
    mesh = par.make_mesh(fsdp=2, tp=4)
    w = par.shard_logical(mesh, jnp.zeros((16, 32)), "embed", "ffn")
    assert w.sharding.spec == jax.sharding.PartitionSpec("fsdp", "model")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = par.make_mesh(sp=8)
    b, h, t, d = 2, 4, 64, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, t, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, t, d), jnp.float32)
    out = par.ring_attention_sharded(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grad_flows():
    mesh = par.make_mesh(sp=4, tp=2)
    b, h, t, d = 1, 2, 32, 8
    q = jax.random.normal(jax.random.PRNGKey(1), (b, h, t, d))

    def loss(q):
        return par.ring_attention_sharded(q, q, q, mesh).sum()

    g = jax.grad(loss)(q)
    assert g.shape == q.shape
    assert bool(jnp.isfinite(g).all())
