"""Mixture-of-experts tier (SURVEY.md §2.3 expert parallelism): router
invariants, dense-MLP equivalence at E=1, aux-loss plumbing, and an
expert-parallel GSPMD train step on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model
from tony_tpu.models.moe import MoEMLP, router_assignment


def _uniformish_gates(g=2, s=16, e=4, seed=0):
    logits = jax.random.normal(jax.random.PRNGKey(seed), (g, s, e))
    return jax.nn.softmax(logits, axis=-1)


def test_router_dispatch_invariants():
    gates = _uniformish_gates()
    k, cap = 2, 16  # ample capacity: nothing dropped
    dispatch, combine, aux = router_assignment(gates, k, cap)
    # Each token occupies exactly k slots, each a 0/1 entry.
    np.testing.assert_allclose(dispatch.sum(axis=(2, 3)), k, atol=1e-6)
    assert float(dispatch.max()) == 1.0 and float(dispatch.min()) == 0.0
    # Combine weights form a convex mixture per token.
    np.testing.assert_allclose(combine.sum(axis=(2, 3)), 1.0, atol=1e-5)
    # No expert exceeds capacity; no capacity slot double-booked.
    assert float(dispatch.sum(axis=(1, 3)).max()) <= cap
    assert float(dispatch.sum(axis=1).max()) <= 1.0 + 1e-6
    # Balanced-ish routing → aux loss near its minimum of 1.
    assert 0.5 < float(aux) < 2.0


def test_router_respects_capacity_and_drops():
    # All tokens want expert 0; capacity 2 → only 2 dispatched per group.
    gates = jnp.zeros((1, 8, 4)).at[:, :, 0].set(1.0)
    dispatch, combine, _ = router_assignment(gates, 1, 2)
    assert float(dispatch[:, :, 0].sum()) == 2.0
    # Dropped tokens carry zero combine weight (pure residual path).
    assert float(combine.sum(axis=(2, 3)).max()) <= 1.0 + 1e-6
    assert float(combine.sum(axis=(2, 3)).min()) == 0.0


def test_moe_single_expert_equals_dense_swiglu():
    """With E=1, k=1 and capacity ≥ T, MoE must reduce to the plain SwiGLU
    it wraps (combine weight is softmax over one expert = 1)."""
    d, f, t = 8, 16, 6
    layer = MoEMLP(dim=d, ffn_hidden=f, n_experts=1, top_k=1,
                   capacity_factor=1.0, dtype=jnp.float32)
    import flax.linen as nn
    x = jax.random.normal(jax.random.PRNGKey(0), (2, t, d))
    variables = nn.unbox(layer.init(jax.random.PRNGKey(1), x))
    y = layer.apply(variables, x)
    p = variables["params"]
    h = jax.nn.silu(x @ p["w_gate"][0]) * (x @ p["w_up"][0])
    expected = h @ p["w_down"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                               atol=1e-5)


def test_moe_model_trains_and_sows_aux_loss():
    model = get_model("llama-moe-tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-2), tokens, jax.random.PRNGKey(0))
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]))
    losses, aux = [], []
    for _ in range(5):
        state, metrics = step(state, {"x": tokens})
        losses.append(float(metrics["loss"]))
        aux.append(float(metrics["aux_loss"]))
    assert losses[-1] < losses[0]
    # Both scanned layers sow: aux ≈ coef · n_layers · (≈1 balanced).
    assert 0.005 < aux[0] < 0.1


def test_moe_remat_scan_path():
    """The mixtral code path (scan + remat + MoE) at toy shapes."""
    model = get_model("llama-moe-tiny", remat=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-2), tokens, jax.random.PRNGKey(0))
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]))
    _, metrics = step(state, {"x": tokens})
    assert np.isfinite(float(metrics["loss"]))


def test_moe_expert_parallel_train_step():
    """EP end-to-end: dp=2 × ep=2 × tp=2 mesh; expert weights sharded over
    the expert axis; loss finite, decreasing, and matching single-device."""
    mesh = par.MeshSpec(dp=2, ep=2, tp=2).build(jax.devices())
    model = get_model("llama-moe-tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-3), tokens, jax.random.PRNGKey(0), mesh=mesh)
    wg = state.params["layers"]["block"]["moe_mlp"]["w_gate"]
    assert "expert" in tuple(wg.sharding.spec), \
        f"expert axis unused: {wg.sharding.spec}"
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
        mesh=mesh)
    losses = []
    for _ in range(5):
        state, metrics = step(state, {"x": tokens})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]

    # Same model/step on one device: the EP sharding must not change the
    # math (tolerance: bf16 collective reordering).
    model1 = get_model("llama-moe-tiny")
    state1 = train.create_train_state(
        model1, optax.adam(1e-3), tokens, jax.random.PRNGKey(0))
    step1 = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]))
    _, m1 = step1(state1, {"x": tokens})
    np.testing.assert_allclose(losses[0], float(m1["loss"]), rtol=2e-2)
