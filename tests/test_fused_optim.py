"""Fused-optimizer tier (tony_tpu.ops.fused_optim): the bucket-major
update plane — pallas kernel vs XLA fallback, AdamW/SGD pinned BIT-exact
in f32 against optax (bf16 with documented tolerance), ZeRO-3 scatter
buckets incl. padded uneven shards and multi-dtype trees, bucket-major
global grad norm/clipping vs the per-leaf value, the leaf-major ckpt
round-trip across a changed fsdp topology, and the profiler update
records — on the virtual 8-device CPU mesh. `make tier1-optim` runs this
file by marker."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tony_tpu import ckpt as ckpt_mod
from tony_tpu import parallel as par
from tony_tpu import profiler
from tony_tpu import train as tr
from tony_tpu.benchmark import fsdp_shard_state
from tony_tpu.models import get_model
from tony_tpu.ops import fused_optim as fo
from tony_tpu.parallel.overlap import GradBuckets

pytestmark = pytest.mark.optim


def _bitexact(a, b):
    return np.array_equal(np.asarray(jax.device_get(a)),
                          np.asarray(jax.device_get(b)))


def _tree_leaves_bitexact(a, b):
    return all(_bitexact(x, y) for x, y in
               zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _params(seed=0):
    """Replicated multi-shape tree: matrices, a vector, a scalar."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    return {"a": jax.random.normal(ks[0], (12, 8), jnp.float32),
            "b": jax.random.normal(ks[1], (33,), jnp.float32) * 0.3,
            "c": jnp.float32(1.7),
            "d": jax.random.normal(ks[2], (7, 3), jnp.float32)}


def _grads(params, seed=1):
    k = jax.random.PRNGKey(seed)
    return jax.tree.map(
        lambda p: (jnp.sin(p.astype(jnp.float32) + 0.1) * 0.05
                   ).astype(p.dtype), params)


class TestKernel:
    """fused_bucket_update: one launch over one bucket's 1-D buffers."""

    @pytest.mark.parametrize("rule,nslots", [("adamw", 2), ("sgd", 1),
                                             ("adafactor", 1)])
    @pytest.mark.parametrize("n", [1, 300, 9000])
    def test_pallas_interpret_matches_xla_fallback(self, rule, nslots, n):
        ks = jax.random.split(jax.random.PRNGKey(0), 2)
        g = jax.random.normal(ks[0], (n,), jnp.float32) * 0.1
        p = jax.random.normal(ks[1], (n,), jnp.float32)
        slots = tuple(jnp.zeros((n,), jnp.float32) for _ in range(nslots))
        fused = fo.FusedOptimizer(rule=rule, lr=1e-3, weight_decay=1e-2)
        scal = fused.scalars(jnp.int32(1))
        xp, xs = fo.fused_bucket_update(g, p, slots, scal, rule=rule,
                                        hyper=fused.hyper, impl="xla")
        kp, ks_ = fo.fused_bucket_update(g, p, slots, scal, rule=rule,
                                         hyper=fused.hyper,
                                         interpret=True)
        # Same _rule_math on both paths; only compile-pipeline rewrites
        # (div -> mul-by-reciprocal) can differ, so pin to float ulps.
        np.testing.assert_allclose(np.asarray(kp), np.asarray(xp),
                                   rtol=1e-6, atol=1e-8)
        for a, b in zip(ks_, xs):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-8)

    def test_bad_rule_and_slot_count_raise(self):
        g = jnp.zeros((4,))
        scal = jnp.zeros((4,))
        with pytest.raises(ValueError, match="rule"):
            fo.fused_bucket_update(g, g, (g,), scal, rule="rmsprop",
                                   hyper={})
        fused = fo.FusedOptimizer(rule="adamw")
        with pytest.raises(ValueError, match="slot"):
            fo.fused_bucket_update(g, g, (g,), scal, rule="adamw",
                                   hyper=fused.hyper, impl="xla")
        with pytest.raises(ValueError, match="rule"):
            fo.FusedOptimizer(rule="nope")

    def test_bf16_params_keep_dtype_f32_slots(self):
        g = jnp.ones((50,), jnp.bfloat16) * 0.1
        p = jnp.ones((50,), jnp.bfloat16)
        fused = fo.FusedOptimizer(rule="adamw")
        scal = fused.scalars(jnp.int32(1))
        slots = (jnp.zeros((50,), jnp.float32),) * 2
        np_, ns = fo.fused_bucket_update(g, p, slots, scal, rule="adamw",
                                         hyper=fused.hyper, impl="xla")
        assert np_.dtype == jnp.bfloat16
        assert all(s.dtype == jnp.float32 for s in ns)


class TestOptaxPin:
    """The replicated-tree pin: fused vs optax, both jitted (optax's own
    helpers are inline-jitted, so eager-vs-jit comparisons see XLA's
    div->reciprocal rewrite; under one compile pipeline the op streams
    are identical and the f32 pin is BIT-exact)."""

    @pytest.mark.parametrize("wd", [0.0, 1e-2])
    def test_adamw_bitexact_f32(self, wd):
        params = _params()
        grads = _grads(params)
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3, weight_decay=wd)
        plan = fused.plan_for(params, None)
        tx = optax.adamw(1e-3, weight_decay=wd)

        fstep = jax.jit(lambda p, s: fo.fused_update_step(
            fused, p, grads, s, plan=plan))

        @jax.jit
        def ostep(p, s):
            u, s2 = tx.update(grads, s, p)
            return optax.apply_updates(p, u), s2

        p1, st = params, fused.init_state(params)
        p2, ost = params, tx.init(params)
        for _ in range(5):
            p1, st, _ = fstep(p1, st)
            p2, ost = ostep(p2, ost)
        assert _tree_leaves_bitexact(p1, p2)
        # The bucket-resident moments convert to optax's, bit-exact.
        lm = fo.slots_to_leaf_major(plan, st["slots"])
        assert _tree_leaves_bitexact(lm["mu"], ost[0].mu)
        assert _tree_leaves_bitexact(lm["nu"], ost[0].nu)

    def test_sgd_momentum_bitexact_f32(self):
        params = _params()
        grads = _grads(params)
        fused = fo.FusedOptimizer(rule="sgd", lr=0.1, momentum=0.9)
        plan = fused.plan_for(params, None)
        tx = optax.sgd(0.1, momentum=0.9)
        fstep = jax.jit(lambda p, s: fo.fused_update_step(
            fused, p, grads, s, plan=plan))

        @jax.jit
        def ostep(p, s):
            u, s2 = tx.update(grads, s, p)
            return optax.apply_updates(p, u), s2

        p1, st = params, fused.init_state(params)
        p2, ost = params, tx.init(params)
        for _ in range(5):
            p1, st, _ = fstep(p1, st)
            p2, ost = ostep(p2, ost)
        assert _tree_leaves_bitexact(p1, p2)

    def test_adamw_bf16_documented_tolerance(self):
        # optax keeps bf16 moments for bf16 params; the fused plane keeps
        # f32 slots and re-rounds only the param write — so the pin is a
        # bf16-ulp tolerance, not bit-exactness (see README).
        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), _params())
        grads = _grads(params)
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-2, weight_decay=1e-2)
        plan = fused.plan_for(params, None)
        tx = optax.adamw(1e-2, weight_decay=1e-2)
        fstep = jax.jit(lambda p, s: fo.fused_update_step(
            fused, p, grads, s, plan=plan))

        @jax.jit
        def ostep(p, s):
            u, s2 = tx.update(grads, s, p)
            return optax.apply_updates(p, u), s2

        p1, st = params, fused.init_state(params)
        p2, ost = params, tx.init(params)
        for _ in range(3):
            p1, st, _ = fstep(p1, st)
            p2, ost = ostep(p2, ost)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=1e-2, atol=1e-2)

    def test_adafactor_style_matches_leaf_major_reference(self):
        # The adafactor rule is self-pinned: second-moment-only,
        # elementwise, non-factored — the leaf-major reference is the
        # same math without any bucket layout.
        params = _params()
        grads = _grads(params)
        b2, eps, lr = 0.999, 1e-8, 1e-3
        fused = fo.FusedOptimizer(rule="adafactor", lr=lr, b2=b2, eps=eps)
        plan = fused.plan_for(params, None)
        fstep = jax.jit(lambda p, s: fo.fused_update_step(
            fused, p, grads, s, plan=plan))

        @jax.jit
        def ref(p, nu):
            nu2 = jax.tree.map(
                lambda g, v: (1 - b2) * (g * g) + b2 * v, grads, nu)
            p2 = jax.tree.map(
                lambda pp, g, v: pp + (-lr) * (g / (jnp.sqrt(v) + eps)),
                p, grads, nu2)
            return p2, nu2

        p1, st = params, fused.init_state(params)
        p2, nu = params, jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        for _ in range(3):
            p1, st, _ = fstep(p1, st)
            p2, nu = ref(p2, nu)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-8)

    def test_clip_norm_matches_optax_chain(self):
        params = _params()
        grads = _grads(params)
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3, clip_norm=0.05)
        plan = fused.plan_for(params, None)
        tx = optax.chain(optax.clip_by_global_norm(0.05),
                         optax.adamw(1e-3, weight_decay=0.0))
        fstep = jax.jit(lambda p, s: fo.fused_update_step(
            fused, p, grads, s, plan=plan))

        @jax.jit
        def ostep(p, s):
            u, s2 = tx.update(grads, s, p)
            return optax.apply_updates(p, u), s2

        p1, st = params, fused.init_state(params)
        p2, ost = params, tx.init(params)
        for _ in range(2):
            p1, st, gnorm = fstep(p1, st)
            p2, ost = ostep(p2, ost)
        # The bucket-major norm differs from the per-leaf one only by fp
        # reassociation, so the clipped trajectories agree to ulps.
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)

    def test_lr_schedule_callable(self):
        params = _params()
        grads = _grads(params)
        fused = fo.FusedOptimizer(rule="sgd", momentum=0.0,
                                  lr=lambda count: 0.1 / count)
        plan = fused.plan_for(params, None)
        st = fused.init_state(params)
        p1, st, _ = fo.fused_update_step(fused, params, grads, st,
                                         plan=plan)
        p2, st, _ = fo.fused_update_step(fused, p1, grads, st, plan=plan)
        # step 1 at lr .1, step 2 at lr .05
        exp = jax.tree.map(lambda p, g: p - 0.1 * g - 0.05 * g,
                           params, grads)
        for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(exp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)


def _zero3_tree(mesh):
    """Sharded + UNEVEN-sharded (explicit spec, committed replicated) +
    bf16 + replicated + scalar — the full menu of bucket kinds."""
    ks = jax.random.split(jax.random.PRNGKey(3), 8)
    params = {
        "w1": jax.random.normal(ks[0], (16, 8), jnp.float32),
        "w2": jax.random.normal(ks[1], (6, 8), jnp.float32),   # 6 % 4 != 0
        "w3": jax.random.normal(ks[2], (8, 4), jnp.bfloat16),
        "bias": jax.random.normal(ks[3], (5,), jnp.float32),
        "scale": jnp.float32(1.5),
    }
    specs = {"w1": P("fsdp"), "w2": P("fsdp"), "w3": P("fsdp"),
             "bias": P(), "scale": P()}
    committed = {k: NamedSharding(mesh, P("fsdp")
                                  if k in ("w1", "w3") else P())
                 for k in params}
    params = jax.device_put(params, committed)
    grads = jax.device_put(_grads(params), committed)
    return params, grads, specs


class TestZero3:
    """Scatter-layout updates: shard-domain buckets, padded uneven
    shards, multi-dtype trees — pinned against leaf-major optax."""

    def _setup(self, bucket_bytes=256):
        mesh = par.make_mesh(fsdp=4)
        params, grads, specs = _zero3_tree(mesh)
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3,
                                  weight_decay=1e-2,
                                  bucket_bytes=bucket_bytes)
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=bucket_bytes)
        return mesh, params, grads, specs, fused, plan

    def test_sharded_update_bitexact_vs_optax(self):
        mesh, params, grads, specs, fused, plan = self._setup()
        assert plan.n_scatter_buckets >= 2 and sum(plan.bucket_padded) == 1
        st = fused.init_state(params, mesh, plan=plan)
        fstep = jax.jit(lambda p, g, s: fo.fused_update_step(
            fused, p, g, s, mesh, plan=plan, param_specs=specs))
        tx = optax.adamw(1e-3, weight_decay=1e-2)
        host_p, host_g = jax.device_get(params), jax.device_get(grads)

        @jax.jit
        def ostep(p, s):
            u, s2 = tx.update(host_g, s, p)
            return optax.apply_updates(p, u), s2

        p1, p2, ost = params, host_p, tx.init(host_p)
        for _ in range(3):
            p1, st, gnorm = fstep(p1, grads, st)
            p2, ost = ostep(p2, ost)
        for k in params:
            a = np.asarray(jax.device_get(p1[k]))
            b = np.asarray(p2[k])
            if str(params[k].dtype) == "bfloat16":
                np.testing.assert_allclose(a.astype(np.float32),
                                           b.astype(np.float32),
                                           rtol=1e-2, atol=1e-2)
            else:
                assert np.array_equal(a, b), k
        # Sharded layouts preserved: even scatter leaves stay fsdp-
        # sharded, uneven/replicated leaves stay whole.
        assert "fsdp" in str(p1["w1"].sharding.spec)
        assert p1["w2"].shape == (6, 8)
        # Bucket-major norm pins against the per-leaf reduction.
        ref = optax.global_norm(jax.tree.map(
            lambda g: np.asarray(g, np.float32), host_g))
        np.testing.assert_allclose(float(gnorm), float(ref), rtol=1e-4)

    def test_pad_rows_stay_inert(self):
        mesh, params, grads, specs, fused, plan = self._setup()
        st = fused.init_state(params, mesh, plan=plan)
        fstep = jax.jit(lambda p, g, s: fo.fused_update_step(
            fused, p, g, s, mesh, plan=plan, param_specs=specs))
        p1 = params
        for _ in range(3):
            p1, st, _ = fstep(p1, grads, st)
        # Indicator: pack a ones-tree — zeros land exactly on pad rows.
        ones = jax.tree.map(
            lambda p: np.ones(p.shape, np.float32),
            jax.device_get(params))
        ind = plan.pack(ones)
        for b in range(plan.n_buckets):
            if not plan._is_padded(b):
                continue
            mask = np.asarray(ind[b]) == 0
            assert mask.any()          # the pad rows exist
            for name in st["slots"]:
                buf = np.asarray(jax.device_get(st["slots"][name][b]))
                assert not buf[mask].any(), \
                    f"slot {name} bucket {b}: pad rows drifted nonzero"
        # ...and therefore the portable round-trip is the identity.
        back = fo.leaf_major_to_slots(
            plan, fo.slots_to_leaf_major(plan, st["slots"]), mesh)
        for name in back:
            for a, b in zip(st["slots"][name], back[name]):
                assert _bitexact(a, b)

    def test_accum_step_fused_matches_optax_path(self):
        """make_accum_train_step(update='fused_bucket') vs the optax
        path: same microbatched reduce, so the whole 2-step trajectory is
        bit-exact in f32 — reduce→update never leaving the bucket domain
        changes nothing numerically."""
        mesh = par.make_mesh(fsdp=4)
        model = get_model("mnist-mlp", hidden=32)
        kx, ky, kr = jax.random.split(jax.random.PRNGKey(1), 3)
        x = jax.random.normal(kx, (64, 784), jnp.float32)
        y = jax.random.randint(ky, (64,), 0, 10)
        data = {"x": x, "y": y}
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3,
                                  weight_decay=1e-2,
                                  bucket_bytes=1 << 16)
        sf = fsdp_shard_state(tr.create_train_state(model, fused, x, kr),
                              mesh)
        so = fsdp_shard_state(tr.create_train_state(
            model, optax.adamw(1e-3, weight_decay=1e-2), x, kr), mesh)
        profiler.reset_update_records()
        step_f = tr.make_accum_train_step(
            mesh=mesh, microbatches=4, bucket_bytes=1 << 16,
            update="fused_bucket", donate=False)
        step_o = tr.make_accum_train_step(
            mesh=mesh, microbatches=4, bucket_bytes=1 << 16, donate=False)
        for _ in range(2):
            sf, mf = step_f(sf, data)
            so, mo = step_o(so, data)
        assert float(mf["loss"]) == float(mo["loss"])
        assert float(mf["grad_norm"]) == pytest.approx(
            float(mo["grad_norm"]), rel=1e-6)
        assert _tree_leaves_bitexact(sf.params, so.params)
        assert int(sf.opt_state["count"]) == 2 and int(sf.step) == 2
        rec = profiler.update_report()["accum_update"]
        assert rec["rule"] == "adamw" and rec["impl"] in ("pallas", "xla")
        assert rec["n_buckets"] >= 1 and rec["n_scatter_buckets"] >= 1

    def test_accum_step_validates_tx_and_bucket_bytes(self):
        mesh = par.make_mesh(fsdp=2)
        model = get_model("mnist-mlp", hidden=16)
        kx, ky, kr = jax.random.split(jax.random.PRNGKey(1), 3)
        x = jax.random.normal(kx, (16, 784), jnp.float32)
        data = {"x": x, "y": jax.random.randint(ky, (16,), 0, 10)}
        state_o = fsdp_shard_state(tr.create_train_state(
            model, optax.sgd(0.1), x, kr), mesh)
        step = tr.make_accum_train_step(mesh=mesh, microbatches=2,
                                        update="fused_bucket")
        with pytest.raises(ValueError, match="FusedOptimizer"):
            step(state_o, data)
        fused = fo.FusedOptimizer(rule="sgd", lr=0.1,
                                  bucket_bytes=1 << 16)
        state_f = fsdp_shard_state(tr.create_train_state(
            model, fused, x, kr), mesh)
        bad = tr.make_accum_train_step(mesh=mesh, microbatches=2,
                                       bucket_bytes=123,
                                       update="fused_bucket")
        with pytest.raises(ValueError, match="bucket_bytes"):
            bad(state_f, data)
        with pytest.raises(ValueError, match="update mode"):
            tr.make_accum_train_step(mesh=mesh, microbatches=2,
                                     update="nope")

    def test_slot_topology_mismatch_raises(self):
        mesh, params, grads, specs, fused, plan = self._setup()
        st = fused.init_state(params, mesh, plan=plan)
        short = {n: bufs[:-1] for n, bufs in st["slots"].items()}
        with pytest.raises(ValueError, match="bucket"):
            fused.check_slots(plan, short)
        renamed = {"m" if n == "mu" else n: b
                   for n, b in st["slots"].items()}
        with pytest.raises(ValueError, match="slots"):
            fused.check_slots(plan, renamed)


class TestCkptPortability:
    """The leaf-major codec: manifests carry topology-independent opt
    state; bucket-resident buffers rebuild for whatever mesh restores."""

    def _fused_state(self, mesh, fused, seed=1):
        model = get_model("mnist-mlp", hidden=32)
        kx, ky, kr = jax.random.split(jax.random.PRNGKey(seed), 3)
        x = jax.random.normal(kx, (64, 784), jnp.float32)
        y = jax.random.randint(ky, (64,), 0, 10)
        state = fsdp_shard_state(
            tr.create_train_state(model, fused, x, kr), mesh)
        return state, {"x": x, "y": y}

    def test_roundtrip_across_changed_fsdp_topology(self, tmp_path):
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3,
                                  weight_decay=1e-2, bucket_bytes=1 << 16)
        mesh4 = par.make_mesh(fsdp=4)
        state, data = self._fused_state(mesh4, fused)
        step = tr.make_accum_train_step(
            mesh=mesh4, microbatches=4, bucket_bytes=1 << 16,
            update="fused_bucket", donate=False)
        for _ in range(2):
            state, _ = step(state, data)
        mgr = ckpt_mod.AsyncCheckpointer(tmp_path, keep=2)
        mgr.save(ckpt_mod.encode_portable(state), step=2, block=True)
        mgr.close()

        mesh2 = par.make_mesh(fsdp=2)
        fresh, _ = self._fused_state(mesh2, fused, seed=99)
        restored = ckpt_mod.decode_portable(ckpt_mod.restore_pytree(
            tmp_path, ckpt_mod.encode_portable(fresh), step=2,
            mesh=mesh2), mesh2)
        # Portable forms agree bit-exact across the topology change...
        pa = ckpt_mod.encode_portable(state).opt_state
        pb = ckpt_mod.encode_portable(restored).opt_state
        assert _tree_leaves_bitexact(pa, pb)
        assert _tree_leaves_bitexact(state.params, restored.params)
        assert int(restored.opt_state["count"]) == 2
        # ...and the restored state steps on the NEW topology with the
        # identical result (same math, different scatter layout).
        step2 = tr.make_accum_train_step(
            mesh=mesh2, microbatches=4, bucket_bytes=1 << 16,
            update="fused_bucket", donate=False)
        restored, m2 = step2(restored, data)
        state, m4 = step(state, data)
        assert float(m2["loss"]) == float(m4["loss"])

    def test_train_loop_saves_portable_and_restores_resident(
            self, tmp_path):
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3,
                                  bucket_bytes=1 << 16)
        mesh = par.make_mesh(fsdp=4)
        state, data = self._fused_state(mesh, fused)
        step = tr.make_accum_train_step(
            mesh=mesh, microbatches=4, bucket_bytes=1 << 16,
            update="fused_bucket", donate=False)
        s1, _ = tr.train_loop(state, step, [data] * 4,
                              ckpt_dir=str(tmp_path), save_every=2,
                              mesh=mesh)
        assert ckpt_mod.committed_steps(tmp_path) == [2, 4]
        # The manifest carries LEAF-major opt-state paths (portable form).
        manifest = ckpt_mod.read_manifest(tmp_path, 4)
        paths = [m["path"] for m in manifest["leaves"]]
        assert any(".opt_state['leaf']['mu']" in p for p in paths)
        assert not any("['slots']" in p for p in paths)
        fresh, _ = self._fused_state(mesh, fused, seed=5)
        s2, _ = tr.train_loop(fresh, step, [], ckpt_dir=str(tmp_path),
                              mesh=mesh)
        assert "slots" in s2.opt_state          # resident again
        assert _tree_leaves_bitexact(s1.params, s2.params)
        assert _tree_leaves_bitexact(
            ckpt_mod.encode_portable(s1).opt_state,
            ckpt_mod.encode_portable(s2).opt_state)

    def test_plain_optax_states_pass_codecs_untouched(self):
        mesh = par.make_mesh(fsdp=2)
        model = get_model("mnist-mlp", hidden=16)
        kx, _, kr = jax.random.split(jax.random.PRNGKey(0), 3)
        x = jax.random.normal(kx, (16, 784), jnp.float32)
        state = fsdp_shard_state(tr.create_train_state(
            model, optax.adamw(1e-3), x, kr), mesh)
        assert ckpt_mod.encode_portable(state) is state
        assert ckpt_mod.decode_portable(state, mesh) is state


class TestRecords:
    def test_fused_update_record_fields(self):
        params = _params()
        fused = fo.FusedOptimizer(rule="sgd", lr=0.1, clip_norm=1.0)
        plan = fused.plan_for(params, None)
        profiler.reset_update_records()
        fo.fused_update_step(fused, params, _grads(params),
                             fused.init_state(params), plan=plan)
        rec = profiler.update_report()["fused_update"]
        assert rec["rule"] == "sgd"
        assert rec["impl"] in ("pallas", "xla")
        assert rec["n_buckets"] == plan.n_buckets
        assert rec["bucket_nbytes"] == list(plan.bucket_nbytes)
        assert rec["slot_names"] == ["trace"]
        assert rec["clip_norm"] == 1.0

    def test_mutating_update_report_does_not_poison_store(self):
        profiler.reset_update_records()
        profiler.safe_record("update", "t", nested={"deep": [1, 2]},
                             bucket_nbytes=[10, 20])
        snap = profiler.update_report()
        snap["t"]["nested"]["deep"].append(99)
        snap["t"]["bucket_nbytes"][0] = -1
        snap["injected"] = {}
        assert profiler.update_report() == {
            "t": {"nested": {"deep": [1, 2]}, "bucket_nbytes": [10, 20]}}
        profiler.reset_update_records()
