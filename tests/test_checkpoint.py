"""Checkpointer unit tier: sharded save/restore round-trips on the virtual
8-device mesh (the e2e gang-restart resume lives in test_e2e.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from tony_tpu import parallel as par
from tony_tpu import train as tr
from tony_tpu.checkpoint import Checkpointer


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.Dense(16, kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("embed", "ffn")))(x)
        return nn.Dense(4)(h)


def test_checkpointer_roundtrip_plain(tmp_path):
    x = jnp.ones((2, 8))
    state = tr.create_train_state(Tiny(), optax.adam(1e-2), x,
                                  jax.random.PRNGKey(0))
    state, _ = tr.make_train_step()(state, {"x": x,
                                            "y": jnp.zeros((2,), jnp.int32)})
    ckpt = Checkpointer(tmp_path / "c")
    ckpt.save(state)
    assert ckpt.latest_step() == 1
    fresh = tr.create_train_state(Tiny(), optax.adam(1e-2), x,
                                  jax.random.PRNGKey(1))
    restored = ckpt.restore_or(fresh)
    assert int(restored.step) == 1
    # Params match the saved state, not the fresh init; non-array leaves
    # (apply_fn, tx) pass through restore intact and the state still steps.
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    restored, metrics = tr.make_train_step()(
        restored, {"x": x, "y": jnp.zeros((2,), jnp.int32)})
    assert int(restored.step) == 2 and jnp.isfinite(metrics["loss"])
    ckpt.close()


def test_checkpointer_roundtrip_sharded_mesh(tmp_path):
    mesh = par.make_mesh(fsdp=2, tp=2, sp=2)
    x = jnp.ones((4, 8))
    state = tr.create_train_state(Tiny(), optax.adam(1e-2), x,
                                  jax.random.PRNGKey(0), mesh=mesh)
    ckpt = Checkpointer(tmp_path / "c")
    ckpt.save(state)
    restored = ckpt.restore_or(
        tr.create_train_state(Tiny(), optax.adam(1e-2), x,
                              jax.random.PRNGKey(1), mesh=mesh))
    # Mesh layouts are restored intact (not resharded to replicated).
    kernel = restored.params["Dense_0"]["kernel"]
    expect = state.params["Dense_0"]["kernel"]
    assert kernel.sharding == expect.sharding
    np.testing.assert_array_equal(np.asarray(kernel), np.asarray(expect))
    ckpt.close()


def test_restore_or_noop_without_checkpoint(tmp_path):
    x = jnp.ones((2, 8))
    state = tr.create_train_state(Tiny(), optax.sgd(0.1), x,
                                  jax.random.PRNGKey(0))
    ckpt = Checkpointer(tmp_path / "c")
    assert ckpt.restore_or(state) is state
    ckpt.close()


def test_restore_or_shardingless_leaves_recover_mesh_layout(tmp_path):
    """Satellite fix: the orbax shim built its abstract target with
    ``sharding=getattr(x, "sharding", None)`` — a target leaf WITHOUT a
    committed sharding (host numpy, e.g. a device_get'ed state) silently
    restored replicated. The native restore maps the manifest's recorded
    PartitionSpecs onto the mesh instead, so the layout survives."""
    mesh = par.make_mesh(fsdp=2, tp=2, sp=2)
    x = jnp.ones((4, 8))
    state = tr.create_train_state(Tiny(), optax.adam(1e-2), x,
                                  jax.random.PRNGKey(0), mesh=mesh)
    ckpt = Checkpointer(tmp_path / "c")
    ckpt.save(state)
    host = jax.device_get(state)        # numpy leaves: no .sharding at all
    restored = ckpt.restore_or(host, mesh=mesh)
    kernel = restored.params["Dense_0"]["kernel"]
    expect = state.params["Dense_0"]["kernel"]
    assert kernel.sharding == expect.sharding      # NOT replicated
    np.testing.assert_array_equal(np.asarray(kernel), np.asarray(expect))
    ckpt.close()


def test_save_async_then_restore_or_sees_it(tmp_path):
    """wait-then-restore ordering: restore_or after an async (wait=False)
    save must observe that save, not a stale latest."""
    x = jnp.ones((2, 8))
    state = tr.create_train_state(Tiny(), optax.sgd(0.1), x,
                                  jax.random.PRNGKey(0))
    state, _ = tr.make_train_step()(state, {"x": x,
                                            "y": jnp.zeros((2,), jnp.int32)})
    ckpt = Checkpointer(tmp_path / "c")
    ckpt.save(state, wait=False)
    restored = ckpt.restore_or(
        tr.create_train_state(Tiny(), optax.sgd(0.1), x,
                              jax.random.PRNGKey(1)))
    assert int(restored.step) == 1
    ckpt.close()
