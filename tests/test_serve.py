"""Serving-plane legs (tony_tpu.serve): paged KV cache invariants, the
flash-decoding kernel pin, the continuous-batching bit-transparency pin
(decode logits bitwise vs sequential full prefill, ragged lengths and
block boundaries included), the restore-time dtype policy, the serve
heartbeat/autoscale control plane, and the end-to-end
train→checkpoint→replica→serve path."""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.serve


# ---------------------------------------------------------------------------
# Shared tiny model + params (built once; serving is read-only on params).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


def pin_vs_full_prefill(eng, completions):
    """THE acceptance pin: every request's streamed decode logits must be
    bit-identical to rows of a sequential full prefill of its final
    token sequence."""
    for c in completions:
        full = list(c.prompt) + list(c.tokens)
        ref = eng.full_prefill_logits(full)
        p = len(c.prompt)
        assert len(c.logits) == len(c.tokens)
        for j, row in enumerate(c.logits):
            assert np.array_equal(ref[p - 1 + j], row), (
                f"request {c.rid}: decode logits at position {p - 1 + j} "
                f"differ from the full-prefill reference "
                f"(max abs diff {np.max(np.abs(ref[p - 1 + j] - row))})")


# ---------------------------------------------------------------------------
# Paged KV cache
# ---------------------------------------------------------------------------

class TestKVCache:
    def _cache(self, n_blocks=8, block_size=4):
        from tony_tpu.serve import PagedKVCache

        return PagedKVCache(2, 8, n_blocks=n_blocks,
                            block_size=block_size)

    def test_alloc_free_reuse_invariants(self):
        c = self._cache()
        t_a = c.reserve("a", 9)      # 3 blocks of 4
        t_b = c.reserve("b", 4)      # 1 block
        assert len(t_a) == 3 and len(t_b) == 1
        assert not set(t_a) & set(t_b), "tables must be disjoint"
        assert c.free_blocks == 4
        owned = c.owned_blocks()
        assert sorted(owned) == ["a", "b"]
        # Growth extends the same table.
        t_a2 = c.reserve("a", 13)
        assert t_a2[:3] == t_a and len(t_a2) == 4
        # Free returns every block; a fresh reservation reuses them.
        assert c.free_seq("a") == 4
        assert c.free_blocks == 7
        t_c = c.reserve("c", 28)     # 7 blocks — only fits if a's returned
        assert len(t_c) == 7
        assert set(t_c) | set(t_b) == set(range(8))
        # Idempotent eviction.
        assert c.free_seq("a") == 0

    def test_exhaustion_is_typed_admission_error_not_oom(self):
        from tony_tpu.serve import AdmissionError

        c = self._cache(n_blocks=4, block_size=4)
        c.reserve("a", 12)           # 3 of 4 blocks
        free_before = c.free_blocks
        with pytest.raises(AdmissionError) as exc:
            c.reserve("b", 8)        # needs 2, only 1 free
        assert exc.value.needed_blocks == 2
        assert exc.value.free_blocks == 1
        assert exc.value.retryable
        # State unchanged: the failed reservation allocated nothing.
        assert c.free_blocks == free_before
        assert "b" not in c.owned_blocks() or not c.owned_blocks()["b"]

    def test_flat_index_and_oob(self):
        c = self._cache()
        table = c.reserve("s", 10)
        assert c.flat_index("s", 0) == table[0] * 4
        assert c.flat_index("s", 5) == table[1] * 4 + 1
        with pytest.raises(IndexError):
            c.flat_index("s", 12)    # beyond the 3-block reservation
        assert c.oob_index == 8 * 4

    def test_table_array_padding_and_overflow(self):
        c = self._cache()
        c.reserve("s", 10)
        arr = c.table_array(["s", "missing"], nb_max=4)
        assert arr.shape == (2, 4) and arr.dtype == np.int32
        assert list(arr[0, :3]) == c.table("s") and arr[0, 3] == 0
        assert (arr[1] == 0).all()
        with pytest.raises(ValueError):
            c.table_array(["s"], nb_max=2)


# ---------------------------------------------------------------------------
# Flash decoding kernel
# ---------------------------------------------------------------------------

class TestFlashDecode:
    @pytest.mark.parametrize("h,hkv,block_k", [(4, 4, 16), (4, 2, 16),
                                               (4, 1, 32)])
    def test_kernel_vs_fallback_bit_identical(self, h, hkv, block_k):
        from tony_tpu.ops import flash_decode

        rng = np.random.RandomState(0)
        b, t, d, ctx = 3, 16, 16, 64
        q = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, hkv, ctx, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, hkv, ctx, d), jnp.bfloat16)
        pos = jnp.asarray(rng.randint(0, ctx, (b, t)), jnp.int32)
        xla = flash_decode(q, k, v, pos, block_k=block_k)
        pal = flash_decode(q, k, v, pos, block_k=block_k, interpret=True)
        assert jnp.all(xla == pal), "pallas kernel != XLA fallback"

    def test_matches_reference_attention(self):
        from tony_tpu.ops import flash_decode, reference_attention

        rng = np.random.RandomState(1)
        b, h, hkv, t, d, ctx = 2, 4, 2, 16, 16, 48
        q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, hkv, ctx, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, hkv, ctx, d), jnp.float32)
        # Rows are the last t positions of a ctx-long causal sequence.
        pos = jnp.broadcast_to(
            jnp.arange(ctx - t, ctx, dtype=jnp.int32)[None], (b, t))
        dec = flash_decode(q, k, v, pos, block_k=16)
        qfull = jnp.zeros((b, h, ctx, d), jnp.float32
                          ).at[:, :, ctx - t:].set(q)
        ref = reference_attention(qfull, k, v, causal=True)[:, :, ctx - t:]
        np.testing.assert_allclose(np.asarray(dec), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_validation_errors(self):
        from tony_tpu.ops import flash_decode

        q = jnp.zeros((1, 4, 16, 16), jnp.bfloat16)
        k = jnp.zeros((1, 3, 32, 16), jnp.bfloat16)
        pos = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="multiple of kv heads"):
            flash_decode(q, k, k, pos)
        k2 = jnp.zeros((1, 2, 32, 16), jnp.bfloat16)
        with pytest.raises(ValueError, match="q_positions"):
            flash_decode(q, k2, k2, jnp.zeros((1, 8), jnp.int32))
        with pytest.raises(ValueError, match="must match"):
            flash_decode(q, k2, jnp.zeros((1, 2, 16, 16), jnp.bfloat16),
                         pos)


# ---------------------------------------------------------------------------
# Continuous-batching engine
# ---------------------------------------------------------------------------

class TestEngine:
    def test_decode_bitwise_vs_full_prefill_ragged(self, tiny):
        """The core numerics pin over ragged prompt lengths that cross
        the KV block boundary (block_size=8: 7/8/9) and the q-block
        boundary (q_block=16: 15/17)."""
        from tony_tpu.serve import Request

        eng = make_engine(tiny)
        rng = np.random.RandomState(0)
        lengths = [7, 8, 9, 15, 17]
        for i, n in enumerate(lengths):
            eng.submit(Request(rid=f"r{i}",
                               tokens=list(rng.randint(0, 256, n)),
                               max_new_tokens=4))
        done = eng.run()
        assert sorted(c.rid for c in done) == [f"r{i}"
                                               for i in range(len(lengths))]
        pin_vs_full_prefill(eng, done)
        # Every evicted sequence returned its blocks.
        assert eng.cache.free_blocks == eng.cache.n_blocks

    def test_overlapping_joins_are_bit_transparent(self, tiny):
        """Requests arriving MID-decode join the running batch at
        iteration granularity; their logits (and everyone else's) stay
        bit-identical to the isolated full-prefill reference."""
        from tony_tpu.serve import Request

        eng = make_engine(tiny)
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, 256, n)) for n in (5, 11, 9, 20)]
        eng.submit(Request(rid="r0", tokens=prompts[0], max_new_tokens=6))
        done = eng.step()                      # r0 prefills + decodes
        eng.submit(Request(rid="r1", tokens=prompts[1], max_new_tokens=5))
        eng.submit(Request(rid="r2", tokens=prompts[2], max_new_tokens=3))
        done += eng.step()                     # r1/r2 join r0 mid-flight
        eng.submit(Request(rid="r3", tokens=prompts[3], max_new_tokens=4))
        done += eng.run()
        assert sorted(c.rid for c in done) == ["r0", "r1", "r2", "r3"]
        pin_vs_full_prefill(eng, done)

    def test_static_and_continuous_emit_identical_tokens(self, tiny):
        from tony_tpu.serve import Request

        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, 256, n)) for n in (4, 13, 8)]

        def tokens_of(policy):
            eng = make_engine(tiny, join_policy=policy, keep_logits=False)
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=i, tokens=p, max_new_tokens=5))
            return {c.rid: c.tokens for c in eng.run()}

        assert tokens_of("continuous") == tokens_of("static")

    def test_never_fits_request_rejected_nonretryable(self, tiny):
        from tony_tpu.serve import AdmissionError, Request

        eng = make_engine(tiny)                # ctx_pad = 64
        with pytest.raises(AdmissionError) as exc:
            eng.submit(Request(rid="big", tokens=list(range(60)),
                               max_new_tokens=10))
        assert not exc.value.retryable
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(Request(rid="empty", tokens=[], max_new_tokens=1))
        # Fits the context but not the ENTIRE pool (explicit small
        # n_blocks): queueing it as retryable would livelock the loop.
        small = make_engine(tiny, n_blocks=4)  # 4 blocks of 8 = 32 slots
        with pytest.raises(AdmissionError) as exc:
            small.submit(Request(rid="poolbig", tokens=list(range(30)),
                                 max_new_tokens=10))
        assert not exc.value.retryable
        assert small.queue_depth == 0

    def test_pool_pressure_queues_then_completes(self, tiny):
        """With a pool sized for ~one sequence, the second request stays
        QUEUED (admission back-pressure, no error) until the first
        evicts — then completes with identical numerics."""
        from tony_tpu.serve import Request

        # 10 blocks of 8 = 80 slots; each request reserves 3 blocks
        # (17 + 4 -> 21 positions), so 2 fit but the pool gate still
        # exercises: size to 5 blocks -> one at a time.
        eng = make_engine(tiny, n_blocks=5)
        rng = np.random.RandomState(3)
        reqs = [Request(rid=i, tokens=list(rng.randint(0, 256, 17)),
                        max_new_tokens=4) for i in range(2)]
        for r in reqs:
            eng.submit(r)
        done = eng.step()
        assert eng.queue_depth == 1            # second couldn't join
        done += eng.run()
        assert sorted(c.rid for c in done) == [0, 1]
        pin_vs_full_prefill(eng, done)
        assert eng.cache.free_blocks == eng.cache.n_blocks

    def test_serve_records_stats_and_stats_file(self, tiny, tmp_path):
        from tony_tpu import profiler
        from tony_tpu.executor import read_serve_stats
        from tony_tpu.serve import Request

        profiler.reset_serve_records()
        eng = make_engine(tiny, tag="serve_test")
        eng.submit(Request(rid="r", tokens=[1, 2, 3], max_new_tokens=2))
        eng.run()
        stats = eng.stats()
        for key in ("qps", "p50_ms", "p99_ms", "queue_depth",
                    "tokens_per_s", "forwards", "tokens_per_forward",
                    "acceptance_rate"):
            assert key in stats
        # Effective throughput (the autoscaler's honest number since the
        # speculative lane): generated tokens per forward launch — this
        # run emitted 2 tokens (max_new_tokens=2).
        assert stats["tokens_per_forward"] == pytest.approx(
            2.0 / stats["forwards"])
        assert stats["acceptance_rate"] == 0.0
        report = profiler.serve_report()
        assert report["serve_test"]["ctx_pad"] == eng.ctx_pad
        assert report["serve_test_stats"]["completed"] == 1.0
        # The planner registration landed in the unified collective
        # schema (ROADMAP: new step-path planes register day one).
        assert profiler.collective_report()["serve_decode"]["plane"] \
            == "serve_decode"
        # Stats file round-trips through the executor's jax-free reader.
        path = tmp_path / "serve-stats.json"
        eng.write_stats(str(path))
        read = read_serve_stats(path)
        assert read is not None and read["completed"] == 1.0

    def test_stats_rates_are_windowed_not_lifetime(self, tiny):
        """A latency spike must age out of qps/p50/p99 (the autoscaler
        reads them as 'now' — a stale p99 would block scale-down
        forever); completed/steps/forwards stay lifetime counters."""
        from tony_tpu.serve import Request

        eng = make_engine(tiny, keep_logits=False, stats_window_s=0.2)
        eng.submit(Request(rid="r", tokens=[1, 2, 3], max_new_tokens=2))
        eng.run()
        busy = eng.stats()
        assert busy["p99_ms"] > 0 and busy["qps"] > 0
        time.sleep(0.3)                       # the window drains
        idle = eng.stats()
        assert idle["p99_ms"] == 0.0 and idle["qps"] == 0.0
        assert idle["completed"] == 1.0       # lifetime counter intact

    def test_mutating_serve_report_does_not_poison_store(self):
        from tony_tpu import profiler

        profiler.reset_serve_records()
        profiler.safe_record("serve", "t", nested={"deep": [1, 2]},
                             n=1)
        snap = profiler.serve_report()
        snap["t"]["nested"]["deep"].append(99)
        snap["t"]["poison"] = True
        clean = profiler.serve_report()
        assert clean["t"]["nested"] == {"deep": [1, 2]}
        assert "poison" not in clean["t"]
        profiler.reset_serve_records()
        assert profiler.serve_report() == {}


# ---------------------------------------------------------------------------
# Restore-time dtype policy + subtree prefix
# ---------------------------------------------------------------------------

class TestDtypePolicy:
    @pytest.fixture()
    def saved_state(self, tmp_path):
        import optax

        from tony_tpu import ckpt, train
        from tony_tpu.models import get_model

        model = get_model("mnist-mlp", hidden=16)
        x = jnp.ones((4, 784), jnp.float32)
        state = train.create_train_state(
            model, optax.adamw(1e-3), x, jax.random.PRNGKey(0))
        mgr = ckpt.AsyncCheckpointer(tmp_path / "ckpt")
        mgr.save(state, step=1)
        mgr.wait()
        mgr.close()
        return state, tmp_path / "ckpt"

    def test_bf16_policy_casts_params_never_opt_slots(self, saved_state):
        from tony_tpu import ckpt

        state, root = saved_state
        restored = ckpt.restore_pytree(root, state, dtype_policy="bf16")
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                restored.params)[0]:
            assert leaf.dtype == jnp.bfloat16, \
                jax.tree_util.keystr(path)
        # Round trip: the bf16 values are exactly the cast f32 master.
        orig = jax.tree.leaves(state.params)
        got = jax.tree.leaves(restored.params)
        for a, b in zip(orig, got):
            assert jnp.all(a.astype(jnp.bfloat16) == b)
        # Optimizer slots: bit-untouched f32.
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                restored.opt_state)[0]:
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                    leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.float32, \
                    jax.tree_util.keystr(path)
        for a, b in zip(jax.tree.leaves(state.opt_state),
                        jax.tree.leaves(restored.opt_state)):
            assert jnp.all(jnp.asarray(a) == jnp.asarray(b))

    def test_find_path_prefix_and_subtree_restore(self, saved_state):
        from tony_tpu import ckpt

        state, root = saved_state
        prefix = ckpt.find_path_prefix(root, state.params)
        assert prefix == ".params"
        params = ckpt.restore_pytree(root, state.params,
                                     path_prefix=prefix,
                                     dtype_policy="bf16")
        # A params-only restore through the prefix: correct values, no
        # optimizer resurrection anywhere.
        for a, b in zip(jax.tree.leaves(state.params),
                        jax.tree.leaves(params)):
            assert jnp.all(a.astype(jnp.bfloat16) == b)
        assert ckpt.find_path_prefix(root, state) == ""
        with pytest.raises(KeyError):
            ckpt.find_path_prefix(root, {"not": jnp.ones((3, 3))})

    def test_unknown_policy_raises(self, saved_state):
        from tony_tpu import ckpt

        state, root = saved_state
        with pytest.raises(ValueError, match="dtype_policy"):
            ckpt.restore_pytree(root, state, dtype_policy="int4")


# ---------------------------------------------------------------------------
# Control plane: heartbeat schema, executor round trip, scaling policy
# ---------------------------------------------------------------------------

class TestControlPlane:
    def test_executor_heartbeat_piggybacks_serve_stats(self, tmp_path):
        """Executor round trip: the replica's stats file → heartbeat RPC
        → session.serve_metrics (the autoscaler's input)."""
        from tony_tpu import constants
        from tony_tpu.conf import TonyConfig
        from tony_tpu.executor import TaskExecutor
        from tony_tpu.rpc import ApplicationRpcHandler, RpcServer
        from tony_tpu.session import TonySession

        conf = TonyConfig({"tony.serve.instances": "1",
                           "tony.serve.command": "x"})
        session = TonySession(conf, app_id="app_serve_hb")
        session.on_registered("serve", 0, "127.0.0.1", 4000)
        server = RpcServer(ApplicationRpcHandler(session),
                           host="127.0.0.1").start()
        conf_path = tmp_path / "conf.json"
        conf_path.write_text(json.dumps(dict(conf.items())))
        try:
            executor = TaskExecutor(env={
                constants.ENV_JOB_NAME: "serve",
                constants.ENV_TASK_INDEX: "0",
                constants.ENV_AM_ADDRESS: server.address,
                constants.ENV_CONF_PATH: str(conf_path),
                constants.ENV_LOG_DIR: str(tmp_path),
            })
            executor.serve_stats_path().write_text(json.dumps(
                {"qps": 3.5, "p99_ms": 12.0, "queue_depth": 2.0}))
            t = threading.Thread(target=executor._heartbeat_loop,
                                 args=(0.05,), daemon=True)
            t.start()
            deadline = time.monotonic() + 10.0
            task = session.task("serve", 0)
            while time.monotonic() < deadline and not task.serve_metrics:
                time.sleep(0.05)
            executor._hb_stop.set()
            t.join(timeout=5)
            assert task.serve_metrics == {"qps": 3.5, "p99_ms": 12.0,
                                          "queue_depth": 2.0}
            assert session.serve_samples("serve") == [task.serve_metrics]
            assert task.to_info()["serve_metrics"]["qps"] == 3.5
        finally:
            server.stop()

    def test_scaling_decide_matrix(self):
        from tony_tpu.serve import scaling

        pol = scaling.ScalingPolicy(min_replicas=1, max_replicas=4,
                                    queue_high=8.0, queue_low=1.0,
                                    p99_high_ms=500.0, cooldown_s=30.0)
        hot = [{"queue_depth": 12.0, "p99_ms": 100.0}]
        cold = [{"queue_depth": 0.0, "p99_ms": 10.0}]
        tail = [{"queue_depth": 2.0, "p99_ms": 900.0}]
        assert scaling.decide(pol, 1, hot, now=0.0) == 1
        assert scaling.decide(pol, 4, hot, now=0.0) == 0      # at ceiling
        assert scaling.decide(pol, 2, cold, now=0.0) == -1
        assert scaling.decide(pol, 1, cold, now=0.0) == 0     # at floor
        assert scaling.decide(pol, 1, tail, now=0.0) == 1     # p99 trips
        # Cooldown holds both directions; repair ignores it.
        assert scaling.decide(pol, 1, hot, now=10.0,
                              last_action=0.0) == 0
        assert scaling.decide(pol, 0, [], now=10.0,
                              last_action=0.0) == 1
        assert scaling.decide(pol, 1, hot, now=40.0,
                              last_action=0.0) == 1
        # No telemetry yet: hold.
        assert scaling.decide(pol, 2, [], now=0.0) == 0

    def test_scaling_policy_validation_and_conf(self):
        from tony_tpu.conf import TonyConfig
        from tony_tpu.serve import scaling

        with pytest.raises(ValueError):
            scaling.ScalingPolicy(min_replicas=0)
        with pytest.raises(ValueError):
            scaling.ScalingPolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            scaling.ScalingPolicy(queue_low=9.0, queue_high=8.0)
        conf = TonyConfig({"tony.serve.replicas.max": "5",
                           "tony.serve.scale.queue-high": "4.5"})
        pol = scaling.ScalingPolicy.from_conf(conf, instances=2)
        assert pol.min_replicas == 2 and pol.max_replicas == 5
        assert pol.queue_high == 4.5 and pol.enabled
        assert not scaling.ScalingPolicy.from_conf(
            TonyConfig(), instances=2).enabled

    def test_session_elastic_tasks_and_scale_down(self):
        from tony_tpu.conf import TonyConfig
        from tony_tpu.session import JobStatus, TaskStatus, TonySession

        conf = TonyConfig({"tony.serve.instances": "1",
                           "tony.serve.command": "x"})
        s = TonySession(conf, "app_el")
        s.on_registered("serve", 0, "127.0.0.1", 4000)
        assert s.all_registered()
        t1 = s.add_task("serve")
        assert t1.index == 1 and t1.elastic
        # Elastic tasks never re-open the gang barrier.
        assert s.all_registered()
        s.on_registered("serve", 1, "127.0.0.1", 4001)
        s.mark_scaled_down(t1, "scale-down")
        assert t1.status == TaskStatus.KILLED
        assert s.job_status == JobStatus.RUNNING, \
            "a deliberate scale-down must not fail the job"
        with pytest.raises(KeyError):
            s.add_task("nonexistent")

    def test_am_floor_repair_runs_with_autoscale_disabled(self, tmp_path):
        """`tony serve` turns fail-fast off on the promise that the AM
        repairs the replica floor — which must hold even when autoscale
        is NOT armed (no replicas.max above the static count): a crashed
        replica gets an elastic replacement launched."""
        from types import SimpleNamespace

        from tony_tpu.am import ApplicationMaster
        from tony_tpu.conf import TonyConfig
        from tony_tpu.session import TonySession

        class _FakeContainer:
            def __init__(self, cid):
                self.container_id = cid
                self.is_running = True

        class _FakeScheduler:
            def __init__(self):
                self.launched = []

            def launch(self, req):
                self.launched.append(req)
                return _FakeContainer(f"c{len(self.launched)}")

            def stop_container(self, c):
                c.is_running = False

            def poll_completed(self):
                return []

            def stop(self):
                pass

        conf = TonyConfig({"tony.serve.instances": "2",
                           "tony.serve.command": "x",
                           "tony.application.fail-fast": "false"})
        sched = _FakeScheduler()
        am = ApplicationMaster(conf, "app_repair", tmp_path,
                               scheduler=sched)
        session = TonySession(conf, "app_repair")
        am.session = session
        am.handler = SimpleNamespace(_all_registered_fired=True)
        am.server = SimpleNamespace(port=1)
        session.on_registered("serve", 0, "h", 1)
        session.on_registered("serve", 1, "h", 2)
        session.on_task_result("serve", 1, 1, "replica crashed")
        am._autoscale_serve(session)
        assert len(sched.launched) == 1, \
            "below-floor repair must launch a replacement"
        repaired = session.task("serve", 2)
        assert repaired.elastic
        # Back at the floor with autoscale off: no further action.
        am._autoscale_serve(session)
        assert len(sched.launched) == 1

    def test_cli_serve_builds_conf(self, tmp_path):
        from tony_tpu import conf as conf_mod
        from tony_tpu.cli import make_parser

        args = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--ckpt_dir",
            str(tmp_path), "--replicas", "2", "--max_replicas", "4",
            "--model_kwargs", '{"n_layers": 2}',
            "--conf", "tony.serve.scale.queue-high=3"])
        assert args.fn.__name__ == "cmd_serve"
        # Reuse cmd_serve's conf assembly up to (not including) submit.
        from tony_tpu.conf import TonyConfig
        cfg = TonyConfig()
        cfg.set(conf_mod.APPLICATION_FRAMEWORK, "standalone")
        cfg.set(conf_mod.instances_key("serve"), str(args.replicas))
        cfg.set(conf_mod.SERVE_MODEL, args.model)
        assert cfg.job_types() == ["serve"]
        assert cfg.instances("serve") == 2


# ---------------------------------------------------------------------------
# End to end: train on fsdp=4 → elastic bf16 restore onto a smaller
# serve mesh → overlapping requests → bitwise pin → RPC through the proxy
# ---------------------------------------------------------------------------

class TestEndToEnd:
    @pytest.mark.slow
    def test_train_ckpt_replica_serve_pin(self, tmp_path):
        import optax

        from tony_tpu import ckpt, parallel as par, train
        from tony_tpu.models import get_model
        from tony_tpu.proxy import ProxyServer
        from tony_tpu.rpc import RpcClient
        from tony_tpu.serve import Request
        from tony_tpu.serve.replica import Replica

        # -- train a couple of real steps on a dp2 x fsdp4 mesh ----------
        model = get_model("llama-tiny", n_layers=2)
        mesh = par.make_mesh(fsdp=4)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 256, (8, 16)), jnp.int32)
        state = train.create_train_state(
            model, optax.adamw(1e-3), tokens, jax.random.PRNGKey(0),
            mesh=mesh)
        step = train.make_train_step(
            loss_of=lambda logits, b: train.next_token_loss(
                logits, b["x"]),
            mesh=mesh, donate=False)
        for _ in range(2):
            state, metrics = step(state, {"x": tokens})
        assert np.isfinite(float(metrics["loss"]))
        mgr = ckpt.AsyncCheckpointer(tmp_path / "ckpt")
        mgr.save(state, step=2)
        mgr.wait()
        mgr.close()

        # -- replica: fsdp=4 ckpt onto a SMALLER serve mesh, bf16 -------
        serve_mesh = par.make_mesh(n_devices=2, fsdp=2)
        replica = Replica(
            model_name="llama-tiny", model_kwargs={"n_layers": 2},
            ckpt_dir=str(tmp_path / "ckpt"), dtype_policy="bf16",
            mesh=serve_mesh, ctx_max=64, block_size=8, q_block=16,
            max_running=4, keep_logits=True)
        assert replica.restored_step == 2
        for leaf in jax.tree.leaves(replica.engine.params):
            assert leaf.dtype == jnp.bfloat16
        # The restore really carries the TRAINED values: serve params ==
        # bf16-cast of the training state's master params.
        trained = jax.tree.leaves(
            jax.tree.map(lambda a: np.asarray(a.astype(jnp.bfloat16)),
                         state.params))
        served = jax.tree.leaves(
            jax.tree.map(np.asarray, replica.engine.params))
        for a, b in zip(trained, served):
            assert np.array_equal(a, b)

        # -- overlapping requests through the engine; the bitwise pin ---
        eng = replica.engine
        # Plain ints: these also travel the JSON RPC wire below.
        prompts = [[int(x) for x in rng.randint(0, 256, n)]
                   for n in (6, 9, 14)]
        eng.submit(Request(rid="a", tokens=prompts[0], max_new_tokens=5))
        done = eng.step()
        eng.submit(Request(rid="b", tokens=prompts[1], max_new_tokens=4))
        eng.submit(Request(rid="c", tokens=prompts[2], max_new_tokens=3))
        done += eng.run()
        assert sorted(c.rid for c in done) == ["a", "b", "c"]
        pin_vs_full_prefill(eng, done)

        # -- and the front door: RPC through the existing TCP proxy -----
        from tony_tpu.rpc import RpcServer

        server = RpcServer(replica.rpc_handler(), host="127.0.0.1")
        server.start()
        try:
            with ProxyServer("127.0.0.1", server.port) as proxy:
                with RpcClient(f"{proxy.local_host}:{proxy.local_port}",
                               timeout=60.0) as client:
                    out = client.call("generate", tokens=prompts[0],
                                      max_new_tokens=5)
                    stats = client.call("serve_stats")
            # Greedy decode of the same prompt through the RPC front
            # reproduces the engine run's tokens exactly.
            ref = next(c for c in done if c.rid == "a")
            assert out["tokens"] == ref.tokens
            assert stats["completed"] >= 4.0
        finally:
            server.stop()

    def test_analyze_serve_config_clean_with_pin(self):
        """The acceptance gate: `tony analyze --config serve` is clean
        with zero waivers against the committed pin (also covered by the
        test_analysis parametrization — this is the serve lane's named
        copy)."""
        from tony_tpu.analysis import cli as acli

        report = acli.run_config(
            "serve", signature_path=str(
                Path(__file__).parent / "signatures" / "serve.json"))
        assert report.ok, report.summary()
        assert not report.waived
        assert report.signature["collectives"] == {}


# ---------------------------------------------------------------------------
# Quant lanes at serve time
# ---------------------------------------------------------------------------

class TestQuantServe:
    @pytest.mark.slow
    def test_quant_lane_engine_is_deterministic(self):
        """The quant= transformer lanes serve through the same engine.
        Per-tensor activation scales are batch-dependent, so the cross-
        batching bit pin doesn't apply — the contract here is that the
        lane runs end to end and a repeated identical submission stream
        reproduces identical tokens."""
        import flax.linen as nn

        from tony_tpu.models import get_model
        from tony_tpu.serve import Request, ServeEngine

        model = get_model("llama-tiny", n_layers=2, quant=True)
        sample = jnp.zeros((1, 16), jnp.int32)
        params = nn.unbox(model.init(jax.random.PRNGKey(0),
                                     sample))["params"]
        params = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, 256, n)) for n in (6, 10)]

        def run_once():
            eng = ServeEngine(model, params, ctx_max=64, block_size=8,
                              q_block=16, decode_buckets=(2,),
                              max_running=2)
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=i, tokens=p, max_new_tokens=3))
            return {c.rid: c.tokens for c in eng.run()}

        first = run_once()
        assert sorted(first) == [0, 1]
        assert all(len(t) == 3 for t in first.values())
        assert run_once() == first
