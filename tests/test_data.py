"""Input-data-plane tier (tony_tpu.data): deterministic sharding across
host counts, counter-based shuffle RNG, device prefetch, and checkpointable
iterator state through the PR 3 ckpt manifest — on the virtual 8-device CPU
mesh. The deterministic-resume acceptance pin lives here."""

import json
import time
from pathlib import Path

import jax
import numpy as np
import optax
import pytest

from tony_tpu import constants, data, parallel as par, profiler, train
from tony_tpu.ckpt import format as fmt
from tony_tpu.models import get_model

pytestmark = pytest.mark.data

N = 48
GB = 8   # global batch


def _arrays(n=N):
    # x encodes the example id so batches are self-identifying even
    # without with_ids().
    return {"x": np.arange(n, dtype=np.float32)[:, None]
            * np.ones((1, 4), np.float32),
            "y": (np.arange(n) % 10).astype(np.int64)}


def _ds(n=N, seed=7, buffer_size=None, epochs=2, gb=GB):
    ds = data.Dataset.from_arrays(_arrays(n), seed=seed)
    ds = ds.shuffle(buffer_size) if buffer_size else ds.shuffle()
    return ds.repeat(epochs).batch(gb).with_ids()


def _ids(it, k=None):
    """Per-batch id lists from an iterator ([k] batches, or all)."""
    out = []
    for batch in it:
        out.append(batch["id"].tolist())
        if k is not None and len(out) >= k:
            break
    return out


class TestShardSpec:
    def test_standalone_default(self, monkeypatch):
        for k in (constants.ENV_PROCESS_ID, constants.ENV_NUM_PROCESSES,
                  constants.ENV_TASK_INDEX, constants.ENV_TASK_NUM):
            monkeypatch.delenv(k, raising=False)
        assert data.ShardSpec.from_env() == data.ShardSpec(0, 1)

    def test_rendezvous_pair_wins_over_task_pair(self, monkeypatch):
        """TONY_PROCESS_ID is the GLOBAL rank; the per-jobtype task index
        only coincides with it in single-jobtype gangs."""
        monkeypatch.setenv(constants.ENV_TASK_INDEX, "0")
        monkeypatch.setenv(constants.ENV_TASK_NUM, "2")
        monkeypatch.setenv(constants.ENV_PROCESS_ID, "3")
        monkeypatch.setenv(constants.ENV_NUM_PROCESSES, "4")
        assert data.ShardSpec.from_env() == data.ShardSpec(3, 4)

    def test_executor_pair_fallback(self, monkeypatch):
        for k in (constants.ENV_PROCESS_ID, constants.ENV_NUM_PROCESSES):
            monkeypatch.delenv(k, raising=False)
        monkeypatch.setenv(constants.ENV_TASK_INDEX, "1")
        monkeypatch.setenv(constants.ENV_TASK_NUM, "2")
        assert data.ShardSpec.from_env() == data.ShardSpec(1, 2)

    def test_validation(self):
        with pytest.raises(ValueError, match="out of range"):
            data.ShardSpec(2, 2)
        with pytest.raises(ValueError, match="world_size"):
            data.ShardSpec(0, 0)
        with pytest.raises(ValueError, match="not divisible"):
            data.ShardSpec(0, 3).local_slice(8)

    def test_local_slices_partition_the_batch(self):
        slices = [data.ShardSpec(i, 4).local_slice(8) for i in range(4)]
        ids = np.arange(8)
        np.testing.assert_array_equal(
            np.concatenate([ids[s] for s in slices]), ids)

    def test_shard_files_round_robin(self):
        files = [f"f{i}" for i in range(6)]
        a = data.ShardSpec(0, 2).shard_files(files)
        b = data.ShardSpec(1, 2).shard_files(files)
        assert a == ["f0", "f2", "f4"] and b == ["f1", "f3", "f5"]
        assert sorted(a + b) == files

    def test_shard_files_uneven_rejected_unless_padded(self):
        """An uneven file split gives hosts different source lengths —
        gang desync at epoch end and a cursor no other host can restore —
        so it must fail loudly at assignment time, with wrap-padding as
        the explicit opt-in."""
        files = [f"f{i}" for i in range(5)]
        with pytest.raises(ValueError, match="not divisible by world_size"):
            data.ShardSpec(0, 2).shard_files(files)
        a = data.ShardSpec(0, 2).shard_files(files, pad=True)
        b = data.ShardSpec(1, 2).shard_files(files, pad=True)
        assert len(a) == len(b) == 3          # equal per-host counts
        assert a == ["f0", "f2", "f4"] and b == ["f1", "f3", "f0"]


class TestDeterminism:
    """The tentpole invariant: the GLOBAL example order is a pure function
    of (seed, state) — independent of host count and shard."""

    @pytest.mark.parametrize("buffer_size", [None, 16])
    def test_global_stream_invariant_across_host_counts(self, buffer_size):
        one = _ids(_ds(buffer_size=buffer_size).iterator(
            data.ShardSpec(0, 1)))
        its = [_ds(buffer_size=buffer_size).iterator(data.ShardSpec(i, 2))
               for i in range(2)]
        two = [sum((next(it)["id"].tolist() for it in its), [])
               for _ in range(len(one))]
        assert one == two
        its4 = [_ds(buffer_size=buffer_size).iterator(
            data.ShardSpec(i, 4)) for i in range(4)]
        four = [sum((next(it)["id"].tolist() for it in its4), [])
                for _ in range(len(one))]
        assert one == four

    def test_epoch_orders_are_distinct_permutations(self):
        ids = _ids(_ds(epochs=2).iterator(data.ShardSpec(0, 1)))
        flat = sum(ids, [])
        e0, e1 = flat[:N], flat[N:2 * N]
        assert sorted(e0) == sorted(e1) == list(range(N))
        assert e0 != e1                       # per-epoch Philox key
        assert e0 != list(range(N))           # actually shuffled

    def test_same_seed_same_stream_different_seed_differs(self):
        a = _ids(_ds(seed=7).iterator(data.ShardSpec(0, 1)))
        b = _ids(_ds(seed=7).iterator(data.ShardSpec(0, 1)))
        c = _ids(_ds(seed=8).iterator(data.ShardSpec(0, 1)))
        assert a == b
        assert a != c

    def test_seed_env_default(self, monkeypatch):
        monkeypatch.setenv(constants.ENV_DATA_SEED, "11")
        assert data.Dataset.from_arrays(_arrays()).seed == 11
        monkeypatch.delenv(constants.ENV_DATA_SEED)
        assert data.Dataset.from_arrays(_arrays()).seed == 0

    def test_unshuffled_is_sequential(self):
        ds = (data.Dataset.from_arrays(_arrays(16), seed=0)
              .batch(8).with_ids())
        assert _ids(ds.iterator(data.ShardSpec(0, 1))) == \
            [list(range(8)), list(range(8, 16))]

    def test_partial_final_batch_dropped(self):
        ds = (data.Dataset.from_arrays(_arrays(20), seed=0)
              .batch(8).with_ids())
        assert len(_ids(ds.iterator(data.ShardSpec(0, 1)))) == 2

    def test_shuffle_buffer_emits_each_id_once_per_epoch(self):
        ids = sum(_ids(_ds(buffer_size=12, epochs=2).iterator(
            data.ShardSpec(0, 1))), [])
        assert sorted(ids) == sorted(list(range(N)) * 2)


class TestSources:
    def test_array_source_leaf_length_mismatch(self):
        with pytest.raises(ValueError, match="leading example dim"):
            data.ArraySource({"x": np.zeros((4, 2)), "y": np.zeros((5,))})

    def test_memmap_source_streams_npy(self, tmp_path):
        arrays = _arrays(16)
        paths = {}
        for k, v in arrays.items():
            paths[k] = tmp_path / f"{k}.npy"
            np.save(paths[k], v)
        src = data.MemmapSource(paths)
        assert len(src) == 16
        got = src.fetch(np.array([3, 1, 9]))
        np.testing.assert_array_equal(got["x"], arrays["x"][[3, 1, 9]])
        # The fetched batch must not alias the mapped file.
        assert isinstance(got["x"], np.ndarray)
        assert not isinstance(got["x"], np.memmap)

    def test_file_list_source_one_example_per_file(self, tmp_path):
        files = []
        for i in range(6):
            p = tmp_path / f"ex{i}.npy"
            np.save(p, np.full((3,), i, np.float32))
            files.append(p)

        def loader(p):
            return {"x": np.load(p)}

        ds = (data.Dataset.from_files(files, loader, seed=0)
              .batch(2).with_ids())
        batches = list(ds.iterator(data.ShardSpec(0, 1)))
        assert [b["id"].tolist() for b in batches] == \
            [[0, 1], [2, 3], [4, 5]]
        np.testing.assert_array_equal(
            batches[1]["x"], [[2, 2, 2], [3, 3, 3]])


class TestIteratorState:
    @pytest.mark.parametrize("buffer_size", [None, 16])
    def test_resume_mid_stream_is_element_identical(self, buffer_size):
        full = _ids(_ds(buffer_size=buffer_size).iterator(
            data.ShardSpec(0, 1)))
        it = _ds(buffer_size=buffer_size).iterator(data.ShardSpec(0, 1))
        _ids(it, k=3)
        # JSON round-trip: the state must survive the manifest encoding.
        state = json.loads(json.dumps(it.state()))
        it2 = _ds(buffer_size=buffer_size).iterator(data.ShardSpec(0, 1))
        it2.restore(state)
        assert _ids(it2) == full[3:]

    def test_restore_across_host_count_change(self):
        """2-host stream, checkpoint mid-epoch, resume on 1 host: the
        global stream continues element-identically (the acceptance pin's
        data-plane half)."""
        full = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        its = [_ds().iterator(data.ShardSpec(i, 2)) for i in range(2)]
        for _ in range(3):
            for it in its:
                next(it)
        states = [it.state() for it in its]
        assert states[0] == states[1]         # cursor is global
        it1 = _ds().iterator(data.ShardSpec(0, 1))
        it1.restore(states[0])
        assert _ids(it1) == full[3:]

    def test_restore_rejects_forked_spec(self):
        it = _ds(seed=7).iterator(data.ShardSpec(0, 1))
        state = it.state()
        other_seed = _ds(seed=8).iterator(data.ShardSpec(0, 1))
        with pytest.raises(ValueError, match="seed"):
            other_seed.restore(state)
        other_batch = _ds(seed=7, gb=4).iterator(data.ShardSpec(0, 1))
        with pytest.raises(ValueError, match="global_batch"):
            other_batch.restore(state)
        with pytest.raises(ValueError, match="version"):
            it.restore(dict(state, version=99))

    def test_transient_fetch_error_rolls_cursor_back(self):
        """A failed fetch/map must not advance the cursor: a retry reads
        the SAME global batch, and a state() taken after the failure
        resumes at it — no silent skip."""
        full = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        calls = {"n": 0}

        def flaky(batch):
            calls["n"] += 1
            if calls["n"] == 3:
                raise OSError("transient read error")
            return batch

        ds = (data.Dataset.from_arrays(_arrays(), seed=7).shuffle()
              .repeat(2).batch(GB).map(flaky).with_ids())
        it = ds.iterator(data.ShardSpec(0, 1))
        out, mid_state = [], None
        while True:
            try:
                out.append(next(it)["id"].tolist())
            except OSError:
                mid_state = it.state()       # taken right after the failure
            except StopIteration:
                break
        assert out == full                   # retry re-read, nothing skipped
        it2 = ds.iterator(data.ShardSpec(0, 1))
        it2.restore(mid_state)
        assert next(it2)["id"].tolist() == full[2]

    def test_map_fn_stopiteration_surfaces_as_error(self):
        """PEP-479 hazard: a StopIteration leaking out of a user map_fn
        must surface as a RuntimeError, not read as clean end-of-stream
        and silently truncate the run — and the cursor must roll back so
        a retry re-reads the same batch."""
        full = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        side = iter(range(2))                # exhausts before the stream

        def leaky(batch):
            next(side)
            return batch

        ds = (data.Dataset.from_arrays(_arrays(), seed=7).shuffle()
              .repeat(2).batch(GB).map(leaky).with_ids())
        it = ds.iterator(data.ShardSpec(0, 1))
        out = [next(it)["id"].tolist() for _ in range(2)]
        with pytest.raises(RuntimeError, match="StopIteration"):
            next(it)
        # Rolled back: a state() taken after the error resumes at the
        # batch the leaky map_fn failed on.
        it2 = _ds().iterator(data.ShardSpec(0, 1))
        it2.restore(it.state())
        assert out + _ids(it2) == full

    def test_with_ids_rejects_existing_leaf(self):
        ds = (data.Dataset.from_arrays({"id": np.arange(N, dtype=np.int64),
                                        "x": _arrays()["x"]}, seed=7)
              .batch(GB).with_ids())
        it = ds.iterator(data.ShardSpec(0, 1))
        with pytest.raises(ValueError, match="already exists"):
            next(it)
        renamed = (data.Dataset.from_arrays(
            {"id": np.arange(N, dtype=np.int64), "x": _arrays()["x"]},
            seed=7).batch(GB).with_ids("stream_id"))
        batch = next(renamed.iterator(data.ShardSpec(0, 1)))
        assert batch["stream_id"].tolist() == batch["id"].tolist()

    def test_exhaustion_rolls_back_dropped_partial_batch(self):
        """StopIteration consumes (and drops) the final partial batch's
        ids internally; the cursor must roll back past them, so a state()
        taken after exhaustion — restored into a pipeline with MORE
        epochs — replays the boundary-spanning batch instead of silently
        skipping the dropped tail."""
        short = _ds(n=10, epochs=3, gb=4).iterator(data.ShardSpec(0, 1))
        emitted = _ids(short)            # 30 ids -> 7 full batches, 2 dropped
        assert len(emitted) == 7
        end_state = short.state()
        longer = _ds(n=10, epochs=5, gb=4)
        resumed = longer.iterator(data.ShardSpec(0, 1))
        resumed.restore(end_state)
        full = _ids(longer.iterator(data.ShardSpec(0, 1)))
        assert emitted + _ids(resumed) == full

    def test_empty_source_rejected_at_construction(self):
        """repeat() over a zero-length source would spin the index stream
        forever — it must fail at iterator construction instead."""
        ds = (data.Dataset.from_arrays({"x": np.empty((0, 4))})
              .shuffle().repeat().batch(1))
        with pytest.raises(ValueError, match="empty"):
            ds.iterator(data.ShardSpec(0, 1))

    def test_restore_rejects_resized_source(self):
        """A source that grew (or shrank) since the save invalidates the
        saved epoch permutation — restore must fail loudly, not silently
        fork the stream."""
        state = _ds().iterator(data.ShardSpec(0, 1)).state()
        grown = _ds(n=N + 8).iterator(data.ShardSpec(0, 1))
        with pytest.raises(ValueError, match="source_len"):
            grown.restore(state)

    def test_restore_rejects_changed_shuffle_config(self):
        state = _ds(buffer_size=16).iterator(data.ShardSpec(0, 1)).state()
        other_buf = _ds(buffer_size=8).iterator(data.ShardSpec(0, 1))
        with pytest.raises(ValueError, match="buffer_size"):
            other_buf.restore(state)
        permuted = _ds().iterator(data.ShardSpec(0, 1))
        with pytest.raises(ValueError, match="shuffle"):
            permuted.restore(state)


class TestPrefetch:
    def test_prefetched_stream_equals_sync(self):
        sync = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        with data.DeviceIterator(_ds().iterator(data.ShardSpec(0, 1)),
                                 None, depth=2) as dit:
            assert [b["id"].tolist() for b in dit] == sync

    def test_depth0_is_synchronous(self):
        sync = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        with data.DeviceIterator(_ds().iterator(data.ShardSpec(0, 1)),
                                 None, depth=0) as dit:
            assert [b["id"].tolist() for b in dit] == sync

    def test_state_tracks_delivered_not_prefetched(self):
        """With depth=2 the producer runs ahead; a checkpoint between
        steps must resume at the next UNDELIVERED batch."""
        full = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        dit = data.DeviceIterator(_ds().iterator(data.ShardSpec(0, 1)),
                                  None, depth=2)
        for _ in range(3):
            next(dit)
        time.sleep(0.05)            # let the producer run ahead
        state = dit.state()
        dit.close()
        dit2 = data.DeviceIterator(_ds().iterator(data.ShardSpec(0, 1)),
                                   None, depth=2)
        dit2.restore(state)
        assert [b["id"].tolist() for b in dit2] == full[3:]
        dit2.close()

    def test_restore_after_start_raises(self):
        dit = data.DeviceIterator(_ds().iterator(data.ShardSpec(0, 1)),
                                  None, depth=1)
        state = dit.state()
        next(dit)
        with pytest.raises(RuntimeError, match="after iteration"):
            dit.restore(state)
        dit.close()

    def test_device_placement_on_mesh(self):
        mesh = par.make_mesh()
        ds = _ds(n=64, gb=8)
        with data.DeviceIterator(ds.iterator(data.ShardSpec(0, 1)),
                                 mesh, depth=1) as dit:
            batch = next(dit)
        assert batch["x"].shape == (8, 4)
        assert batch["x"].sharding.is_equivalent_to(
            par.batch_sharding(mesh), 2)

    def test_map_error_propagates(self):
        def boom(batch):
            raise RuntimeError("decode failed")

        ds = (data.Dataset.from_arrays(_arrays(16), seed=0)
              .batch(8).map(boom))
        with data.DeviceIterator(ds.iterator(data.ShardSpec(0, 1)),
                                 None, depth=1) as dit:
            with pytest.raises(RuntimeError, match="prefetch thread"):
                next(dit)
            # The error stays latched: a caller that caught it and reads
            # again must NOT see a clean StopIteration (that would make a
            # failed feed look like a completed epoch).
            with pytest.raises(RuntimeError, match="prefetch thread"):
                next(dit)

    def test_depth0_place_failure_does_not_skip(self, monkeypatch):
        """Transient device-transfer failure at depth 0: a retried next()
        must re-place the SAME batch — the synchronous twin of the
        pipeline's cursor rollback."""
        sync = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        orig = data.DeviceIterator._place
        calls = {"n": 0}

        def flaky(self, batch):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("transient transfer error")
            return orig(self, batch)

        monkeypatch.setattr(data.DeviceIterator, "_place", flaky)
        dit = data.DeviceIterator(
            _ds().iterator(data.ShardSpec(0, 1)), None, depth=0)
        out = []
        while True:
            try:
                out.append(next(dit)["id"].tolist())
            except RuntimeError:
                continue
            except StopIteration:
                break
        assert out == sync

    def test_depth0_state_in_pending_retry_window(self, monkeypatch):
        """state() taken between a depth-0 place failure and its retry
        must return the cursor of the last DELIVERED batch: the pending
        batch was never delivered, so a resume from that state replays
        it (depth 0 reads the pipeline lazily — this is the one window
        where the raw cursor is a batch ahead)."""
        sync = _ids(_ds().iterator(data.ShardSpec(0, 1)))
        orig = data.DeviceIterator._place
        calls = {"n": 0}

        def flaky(self, batch):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("transient transfer error")
            return orig(self, batch)

        monkeypatch.setattr(data.DeviceIterator, "_place", flaky)
        dit = data.DeviceIterator(
            _ds().iterator(data.ShardSpec(0, 1)), None, depth=0)
        first = next(dit)["id"].tolist()
        with pytest.raises(RuntimeError, match="transient"):
            next(dit)                    # batch 1 pulled, left pending
        mid = dit.state()                # cursor must say "after batch 0"
        it2 = _ds().iterator(data.ShardSpec(0, 1))
        it2.restore(mid)
        assert [first] + _ids(it2) == sync

    def test_depth0_restore_discards_pending_batch(self, monkeypatch):
        """A depth-0 place failure on the FIRST next() leaves its batch
        pending for retry; restore() must discard it — the pending batch
        predates the restored cursor and delivering it would pair a stale
        example with the new stream position."""
        ref = _ds().iterator(data.ShardSpec(0, 1))
        next(ref)
        mid_state = ref.state()          # cursor after batch 1
        expect = next(ref)["id"].tolist()

        orig = data.DeviceIterator._place

        def failing(self, batch):
            raise RuntimeError("transient transfer error")

        monkeypatch.setattr(data.DeviceIterator, "_place", failing)
        dit = data.DeviceIterator(
            _ds().iterator(data.ShardSpec(0, 1)), None, depth=0)
        with pytest.raises(RuntimeError):
            next(dit)                    # batch 0 pulled, left pending
        monkeypatch.setattr(data.DeviceIterator, "_place", orig)
        dit.restore(mid_state)
        assert next(dit)["id"].tolist() == expect

    def test_dropped_iterator_producer_thread_exits(self):
        """A DeviceIterator dropped WITHOUT close() must not leak its
        producer: the thread holds the iterator only weakly, observes the
        drop, and exits."""
        import gc

        dit = data.DeviceIterator(
            _ds().iterator(data.ShardSpec(0, 1)), None, depth=1)
        next(dit)                      # start the producer; queue fills
        thread = dit._thread
        del dit
        gc.collect()
        thread.join(timeout=10.0)
        assert not thread.is_alive()

    def test_input_stall_recorded_in_profiler(self):
        profiler.reset_input_records()
        with data.DeviceIterator(_ds().iterator(data.ShardSpec(0, 1)),
                                 None, depth=1, tag="t_input") as dit:
            next(dit)
            next(dit)
        report = profiler.input_report()
        assert "t_input" in report
        rec = report["t_input"]
        assert rec["depth"] == 1 and rec["steps"] == 2
        assert rec["wait_s_last"] >= 0.0
        assert rec["wait_s_total"] >= rec["wait_s_last"]
        # Deep-copied snapshot: mutating it must not alias the registry.
        rec["steps"] = -1
        assert profiler.input_report()["t_input"]["steps"] == 2


def _mlp_state(key=2, hidden=32):
    model = get_model("mnist-mlp", hidden=hidden)
    x = np.zeros((GB, 784), np.float32)
    return train.create_train_state(
        model, optax.sgd(0.1, momentum=0.9), x, jax.random.PRNGKey(key))


def _train_ds(n=64, seed=5, epochs=1):
    xs = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, 784)) / n
    ys = (np.arange(n) % 10).astype(np.int64)
    return (data.Dataset.from_arrays({"x": xs, "y": ys}, seed=seed)
            .shuffle().repeat(epochs).batch(GB).with_ids())


class TestCkptIntegration:
    """The acceptance pin: a checkpoint-interrupted run's example stream —
    and the model trajectory it drives — is identical to an uninterrupted
    run's, via the real PR 3 ckpt plane (manifest + atomic commit)."""

    def _run(self, step_fn_ids, ckpt_dir=None, save_every=0, bomb_at=None):
        base = train.make_train_step(donate=False)

        def step_fn(state, batch):
            step_fn_ids.append(batch["id"].tolist())
            return base(state, {"x": batch["x"], "y": batch["y"]})

        def on_step(done, _metrics):
            if bomb_at is not None and done == bomb_at:
                raise KeyboardInterrupt   # the "kill"

        dit = data.DeviceIterator(
            _train_ds().iterator(data.ShardSpec(0, 1)), None, depth=2)
        try:
            return train.train_loop(
                _mlp_state(), step_fn, data=dit,
                ckpt_dir=str(ckpt_dir) if ckpt_dir else None,
                save_every=save_every, on_step=on_step)
        finally:
            dit.close()

    def test_interrupted_resume_is_element_identical(self, tmp_path):
        full_ids = []
        s_full, _ = self._run(full_ids)
        assert len(full_ids) == 8

        part_ids = []
        with pytest.raises(KeyboardInterrupt):
            self._run(part_ids, ckpt_dir=tmp_path, save_every=2, bomb_at=5)
        assert fmt.committed_steps(tmp_path) == [2, 4]

        resumed_ids = []
        s_res, _ = self._run(resumed_ids, ckpt_dir=tmp_path, save_every=2)
        # Stream: replay starts exactly after the last committed step.
        assert resumed_ids == full_ids[4:]
        # Trajectory: final params bit-exact vs the uninterrupted run.
        for a, b in zip(jax.tree.leaves(s_full.params),
                        jax.tree.leaves(s_res.params)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))

    def test_two_host_to_one_host_resume_via_manifest(self, tmp_path):
        """Elastic half of the pin: the cursor saved by a 2-host gang
        restores onto a 1-host gang and the GLOBAL stream continues
        element-identically — through the real manifest encode/decode."""
        from tony_tpu import ckpt as ckpt_mod

        full = _ids(_train_ds().iterator(data.ShardSpec(0, 1)))
        its = [_train_ds().iterator(data.ShardSpec(i, 2)) for i in range(2)]
        two_host = [sum((next(it)["id"].tolist() for it in its), [])
                    for _ in range(3)]
        assert two_host == full[:3]
        c = ckpt_mod.AsyncCheckpointer(tmp_path, keep=2)
        c.save(data.wrap_for_save({"w": np.ones((2,), np.float32)},
                                  its[0].state()), step=3, block=True)
        c.close()
        assert data.has_iter_state(tmp_path, 3)
        restored = data.load_iter_state(tmp_path)
        one = _train_ds().iterator(data.ShardSpec(0, 1))
        one.restore(restored)
        assert _ids(one) == full[3:]

    def test_train_loop_closes_data_iterator_on_step_failure(self):
        """A step_fn exception must not leak the prefetch thread and its
        staged device batches — train_loop owns the iteration."""
        dit = data.DeviceIterator(
            _train_ds().iterator(data.ShardSpec(0, 1)), None, depth=2)

        def boom(_s, _b):
            raise RuntimeError("nan guard")

        with pytest.raises(RuntimeError, match="nan guard"):
            train.train_loop(_mlp_state(), boom, data=dit)
        assert dit._closed
        if dit._started:
            dit._thread.join(timeout=5.0)
            assert not dit._thread.is_alive()

    def test_wrapped_checkpoint_restores_into_batches_run(self, tmp_path,
                                                          caplog):
        """The reverse of the bare-ckpt case: a data= run's wrapped
        {model, data_iter} save restored by a batches= caller (e.g. an
        eval script) must unwrap the model — keyed on what the manifest
        contains, not on what this caller passed — and warn that the
        stream is not resumed."""
        from tony_tpu import ckpt as ckpt_mod

        saved = _mlp_state(key=4)
        it = _train_ds().iterator(data.ShardSpec(0, 1))
        next(it)
        c = ckpt_mod.AsyncCheckpointer(tmp_path, keep=2)
        c.save(data.wrap_for_save(saved, it.state()), step=1, block=True)
        c.close()
        assert data.has_iter_state(tmp_path, 1)
        with caplog.at_level("WARNING", logger="tony_tpu.train"):
            s_res, _ = train.train_loop(
                _mlp_state(), lambda s, b: (s, {}), batches=[],
                ckpt_dir=str(tmp_path), save_every=0, save_final=False)
        assert "data-iterator state" in caplog.text
        for a, b in zip(jax.tree.leaves(saved.params),
                        jax.tree.leaves(s_res.params)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))

    def test_bare_pre_data_checkpoint_still_restores_model(self, tmp_path):
        """A PR 3-era checkpoint (no data_iter leaf) must restore the
        model and leave the stream at the iterator's start."""
        from tony_tpu import ckpt as ckpt_mod

        state = _mlp_state(key=9)
        c = ckpt_mod.AsyncCheckpointer(tmp_path, keep=2)
        c.save(state, step=1, block=True)
        c.close()
        assert not data.has_iter_state(tmp_path, 1)
        with pytest.raises(KeyError, match="no.*data_iter"):
            data.load_iter_state(tmp_path)
        ids = []
        base = train.make_train_step(donate=False)

        def step_fn(s, b):
            ids.append(b["id"].tolist())
            return base(s, {"x": b["x"], "y": b["y"]})

        dit = data.DeviceIterator(
            _train_ds().iterator(data.ShardSpec(0, 1)), None, depth=1)
        s_res, _ = train.train_loop(_mlp_state(), step_fn, data=dit,
                                    ckpt_dir=str(tmp_path), save_every=0,
                                    save_final=False)
        dit.close()
        assert ids == _ids(_train_ds().iterator(data.ShardSpec(0, 1)))
        # s_res started from the restored (key=9) params, then trained —
        # its trajectory must equal training the SAVED state directly.
        expect = state
        base2 = train.make_train_step(donate=False)
        for id_list, b in zip(
                ids, _train_ds().iterator(data.ShardSpec(0, 1))):
            expect, _ = base2(expect, {"x": b["x"], "y": b["y"]})
        for a, b in zip(jax.tree.leaves(expect.params),
                        jax.tree.leaves(s_res.params)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)),
                np.asarray(jax.device_get(b)))

    def test_state_roundtrip_through_encode_decode(self):
        it = _train_ds().iterator(data.ShardSpec(0, 1))
        next(it)
        state = it.state()
        assert data.decode_state(data.encode_state(state)) == state

    def test_train_loop_rejects_both_batches_and_data(self):
        with pytest.raises(ValueError, match="exactly one"):
            train.train_loop(_mlp_state(), lambda s, b: (s, {}),
                             batches=[], data=iter([]))
        with pytest.raises(ValueError, match="exactly one"):
            train.train_loop(_mlp_state(), lambda s, b: (s, {}))


class TestGlobalBatchValidation:
    """Satellite: the opaque make_array_from_process_local_data failure is
    replaced by a ValueError naming the offending leaf."""

    def test_mismatched_leaf_batch_dim_names_leaf(self):
        mesh = par.make_mesh()
        with pytest.raises(ValueError) as e:
            train.global_batch(mesh, {"x": np.zeros((8, 4)),
                                      "y": np.zeros((6,))})
        assert "['y']" in str(e.value) and "['x']" in str(e.value)

    def test_indivisible_batch_dim_names_sharding(self):
        mesh = par.make_mesh()
        with pytest.raises(ValueError, match="not divisible by the 8-way"):
            train.global_batch(mesh, {"x": np.zeros((7, 4)),
                                      "y": np.zeros((7,))})

    def test_rank0_leaf_rejected(self):
        mesh = par.make_mesh()
        with pytest.raises(ValueError, match=r"\['n'\]"):
            train.global_batch(mesh, {"n": np.float32(3.0)})

    def test_seq_axis_divisibility_checked(self):
        mesh = par.make_mesh(sp=2, dp=4)
        with pytest.raises(ValueError, match="sequence dim 7"):
            train.global_batch(mesh, {"x": np.zeros((8, 7))},
                               seq_axis=True)

    def test_validation_memoized_per_contract(self, monkeypatch):
        """The shape contract is invariant per pipeline: per-step callers
        must pay the full pre-flight once per (mesh, shapes) signature,
        not every step — and a BAD contract must keep raising (failures
        are never cached)."""
        calls = {"n": 0}
        orig = train._validate_local_batch

        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        import weakref
        monkeypatch.setattr(train, "_validate_local_batch", counting)
        monkeypatch.setattr(train, "_VALIDATED_CONTRACTS",
                            weakref.WeakKeyDictionary())
        mesh = par.make_mesh()
        good = {"x": np.zeros((8, 4)), "y": np.zeros((8,))}
        for _ in range(3):
            train.global_batch(mesh, good)
        assert calls["n"] == 1
        for _ in range(2):
            with pytest.raises(ValueError):
                train.global_batch(mesh, {"x": np.zeros((8, 4)),
                                          "y": np.zeros((6,))})
        assert calls["n"] == 3

    def test_valid_batch_passes_and_check_can_be_skipped(self):
        mesh = par.make_mesh()
        out = train.global_batch(mesh, {"x": np.zeros((8, 4)),
                                        "y": np.zeros((8,))})
        assert out["x"].shape == (8, 4)
        # check=False falls through to jax's own (opaque) error.
        with pytest.raises(Exception):
            train.global_batch(mesh, {"x": np.zeros((7, 4))}, check=False)


class TestInputBench:
    @pytest.mark.slow
    def test_run_input_bench_smoke(self):
        from tony_tpu.benchmark import run_input_bench

        r = run_input_bench(steps=6, depths=(0, 1), feed_latency_ms=2.0)
        assert set(r["per_depth"]) == {"0", "1"}
        assert r["input_stall_ms_depth0"] > 0
        assert "input_d1" in r["input_records"]
