"""Session state-machine tests (reference tier: TestTonySession).

The success-policy matrix (SURVEY.md §7 hard part #2) is the point of these.
"""

import pytest

from tony_tpu.conf import TonyConfig
from tony_tpu.session import JobStatus, TaskStatus, TonySession


def make_session(**props):
    base = {"tony.worker.instances": "2"}
    base.update({k: str(v) for k, v in props.items()})
    return TonySession(TonyConfig(base), app_id="app_1_0001")


def register_all(s: TonySession, port_base=4000):
    i = 0
    for t in s.tasks():
        s.on_registered(t.job_type, t.index, "127.0.0.1", port_base + i)
        i += 1
    s.on_running()


def test_gang_barrier_and_cluster_spec():
    s = make_session(**{"tony.ps.instances": "1"})
    assert not s.all_registered()
    s.on_registered("worker", 0, "hostA", 4000)
    s.on_registered("worker", 1, "hostB", 4001)
    assert not s.all_registered()
    s.on_registered("ps", 0, "hostC", 4002)
    assert s.all_registered()
    spec = s.cluster_spec()
    assert spec == {"ps": ["hostC:4002"], "worker": ["hostA:4000", "hostB:4001"]}


def test_all_workers_succeed():
    s = make_session()
    register_all(s)
    s.on_task_result("worker", 0, 0)
    assert s.job_status == JobStatus.RUNNING
    s.on_task_result("worker", 1, 0)
    assert s.job_status == JobStatus.SUCCEEDED


def test_fail_fast_on_first_tracked_failure():
    s = make_session()
    register_all(s)
    s.on_task_result("worker", 1, 42, "boom")
    assert s.job_status == JobStatus.FAILED
    assert "worker:1" in s.final_message


def test_no_fail_fast_waits_for_all():
    s = make_session(**{"tony.application.fail-fast": "false"})
    register_all(s)
    s.on_task_result("worker", 0, 1)
    assert s.job_status == JobStatus.RUNNING     # still waiting for worker:1
    s.on_task_result("worker", 1, 0)
    assert s.job_status == JobStatus.FAILED      # but one failure fails the job


def test_untracked_failure_ignored():
    s = make_session(**{"tony.ps.instances": "1"})   # ps untracked by default
    register_all(s)
    s.on_task_result("ps", 0, 137, "ps crash")
    assert s.job_status == JobStatus.RUNNING
    s.on_task_result("worker", 0, 0)
    s.on_task_result("worker", 1, 0)
    assert s.job_status == JobStatus.SUCCEEDED
    killed = s.kill_remaining("job done")          # untracked teardown
    assert killed == []                            # ps already terminal


def test_chief_done_policy():
    s = make_session(**{"tony.chief.instances": "1"})
    register_all(s)
    s.on_task_result("chief", 0, 0)
    # Chief success ends the job even with workers still running.
    assert s.job_status == JobStatus.SUCCEEDED
    assert s.kill_remaining("chief done")          # workers get torn down
    assert all(t.status == TaskStatus.KILLED
               for t in s.tasks() if t.job_type == "worker")


def test_chief_failure_fails_job():
    s = make_session(**{"tony.chief.instances": "1"})
    register_all(s)
    s.on_task_result("chief", 0, 3, "chief oom")
    assert s.job_status == JobStatus.FAILED


def test_lost_task_fails_job():
    s = make_session()
    register_all(s)
    t = s.task("worker", 0)
    s.on_task_lost(t, "missed 25 heartbeats")
    assert t.status == TaskStatus.LOST
    assert s.job_status == JobStatus.FAILED
    assert "LOST" in s.final_message


def test_global_rank_dense_and_stable():
    s = make_session(**{"tony.chief.instances": "1", "tony.ps.instances": "2"})
    # Order: chief-like first, then alphabetical: chief, ps, worker
    assert s.global_rank("chief", 0) == 0
    assert s.global_rank("ps", 0) == 1
    assert s.global_rank("ps", 1) == 2
    assert s.global_rank("worker", 0) == 3
    assert s.global_rank("worker", 1) == 4
    with pytest.raises(KeyError):
        s.global_rank("worker", 5)


def test_terminal_result_is_idempotent():
    s = make_session()
    register_all(s)
    s.on_task_result("worker", 0, 1)
    s.on_task_result("worker", 0, 0)   # late duplicate must not flip status
    assert s.task("worker", 0).exit_code == 1
    assert s.job_status == JobStatus.FAILED


# --- round-2 policy fixes ---------------------------------------------------

def test_multi_chief_requires_all_chiefs():
    s = make_session(**{"tony.chief.instances": "2", "tony.worker.instances": "1"})
    s.on_task_result("chief", 0, 0)
    assert s.job_status is JobStatus.RUNNING      # one of two chiefs done
    s.on_task_result("chief", 1, 0)
    assert s.job_status is JobStatus.SUCCEEDED


def test_multi_chief_any_failure_fails():
    s = make_session(**{"tony.chief.instances": "2", "tony.worker.instances": "1"})
    s.on_task_result("chief", 1, 3)
    assert s.job_status is JobStatus.FAILED


def test_worker_failfast_applies_while_chief_runs():
    s = make_session(**{"tony.chief.instances": "1", "tony.worker.instances": "2"})
    s.on_task_result("worker", 0, 1)
    assert s.job_status is JobStatus.FAILED


def test_global_rank_skips_sidecars():
    s = make_session(**{"tony.chief.instances": "1", "tony.worker.instances": "2",
                        "tony.tensorboard.instances": "1"})
    assert s.global_rank("chief", 0) == 0
    assert s.global_rank("worker", 1) == 2
    with pytest.raises(KeyError):
        s.global_rank("tensorboard", 0)
