"""Model + train-harness tests on the 8-device CPU mesh: forward shapes,
sharded init, one GSPMD train step per parallelism layout."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu import parallel as par
from tony_tpu.compat import mesh_context
from tony_tpu import train
from tony_tpu.models import get_model
from tony_tpu.models.resnet import resnet50_flops


def test_mnist_models_forward():
    x = jnp.zeros((4, 28 * 28))
    for name in ("mnist-mlp", "mnist-cnn"):
        model = get_model(name)
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        assert out.shape == (4, 10)


def test_resnet_forward_and_bn_state():
    model = get_model("resnet18-thin")
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" in variables
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert resnet50_flops(32) > 1e11


def test_llama_tiny_forward_loss_decreases():
    model = get_model("llama-tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    tx = optax.adam(1e-2)
    state = train.create_train_state(
        model, tx, tokens, jax.random.PRNGKey(0))
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]))
    losses = []
    for _ in range(5):
        state, metrics = step(state, {"x": tokens})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("spec_kw", [
    dict(),                      # pure DP over 8 devices
    dict(fsdp=2, tp=2),          # DP×FSDP×TP
    dict(tp=4),                  # DP×TP
])
def test_llama_sharded_train_step(spec_kw):
    mesh = par.make_mesh(**spec_kw)
    model = get_model("llama-tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 256)
    tx = optax.adam(1e-3)
    state = train.create_train_state(
        model, tx, tokens, jax.random.PRNGKey(0), mesh=mesh)
    # Params actually sharded per the rules: an ffn kernel splits over model.
    if spec_kw.get("tp", 1) > 1:
        ffn = state.params["layers"]["block"]["mlp"]["w_gate"]["kernel"]
        assert "model" in jax.tree_util.tree_leaves(
            [ffn.sharding.spec])[0] or any(
            "model" == s or (isinstance(s, tuple) and "model" in s)
            for s in ffn.sharding.spec if s)
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
        mesh=mesh)
    state, metrics = step(state, {"x": tokens})
    assert np.isfinite(float(metrics["loss"]))
    state, metrics2 = step(state, {"x": tokens})
    assert float(metrics2["loss"]) < float(metrics["loss"]) + 1.0


def test_llama_ring_attention_end_to_end():
    mesh = par.make_mesh(sp=4)
    model = get_model("llama-tiny", attention="ring", mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    tx = optax.sgd(1e-3)
    state = train.create_train_state(
        model, tx, tokens, jax.random.PRNGKey(0), mesh=mesh)
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
        mesh=mesh)
    state, metrics = step(state, {"x": tokens})
    assert np.isfinite(float(metrics["loss"]))


def test_ring_equals_reference_attention_in_model():
    """Same weights, ring vs reference attention → same logits."""
    mesh = par.make_mesh(sp=4)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 256)
    ref_model = get_model("llama-tiny", attention="reference")
    ring_model = get_model("llama-tiny", attention="ring", mesh=mesh)
    import flax.linen as nn
    variables = nn.unbox(ref_model.init(jax.random.PRNGKey(0), tokens))
    with nn.logical_axis_rules(par.RULES):
        ref_out = ref_model.apply(variables, tokens)
        with mesh_context(mesh):
            ring_out = jax.jit(ring_model.apply)(variables, tokens)
    np.testing.assert_allclose(np.asarray(ref_out), np.asarray(ring_out),
                               atol=2e-4, rtol=2e-4)


def test_resnet_dp_train_step_on_mesh():
    mesh = par.make_mesh()   # 8-way DP
    model = get_model("resnet18-thin", dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 10)

    variables = model.init(jax.random.PRNGKey(2), x)
    import flax.linen as nn

    # BN models carry batch_stats: run a manual step with mutable state.
    def loss_fn(params, batch_stats):
        logits, updates = model.apply(
            {"params": nn.unbox(params), "batch_stats": batch_stats},
            x, train=True, mutable=["batch_stats"])
        return train.cross_entropy_loss(logits, y), updates["batch_stats"]

    with mesh_context(mesh):
        (loss, _), grads = jax.jit(
            jax.value_and_grad(loss_fn, has_aux=True))(
            variables["params"], variables["batch_stats"])
    assert np.isfinite(float(loss))


def test_llama_packed_attention_branch_matches_reference():
    """head_dim=128 + flash + no mesh takes the packed-layout attention
    branch (rope seq_axis=1, GQA repeat in packed form); its logits must
    match the classic reference-attention model on the same params."""
    kw = dict(dim=512, n_heads=4, n_kv_heads=2, ffn_hidden=256,
              vocab=128, n_layers=2, max_seq=32, scan_layers=True,
              remat=False)
    flash_model = get_model("llama-tiny", attention="flash", **kw)
    ref_model = get_model("llama-tiny", attention="reference", **kw)
    assert flash_model.cfg.head_dim == 128  # packed branch precondition
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    variables = flash_model.init(jax.random.PRNGKey(0), tokens)
    out_flash = flash_model.apply(variables, tokens)
    out_ref = ref_model.apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_ref),
                               atol=5e-2, rtol=5e-2)


def test_chunked_xent_matches_plain_head():
    """cfg.xent_chunk fuses head+loss without materializing logits; the
    loss AND all shared-param grads must match the plain head + 
    next_token_loss path (the lm_head kernel moves from lm_head/kernel to
    lm_head_kernel — remapped here)."""
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 256)
    plain = get_model("llama-tiny", dtype=jnp.float32)
    fused = get_model("llama-tiny", dtype=jnp.float32, xent_chunk=8)
    variables = plain.init(jax.random.PRNGKey(0), tokens)
    fparams = dict(variables["params"])
    fparams["lm_head_kernel"] = fparams.pop("lm_head")["kernel"]

    def loss_plain(p):
        logits = plain.apply({"params": p}, tokens)
        return train.next_token_loss(logits, tokens)

    def loss_fused(p):
        return fused.apply({"params": p}, tokens, targets=tokens)

    lp, gp = jax.value_and_grad(loss_plain)(variables["params"])
    lf, gf = jax.value_and_grad(loss_fused)(fparams)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
    gp = dict(gp)
    gp["lm_head_kernel"] = gp.pop("lm_head")["kernel"]
    for (kp, a), (kf, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(gp),
                   key=lambda t: str(t[0])),
            sorted(jax.tree_util.tree_leaves_with_path(gf),
                   key=lambda t: str(t[0]))):
        assert str(kp) == str(kf)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-5, rtol=1e-4, err_msg=str(kp))
    # 2·16 = 32 rows over chunk=8 → 4 whole chunks; also exercise padding.
    fused_pad = get_model("llama-tiny", dtype=jnp.float32, xent_chunk=7)
    lpad = fused_pad.apply({"params": fparams}, tokens, targets=tokens)
    np.testing.assert_allclose(float(lpad), float(lp), rtol=1e-5)


def test_chunked_xent_through_train_step():
    """The train harness drives the fused-loss model via apply_kwargs_of;
    loss decreases like the plain path."""
    model = get_model("llama-tiny", xent_chunk=8)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-2), tokens, jax.random.PRNGKey(0))
    step = train.make_train_step(
        loss_of=lambda out, batch: out,
        apply_kwargs_of=lambda batch: {"targets": batch["x"]})
    losses = []
    for _ in range(5):
        state, metrics = step(state, {"x": tokens})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_s2d_stem_equivalence():
    """The space-to-depth stem is EXACTLY the 7x7/s2 stem: transporting a
    7x7 kernel through s2d_stem_kernel and running the 4x4/s1 conv on the
    packed input reproduces the original conv's output."""
    from tony_tpu.models.resnet import s2d_stem_kernel

    k = jax.random.PRNGKey(3)
    x = jax.random.normal(k, (2, 32, 32, 3), jnp.float32)
    k7 = jax.random.normal(jax.random.PRNGKey(4), (7, 7, 3, 8), jnp.float32)
    ref = jax.lax.conv_general_dilated(
        x, k7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    n, h, w, c = x.shape
    xp = x.reshape(n, h // 2, 2, w // 2, 2, c)
    xp = xp.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
    out = jax.lax.conv_general_dilated(
        xp, s2d_stem_kernel(k7), window_strides=(1, 1),
        padding=[(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_s2d_resnet_trains():
    """The s2d_stem model variant runs a full train step (shapes line up
    through maxpool and the stages) and matches the baseline parameter
    structure apart from the stem kernel shape."""
    model = get_model("resnet18-thin", s2d_stem=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3), jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(1), x, train=False)
    assert variables["params"]["stem"]["kernel"].shape == (4, 4, 12, 8)
    out, updates = model.apply(variables, x, train=True,
                               mutable=["batch_stats"])
    assert out.shape == (2, 10)


def test_remat_policy_variants():
    """remat_policy selects a jax.checkpoint policy (dots = save matmul
    outputs); all variants train and an unknown name fails loudly."""
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 256)
    for policy in (None, "dots", "dots_no_batch"):
        model = get_model("llama-tiny", remat=True, remat_policy=policy,
                          scan_layers=False)
        state = train.create_train_state(
            model, optax.adam(1e-3), tokens, jax.random.PRNGKey(1))
        step = train.make_train_step(
            loss_of=lambda lg, b: train.next_token_loss(lg, b["x"]))
        _, m = step(state, {"x": tokens})
        assert jnp.isfinite(m["loss"]), policy
    import pytest as _pytest
    bad = get_model("llama-tiny", remat=True, remat_policy="nope")
    with _pytest.raises(ValueError, match="remat_policy"):
        bad.init(jax.random.PRNGKey(0), tokens)
