"""Static-analysis tier (tony_tpu.analysis): the jaxpr invariant analyzer
— shipped accum-step configs analyze CLEAN with their committed
step-signature pins, and every rule demonstrably FIRES on a seeded
violation (leaf-major gather outside the window, unplanned collective,
bf16 moment slot / bf16 reduction / f64, undonated state, signature
drift) with equation provenance. Plus the waiver mechanism, the profiler
report plumbing, and the pack-site source lint. `make tier1-analysis`
runs this file by marker."""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu import analysis, profiler, train
from tony_tpu import parallel as par
from tony_tpu.analysis import cli as acli
from tony_tpu.analysis import rules, srclint
from tony_tpu.analysis import signature as sigmod
from tony_tpu.compat import shard_map
from tony_tpu.parallel import FSDP, overlap
from tony_tpu.parallel.sched import GatherPlan

pytestmark = pytest.mark.analysis

SIG_DIR = Path(__file__).parent / "signatures"

# Targets are trace-only but their construction jits param init — build
# each (config, donate) once per test session.
_TARGETS = {}


def target(name, donate=True):
    key = (name, donate)
    if key not in _TARGETS:
        _TARGETS[key] = acli.build_target(name, donate=donate)
    return _TARGETS[key]


def _seeded_zero3(evil_loss):
    """(closed_jaxpr, plan, gplan, expected) of a ZeRO-3 accum trace
    whose loss_fn is ``evil_loss`` — the seeded-violation surface."""
    stepper, state, batch = target("zero3")
    mesh = stepper.inspect(state)["mesh"]
    specs = overlap.fsdp_param_specs(state.params, mesh)
    plan, gplan = overlap.step_plans(state.params, mesh,
                                     bucket_bytes=32 << 10,
                                     param_specs=specs, prefetch=1)

    def loss(p, mb):
        logits = state.apply_fn({"params": p}, mb["x"])
        return train.cross_entropy_loss(logits, mb["y"]) \
            + evil_loss(p, mb)

    closed = jax.make_jaxpr(lambda s, b: overlap.microbatch_grads(
        loss, s.params, b, mesh, microbatches=4, bucket_bytes=32 << 10,
        param_specs=specs))(state, batch)
    expected = analysis.expected_accum_collectives(plan, gplan, mesh)
    return closed, plan, gplan, expected


class TestShippedConfigsClean:
    """THE acceptance gate: every shipped make_accum_train_step config
    analyzes clean — zero unwaived findings — and matches its COMMITTED
    step-signature pin (regenerate deliberately with
    TONY_UPDATE_SIGNATURES=1 + `tony analyze --update-signatures`, then
    review the diff)."""

    @pytest.mark.parametrize("name", acli.CONFIG_NAMES)
    def test_clean_with_pinned_signature(self, name):
        if name in acli._SERVE_CONFIGS:
            # The serving plane's decode/verify configs build through
            # their own targets (an engine, not an accum stepper) —
            # run_config is the shared entry both this gate and the CLI
            # use.
            report = acli.run_config(
                name, signature_path=SIG_DIR / f"{name}.json")
        else:
            stepper, state, batch = target(name)
            report = analysis.analyze_accum_step(
                stepper, state, batch, tag=name,
                signature_path=SIG_DIR / f"{name}.json")
        assert report.ok, report.summary()
        pinned = sigmod.load_signature(SIG_DIR / f"{name}.json")
        assert pinned is not None, "signature pin not committed"
        assert report.signature == pinned, "\n".join(
            sigmod.diff_signature(pinned, report.signature))

    def test_zero3_census_matches_plan(self):
        """The audit consumed a real plan, not an empty one: the census
        carries the 3 bucketed fwd gathers, 3 scatter reduce_scatters,
        and the intact 2-barrier prefetch chain."""
        stepper, state, batch = target("zero3")
        report = analysis.analyze_accum_step(stepper, state, batch)
        kinds = {}
        for c in report.collectives:
            kinds[c.kind] = kinds.get(c.kind, 0) + 1
        assert kinds["all_gather"] == 3
        assert kinds["reduce_scatter"] == 3
        assert report.signature["optimization_barriers"] == 2
        gplan = stepper.inspect(state)["gplan"]
        assert gplan.n_gather_buckets == 3
        # The window promise is a real bound: prefetch=1 -> the two
        # largest adjacent gathers, strictly less than the total.
        assert 0 < gplan.window_nbytes() < sum(gplan.gather_nbytes)

    def test_report_banked_in_profiler(self):
        profiler.reset_analysis_records()
        stepper, state, batch = target("zero3")
        analysis.analyze_accum_step(stepper, state, batch, tag="bank")
        rep = profiler.analysis_report()
        assert rep["bank"]["ok"] is True
        assert rep["bank"]["findings"] == 0
        assert rep["bank"]["eqns"] > 0
        # Same aliasing contract as every other report family: mutating
        # the snapshot must not poison the live registry.
        rep["bank"]["findings_by_rule"]["poison"] = 1
        assert "poison" not in \
            profiler.analysis_report()["bank"]["findings_by_rule"]


class TestReplicationLeak:
    def test_leaf_major_gather_outside_window_fires(self):
        """Rule 1 seeded violation: the loss gathers a FULL fsdp-sharded
        param leaf itself (leaf-major, outside the planned prefetch
        chain) — the finding is a replication_leak with the seeding
        site's equation provenance."""
        def evil(p, mb):
            leaf = jax.tree.leaves(p)[1]
            return jax.lax.all_gather(leaf, FSDP, tiled=True).sum() * 0

        closed, _plan, gplan, expected = _seeded_zero3(evil)
        report = analysis.analyze_jaxpr(closed, expected=expected,
                                        gplan=gplan)
        leaks = [f for f in report.findings
                 if f.rule == "replication_leak"
                 and f.kind == "unplanned_gather"]
        assert leaks, report.summary()
        assert "test_analysis" in leaks[0].provenance
        assert leaks[0].nbytes > 0

    def test_broken_prefetch_chain_fires(self):
        """Rule 1 structural half: a bucketed plan promising prefetch=1
        over a trace with NO optimization_barrier chain (the per-leaf
        trace stands in for a refactor that dropped the barriers)."""
        stepper, state, batch = target("per_leaf")
        info = stepper.inspect(state)
        traced = info["jitted"].trace(state, batch)
        findings = rules.check_prefetch_chain(
            traced.jaxpr, info["gplan"], "bucketed")
        assert findings
        assert findings[0].kind == "prefetch_chain_broken"

    def test_clean_trace_no_leak(self):
        closed, _plan, gplan, expected = _seeded_zero3(
            lambda p, mb: jnp.float32(0.0))
        report = analysis.analyze_jaxpr(closed, expected=expected,
                                        gplan=gplan)
        assert report.ok, report.summary()


class TestCollectiveAudit:
    def test_unplanned_all_to_all_fires(self):
        """Rule 2 seeded violation: an all_to_all no plane registered —
        unplanned_collective, provenance pointing at the seeding line."""
        def evil(p, mb):
            t = jax.lax.all_to_all(mb["x"].reshape(4, -1), FSDP,
                                   split_axis=0, concat_axis=1,
                                   tiled=True)
            return t.sum() * 0

        closed, _plan, gplan, expected = _seeded_zero3(evil)
        report = analysis.analyze_jaxpr(closed, expected=expected,
                                        gplan=gplan)
        hits = [f for f in report.findings
                if f.kind == "unplanned_collective"
                and "all_to_all" in f.message]
        assert hits, report.summary()
        assert "test_analysis" in hits[0].provenance

    def test_planned_missing_fires(self):
        """A planned transfer that never appears in the trace (stale
        plan) is reported too — the audit is two-sided."""
        closed, _plan, gplan, expected = _seeded_zero3(
            lambda p, mb: jnp.float32(0.0))
        expected = list(expected) + [rules.Expected(
            "all_gather", frozenset({FSDP}), 999424, 1, "fwd_gather",
            "phantom")]
        report = analysis.analyze_jaxpr(closed, expected=expected,
                                        gplan=gplan)
        assert any(f.kind == "planned_missing" and "phantom" in f.message
                   for f in report.findings), report.summary()

    def test_scalar_collectives_auto_accepted(self):
        """Loss/aux psums (4 B) never need waivers."""
        closed, _plan, gplan, expected = _seeded_zero3(
            lambda p, mb: jnp.float32(0.0))
        report = analysis.analyze_jaxpr(closed, expected=expected,
                                        gplan=gplan)
        assert not [f for f in report.findings
                    if f.rule == "collective_audit"]


class TestDtypePolicy:
    def test_bf16_reduction_fires(self):
        """Rule 3 seeded violation: a psum carrying bf16 — reductions
        must accumulate in f32."""
        mesh = par.make_mesh()

        def spmd(x):
            return jax.lax.psum(x, ("data",))

        closed = jax.make_jaxpr(shard_map(
            spmd, mesh, in_specs=(P(),), out_specs=P()))(
                jnp.ones((8, 4), jnp.bfloat16))
        hits = [f for f in rules.dtype_findings(closed)
                if f.kind == "low_precision_reduction"]
        assert hits
        assert "psum" in hits[0].message

    def test_jnp_sum_of_bf16_is_legal(self):
        """jnp.sum upcasts its accumulator to f32 in the jaxpr — the
        rule must accept that (it gates the CARRY dtype, not inputs)."""
        closed = jax.make_jaxpr(lambda x: jnp.sum(x, axis=0))(
            jnp.ones((8, 4), jnp.bfloat16))
        assert not rules.dtype_findings(closed)

    def test_f64_promotion_fires(self):
        with jax.experimental.enable_x64():
            closed = jax.make_jaxpr(lambda x: x * 2.0)(
                np.ones((4,), np.float64))
        hits = [f for f in rules.dtype_findings(closed)
                if f.kind == "f64_promotion"]
        assert hits

    def test_int8_carried_reduction_fires(self):
        """Rule 3 seeded violation (quantized lane): a psum carrying
        int8 — narrow integer reductions saturate; int8 rides only
        non-accumulating collectives like the quantized gather."""
        mesh = par.make_mesh()

        def spmd(x):
            return jax.lax.psum(x, ("data",))

        closed = jax.make_jaxpr(shard_map(
            spmd, mesh, in_specs=(P(),), out_specs=P()))(
                jnp.ones((8, 4), jnp.int8))
        hits = [f for f in rules.dtype_findings(closed)
                if f.kind == "int_carried_reduction"]
        assert hits
        assert "int8" in hits[0].message and "psum" in hits[0].message

    def test_int8_narrow_accumulation_fires(self):
        """Rule 3 seeded violation: an int8×int8 dot_general without
        preferred_element_type=int32 accumulates in int8."""
        closed = jax.make_jaxpr(lambda a, b: jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ()))))(
                jnp.ones((4, 8), jnp.int8), jnp.ones((8, 4), jnp.int8))
        hits = [f for f in rules.dtype_findings(closed)
                if f.kind == "narrow_int_accumulation"]
        assert hits
        assert "int32" in hits[0].message

    def test_quant_dot_int32_accumulation_blessed(self):
        """The quantized lane's pattern — int8→int32 dot_general with
        f32 rescale — passes rule 3 with ZERO findings (including the
        quantize round/clip and the rescale casts)."""
        from tony_tpu.ops import quant as quant_mod

        closed = jax.make_jaxpr(
            lambda x, w: quant_mod.quant_dot(x, w, impl="xla"))(
                jnp.ones((8, 16), jnp.float32),
                jnp.ones((16, 8), jnp.float32))
        assert not rules.dtype_findings(closed)

    def test_bf16_moment_slot_fires(self):
        """Rule 3 seeded violation: one fused moment-slot bucket cast to
        bf16 — the finding names the exact slot and bucket."""
        _stepper, state, _batch = target("fused_bucket")
        slots = {n: list(bufs)
                 for n, bufs in state.opt_state["slots"].items()}
        slots["mu"][1] = slots["mu"][1].astype(jnp.bfloat16)
        bad = state.replace(opt_state={**state.opt_state,
                                       "slots": slots})
        hits = [f for f in rules.opt_state_findings(bad)
                if f.kind == "non_f32_moments"]
        assert len(hits) == 1
        assert "'mu'" in hits[0].provenance and "[1]" in hits[0].provenance

    def test_f32_slots_clean(self):
        _stepper, state, _batch = target("fused_bucket")
        assert not rules.opt_state_findings(state)


class TestDonation:
    def test_undonated_state_fires_with_byte_cost(self):
        """Rule 4 seeded violation: donate=False — the finding names the
        state argument and its byte cost."""
        stepper, state, batch = acli.build_target("zero3", donate=False)
        report = analysis.analyze_accum_step(stepper, state, batch,
                                             tag="nodonate")
        hits = [f for f in report.findings
                if f.kind == "undonated_argument"]
        assert len(hits) == 1, report.summary()
        assert "'state'" in hits[0].message
        total = sum(
            int(np.prod(np.shape(leaf), dtype=np.int64))
            * np.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(state)
            if hasattr(leaf, "dtype"))   # step=0 is a python int leaf
        assert hits[0].nbytes == total

    def test_donation_shrinks_live_high_water(self):
        """The satellite's before/after: donating the state (params +
        bucket-resident opt slots) measurably lowers the live-buffer
        estimate, because XLA may alias the update into the inputs."""
        stepper_n, state_n, batch_n = acli.build_target("zero3",
                                                        donate=False)
        hw_n = analysis.analyze_accum_step(
            stepper_n, state_n, batch_n,
            tag="hw_n").signature["live_high_water_nbytes"]
        stepper_d, state_d, batch_d = target("zero3")
        hw_d = analysis.analyze_accum_step(
            stepper_d, state_d, batch_d,
            tag="hw_d").signature["live_high_water_nbytes"]
        assert hw_d < hw_n


class TestWaivers:
    def test_waiver_accepts_named_finding(self):
        def evil(p, mb):
            t = jax.lax.all_to_all(mb["x"].reshape(4, -1), FSDP,
                                   split_axis=0, concat_axis=1,
                                   tiled=True)
            return t.sum() * 0

        closed, _plan, gplan, expected = _seeded_zero3(evil)
        waiver = analysis.Waiver(
            rule="collective_audit", match="all_to_all",
            reason="seeded a2a accepted for this test")
        report = analysis.analyze_jaxpr(closed, expected=expected,
                                        gplan=gplan, waivers=[waiver])
        assert report.ok, report.summary()
        assert any(f.waived and f.waived_by == waiver.reason
                   for f in report.waived)

    def test_waiver_does_not_overmatch(self):
        """A waiver for another rule must not swallow the finding."""
        def evil(p, mb):
            t = jax.lax.all_to_all(mb["x"].reshape(4, -1), FSDP,
                                   split_axis=0, concat_axis=1,
                                   tiled=True)
            return t.sum() * 0

        closed, _plan, gplan, expected = _seeded_zero3(evil)
        report = analysis.analyze_jaxpr(
            closed, expected=expected, gplan=gplan,
            waivers=[analysis.Waiver(rule="dtype_policy",
                                     match="all_to_all", reason="wrong")])
        assert not report.ok


class TestSignature:
    def test_drift_detected(self, tmp_path):
        """Rule 5 seeded violation: a pinned signature whose eqn count
        drifted — the finding carries the per-key diff."""
        stepper, state, batch = target("zero3")
        good = analysis.analyze_accum_step(stepper, state,
                                           batch).signature
        drifted = dict(good)
        drifted["eqns"] = good["eqns"] - 17
        sigmod.save_signature(tmp_path / "pin.json", drifted)
        report = analysis.analyze_accum_step(
            stepper, state, batch,
            signature_path=tmp_path / "pin.json")
        hits = [f for f in report.findings
                if f.kind == "signature_drift"]
        assert hits and "eqns" in hits[0].message

    def test_missing_pin_is_drift(self, tmp_path):
        lines = sigmod.check_signature({"eqns": 1},
                                       tmp_path / "absent.json")
        assert lines and "TONY_UPDATE_SIGNATURES" in lines[0]

    def test_update_env_rewrites(self, tmp_path, monkeypatch):
        monkeypatch.setenv(sigmod.UPDATE_ENV, "1")
        assert sigmod.check_signature({"eqns": 1},
                                      tmp_path / "new.json") == []
        assert sigmod.load_signature(tmp_path / "new.json") == {"eqns": 1}

    def test_signature_deterministic(self):
        stepper, state, batch = target("bucketed")
        info = stepper.inspect(state)
        a = sigmod.step_signature(info["jitted"].trace(state,
                                                       batch).jaxpr)
        b = sigmod.step_signature(info["jitted"].trace(state,
                                                       batch).jaxpr)
        assert a == b


class TestSrclint:
    def test_naked_concat_flagged(self):
        src = "import jax.numpy as jnp\n\ndef f(xs):\n" \
              "    return jnp.concatenate(xs)\n"
        hits = srclint.lint_source(src, "models/foo.py", "foo.py")
        assert len(hits) == 1
        assert "jnp.concatenate" in str(hits[0])

    def test_jax_numpy_spelling_and_stack_flagged(self):
        src = "import jax\n\ndef f(xs):\n" \
              "    return jax.numpy.stack(xs)\n"
        assert srclint.lint_source(src, "train/foo.py", "foo.py")

    def test_pragma_blesses_site(self):
        src = "import jax.numpy as jnp\n\ndef f(xs):\n" \
              "    # packsite: region-local — per-device shard buffers\n" \
              "    return jnp.concatenate(xs)\n"
        assert not srclint.lint_source(src, "models/foo.py", "foo.py")

    def test_approved_pack_planes_pass(self):
        src = "import jax.numpy as jnp\nx = jnp.concatenate([])\n"
        assert not srclint.lint_source(src, "parallel/overlap.py", "o.py")
        assert not srclint.lint_source(src, "ckpt/format.py", "f.py")
        assert srclint.lint_source(src, "parallel/sched.py", "s.py")

    def test_host_numpy_never_flagged(self):
        src = "import numpy as np\nx = np.concatenate([])\n"
        assert not srclint.lint_source(src, "train/foo.py", "foo.py")

    def test_pragma_never_blesses_later_statement(self):
        """A pragma blesses ONLY its own call — an unaudited concat
        stacked right below an audited one must still be flagged."""
        src = ("import jax.numpy as jnp\n\ndef f(xs, ys):\n"
               "    # packsite: region-local — audited site\n"
               "    a = jnp.concatenate(xs)\n"
               "    b = jnp.concatenate(ys)\n"
               "    return a, b\n")
        hits = srclint.lint_source(src, "models/foo.py", "foo.py")
        assert len(hits) == 1 and hits[0].line == 6

    def test_explicit_file_and_subdir_keep_allowlist(self):
        """Linting one approved file (or its parent dir) directly must
        still resolve the package-relative allowlist path."""
        root = srclint.default_root()
        assert not srclint.lint_file(root / "parallel" / "overlap.py",
                                     root / "parallel")
        assert not srclint.lint_tree(root / "parallel")

    def test_package_tree_lints_clean(self):
        """The shipped tree carries no unaudited pack sites — the gate
        `make lint` enforces, pinned here so tier-1 catches it too."""
        assert srclint.lint_tree(srclint.default_root()) == []


class TestCliEntry:
    def test_tony_analyze_runs_clean(self, tmp_path):
        from tony_tpu.cli import main

        out = tmp_path / "report.json"
        rc = main(["analyze", "--config", "zero3",
                   "--signatures", str(SIG_DIR), "--json", str(out)])
        assert rc == 0
        data = json.loads(out.read_text())
        assert data["zero3"]["ok"] is True
        assert data["zero3"]["signature"]["eqns"] > 0

    def test_unknown_config_rejected(self):
        with pytest.raises(ValueError, match="unknown analyze config"):
            acli.build_target("nope")

    def test_update_signatures_needs_dir_and_restores_env(self, tmp_path,
                                                          monkeypatch):
        """--update-signatures without --signatures is a loud error, and
        a successful update run must not leak TONY_UPDATE_SIGNATURES into
        the process (it would neuter every later drift check)."""
        from tony_tpu.cli import main

        monkeypatch.delenv(sigmod.UPDATE_ENV, raising=False)
        assert main(["analyze", "--config", "zero3",
                     "--update-signatures"]) == 2
        sigs = tmp_path / "sigs"
        assert main(["analyze", "--config", "zero3", "--signatures",
                     str(sigs), "--update-signatures"]) == 0
        assert sigmod.UPDATE_ENV not in __import__("os").environ
        assert sigmod.load_signature(sigs / "zero3.json") \
            == sigmod.load_signature(SIG_DIR / "zero3.json")


class TestGatherPlanWindow:
    def test_window_nbytes_semantics(self):
        stepper, state, _batch = target("zero3")
        gplan = stepper.inspect(state)["gplan"]
        sizes = gplan.gather_nbytes
        # prefetch=1: the largest adjacent pair.
        assert gplan.window_nbytes() == max(
            sizes[k] + sizes[k + 1] for k in range(len(sizes) - 1))
        eager = GatherPlan.from_buckets(gplan.plan, prefetch=0)
        assert eager.window_nbytes() == sum(sizes)
