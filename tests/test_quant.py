"""Quantized-lane tier (tony_tpu.ops.quant): the int8 compute lane —
pallas kernel bit-identical to the XLA int32 fallback, per-channel vs
per-tensor scales on skewed distributions, delayed-scaling amax windows,
quantize-on-gather bit-exactness / pad inertness / validation, the
LOSS-PIN GATE (quantized mnist-mlp and tiny-transformer curves track the
unquantized ones within the committed tolerances), and the scale-state
ckpt round-trip across changed fsdp topologies — on the virtual 8-device
CPU mesh. `make tier1-quant` runs this file by marker."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu import ckpt as ckpt_mod
from tony_tpu import parallel as par
from tony_tpu import profiler
from tony_tpu import train as tr
from tony_tpu.benchmark import fsdp_shard_state
from tony_tpu.models import get_model
from tony_tpu.ops import fused_optim as fo
from tony_tpu.ops import quant as q
from tony_tpu.parallel import overlap

pytestmark = pytest.mark.quant

# THE committed loss-pin tolerances (the acceptance gate of the lane):
# relative disagreement of the final training loss, quantized vs
# unquantized, after the short canonical trainings below. Measured slack
# is ~10× tighter; a tolerance bump is a reviewed numbers change.
MLP_LOSS_TOL = 0.08          # mnist-mlp, all-layer int8, 25 steps
TRANSFORMER_LOSS_TOL = 0.05  # llama-tiny, qkv/o/mlp int8, 6 steps
GATHER_LOSS_TOL = 0.02       # ZeRO-3 int8 gathers, 8 accum steps


def _bitexact(a, b):
    return np.array_equal(np.asarray(jax.device_get(a)),
                          np.asarray(jax.device_get(b)))


class TestKernel:
    """quant_dot: the pallas kernel and the XLA fallback share one
    integer accumulation and one rescale expression — BIT-identical."""

    @pytest.mark.parametrize("m,k,n", [(1, 1, 1), (33, 70, 130),
                                       (64, 128, 128)])
    def test_pallas_interpret_bitexact_vs_xla(self, m, k, n):
        ks = jax.random.split(jax.random.PRNGKey(0), 2)
        x = jax.random.normal(ks[0], (m, k), jnp.float32)
        w = jax.random.normal(ks[1], (k, n), jnp.float32) * 0.3
        y_xla = q.quant_dot(x, w, impl="xla")
        y_pl = q.quant_dot(x, w, interpret=True)
        assert _bitexact(y_xla, y_pl)
        # ...and the quantization error against the f32 matmul is the
        # expected ~1e-2 relative, not garbage.
        ref = x @ w
        rel = float(jnp.linalg.norm(y_xla - ref)
                    / jnp.maximum(jnp.linalg.norm(ref), 1e-9))
        assert rel < 0.05

    def test_batched_lhs_and_dot_general(self):
        ks = jax.random.split(jax.random.PRNGKey(1), 2)
        x = jax.random.normal(ks[0], (4, 9, 24), jnp.float32)
        w = jax.random.normal(ks[1], (24, 16), jnp.float32)
        y = q.quant_dot(x, w, impl="xla")
        assert y.shape == (4, 9, 16)
        y2 = q.quant_dot_general(x, w, (((2,), (0,)), ((), ())),
                                 impl="xla")
        assert _bitexact(y, y2)
        # Contraction on a non-leading rhs dim transposes through.
        y3 = q.quant_dot_general(x, w.T, (((2,), (1,)), ((), ())),
                                 impl="xla")
        assert _bitexact(y, y3)

    def test_validation_raises(self):
        x = jnp.ones((4, 8))
        with pytest.raises(ValueError, match="rank-2"):
            q.quant_dot(x, jnp.ones((8, 2, 2)))
        with pytest.raises(ValueError, match="mismatch"):
            q.quant_dot(x, jnp.ones((9, 4)))
        with pytest.raises(ValueError, match="impl"):
            q.quant_dot(x, jnp.ones((8, 4)), impl="cuda")
        with pytest.raises(NotImplementedError, match="batch"):
            q.quant_dot_general(jnp.ones((2, 3, 4)), jnp.ones((2, 4, 3)),
                                (((2,), (1,)), ((0,), (0,))))

    def test_ste_gradients_flow_in_primal_dtypes(self):
        ks = jax.random.split(jax.random.PRNGKey(2), 2)
        x = jax.random.normal(ks[0], (8, 16), jnp.bfloat16)
        w = jax.random.normal(ks[1], (16, 8), jnp.float32)
        gx, gw = jax.grad(
            lambda x, w: jnp.sum(q.quant_dot(x, w) ** 2),
            argnums=(0, 1))(x, w)
        assert gx.dtype == jnp.bfloat16 and gw.dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(gw)))
        assert float(jnp.abs(gw).max()) > 0   # not a dead STE


class TestScales:
    def test_per_channel_rescues_small_columns(self):
        """Skewed per-column magnitudes: a per-tensor scale is sized by
        the loud columns and rounds the quiet ones to junk; per-channel
        keeps every column at int8's ~0.4% relative error."""
        ks = jax.random.split(jax.random.PRNGKey(3), 2)
        x = jax.random.normal(ks[0], (64, 32), jnp.float32)
        w = jax.random.normal(ks[1], (32, 64), jnp.float32)
        col_scale = jnp.where(jnp.arange(64) < 32, 100.0, 0.01)
        w = w * col_scale
        ref = x @ w
        quiet = ref[:, 32:]

        def quiet_err(y):
            return float(jnp.linalg.norm(y[:, 32:] - quiet)
                         / jnp.linalg.norm(quiet))

        e_pc = quiet_err(q.quant_dot(x, w, impl="xla"))
        e_pt = quiet_err(q.quant_dot(x, w, per_channel=False, impl="xla"))
        assert e_pc < 0.05
        assert e_pt > 10 * e_pc

    def test_delayed_scaling_window(self):
        hist = jnp.zeros((4,), jnp.float32)
        for v in (1.0, 8.0, 2.0):
            hist = q.push_amax(hist, jnp.float32(v))
        assert np.allclose(np.asarray(hist), [0.0, 1.0, 8.0, 2.0])
        # Scale reacts to the WINDOW max, not the newest value.
        assert float(q.hist_scale(hist)) == pytest.approx(8.0 / 127.0)
        # The 8.0 falls out once enough pushes age it past the window.
        for _ in range(3):
            hist = q.push_amax(hist, jnp.float32(0.5))
        assert float(q.hist_scale(hist)) == pytest.approx(2.0 / 127.0)
        # Zero amax floors instead of dividing by zero.
        assert float(q.scale_of(jnp.float32(0.0))) > 0
        assert _bitexact(q.quantize(jnp.zeros((4,)), q.scale_of(
            jnp.float32(0.0))), jnp.zeros((4,), jnp.int8))

    def test_window_validation(self):
        with pytest.raises(ValueError, match="window"):
            q.QuantConfig(window=0)


def _mnist_data(n=128, seed=0):
    kx, ky = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(kx, (n, 784), jnp.float32)
    y = jax.random.randint(ky, (n,), 0, 10)
    return {"x": x, "y": y}


class TestLossPin:
    """THE gate: quantized training curves track the unquantized ones
    within the committed tolerances, and training actually happens.

    The two single-device model pins are marked ``slow`` (two full
    model+step compiles each) — the 870 s tier-1 budget was already at
    its edge before this lane landed, and the `slow` marker is the
    repo's mechanism for exactly that (the PR 3 async-save test rides it
    too). `make tier1-quant` runs the ENTIRE quant selection, slow
    included, so the loss-pin gate stays enforced by name; the cheapest
    pin (the quantize-on-gather lane, which is the tentpole's own wire
    format) stays inside the tier-1 sweep."""

    @pytest.mark.slow
    def test_mnist_mlp_quant_tracks_f32(self):
        data = _mnist_data()
        finals = {}
        for quant in (False, True):
            model = get_model("mnist-mlp", hidden=64, quant=quant)
            state = tr.create_train_state(
                model, optax.adam(1e-3), data["x"], jax.random.PRNGKey(7))
            step = tr.make_train_step()
            first = None
            for _ in range(25):
                state, m = step(state, data)
                first = float(m["loss"]) if first is None else first
            finals[quant] = float(m["loss"])
            assert finals[quant] < 0.8 * first   # it learns
        rel = abs(finals[True] - finals[False]) / finals[False]
        assert rel < MLP_LOSS_TOL, finals

    @pytest.mark.slow
    def test_tiny_transformer_quant_tracks_bf16(self):
        toks = jax.random.randint(jax.random.PRNGKey(0), (8, 32), 0, 256)
        finals = {}
        for quant in (None, True):
            model = get_model("llama-tiny", quant=quant)
            state = tr.create_train_state(
                model, optax.adamw(1e-3), toks, jax.random.PRNGKey(1))
            step = tr.make_train_step(
                loss_of=lambda lg, b: tr.next_token_loss(lg, b["x"]))
            first = None
            for _ in range(6):
                state, m = step(state, {"x": toks})
                first = float(m["loss"]) if first is None else first
            finals[bool(quant)] = float(m["loss"])
            assert finals[bool(quant)] < first   # it learns
        rel = abs(finals[True] - finals[False]) / finals[False]
        assert rel < TRANSFORMER_LOSS_TOL, finals

    def test_quant_gather_accum_tracks_unquantized(self):
        mesh = par.make_mesh(fsdp=4)
        data = _mnist_data(64, seed=1)
        bb = 1 << 15
        model = get_model("mnist-mlp", hidden=32)

        def fresh():
            return fsdp_shard_state(tr.create_train_state(
                model, optax.adamw(1e-3), data["x"],
                jax.random.PRNGKey(2)), mesh)

        profiler.reset_quant_records()
        sp = fresh()
        sq = q.with_gather_quant(fresh(), mesh, window=4, bucket_bytes=bb)
        step_p = tr.make_accum_train_step(mesh=mesh, microbatches=4,
                                          bucket_bytes=bb, donate=False)
        step_q = tr.make_accum_train_step(mesh=mesh, microbatches=4,
                                          bucket_bytes=bb, quant=True,
                                          donate=False)
        for _ in range(8):
            sp, mp = step_p(sp, data)
            sq, mq = step_q(sq, data)
        rel = abs(float(mq["loss"]) - float(mp["loss"])) / float(mp["loss"])
        assert rel < GATHER_LOSS_TOL, (float(mp["loss"]), float(mq["loss"]))
        # Delayed scaling actually tracked the shrinking params: the
        # histories moved off their attach-time seed.
        hist = np.asarray(jax.device_get(sq.quant_state["amax"][-1]))
        assert len(set(hist.tolist())) > 1
        # The trace banked the gather schedule: int8 wire = raw/4 for
        # f32 params, bytes_saved positive.
        g = profiler.quant_report()["accum_gather"]
        assert g["bytes_saved"] > 0
        assert sum(g["raw_nbytes"]) == 4 * sum(g["int8_nbytes"])
        assert g["window"] == 4


class TestQuantGather:
    def _tree(self, mesh):
        """Even + uneven + bf16 + replicated + scalar — the full menu."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        ks = jax.random.split(jax.random.PRNGKey(3), 4)
        params = {
            "w1": jax.random.normal(ks[0], (16, 8), jnp.float32),
            "w2": jax.random.normal(ks[1], (6, 8), jnp.float32),  # 6%4!=0
            "w3": jax.random.normal(ks[2], (8, 4), jnp.bfloat16),
            "bias": jax.random.normal(ks[3], (5,), jnp.float32),
            "scale": jnp.float32(1.5),
        }
        committed = {k: NamedSharding(mesh, P("fsdp")
                                      if k in ("w1", "w3") else P())
                     for k in params}
        return jax.device_put(params, committed)

    def test_gather_roundtrip_bit_exact(self):
        mesh = par.make_mesh(fsdp=4)
        params = self._tree(mesh)
        assert q.gather_roundtrip_exact(params, mesh, 256)

    def test_padded_buckets_stay_out_of_the_quant_lane(self):
        """Uneven (padded) buckets are gather-passthrough: the int8 wire
        format never touches them, so pad rows can't quantize-drift."""
        from jax.sharding import PartitionSpec as P

        mesh = par.make_mesh(fsdp=4)
        params = self._tree(mesh)
        # Explicit specs: the uneven w2 (6 % 4 != 0) is DECLARED sharded
        # so the planner pads it into a dedicated scatter bucket.
        specs = {"w1": P("fsdp"), "w2": P("fsdp"), "w3": P("fsdp"),
                 "bias": P(), "scale": P()}
        plan, gplan = overlap.step_plans(params, mesh, bucket_bytes=256,
                                         param_specs=specs)
        assert any(plan._is_padded(b) for b in range(plan.n_buckets))
        assert all(not plan._is_padded(b) for b in gplan.gather_buckets)

    def test_no_gatherable_buckets_is_identity_step(self):
        """A tree with no even scatter buckets (uneven + replicated
        only): quantize-on-gather has nothing to quantize and the step
        is BIT-exact the unquantized one — the lane degrades to zero,
        not to noise."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = par.make_mesh(fsdp=4)
        ks = jax.random.split(jax.random.PRNGKey(4), 2)
        params = jax.device_put(
            {"w": jax.random.normal(ks[0], (6, 8), jnp.float32),
             "b": jax.random.normal(ks[1], (5,), jnp.float32)},
            {"w": NamedSharding(mesh, P()),
             "b": NamedSharding(mesh, P())})
        specs = {"w": P("fsdp"), "b": P()}
        batch = {"x": jnp.ones((32, 4), jnp.float32)}

        def loss(p, mb):
            return (jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2)) \
                * jnp.mean(mb["x"])

        _, gplan = overlap.step_plans(params, mesh, bucket_bytes=256,
                                      param_specs=specs)
        assert gplan.n_gather_buckets == 0
        l0, g0 = overlap.microbatch_grads(
            loss, params, batch, mesh, microbatches=2, bucket_bytes=256,
            param_specs=specs)
        l1, g1, hist = overlap.microbatch_grads(
            loss, params, batch, mesh, microbatches=2, bucket_bytes=256,
            param_specs=specs, quant_amax=[])
        assert hist == []
        assert _bitexact(l0, l1)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            assert _bitexact(a, b)

    def test_validation_errors(self):
        mesh = par.make_mesh(fsdp=4)
        data = _mnist_data(32, seed=5)
        model = get_model("mnist-mlp", hidden=16)
        state = fsdp_shard_state(tr.create_train_state(
            model, optax.sgd(0.1), data["x"], jax.random.PRNGKey(0)),
            mesh)
        with pytest.raises(ValueError, match="bucket boundary"):
            tr.make_accum_train_step(mesh=mesh, microbatches=2,
                                     gather="per_leaf", quant=True)
        step = tr.make_accum_train_step(mesh=mesh, microbatches=2,
                                        quant=True)
        with pytest.raises(ValueError, match="QuantTrainState"):
            step(state, data)
        qs = q.with_gather_quant(state, mesh, window=2,
                                 bucket_bytes=1 << 15)
        bad = tr.make_accum_train_step(mesh=mesh, microbatches=2,
                                       bucket_bytes=1 << 14, quant=True)
        with pytest.raises(ValueError, match="bucket_bytes"):
            bad(qs, data)
        # Replicated layout: nothing to quantize-gather.
        plain = tr.create_train_state(model, optax.sgd(0.1), data["x"],
                                      jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="fsdp-sharded"):
            q.with_gather_quant(plain, mesh)
        # Histories for the wrong geometry are named, not garbled.
        with pytest.raises(ValueError, match="histories"):
            overlap.microbatch_grads(
                lambda p, mb: jnp.float32(0.0) * jnp.mean(mb["x"]),
                qs.params, data, mesh, microbatches=2,
                bucket_bytes=1 << 15,
                param_specs=overlap.fsdp_param_specs(qs.params, mesh),
                quant_amax=qs.quant_state["amax"][:-1])


class TestCkptPortability:
    """The amax state rides the PR 3 manifest through the quant codec:
    per-leaf portable form, rebuilt per-bucket for whatever topology
    restores (composing with the fused-optimizer codec)."""

    def _state(self, mesh, tx, seed=1, window=4, bb=1 << 15):
        model = get_model("mnist-mlp", hidden=16)
        data = _mnist_data(64, seed=seed)
        state = fsdp_shard_state(tr.create_train_state(
            model, tx, data["x"], jax.random.PRNGKey(seed)), mesh)
        return q.with_gather_quant(state, mesh, window=window,
                                   bucket_bytes=bb), data

    def test_same_topology_roundtrip_exact(self):
        mesh = par.make_mesh(fsdp=4)
        state, _ = self._state(mesh, optax.adamw(1e-3))
        enc = ckpt_mod.encode_portable(state)
        assert "amax_leaf" in enc.quant_state
        dec = ckpt_mod.decode_portable(enc, mesh)
        assert "amax" in dec.quant_state
        for a, b in zip(state.quant_state["amax"],
                        dec.quant_state["amax"]):
            assert _bitexact(a, b)
        # Encode of the decode is the identity on the portable form.
        enc2 = ckpt_mod.encode_portable(dec)
        for a, b in zip(jax.tree.leaves(enc.quant_state),
                        jax.tree.leaves(enc2.quant_state)):
            assert _bitexact(a, b)

    @pytest.mark.slow
    def test_cross_topology_restore_steps(self, tmp_path):
        bb = 1 << 15
        fused = fo.FusedOptimizer(rule="adamw", lr=1e-3, bucket_bytes=bb)
        mesh4 = par.make_mesh(fsdp=4)
        s4, data = self._state(mesh4, fused, bb=bb)
        step4 = tr.make_accum_train_step(
            mesh=mesh4, microbatches=4, bucket_bytes=bb,
            update="fused_bucket", quant=True, donate=False)
        for _ in range(2):
            s4, _ = step4(s4, data)
        mgr = ckpt_mod.AsyncCheckpointer(tmp_path, keep=2)
        mgr.save(ckpt_mod.encode_portable(s4), step=2, block=True)
        mgr.close()

        mesh2 = par.make_mesh(fsdp=2)
        fresh, _ = self._state(mesh2, fused, seed=9, bb=bb)
        restored = ckpt_mod.decode_portable(ckpt_mod.restore_pytree(
            tmp_path, ckpt_mod.encode_portable(fresh), step=2,
            mesh=mesh2), mesh2)
        # Both planes came back live and re-bucketed for fsdp=2...
        assert "amax" in restored.quant_state
        assert "slots" in restored.opt_state
        assert int(restored.opt_state["count"]) == 2
        assert restored.qconfig.window == 4
        # ...the params are the saved ones bit-exact...
        for a, b in zip(jax.tree.leaves(s4.params),
                        jax.tree.leaves(restored.params)):
            assert _bitexact(a, b)
        # ...and the restored state STEPS on the new topology, tracking
        # the original run within quantization-level disagreement (the
        # re-bucketed amax merge is conservative, not identical).
        step2 = tr.make_accum_train_step(
            mesh=mesh2, microbatches=4, bucket_bytes=bb,
            update="fused_bucket", quant=True, donate=False)
        restored, m2 = step2(restored, data)
        s4, m4 = step4(s4, data)
        assert float(m2["loss"]) == pytest.approx(float(m4["loss"]),
                                                  rel=1e-3)

    def test_newly_gatherable_bucket_reseeds_from_params(self):
        """A leaf that was UNEVEN (non-gatherable) at the saving fsdp
        degree carries a zero portable history; if it becomes gatherable
        on the restoring topology, the merged history would be zero and
        the floored scale would CLIP its params to ~0 on the first step
        — decode must re-seed such buckets from the live params, like
        with_gather_quant does at attach time."""
        from flax.training.train_state import TrainState
        from jax.sharding import NamedSharding, PartitionSpec as P

        bb = 256
        ks = jax.random.split(jax.random.PRNGKey(0), 2)
        vals = {"a": jax.random.normal(ks[0], (8, 8), jnp.float32),
                "b": jax.random.normal(ks[1], (6, 8), jnp.float32)}

        def state_on(mesh, b_sharded):
            committed = {
                "a": NamedSharding(mesh, P("fsdp")),
                "b": NamedSharding(mesh, P("fsdp") if b_sharded
                                   else P())}
            params = jax.device_put(vals, committed)
            return TrainState.create(apply_fn=lambda *a: None,
                                     params=params, tx=optax.sgd(0.1))

        mesh4 = par.make_mesh(fsdp=4)
        s4 = q.with_gather_quant(state_on(mesh4, False), mesh4,
                                 window=3, bucket_bytes=bb)
        enc = q.encode_state(s4)
        # "b" was non-gatherable at fsdp=4 → zero portable history.
        assert float(np.max(np.asarray(
            jax.tree.leaves(enc.quant_state["amax_leaf"])[1]))) == 0.0

        mesh2 = par.make_mesh(fsdp=2)
        template = state_on(mesh2, True)       # b gatherable now
        portable = q.QuantTrainState(
            step=template.step, apply_fn=template.apply_fn,
            params=template.params, tx=template.tx,
            opt_state=template.opt_state, qconfig=enc.qconfig,
            quant_state=enc.quant_state)
        dec = q.decode_state(portable, mesh2)
        # Every gatherable bucket's history is live and positive — the
        # zero-merged one got re-seeded from |b|'s amax.
        b_amax = float(jnp.max(jnp.abs(vals["b"])))
        hists = [np.asarray(jax.device_get(h))
                 for h in dec.quant_state["amax"]]
        assert all(h.max() > 0 for h in hists)
        assert any(np.allclose(h, b_amax) for h in hists)

    def test_fused_only_states_keep_their_codec(self):
        """Registry order: the quant codec PREPENDS but must not hijack
        plain fused (or plain optax) states."""
        mesh = par.make_mesh(fsdp=2)
        model = get_model("mnist-mlp", hidden=16)
        data = _mnist_data(32, seed=3)
        fused_state = fsdp_shard_state(tr.create_train_state(
            model, fo.FusedOptimizer(rule="sgd", lr=0.1,
                                     bucket_bytes=1 << 15),
            data["x"], jax.random.PRNGKey(0)), mesh)
        enc = ckpt_mod.encode_portable(fused_state)
        assert "leaf" in enc.opt_state          # fused codec applied
        assert getattr(enc, "quant_state", None) is None
        plain = fsdp_shard_state(tr.create_train_state(
            model, optax.sgd(0.1), data["x"], jax.random.PRNGKey(0)),
            mesh)
        assert ckpt_mod.encode_portable(plain) is plain


class TestRecords:
    def test_dense_records(self):
        # QuantDense call sites bank their shapes + impl at trace time
        # (the accum_gather record is asserted where it is produced, in
        # TestLossPin.test_quant_gather_accum_tracks_unquantized).
        profiler.reset_quant_records()
        qmodel = get_model("mnist-mlp", hidden=16, quant=True)
        qmodel.init(jax.random.PRNGKey(0), jnp.ones((2, 784)))
        dense = [v for k, v in profiler.quant_report().items()
                 if k.startswith("dense.")]
        assert dense and all(d["impl"] in ("pallas", "xla")
                             and d["k"] > 0 for d in dense)

    def test_mutating_quant_report_does_not_poison_store(self):
        profiler.reset_quant_records()
        profiler.safe_record("quant", "t", nested={"deep": [1, 2]},
                             raw_nbytes=[10, 20])
        snap = profiler.quant_report()
        snap["t"]["nested"]["deep"].append(99)
        snap["t"]["raw_nbytes"][0] = -1
        snap["injected"] = {}
        assert profiler.quant_report() == {
            "t": {"nested": {"deep": [1, 2]}, "raw_nbytes": [10, 20]}}
        profiler.reset_quant_records()
