"""Pallas-kernel tests (interpret mode on CPU) against the pure-JAX
reference — the kernel-correctness tier of the compute plane."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import flash_attention, reference_attention


def rand_qkv(b=2, h=3, t=64, d=16, dtype=jnp.float32, tk=None):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    tk = t if tk is None else tk
    return (jax.random.normal(ks[0], (b, h, t, d), dtype),
            jax.random.normal(ks[1], (b, h, tk, d), dtype),
            jax.random.normal(ks[2], (b, h, tk, d), dtype))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    q, k, v = rand_qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_kernel_uneven_blocks():
    # block sizes that don't divide T fall back to the reference — still exact.
    q, k, v = rand_qkv(t=48)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_kernel_bf16():
    q, k, v = rand_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grad_matches_reference_grad(causal):
    q, k, v = rand_qkv(b=1, h=2, t=32, d=8)
    # Non-uniform cotangent so dq/dk/dv all get exercised asymmetrically.
    w = jax.random.normal(jax.random.PRNGKey(7), (1, 2, 32, 8))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=16,
                                block_k=16, interpret=True) * w).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=causal) * w).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_cpu_dispatch_uses_reference():
    # On the CPU backend with no interpret flag, dispatch must not try to
    # compile a TPU kernel.
    q, k, v = rand_qkv(t=32)
    out = flash_attention(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
