"""Pallas-kernel tests (interpret mode on CPU) against the pure-JAX
reference — the kernel-correctness tier of the compute plane."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import flash_attention, reference_attention


def rand_qkv(b=2, h=3, t=64, d=16, dtype=jnp.float32, tk=None):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    tk = t if tk is None else tk
    return (jax.random.normal(ks[0], (b, h, t, d), dtype),
            jax.random.normal(ks[1], (b, h, tk, d), dtype),
            jax.random.normal(ks[2], (b, h, tk, d), dtype))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    q, k, v = rand_qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_kernel_uneven_blocks():
    # Causal self-attention with T not divisible by ANY tile-legal block
    # (t=40 isn't a multiple of 16) takes the zero-pad kernel path —
    # still exact.
    q, k, v = rand_qkv(t=40)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_block_shrinks_to_dividing_size(causal):
    # T divisible by 16 but not by the requested block must shrink the
    # block (96 @ limit 64 → 48) and run the kernel unpadded — no
    # fallback warning even non-causal (the t=384-at-default-256 case).
    import warnings

    from tony_tpu.ops import attention as att

    assert att._fit_block(64, 96) == 48
    q, k, v = rand_qkv(t=96)
    att._warned.clear()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = flash_attention(q, k, v, causal=causal, block_q=64,
                              block_k=64, interpret=True)
    assert not caught
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def _assert_kernel_matches_reference(q, k, v, causal, block=32):
    """Values AND grads through the kernel path, with NO fallback warning
    — the BENCH_r02 block-shape regression guard (ragged/odd shapes used
    to silently materialize the T×T reference score matrix)."""
    import warnings

    from tony_tpu.ops import attention as att

    att._warned.clear()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = flash_attention(q, k, v, causal=causal, block_q=block,
                              block_k=block, interpret=True)
    assert not caught
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    w = jax.random.normal(jax.random.PRNGKey(17), q.shape)
    g_f = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, causal=causal, block_q=block, block_k=block,
        interpret=True) * w).sum(), (0, 1, 2))(q, k, v)
    g_r = jax.grad(lambda q, k, v: (reference_attention(
        q, k, v, causal=causal) * w).sum(), (0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_flash_ragged_noncausal_pads_and_masks():
    # Non-causal ragged shapes used to fall back to the reference (end-
    # padded keys would soak up softmax mass); now the kernels mask the
    # padded keys via the static kv_len and stay on the kernel path.
    q, k, v = rand_qkv(t=48, tk=40)
    _assert_kernel_matches_reference(q, k, v, causal=False)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_cross_lengths_run_kernel(causal):
    # Cross-attention lengths (t != tk, neither dividing the blocks).
    q, k, v = rand_qkv(b=1, h=2, t=40, d=16, tk=24)
    _assert_kernel_matches_reference(q, k, v, causal=causal)


@pytest.mark.parametrize("streamed", [False, True])
def test_flash_ragged_streamed_kernels(streamed):
    # The same mask through the streamed-KV kernel family.
    from tony_tpu.ops import attention as att

    old = att._RESIDENT_KV_BYTES
    att._RESIDENT_KV_BYTES = 0 if streamed else old
    try:
        q, k, v = rand_qkv(b=1, h=2, t=40, tk=24, d=16)
        _assert_kernel_matches_reference(q, k, v, causal=False)
    finally:
        att._RESIDENT_KV_BYTES = old


@pytest.mark.parametrize("d", [20, 12])
def test_flash_odd_head_dim_runs_kernel(d):
    # head_dim off the 8-row sublane tile: zero-padded feature dim, still
    # the kernel path — values and grads exact, output dtype/shape kept.
    q, k, v = rand_qkv(b=1, h=2, t=32, d=d)
    _assert_kernel_matches_reference(q, k, v, causal=True)
    q, k, v = rand_qkv(b=1, h=2, t=40, tk=24, d=d)
    _assert_kernel_matches_reference(q, k, v, causal=False)


def test_flash_kernel_bf16():
    q, k, v = rand_qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = reference_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=2e-2, rtol=2e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grad_matches_reference_grad(causal):
    q, k, v = rand_qkv(b=1, h=2, t=32, d=8)
    # Non-uniform cotangent so dq/dk/dv all get exercised asymmetrically.
    w = jax.random.normal(jax.random.PRNGKey(7), (1, 2, 32, 8))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=16,
                                block_k=16, interpret=True) * w).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=causal) * w).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_flash_padded_grad_matches_reference():
    # Causal self-attention with T not divisible by the blocks takes the
    # zero-pad path (not the reference fallback); grads must stay exact
    # including the pad-slice boundary.
    q, k, v = rand_qkv(b=1, h=2, t=40, d=8)
    w = jax.random.normal(jax.random.PRNGKey(3), (1, 2, 40, 8))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True, block_q=16,
                                block_k=16, interpret=True) * w).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) * w).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_flash_sharded_matches_reference():
    # The shard_map wrapper (batch on dp, heads on tp) must agree with the
    # unsharded reference on an 8-device mesh.
    from tony_tpu.ops import flash_attention_sharded
    from tony_tpu.parallel import make_mesh

    mesh = make_mesh(dp=2, sp=2, tp=2)
    q, k, v = rand_qkv(b=4, h=8, t=32, d=8)
    out = jax.jit(
        lambda q, k, v: flash_attention_sharded(
            q, k, v, mesh, block_q=16, block_k=16, interpret=True))(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_cpu_dispatch_uses_reference():
    # On the CPU backend with no interpret flag, dispatch must not try to
    # compile a TPU kernel.
    q, k, v = rand_qkv(t=32)
    out = flash_attention(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_packed_matches_classic(causal):
    # The packed [B, T, H*D] entry must agree with the classic layout on
    # values AND grads (interpret mode; d=128 for lane alignment).
    b, h, t, d = 2, 2, 64, 128
    q, k, v = rand_qkv(b=b, h=h, t=t, d=d)
    from tony_tpu.ops import flash_attention_packed

    pack = lambda x: x.transpose(0, 2, 1, 3).reshape(b, t, h * d)

    def loss_packed(q, k, v):
        return flash_attention_packed(
            pack(q), pack(k), pack(v), h, causal=causal, block_q=16,
            block_k=16, interpret=True).sum()

    def loss_classic(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=16,
                               block_k=16, interpret=True).sum()

    np.testing.assert_allclose(float(loss_packed(q, k, v)),
                               float(loss_classic(q, k, v)), rtol=1e-4)
    gp = jax.grad(loss_packed, (0, 1, 2))(q, k, v)
    gc = jax.grad(loss_classic, (0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gc):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=2e-4)


def test_flash_packed_bad_head_dim_falls_back():
    # head_dim not lane-aligned: warn + unpacked fallback, still correct.
    import warnings

    from tony_tpu.ops import attention as att
    from tony_tpu.ops import flash_attention_packed

    b, h, t, d = 2, 3, 32, 16
    q, k, v = rand_qkv(b=b, h=h, t=t, d=d)
    pack = lambda x: x.transpose(0, 2, 1, 3).reshape(b, t, h * d)
    att._warned.clear()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = flash_attention_packed(pack(q), pack(k), pack(v), h,
                                     block_q=16, block_k=16, interpret=True)
    assert any("head_dim" in str(w.message) for w in caught)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out.reshape(b, t, h, d).transpose(0, 2, 1, 3)),
        np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_streamed_kv_matches_reference(causal):
    """Long-context path: force the streamed-KV kernels (k-block grid axis
    + VMEM scratch accumulators) by shrinking the resident threshold, and
    check values AND grads against the reference."""
    from tony_tpu.ops import attention as att

    old = att._RESIDENT_KV_BYTES
    att._RESIDENT_KV_BYTES = 0   # every shape takes the streamed kernels
    try:
        q, k, v = rand_qkv(b=1, h=2, t=64, d=16)
        w = jax.random.normal(jax.random.PRNGKey(5), (1, 2, 64, 16))

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=causal, block_q=16,
                                    block_k=16, interpret=True) * w).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=causal) * w).sum()

        np.testing.assert_allclose(float(loss_flash(q, k, v)),
                                   float(loss_ref(q, k, v)), rtol=1e-4)
        g_f = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
        g_r = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for a, b in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-5)

        # Packed layout through the streamed kernels too.
        from tony_tpu.ops import flash_attention_packed
        b_, h_, t_, d_ = 1, 2, 32, 128
        q2, k2, v2 = rand_qkv(b=b_, h=h_, t=t_, d=d_)
        pack = lambda x: x.transpose(0, 2, 1, 3).reshape(b_, t_, h_ * d_)
        out_p = flash_attention_packed(pack(q2), pack(k2), pack(v2), h_,
                                       causal=causal, block_q=16,
                                       block_k=16, interpret=True)
        ref2 = reference_attention(q2, k2, v2, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out_p.reshape(b_, t_, h_, d_).transpose(0, 2, 1, 3)),
            np.asarray(ref2), atol=2e-5, rtol=2e-5)
    finally:
        att._RESIDENT_KV_BYTES = old


def rand_gqa(b=1, h=4, hkv=2, t=64, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    return (jax.random.normal(ks[0], (b, h, t, d), dtype),
            jax.random.normal(ks[1], (b, hkv, t, d), dtype),
            jax.random.normal(ks[2], (b, hkv, t, d), dtype))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("streamed", [False, True])
def test_flash_gqa_matches_reference(causal, streamed):
    """Zero-copy GQA (VERDICT r4 #5): K/V carry fewer heads than Q and the
    kernels' index maps do the head grouping — values AND all three grads
    must match the repeat-then-attend reference, through both the resident
    and streamed kernel families."""
    from tony_tpu.ops import attention as att

    q, k, v = rand_gqa()
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=16,
                                block_k=16, interpret=True) * w).sum()

    def loss_ref(q, k, v):
        # reference_attention repeats K/V internally — the semantic spec.
        return (reference_attention(q, k, v, causal=causal) * w).sum()

    old = att._RESIDENT_KV_BYTES
    att._RESIDENT_KV_BYTES = 0 if streamed else old
    try:
        np.testing.assert_allclose(float(loss_flash(q, k, v)),
                                   float(loss_ref(q, k, v)), rtol=1e-4)
        g_f = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
        g_r = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for a, b in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-5)
    finally:
        att._RESIDENT_KV_BYTES = old


@pytest.mark.parametrize("streamed", [False, True])
def test_flash_gqa_packed_matches_reference(streamed):
    """Packed-layout GQA: K/V packed [B, T, Hkv·D]; query head h reads kv
    lane-block h·Hkv/H. Values and grads vs the classic-layout reference."""
    from tony_tpu.ops import attention as att
    from tony_tpu.ops import flash_attention_packed

    b, h, hkv, t, d = 1, 4, 2, 32, 128
    q, k, v = rand_gqa(b=b, h=h, hkv=hkv, t=t, d=d)
    pack = lambda x: x.transpose(0, 2, 1, 3).reshape(
        b, t, x.shape[1] * d)
    w = jax.random.normal(jax.random.PRNGKey(13), (b, t, h * d))

    def loss_packed(qp, kp, vp):
        return (flash_attention_packed(qp, kp, vp, h, causal=True,
                                       block_q=16, block_k=16,
                                       interpret=True) * w).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        return (pack(out) * w).sum()

    old = att._RESIDENT_KV_BYTES
    att._RESIDENT_KV_BYTES = 0 if streamed else old
    try:
        np.testing.assert_allclose(
            float(loss_packed(pack(q), pack(k), pack(v))),
            float(loss_ref(q, k, v)), rtol=1e-4)
        g_p = jax.grad(loss_packed, (0, 1, 2))(pack(q), pack(k), pack(v))
        g_r = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
        for a, b_ in zip(g_p, (pack(x) for x in g_r)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-5, rtol=2e-5)
    finally:
        att._RESIDENT_KV_BYTES = old


def test_flash_gqa_rejects_ragged_heads():
    q, k, v = rand_gqa(h=4, hkv=3)
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, k, v, interpret=True)
