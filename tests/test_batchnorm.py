"""Fused BatchNorm(+add)(+ReLU) kernels vs the reference math (reference
tier: op unit tests, SURVEY.md §4; VERDICT r3 #1). Interpret mode on the
CPU mesh — the kernels themselves are exercised compiled on TPU by bench.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops.batchnorm import fused_bn_act, pick_block_rows


def ref_bn_act(x, gamma, beta, residual=None, eps=1e-5, relu=True):
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean((xf - mean) ** 2, axis=axes)
    out = (xf - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    if residual is not None:
        out = out + residual.astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    return out.astype(x.dtype), mean, var


def rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)


@pytest.mark.parametrize("relu", [True, False])
@pytest.mark.parametrize("with_residual", [False, True])
def test_fused_bn_matches_reference_fwd_bwd(relu, with_residual):
    n, h, w, c = 4, 8, 8, 16
    x = rand(0, (n, h, w, c))
    gamma = rand(1, (c,)) * 0.5 + 1.0
    beta = rand(2, (c,)) * 0.1
    res = rand(3, (n, h, w, c)) if with_residual else None
    wgt = rand(4, (n, h, w, c))

    def loss_fused(x, gamma, beta, res):
        out, mean, var = fused_bn_act(x, gamma, beta, res, relu=relu,
                                      interpret=True)
        return (out * wgt).sum(), (mean, var)

    def loss_ref(x, gamma, beta, res):
        out, mean, var = ref_bn_act(x, gamma, beta, res, relu=relu)
        return (out * wgt).sum(), (mean, var)

    args = (x, gamma, beta, res)
    diff = (0, 1, 2, 3) if with_residual else (0, 1, 2)
    (lf, (mf, vf)), gf = jax.value_and_grad(
        loss_fused, diff, has_aux=True)(*args)
    (lr, (mr, vr)), gr = jax.value_and_grad(
        loss_ref, diff, has_aux=True)(*args)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(mf), np.asarray(mr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(vf), np.asarray(vr),
                               atol=1e-4, rtol=1e-4)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_fused_bn_bf16_inputs():
    n, h, w, c = 2, 4, 4, 32
    x = rand(0, (n, h, w, c)).astype(jnp.bfloat16)
    gamma = jnp.ones((c,))
    beta = jnp.zeros((c,))
    out, mean, var = fused_bn_act(x, gamma, beta, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref, rmean, rvar = ref_bn_act(x, gamma, beta)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(rmean),
                               atol=2e-2, rtol=2e-2)


def test_pick_block_rows_budget_and_divisibility():
    bm = pick_block_rows(1024, 64)
    assert bm is not None and 1024 % bm == 0
    for n_bufs in (3, 5):   # plain and residual dx kernels
        bm = pick_block_rows(18816, 2048, 2, n_bufs)  # batch 384·7², C 2048
        assert bm is not None and 18816 % bm == 0
        # Double-buffered blocks of the worst kernel fit the VMEM budget.
        assert 2 * n_bufs * bm * 2048 * 2 <= 8 << 20
    assert pick_block_rows(17, 64) is None  # prime-ish M: no clean tiling
    # Very wide C: even 16 rows blow the budget — must fall back to XLA,
    # not dispatch a kernel that OOMs VMEM at compile time.
    assert pick_block_rows(1024, 32768) is None


def _rename_fused(tree):
    """Map the plain model's param/stat paths onto the fused model's
    (Bottleneck→FusedBottleneck, BatchNorm→FusedBNAct; numbering and
    explicit names line up by construction)."""
    if not isinstance(tree, dict):
        return tree
    out = {}
    for k, v in tree.items():
        k2 = k.replace("Bottleneck", "FusedBottleneck").replace(
            "BatchNorm", "FusedBNAct")
        out[k2] = _rename_fused(v)
    return out


@pytest.mark.slow
def test_fused_resnet_matches_plain_resnet():
    """Whole-model equivalence: same params ⇒ same logits, same grads,
    same running-stat updates (f32 to isolate kernel math from bf16)."""
    from tony_tpu.models import get_model

    plain = get_model("resnet18-thin", dtype=jnp.float32)
    fused = get_model("resnet18-thin", dtype=jnp.float32, fused_bn=True,
                      bn_interpret=True)
    x = rand(0, (4, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(9), (4,), 0, 10)
    variables = plain.init(jax.random.PRNGKey(1), x, train=False)
    fvars = _rename_fused(variables)

    def loss(model, vars_, x):
        logits, updates = model.apply(
            vars_, x, train=True, mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(y, 10)
        return -(one_hot * jax.nn.log_softmax(logits)).sum(), updates

    (lp, up), gp = jax.value_and_grad(
        lambda v: loss(plain, {"params": v,
                               "batch_stats": variables["batch_stats"]}, x),
        has_aux=True)(variables["params"])
    (lf, uf), gf = jax.value_and_grad(
        lambda v: loss(fused, {"params": v,
                               "batch_stats": fvars["batch_stats"]}, x),
        has_aux=True)(fvars["params"])
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-4)
    flat_p = jax.tree_util.tree_leaves_with_path(_rename_fused(gp))
    flat_f = jax.tree_util.tree_leaves_with_path(gf)
    assert len(flat_p) == len(flat_f)
    for (kp, a), (kf, b) in zip(sorted(flat_p, key=lambda t: str(t[0])),
                                sorted(flat_f, key=lambda t: str(t[0]))):
        assert str(kp) == str(kf)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-3, rtol=5e-3, err_msg=str(kp))
    # Running stats advanced identically.
    sp = jax.tree_util.tree_leaves(_rename_fused(up["batch_stats"]))
    sf = jax.tree_util.tree_leaves(uf["batch_stats"])
    for a, b in zip(sp, sf):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4)


def test_fused_resnet_eval_path_uses_running_stats():
    from tony_tpu.models import get_model

    fused = get_model("resnet18-thin", dtype=jnp.float32, fused_bn=True,
                      bn_interpret=True)
    x = rand(0, (2, 32, 32, 3))
    variables = fused.init(jax.random.PRNGKey(1), x, train=False)
    out = fused.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(out)))
