"""History-plane + multi-tenant QoS legs (tony_tpu.serve.qos PR 18):
weighted-fair KV-block budget math, the tenant-isolation pin (an
aggressor burst leaves a victim tenant's token streams AND per-token
logits bitwise identical to an unloaded engine, with the aggressor —
never the victim — deferred or typed-rejected), the budgets-off path
byte-identical to an unarmed engine, the widened jhist vocabulary
(SERVE_WINDOW / TRAIN_STEP / self-verifying SCALE_DECISION) with
bounded rotation and the read-side rename-race fix, the tenants
heartbeat schema round trip, the ScalingPolicy queue-depth matrix
pinned unchanged next to the new SLO mode, exact decision replay from
the log, and the `tony history` conf-resolution fix + dashboards."""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu import events as ev
from tony_tpu.serve import scaling
from tony_tpu.serve.qos import QosPolicy, parse_tenants

pytestmark = pytest.mark.qos


# ---------------------------------------------------------------------------
# Tenant spec parsing + weighted-fair budget math (pure)
# ---------------------------------------------------------------------------

class TestParseTenants:
    def test_weighted_and_bare_names(self):
        assert parse_tenants("gold:3,silver:1") == {"gold": 3.0,
                                                    "silver": 1.0}
        assert parse_tenants("solo") == {"solo": 1.0}
        assert parse_tenants(" a :2 , b ") == {"a": 2.0, "b": 1.0}

    @pytest.mark.parametrize("spec", [
        "", " , ", ":3", "a:0", "a:-1", "a:nan", "a:x", "a:1,a:2"])
    def test_invalid_specs_raise(self, spec):
        with pytest.raises(ValueError):
            parse_tenants(spec)


class TestQosPolicy:
    def test_budget_is_weighted_fair_share(self):
        p = QosPolicy(classes=parse_tenants("gold:3,silver:1"))
        active = {"gold", "silver"}
        assert p.budget("gold", 64, active) == 48
        assert p.budget("silver", 64, active) == 16

    def test_work_conserving_idle_tenant_redistributes(self):
        p = QosPolicy(classes=parse_tenants("gold:3,silver:1"))
        # silver idle: gold's denominator is its own weight — full pool.
        assert p.budget("gold", 64, {"gold"}) == 64
        # budget() adds the asked-for tenant to the active set itself.
        assert p.budget("silver", 64, set()) == 64

    def test_floor_of_one_block(self):
        p = QosPolicy(classes={"big": 1000.0, "tiny": 1.0})
        assert p.budget("tiny", 4, {"big", "tiny"}) == 1

    def test_unknown_tenant_gets_default_weight(self):
        p = QosPolicy(classes={"gold": 3.0})
        assert p.weight("stranger") == 1.0
        assert p.budget("stranger", 64, {"gold", "stranger"}) == 16

    def test_from_conf_off_is_none(self):
        from tony_tpu.conf import TonyConfig

        assert QosPolicy.from_conf(TonyConfig()) is None

    def test_from_conf_round_trip(self):
        from tony_tpu.conf import (SERVE_QOS_MAX_QUEUE,
                                   SERVE_QOS_TENANTS, TonyConfig)

        conf = TonyConfig({SERVE_QOS_TENANTS: "gold:3,silver:1",
                           SERVE_QOS_MAX_QUEUE: "5"})
        p = QosPolicy.from_conf(conf)
        assert p.classes == {"gold": 3.0, "silver": 1.0}
        assert p.max_queue == 5

    def test_invalid_policy_raises(self):
        with pytest.raises(ValueError):
            QosPolicy(classes={"a": -1.0})
        with pytest.raises(ValueError):
            QosPolicy(classes={"a": 1.0}, max_queue=-1)


# ---------------------------------------------------------------------------
# ScalingPolicy: queue-depth matrix pinned unchanged + SLO mode + replay
# ---------------------------------------------------------------------------

def _pol(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    return scaling.ScalingPolicy(**kw)


class TestQueueDepthMatrixPinned:
    """The historical queue-depth decision matrix, verbatim — arming
    the history plane must not move a single verdict."""

    def test_hot_queue_scales_up(self):
        p = _pol(queue_high=8.0)
        assert scaling.decide(p, 2, [{"queue_depth": 9.0}], now=100.0) == 1

    def test_cold_queue_scales_down(self):
        p = _pol(queue_low=1.0)
        assert scaling.decide(p, 2, [{"queue_depth": 0.5}], now=100.0) == -1

    def test_p99_high_water_scales_up(self):
        p = _pol(p99_high_ms=200.0)
        assert scaling.decide(
            p, 2, [{"queue_depth": 0.0, "p99_ms": 500.0}], now=100.0) == 1

    def test_midband_holds(self):
        p = _pol(queue_high=8.0, queue_low=1.0)
        assert scaling.decide(p, 2, [{"queue_depth": 4.0}], now=100.0) == 0

    def test_repair_below_floor_ignores_cooldown(self):
        p = _pol(min_replicas=2, cooldown_s=30.0)
        assert scaling.decide(p, 0, [], now=100.0, last_action=99.0) == 2

    def test_cooldown_holds(self):
        p = _pol(cooldown_s=30.0)
        assert scaling.decide(p, 2, [{"queue_depth": 99.0}], now=100.0,
                              last_action=90.0) == 0

    def test_no_samples_holds(self):
        assert scaling.decide(_pol(), 2, [], now=100.0) == 0

    def test_ceiling_and_floor_clamp(self):
        p = _pol(max_replicas=2)
        assert scaling.decide(p, 2, [{"queue_depth": 99.0}], now=100.0) == 0
        assert scaling.decide(p, 1, [{"queue_depth": 0.0}], now=100.0) == 0


class TestSloMode:
    def test_p99_over_target_scales_up(self):
        p = _pol(slo_target_ms=100.0)
        assert scaling.decide(
            p, 2, [{"p99_ms": 150.0, "queue_depth": 0.0}], now=100.0) == 1

    def test_deep_queues_alone_do_not_scale_in_slo_mode(self):
        # SLO mode acts on the latency promise, not raw queue depth.
        p = _pol(slo_target_ms=100.0, queue_high=8.0)
        assert scaling.decide(
            p, 2, [{"p99_ms": 50.0, "queue_depth": 99.0}], now=100.0) == 0

    def test_cold_needs_latency_headroom_and_idle_queue(self):
        p = _pol(slo_target_ms=100.0, queue_low=1.0)
        assert scaling.decide(
            p, 2, [{"p99_ms": 20.0, "queue_depth": 0.0}], now=100.0) == -1
        # An empty window reads p99=0 — queue depth gates the retreat.
        assert scaling.decide(
            p, 2, [{"p99_ms": 20.0, "queue_depth": 5.0}], now=100.0) == 0
        # Halfway to target is not headroom.
        assert scaling.decide(
            p, 2, [{"p99_ms": 80.0, "queue_depth": 0.0}], now=100.0) == 0

    def test_worst_replica_sets_the_verdict(self):
        p = _pol(slo_target_ms=100.0)
        samples = [{"p99_ms": 10.0, "queue_depth": 0.0},
                   {"p99_ms": 300.0, "queue_depth": 0.0}]
        assert scaling.decide(p, 2, samples, now=100.0) == 1

    def test_negative_target_rejected(self):
        with pytest.raises(ValueError):
            _pol(slo_target_ms=-1.0)

    def test_from_conf_reads_target(self):
        from tony_tpu.conf import SERVE_SLO_TARGET_MS, TonyConfig

        conf = TonyConfig({SERVE_SLO_TARGET_MS: "250",
                           "tony.serve.replicas.max": "4"})
        p = scaling.ScalingPolicy.from_conf(conf, 1)
        assert p.slo_target_ms == 250.0


class TestReplayDecisions:
    def _record(self, policy, n_active, samples, now, last_action):
        delta = scaling.decide(policy, n_active, samples, now=now,
                               last_action=last_action)
        return {"job_type": "serve", "delta": delta, "n_active": n_active,
                "samples": samples, "now": now,
                "last_action": last_action,
                "policy": __import__("dataclasses").asdict(policy)}

    def test_replay_reproduces_live_decisions_exactly(self):
        p = _pol(slo_target_ms=100.0, cooldown_s=30.0)
        recs = [
            self._record(p, 1, [{"p99_ms": 500.1234, "queue_depth": 2.0}],
                         17.125, None),
            self._record(p, 2, [{"p99_ms": 3.0, "queue_depth": 0.25}],
                         99.5, 60.0),
            self._record(p, 2, [{"p99_ms": 5000.0, "queue_depth": 9.0}],
                         61.0, 60.0),   # cooldown hold
        ]
        # The wire is JSON: the replay must survive the round trip
        # bit-exactly (floats round-trip through json by contract).
        recs = json.loads(json.dumps(recs))
        verdicts = scaling.replay_decisions(recs)
        assert [v["logged"] for v in verdicts] == [1, -1, 0]
        assert all(v["match"] for v in verdicts)

    def test_tampered_record_is_flagged_not_hidden(self):
        p = _pol(slo_target_ms=100.0)
        rec = self._record(p, 1, [{"p99_ms": 500.0, "queue_depth": 0.0}],
                           10.0, None)
        rec["delta"] = 0    # the log stopped carrying the true inputs
        v = scaling.replay_decisions([rec])[0]
        assert not v["match"] and v["replayed"] == 1


# ---------------------------------------------------------------------------
# Events plane: new vocabulary, bounded rotation, rename-race fix
# ---------------------------------------------------------------------------

class TestEventVocabulary:
    def test_serve_window_records_stats_verbatim(self, tmp_path):
        h = ev.EventHandler(tmp_path, "app_w")
        stats = {"qps": 2.0, "p99_ms": 12.5, "queue_depth": 1.0,
                 "admission_rejections": 3.0, "qos_deferrals": 1.0,
                 "tenants": {"gold": {"p99_ms": 12.5, "qps": 1.5}}}
        h.serve_window("serve", 0, stats)
        h.close()
        recs = [r for r in ev.read_events(h.finished_path)
                if r["type"] == ev.SERVE_WINDOW]
        assert len(recs) == 1
        assert recs[0]["payload"]["job_type"] == "serve"
        assert recs[0]["payload"]["stats"] == stats

    def test_train_step_record(self, tmp_path):
        h = ev.EventHandler(tmp_path, "app_t")
        h.train_step("worker", 1, step=7, step_time_s=0.125,
                     collective_bytes=4096.0, mfu=0.41)
        h.close()
        p = [r["payload"] for r in ev.read_events(h.finished_path)
             if r["type"] == ev.TRAIN_STEP][0]
        assert p == {"job_type": "worker", "index": 1, "step": 7,
                     "step_time_s": 0.125, "collective_bytes": 4096.0,
                     "mfu": 0.41}

    def test_scale_decision_carries_complete_decide_input(self, tmp_path):
        import dataclasses

        pol = _pol(slo_target_ms=100.0)
        samples = [{"p99_ms": 500.0, "queue_depth": 2.0}]
        delta = scaling.decide(pol, 1, samples, now=10.0, last_action=None)
        h = ev.EventHandler(tmp_path, "app_s")
        h.scale_decision("serve", delta, 1, samples, 10.0, None,
                         dataclasses.asdict(pol))
        h.close()
        payloads = [r["payload"] for r in ev.read_events(h.finished_path)
                    if r["type"] == ev.SCALE_DECISION]
        verdicts = scaling.replay_decisions(payloads)
        assert verdicts == [{"job_type": "serve", "logged": 1,
                             "replayed": 1, "match": True}]


class TestRotation:
    def test_log_stays_bounded_and_keeps_lifecycle(self, tmp_path):
        h = ev.EventHandler(tmp_path, "app_r", app_name="rot",
                            max_bytes=8192)
        h.application_inited(1, 2)
        import dataclasses
        h.scale_decision("serve", 1, 1, [{"p99_ms": 1.0}], 5.0, None,
                         dataclasses.asdict(_pol()))
        for i in range(500):
            h.serve_window("serve", 0, {"qps": float(i), "p99_ms": 1.0,
                                        "pad": "x" * 64})
        assert h.rotations > 0
        assert h.inprogress_path.stat().st_size <= 2 * 8192
        recs = ev.read_events(h.inprogress_path)
        types = [r["type"] for r in recs]
        # METADATA survives as line one (job_metadata still resolves),
        # lifecycle + SCALE_DECISION records survive whole, and the
        # high-rate tail keeps its NEWEST windows.
        assert ev.job_metadata(h.inprogress_path)["app_name"] == "rot"
        assert ev.APPLICATION_INITED in types
        assert ev.SCALE_DECISION in types
        windows = [r["payload"]["stats"]["qps"] for r in recs
                   if r["type"] == ev.SERVE_WINDOW]
        assert windows and windows[-1] == 499.0
        assert windows == sorted(windows)
        # The writer stays live across rotations.
        h.application_finished("SUCCEEDED")
        h.close()
        assert ev.read_events(h.finished_path)[-1]["type"] == \
            ev.APPLICATION_FINISHED


class TestRenameRace:
    def test_read_events_follows_finish_rename(self, tmp_path):
        h = ev.EventHandler(tmp_path, "app_race")
        h.application_inited(1, 1)
        stale = Path(h.inprogress_path)
        assert ev.read_events(stale)          # prime the parse cache
        h.application_finished("SUCCEEDED")
        h.close()                             # inprogress → finished
        assert not stale.exists()
        recs = ev.read_events(stale)          # the regression: raised
        assert [r["type"] for r in recs][-1] == ev.APPLICATION_FINISHED

    def test_job_metadata_follows_finish_rename(self, tmp_path):
        h = ev.EventHandler(tmp_path, "app_race2", app_name="meta")
        stale = Path(h.inprogress_path)
        h.close()
        assert ev.job_metadata(stale)["app_name"] == "meta"

    def test_truly_missing_file_still_raises(self, tmp_path):
        with pytest.raises(OSError):
            ev.read_events(tmp_path / "intermediate"
                           / "ghost.jhist.inprogress")


class TestParseCache:
    def test_cached_reads_are_isolated_copies(self, tmp_path):
        h = ev.EventHandler(tmp_path, "app_c")
        h.application_inited(1, 1)
        h.close()
        first = ev.read_events(h.finished_path)
        first.append({"type": "FORGED", "timestamp": 0, "payload": {}})
        second = ev.read_events(h.finished_path)
        assert [r["type"] for r in second
                if r["type"] != "METADATA"] == [ev.APPLICATION_INITED]


@pytest.mark.slow
class TestEventsConcurrency:
    def test_writer_vs_concurrent_readers(self, tmp_path):
        """One writer appending serve windows while reader threads hammer
        read_events/list_jobs through the close() rename — every read
        returns a clean prefix (no torn/partial records), and the
        post-rename reads land on the finished sibling."""
        h = ev.EventHandler(tmp_path, "app_mt")
        h.application_inited(1, 1)
        path = Path(h.inprogress_path)
        stop = threading.Event()
        failures: list = []

        def reader():
            while not stop.is_set():
                try:
                    recs = ev.read_events(path)
                    for r in recs:
                        assert "type" in r and "payload" in r
                    list(ev.list_jobs(tmp_path))
                except Exception as e:   # noqa: BLE001 — collected
                    failures.append(repr(e))
                    return

        threads = [threading.Thread(target=reader, name=f"qos-reader-{i}")
                   for i in range(3)]
        for t in threads:
            t.start()
        try:
            for i in range(200):
                h.serve_window("serve", 0, {"qps": float(i)})
            h.application_finished("SUCCEEDED")
            h.close()
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                time.sleep(0.01)        # readers race the rename window
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
        assert not failures, failures
        recs = ev.read_events(path)     # stale path → finished sibling
        assert recs[-1]["type"] == ev.APPLICATION_FINISHED


# ---------------------------------------------------------------------------
# Heartbeat schema: tenants breakdown round trip stats-file → session
# ---------------------------------------------------------------------------

class TestTelemetrySchema:
    STATS = {"qps": 1.5, "p99_ms": 20.0, "queue_depth": 2.0,
             "admission_rejections": 4.0, "qos_deferrals": 1.0,
             "tenants": {"gold": {"qps": 1.0, "p99_ms": 20.0,
                                  "queued": 0.0, "blocks": 6.0,
                                  "completed": 9.0,
                                  "tokens_per_s": 12.0}}}

    def test_normalize_keeps_tenants_nesting(self):
        from tony_tpu.util import normalize_serve_telemetry

        out = normalize_serve_telemetry(self.STATS)
        assert out["tenants"]["gold"]["p99_ms"] == 20.0
        assert isinstance(out["tenants"], dict)

    def test_deeper_nesting_rejected(self):
        from tony_tpu.util import normalize_serve_telemetry

        with pytest.raises(TypeError):
            normalize_serve_telemetry(
                {"tenants": {"g": {"sub": {"deeper": 1.0}}}})

    def test_stats_file_to_session_round_trip(self, tmp_path):
        from tony_tpu.conf import TonyConfig
        from tony_tpu.executor import read_serve_stats
        from tony_tpu.session import TonySession

        path = tmp_path / "stats.json"
        tmp = tmp_path / "stats.json.tmp"
        tmp.write_text(json.dumps(self.STATS))
        tmp.rename(path)
        norm = read_serve_stats(path)
        assert norm is not None
        s = TonySession(TonyConfig({"tony.serve.instances": "1"}),
                        app_id="app_1_0001")
        s.on_registered("serve", 0, "127.0.0.1", 4000)
        s.on_heartbeat("serve", 0, serve=norm)
        samples = s.serve_samples("serve")
        assert len(samples) == 1
        assert samples[0]["tenants"]["gold"]["completed"] == 9.0
        assert samples[0]["admission_rejections"] == 4.0


# ---------------------------------------------------------------------------
# AM emission: heartbeat dicts → jhist with per-task dedup
# ---------------------------------------------------------------------------

class TestAmEmission:
    def _fake_am(self, tmp_path):
        import types

        from tony_tpu.am import ApplicationMaster

        fake = types.SimpleNamespace(
            events=ev.EventHandler(tmp_path, "app_am"))
        fake._log_history_events = types.MethodType(
            ApplicationMaster._log_history_events, fake)
        return fake

    def _session(self):
        from tony_tpu.conf import TonyConfig
        from tony_tpu.session import TonySession

        s = TonySession(TonyConfig({"tony.serve.instances": "1",
                                    "tony.worker.instances": "1"}),
                        app_id="app_1_0001")
        for t in s.tasks():
            s.on_registered(t.job_type, t.index, "127.0.0.1", 4000)
        return s

    def test_serve_and_train_windows_logged_with_dedup(self, tmp_path):
        fake = self._fake_am(tmp_path)
        s = self._session()
        s.on_heartbeat("serve", 0, serve={"qps": 1.0, "p99_ms": 5.0})
        s.on_heartbeat("worker", 0, serve={"step": 3.0,
                                           "step_time_s": 0.2,
                                           "collective_bytes": 64.0,
                                           "mfu": 0.5})
        fake._log_history_events(s)
        fake._log_history_events(s)      # identical tick: appends nothing
        s.on_heartbeat("serve", 0, serve={"qps": 2.0, "p99_ms": 6.0})
        fake._log_history_events(s)
        fake.events.close()
        recs = ev.read_events(fake.events.finished_path)
        windows = [r["payload"] for r in recs
                   if r["type"] == ev.SERVE_WINDOW]
        steps = [r["payload"] for r in recs if r["type"] == ev.TRAIN_STEP]
        assert [w["stats"]["qps"] for w in windows] == [1.0, 2.0]
        assert steps == [{"job_type": "worker", "index": 0, "step": 3,
                          "step_time_s": 0.2, "collective_bytes": 64.0,
                          "mfu": 0.5}]


# ---------------------------------------------------------------------------
# tony history: conf-resolved roots + the dashboards
# ---------------------------------------------------------------------------

class TestHistoryRoots:
    def test_workdir_scan_honors_history_location_conf(
            self, tmp_path, monkeypatch):
        from tony_tpu import constants, history

        workdir = tmp_path / "jobs"
        redirect = tmp_path / "shared-history"
        jobdir = workdir / "app_redir_0001"
        jobdir.mkdir(parents=True)
        (jobdir / constants.TONY_JOB_JSON).write_text(json.dumps(
            {"tony.history.location": str(redirect)}))
        h = ev.EventHandler(redirect, "app_redir_0001", app_name="redir")
        h.application_finished("SUCCEEDED")
        h.close()
        monkeypatch.setenv("TONY_WORK_DIR", str(workdir))
        jobs = history.gather_jobs(None)
        assert [j["app_id"] for j in jobs] == ["app_redir_0001"]
        # The conventional fallback still works next to it.
        jobdir2 = workdir / "app_conv_0001"
        h2 = ev.EventHandler(jobdir2 / "history", "app_conv_0001")
        h2.close()
        assert sorted(j["app_id"] for j in history.gather_jobs(None)) == [
            "app_conv_0001", "app_redir_0001"]

    def test_shared_root_not_double_listed(self, tmp_path, monkeypatch):
        from tony_tpu import constants, history

        workdir = tmp_path / "jobs"
        shared = tmp_path / "shared"
        for app in ("app_a_0001", "app_b_0001"):
            jobdir = workdir / app
            jobdir.mkdir(parents=True)
            (jobdir / constants.TONY_JOB_JSON).write_text(json.dumps(
                {"tony.history.location": str(shared)}))
            h = ev.EventHandler(shared, app)
            h.close()
        monkeypatch.setenv("TONY_WORK_DIR", str(workdir))
        jobs = history.gather_jobs(None)
        assert sorted(j["app_id"] for j in jobs) == ["app_a_0001",
                                                     "app_b_0001"]


class TestHistoryDashboards:
    def _job(self, tmp_path):
        import dataclasses

        from tony_tpu import history

        h = ev.EventHandler(tmp_path, "app_dash_0001", app_name="dash")
        h.application_inited(1, 2)
        h.serve_window("serve", 0, {
            "qps": 3.0, "p99_ms": 40.0, "queue_depth": 1.0,
            "admission_rejections": 2.0, "qos_deferrals": 5.0,
            "tenants": {"gold": {"qps": 2.0, "p99_ms": 40.0,
                                 "tokens_per_s": 16.0, "queued": 1.0,
                                 "blocks": 8.0, "completed": 11.0},
                        "silver": {"qps": 1.0, "p99_ms": 9.0,
                                   "tokens_per_s": 4.0, "queued": 0.0,
                                   "blocks": 2.0, "completed": 3.0}}})
        h.train_step("worker", 0, step=5, step_time_s=0.25,
                     collective_bytes=1024.0, mfu=0.33)
        pol = _pol(slo_target_ms=100.0)
        samples = [{"p99_ms": 500.0, "queue_depth": 2.0}]
        delta = scaling.decide(pol, 1, samples, now=10.0,
                               last_action=None)
        h.scale_decision("serve", delta, 1, samples, 10.0, None,
                         dataclasses.asdict(pol))
        h.application_finished("SUCCEEDED")
        h.close()
        (job,) = history.gather_jobs(tmp_path)
        return history.job_detail(job)

    def test_detail_builds_dashboards_from_the_log_alone(self, tmp_path):
        detail = self._job(tmp_path)
        assert detail["tenant_slo"]["gold"]["p99_ms"] == 40.0
        assert detail["tenant_slo"]["gold"]["completed"] == 11.0
        assert detail["tenant_slo"]["silver"]["qps"] == 1.0
        assert detail["train_steps"]["worker:0"][0]["mfu"] == 0.33
        assert detail["serve_windows"]["serve:0"][0][
            "admission_rejections"] == 2.0
        assert detail["scale_replay"] == [
            {"job_type": "serve", "logged": 1, "replayed": 1,
             "match": True}]

    def test_render_show_and_portal_page(self, tmp_path):
        from tony_tpu import history

        detail = self._job(tmp_path)
        text = history.render_show(detail)
        assert "tenant SLO" in text
        assert "gold" in text and "silver" in text
        assert "replay exactly" in text and "1/1" in text
        assert "mfu=0.330" in text
        page = history._job_page(detail)
        assert "Tenant SLO dashboard" in page
        assert "Autoscale decisions" in page
        assert "match" in page and "mismatch" not in page
        assert "Train step trend" in page


# ---------------------------------------------------------------------------
# Engine-level QoS: budgets, back-pressure, and the isolation pins
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


def _gold_silver(max_queue=0):
    return QosPolicy(classes=parse_tenants("gold:3,silver:1"),
                     max_queue=max_queue)


@pytest.mark.slow
class TestTenantIsolation:
    def test_aggressor_burst_leaves_victim_bitwise_unchanged(self, tiny):
        """THE acceptance pin: the victim tenant's token streams and
        per-token logits on a QoS engine under an aggressor prefill
        burst are bitwise identical to the same requests on an UNLOADED
        engine — and the throttling lands on the aggressor (deferrals),
        never the victim."""
        from tony_tpu.serve import Request

        rng = np.random.RandomState(3)
        victims = [list(rng.randint(0, 256, n)) for n in (7, 9, 15)]

        ref = make_engine(tiny)
        for i, p in enumerate(victims):
            ref.submit(Request(rid=f"v{i}", tokens=p, max_new_tokens=4))
        ref_done = {c.rid: c for c in ref.run()}

        qos = QosPolicy(classes={"victim": 1.0, "aggr": 1.0})
        eng = make_engine(tiny, qos=qos)
        # Aggressor burst FIRST: enough long prefills to swallow the
        # whole pool were budgets off.
        aggr = [list(rng.randint(0, 256, 30)) for _ in range(6)]
        for i, p in enumerate(aggr):
            eng.submit(Request(rid=f"a{i}", tokens=p, max_new_tokens=8,
                               tenant="aggr"))
        for i, p in enumerate(victims):
            eng.submit(Request(rid=f"v{i}", tokens=p, max_new_tokens=4,
                               tenant="victim"))
        done = {c.rid: c for c in eng.run()}
        assert sorted(done) == sorted(
            [f"a{i}" for i in range(len(aggr))]
            + [f"v{i}" for i in range(len(victims))])
        for i in range(len(victims)):
            got, want = done[f"v{i}"], ref_done[f"v{i}"]
            assert got.tokens == want.tokens
            assert len(got.logits) == len(want.logits)
            for a, b in zip(got.logits, want.logits):
                assert np.array_equal(a, b)
        st = eng.stats()
        # The budget deferred the aggressor at least once; the victim
        # was never rejected (rejections need a queue cap).
        assert st["qos_deferrals"] > 0
        assert st["admission_rejections"] == 0.0
        assert st["tenants"]["victim"]["completed"] == float(len(victims))

    def test_queue_cap_rejects_aggressor_with_typed_backpressure(
            self, tiny):
        from tony_tpu.serve import AdmissionError, Request

        eng = make_engine(tiny, qos=_gold_silver(max_queue=2))
        for i in range(2):
            eng.submit(Request(rid=f"a{i}", tokens=[1, 2, 3],
                               max_new_tokens=2, tenant="gold"))
        with pytest.raises(AdmissionError) as exc:
            eng.submit(Request(rid="a2", tokens=[1, 2, 3],
                               max_new_tokens=2, tenant="gold"))
        assert exc.value.retryable
        assert "gold" in str(exc.value)
        # The OTHER tenant's lane is open — the cap is per tenant.
        eng.submit(Request(rid="s0", tokens=[4, 5], max_new_tokens=2,
                           tenant="silver"))
        done = eng.run()
        assert sorted(str(c.rid) for c in done) == ["a0", "a1", "s0"]
        assert eng.stats()["admission_rejections"] == 1.0

    def test_budgets_off_is_byte_identical_to_unarmed_engine(self, tiny):
        """qos=None with tagged requests AND a qos engine with untagged
        requests both reproduce the unarmed engine bit-for-bit."""
        from tony_tpu.serve import Request

        rng = np.random.RandomState(4)
        prompts = [list(rng.randint(0, 256, n)) for n in (5, 11, 17)]

        def run(qos=None, tenant=None):
            eng = make_engine(tiny, qos=qos)
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=i, tokens=p, max_new_tokens=3,
                                   tenant=tenant))
            out = {c.rid: c for c in eng.run()}
            return eng, out

        _, ref = run()
        _, tagged_no_qos = run(tenant="gold")
        armed_eng, untagged_qos = run(qos=_gold_silver())
        for variant in (tagged_no_qos, untagged_qos):
            for rid, want in ref.items():
                assert variant[rid].tokens == want.tokens
                for a, b in zip(variant[rid].logits, want.logits):
                    assert np.array_equal(a, b)
        st = armed_eng.stats()
        assert st["qos_deferrals"] == 0.0 and st["tenants"] == {}

    def test_tenant_accounting_drains_to_zero(self, tiny):
        from tony_tpu.serve import Request

        eng = make_engine(tiny, qos=_gold_silver())
        rng = np.random.RandomState(5)
        for i in range(3):
            eng.submit(Request(rid=i, tokens=list(rng.randint(0, 256, 9)),
                               max_new_tokens=3,
                               tenant="gold" if i % 2 else "silver"))
        eng.run()
        assert eng._tenant_blocks == {}
        assert eng.cache.free_blocks == eng.cache.n_blocks
        st = eng.stats()
        assert st["tenants"]["gold"]["blocks"] == 0.0
        assert st["tenants"]["gold"]["completed"] \
            + st["tenants"]["silver"]["completed"] == 3.0
