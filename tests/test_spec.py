"""Speculative decoding lane (tony_tpu.serve.spec): paged-cache
speculative reservation/rollback invariants (block-table truncation,
write cursor, LIFO reuse, leak-free pool accounting under randomized
accept/reject), the n-gram draft lane, the BITWISE greedy-parity pin
against the non-speculative PR 10 engine (token streams AND per-token
logits, overlapping/ragged/block-boundary request mixes, n-gram and
model-draft lanes), the tokens_per_forward / acceptance-rate heartbeat
fields through the executor round trip, the seventh `tony analyze`
config, and the replica construction path."""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.spec


@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


ENGINE_KW = dict(ctx_max=64, block_size=8, q_block=16,
                 decode_buckets=(2, 4), max_running=4, keep_logits=True)


def make_plain(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    return ServeEngine(model, params, **{**ENGINE_KW, **kw})


def make_spec(tiny, **kw):
    from tony_tpu.serve import SpecEngine

    model, params = tiny
    return SpecEngine(model, params, **{**ENGINE_KW, **kw})


def drive_overlapping(eng, prompts, new_tokens):
    """The shared overlapping-arrival schedule both engines run for the
    parity pin: r0 alone for a step, then r1/r2 join mid-flight, then
    r3 late."""
    from tony_tpu.serve import Request

    done = []
    eng.submit(Request(rid="r0", tokens=prompts[0],
                       max_new_tokens=new_tokens[0]))
    done += eng.step()
    for i in (1, 2):
        eng.submit(Request(rid=f"r{i}", tokens=prompts[i],
                           max_new_tokens=new_tokens[i]))
    done += eng.step()
    eng.submit(Request(rid="r3", tokens=prompts[3],
                       max_new_tokens=new_tokens[3]))
    done += eng.run()
    return {c.rid: c for c in done}


def assert_bitwise_equal(base, spec):
    assert sorted(base) == sorted(spec)
    for rid in base:
        assert base[rid].tokens == spec[rid].tokens, (
            f"{rid}: token streams diverge")
        assert len(base[rid].logits) == len(spec[rid].logits)
        for j, (a, b) in enumerate(zip(base[rid].logits,
                                       spec[rid].logits)):
            assert np.array_equal(a, b), (
                f"{rid}: logits at generated position {j} differ "
                f"(max abs diff {np.max(np.abs(a - b))})")


# ---------------------------------------------------------------------------
# Paged-cache speculative reservation / rollback
# ---------------------------------------------------------------------------

class TestSpecCache:
    def _cache(self, n_blocks=8, block_size=4):
        from tony_tpu.serve import PagedKVCache

        return PagedKVCache(2, 8, n_blocks=n_blocks, block_size=block_size)

    def test_reserve_reject_rollback_invariants(self):
        c = self._cache()
        c.reserve("s", 6)                  # 2 permanent blocks
        c.commit("s", 6)
        assert c.committed_len("s") == 6
        table_before = c.table("s")
        free_before = c.free_blocks
        # Speculative extension across a block boundary: +2 blocks.
        c.spec_reserve("s", 14)
        assert len(c.table("s")) == 4
        assert c.free_blocks == free_before - 2
        spec_blocks = c.table("s")[2:]
        # Rejection: table truncates back to the committed extent, the
        # extension returns to the pool, cursor untouched.
        assert c.rollback("s") == 2
        assert c.table("s") == table_before
        assert c.free_blocks == free_before
        assert c.committed_len("s") == 6
        # LIFO reuse: rollback returns the extension in reverse
        # allocation order, so re-reserving hands back the SAME blocks
        # in the SAME order — rollback-then-redo reproduces the table.
        again = c.spec_reserve("s", 14)[2:]
        assert again == spec_blocks
        c.rollback("s")

    def test_commit_promotes_covering_blocks(self):
        c = self._cache()
        c.reserve("s", 4)                  # 1 permanent block
        c.spec_reserve("s", 12)            # +2 speculative
        # Accept through position 6: the first speculative block is now
        # load-bearing and must survive the rollback.
        c.commit("s", 7)
        freed = c.rollback("s")
        assert freed == 1
        assert len(c.table("s")) == 2
        assert c.committed_len("s") == 7
        # The cursor never moves backwards.
        c.commit("s", 5)
        assert c.committed_len("s") == 7

    def test_spec_exhaustion_typed_and_state_unchanged(self):
        from tony_tpu.serve import AdmissionError

        c = self._cache(n_blocks=4, block_size=4)
        c.reserve("a", 12)                 # 3 of 4
        free = c.free_blocks
        with pytest.raises(AdmissionError) as exc:
            c.spec_reserve("a", 24)        # needs 3 more, 1 free
        assert exc.value.retryable
        assert c.free_blocks == free and len(c.table("a")) == 3

    def test_permanent_reserve_refuses_interleaving(self):
        c = self._cache()
        c.spec_reserve("s", 4)
        with pytest.raises(ValueError, match="speculative extension"):
            c.reserve("s", 8)
        c.rollback("s")
        c.reserve("s", 8)                  # clean after rollback

    def test_free_seq_returns_speculative_tail(self):
        c = self._cache()
        c.reserve("s", 4)
        c.spec_reserve("s", 16)
        assert c.free_seq("s") == 4
        assert c.free_blocks == c.n_blocks
        assert c.committed_len("s") == 0   # bookkeeping fully cleared

    def test_randomized_accept_reject_never_leaks(self):
        """Pool accounting under a random interleave of reserve /
        spec_reserve / commit / rollback / free across sequences: free +
        owned always partitions the pool, tables stay disjoint, and a
        full drain returns every block."""
        rng = np.random.RandomState(7)
        c = self._cache(n_blocks=16, block_size=4)
        from tony_tpu.serve import AdmissionError

        live: dict = {}
        for _ in range(300):
            op = rng.randint(5)
            sid = int(rng.randint(6))
            try:
                if op == 0:
                    if not c._spec.get(sid):
                        c.reserve(sid, int(rng.randint(1, 24)))
                        live[sid] = True
                elif op == 1:
                    c.spec_reserve(sid, int(rng.randint(1, 32)))
                    live[sid] = True
                elif op == 2 and sid in live:
                    covered = len(c.table(sid)) * c.block_size
                    if covered:
                        c.commit(sid, int(rng.randint(0, covered + 1)))
                elif op == 3 and sid in live:
                    c.rollback(sid)
                elif op == 4 and sid in live:
                    c.free_seq(sid)
                    live.pop(sid)
            except AdmissionError:
                pass
            owned = c.owned_blocks()
            flat = [b for t in owned.values() for b in t]
            assert len(flat) == len(set(flat)), "tables overlap"
            assert len(flat) + c.free_blocks == c.n_blocks, "leak"
        for sid in list(live):
            c.free_seq(sid)
        assert c.free_blocks == c.n_blocks

    def test_rollback_then_regenerate_is_bit_identical(self, tiny):
        """The stale-bytes contract, end to end: run a request through
        the speculative engine (rejected drafts DID scatter rows into
        the pool before rolling back), then reuse the same engine for a
        fresh request that regenerates over those stale blocks — its
        logits must equal the never-speculated reference bitwise."""
        from tony_tpu.serve import Request

        eng = make_spec(tiny, spec_k=4)
        rng = np.random.RandomState(3)
        p1 = list(rng.randint(0, 256, 9))
        eng.submit(Request(rid="warm", tokens=p1, max_new_tokens=6))
        eng.run()
        # Second pass reuses rolled-back blocks (LIFO pool).
        p2 = list(rng.randint(0, 256, 11))
        eng.submit(Request(rid="re", tokens=p2, max_new_tokens=5))
        done = {c.rid: c for c in eng.run()}
        full = p2 + done["re"].tokens
        ref = eng.full_prefill_logits(full)
        for j, row in enumerate(done["re"].logits):
            assert np.array_equal(ref[len(p2) - 1 + j], row)


# ---------------------------------------------------------------------------
# N-gram draft lane
# ---------------------------------------------------------------------------

class TestNgramDraft:
    def test_prompt_lookup_continuation(self):
        from tony_tpu.serve import NgramDraft

        d = NgramDraft(max_n=3)

        class S:
            rid = "s1"
            tokens = [1, 2, 3, 9, 1, 2, 3]

        # Suffix (1,2,3) matched at the front -> continues with 9, then
        # the draft's own history extends the match.
        assert d.propose([S()], [3])[0] == [9, 1, 2]
        # The persistent index only ever holds REAL history: a second
        # round over unchanged tokens proposes identically (the round's
        # draft overlay died with it).
        assert d.propose([S()], [3])[0] == [9, 1, 2]
        d.evict(S())
        assert not d._index

    def test_repeat_last_fallback_and_validation(self):
        from tony_tpu.serve import NgramDraft

        d = NgramDraft(max_n=3)

        class S:
            rid = "s2"
            tokens = [5]

        assert d.propose([S()], [2])[0] == [5, 5]
        with pytest.raises(ValueError):
            NgramDraft(max_n=0)
        with pytest.raises(ValueError):
            NgramDraft(max_n=2, min_n=3)


# ---------------------------------------------------------------------------
# The bitwise greedy-parity pin
# ---------------------------------------------------------------------------

class TestGreedyParity:
    def test_ragged_lengths_bitwise_vs_plain_engine(self, tiny):
        """THE acceptance pin: the speculative engine's token streams
        and per-token logits equal the non-speculative engine's BITWISE,
        over prompt lengths crossing the KV block boundary (7/8/9) and
        the q-block boundary (15/17)."""
        from tony_tpu.serve import Request

        rng = np.random.RandomState(0)
        lengths = [7, 8, 9, 15, 17]
        prompts = [list(rng.randint(0, 256, n)) for n in lengths]

        def run(eng):
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=f"r{i}", tokens=p,
                                   max_new_tokens=6))
            return {c.rid: c for c in eng.run()}

        base = run(make_plain(tiny))
        spec_eng = make_spec(tiny, spec_k=4)
        spec = run(spec_eng)
        assert_bitwise_equal(base, spec)
        # Speculation actually engaged and the pool drained clean.
        assert spec_eng.spec_proposed > 0
        assert spec_eng.verify_launches > 0
        assert spec_eng.cache.free_blocks == spec_eng.cache.n_blocks

    def test_overlapping_joins_bitwise(self, tiny):
        """Mixed batches with variable per-iteration advance: requests
        joining mid-flight stay bit-transparent, exactly like decode."""
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, 256, n)) for n in (5, 11, 9, 20)]
        new = [6, 5, 3, 4]
        base = drive_overlapping(make_plain(tiny), prompts, new)
        spec = drive_overlapping(make_spec(tiny, spec_k=4), prompts, new)
        assert_bitwise_equal(base, spec)

    # Slow-marked variants: each builds fresh engines (fresh jit
    # families), and the tier-1 870 s budget is already tight at HEAD
    # (ROADMAP) — `make tier1-spec` is the lane's named gate and runs
    # them; the core ragged/overlapping bitwise pins above stay in the
    # 'not slow' selection.
    @pytest.mark.slow
    @pytest.mark.parametrize("k", [1, 4, 15])
    def test_depth_sweep_bitwise(self, tiny, k):
        """Every legal draft depth (1 .. q_block-1) preserves parity —
        including k=15 where the verify block has zero padding rows."""
        from tony_tpu.serve import Request

        rng = np.random.RandomState(2)
        prompts = [list(rng.randint(0, 256, n)) for n in (6, 13)]

        def run(eng):
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=i, tokens=p, max_new_tokens=7))
            return {c.rid: c for c in eng.run()}

        assert_bitwise_equal(run(make_plain(tiny)),
                             run(make_spec(tiny, spec_k=k)))

    @pytest.mark.slow
    def test_model_draft_same_params_fully_accepts(self, tiny):
        """Draft == target: every draft token matches the target's
        argmax, so acceptance is total, the draft cache's speculative
        extensions commit (never roll back), and parity still holds."""
        model, params = tiny
        rng = np.random.RandomState(4)
        prompts = [list(rng.randint(0, 256, n)) for n in (7, 10, 16, 9)]
        new = [6, 5, 4, 6]
        base = drive_overlapping(make_plain(tiny), prompts, new)
        eng = make_spec(tiny, spec_k=4, draft_model=model,
                        draft_params=params)
        spec = drive_overlapping(eng, prompts, new)
        assert_bitwise_equal(base, spec)
        assert eng.spec_accepted == eng.spec_proposed > 0
        assert eng.draft.forwards > 0
        # Both pools drain clean — the draft lane's lazy reservation and
        # commit/rollback cycling leaked nothing.
        assert eng.cache.free_blocks == eng.cache.n_blocks
        assert eng.draft.cache.free_blocks == eng.draft.cache.n_blocks

    @pytest.mark.slow
    def test_model_draft_different_params_partial_accept(self, tiny):
        """A draft that disagrees with the target (fresh init) still
        preserves parity — the accept/reject path, draft-cache rollback,
        and resync machinery all engage."""
        import flax.linen as nn

        from tony_tpu.models import get_model

        model, params = tiny
        draft_model = get_model("llama-tiny", n_layers=1)
        sample = jnp.zeros((1, 16), jnp.int32)
        draft_params = nn.unbox(draft_model.init(
            jax.random.PRNGKey(9), sample))["params"]
        draft_params = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, draft_params)
        rng = np.random.RandomState(5)
        prompts = [list(rng.randint(0, 256, n)) for n in (8, 12, 6, 15)]
        new = [6, 4, 6, 5]
        base = drive_overlapping(make_plain(tiny), prompts, new)
        eng = make_spec(tiny, spec_k=4, draft_model=draft_model,
                        draft_params=draft_params)
        spec = drive_overlapping(eng, prompts, new)
        assert_bitwise_equal(base, spec)
        assert eng.draft.cache.free_blocks == eng.draft.cache.n_blocks

    def test_draft_pool_pressure_degrades_never_wedges(self, tiny):
        """A draft pool too small for the batch must degrade per
        sequence (empty proposal = plain decode row that round) and
        retry — never leak an AdmissionError out of step() or wedge the
        draft cache with an uncommitted extension. Parity holds
        throughout: speculation depth is a performance knob, never a
        correctness one."""
        from tony_tpu.serve import Request
        from tony_tpu.serve.spec import ModelDraft

        model, params = tiny
        rng = np.random.RandomState(8)
        prompts = [list(rng.randint(0, 256, n)) for n in (9, 12, 7)]

        def run(eng):
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=i, tokens=p, max_new_tokens=5))
            return {c.rid: c for c in eng.run()}

        base = run(make_plain(tiny))
        # 3 blocks of 8 = 24 draft positions: one sequence syncs, the
        # rest see AdmissionError on sync or extension every round.
        draft = ModelDraft(model, params, ctx_max=64, block_size=8,
                           q_block=16, decode_buckets=(2, 4),
                           max_running=4, n_blocks=3)
        eng = make_spec(tiny, spec_k=4, draft=draft)
        spec = run(eng)
        assert_bitwise_equal(base, spec)
        # The draft pool survived the pressure cycles leak-free.
        assert draft.cache.free_blocks == draft.cache.n_blocks

    def test_spec_tokens_match_full_prefill_reference(self, tiny):
        """Transitivity check straight against the PR 10 reference: the
        speculative engine's logits are bitwise rows of a sequential
        full prefill (the same pin the plain engine carries)."""
        from tony_tpu.serve import Request

        eng = make_spec(tiny, spec_k=4)
        rng = np.random.RandomState(6)
        prompts = [list(rng.randint(0, 256, n)) for n in (7, 16)]
        for i, p in enumerate(prompts):
            eng.submit(Request(rid=i, tokens=p, max_new_tokens=5))
        for c in eng.run():
            full = list(c.prompt) + list(c.tokens)
            ref = eng.full_prefill_logits(full)
            p = len(c.prompt)
            for j, row in enumerate(c.logits):
                assert np.array_equal(ref[p - 1 + j], row)

    def test_validation_errors(self, tiny):
        model, params = tiny
        with pytest.raises(ValueError, match="spec_k"):
            make_spec(tiny, spec_k=0)
        with pytest.raises(ValueError, match="spec_k"):
            make_spec(tiny, spec_k=16)     # == q_block
        from tony_tpu.serve import NgramDraft

        with pytest.raises(ValueError, match="not both"):
            make_spec(tiny, spec_k=2, draft=NgramDraft(),
                      draft_model=model, draft_params=params)


# ---------------------------------------------------------------------------
# Telemetry: stats fields, heartbeat round trip, profiler records
# ---------------------------------------------------------------------------

class TestSpecTelemetry:
    def test_stats_fields_and_records(self, tiny):
        from tony_tpu import profiler
        from tony_tpu.serve import Request

        profiler.reset_serve_records()
        eng = make_spec(tiny, spec_k=3, tag="spec_test")
        eng.submit(Request(rid="r", tokens=[1, 2, 3, 1, 2, 3],
                           max_new_tokens=5))
        eng.run()
        stats = eng.stats()
        for key in ("tokens_per_forward", "acceptance_rate",
                    "spec_proposed", "spec_accepted", "verify_launches",
                    "draft_forwards", "tokens_per_verify",
                    "tokens_per_seq_round"):
            assert key in stats, key
        assert stats["verify_launches"] > 0
        assert stats["tokens_per_forward"] > 0
        # One launch per iteration emits >= 1 token per sequence.
        assert stats["tokens_per_seq_round"] >= 1.0
        report = profiler.serve_report()
        assert report["spec_test_spec"]["k"] == 3
        assert report["spec_test_spec"]["draft"] == "ngram"
        assert report["spec_test_stats"]["verify_launches"] == \
            stats["verify_launches"]
        # The plain engine publishes the same schema (zeros) so the
        # autoscaler sees one field set fleet-wide.
        plain = make_plain(tiny, keep_logits=False, tag="plain_test")
        pstats = plain.stats()
        assert pstats["acceptance_rate"] == 0.0
        assert "tokens_per_forward" in pstats
        profiler.reset_serve_records()

    def test_executor_heartbeat_carries_effective_throughput(
            self, tmp_path):
        """Executor round trip with the NEW fields: stats file →
        heartbeat RPC → session.serve_metrics — the autoscaler's input
        now sees tokens_per_forward / acceptance_rate."""
        from tony_tpu import constants
        from tony_tpu.conf import TonyConfig
        from tony_tpu.executor import TaskExecutor
        from tony_tpu.rpc import ApplicationRpcHandler, RpcServer
        from tony_tpu.session import TonySession

        conf = TonyConfig({"tony.serve.instances": "1",
                           "tony.serve.command": "x"})
        session = TonySession(conf, app_id="app_spec_hb")
        session.on_registered("serve", 0, "127.0.0.1", 4000)
        server = RpcServer(ApplicationRpcHandler(session),
                           host="127.0.0.1").start()
        conf_path = tmp_path / "conf.json"
        conf_path.write_text(json.dumps(dict(conf.items())))
        sample = {"qps": 2.0, "p99_ms": 9.0, "queue_depth": 1.0,
                  "tokens_per_forward": 3.4, "acceptance_rate": 0.8}
        try:
            executor = TaskExecutor(env={
                constants.ENV_JOB_NAME: "serve",
                constants.ENV_TASK_INDEX: "0",
                constants.ENV_AM_ADDRESS: server.address,
                constants.ENV_CONF_PATH: str(conf_path),
                constants.ENV_LOG_DIR: str(tmp_path),
            })
            executor.serve_stats_path().write_text(json.dumps(sample))
            t = threading.Thread(target=executor._heartbeat_loop,
                                 args=(0.05,), daemon=True)
            t.start()
            deadline = time.monotonic() + 10.0
            task = session.task("serve", 0)
            while time.monotonic() < deadline and not task.serve_metrics:
                time.sleep(0.05)
            executor._hb_stop.set()
            t.join(timeout=5)
            assert task.serve_metrics == sample
            assert session.serve_samples("serve") == [sample]
            # The scaling decision matrix is unchanged by the extra
            # fields: the same sample decides exactly as before.
            from tony_tpu.serve import scaling

            pol = scaling.ScalingPolicy(min_replicas=1, max_replicas=4)
            assert scaling.decide(pol, 2, [sample], now=0.0) == 0
            hot = dict(sample, queue_depth=12.0)
            assert scaling.decide(pol, 2, [hot], now=0.0) == 1
        finally:
            server.stop()

    def test_mutating_spec_report_does_not_poison_store(self):
        from tony_tpu import profiler

        profiler.reset_serve_records()
        profiler.safe_record("serve", "spec_t",
                             nested={"accept": [1, 0, 1]}, k=4)
        snap = profiler.serve_report()
        snap["spec_t"]["nested"]["accept"].append(9)
        snap["spec_t"]["poison"] = True
        clean = profiler.serve_report()
        assert clean["spec_t"]["nested"] == {"accept": [1, 0, 1]}
        assert "poison" not in clean["spec_t"]
        profiler.reset_serve_records()


# ---------------------------------------------------------------------------
# Static analysis: the seventh config
# ---------------------------------------------------------------------------

class TestAnalyzeSpec:
    def test_analyze_spec_config_clean_with_pin(self):
        """`tony analyze --config spec` is clean with zero waivers
        against the committed pin: zero inter-chip collectives in the
        verify program, KV pools donated (also covered by the
        test_analysis parametrization — this is the lane's named
        copy)."""
        from tony_tpu.analysis import cli as acli

        report = acli.run_config(
            "spec", signature_path=str(
                Path(__file__).parent / "signatures" / "spec.json"))
        assert report.ok, report.summary()
        assert not report.waived
        assert report.signature["collectives"] == {}
        assert report.config["plane"] == "serve_verify"
        assert report.config["spec_k"] == 4
        assert report.config["draft"] == "ngram"

    def test_unknown_step_rejected(self, tiny):
        # "prefill" joined the step family in PR 13 (the route config);
        # the reject path needs a genuinely unknown name.
        from tony_tpu import analysis

        eng = make_spec(tiny, spec_k=2)
        with pytest.raises(ValueError, match="unknown serve step"):
            analysis.analyze_serve_step(eng, step="sample")


# ---------------------------------------------------------------------------
# CLI + replica construction
# ---------------------------------------------------------------------------

class TestSpecControlPlane:
    def test_cli_serve_spec_flags(self, tmp_path):
        from tony_tpu import conf as conf_mod
        from tony_tpu.cli import make_parser

        args = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--ckpt_dir",
            str(tmp_path), "--spec_k", "4", "--draft_model",
            "llama-tiny", "--draft_model_kwargs", '{"n_layers": 1}'])
        assert args.spec_k == 4 and args.draft_model == "llama-tiny"
        # Bad flag combinations are rejected at SUBMIT time, not replica
        # launch: --draft_model without --spec_k, orphaned draft flags
        # (they would silently serve the n-gram lane), out-of-range k.
        for argv in (["--draft_model", "llama-tiny"],
                     ["--spec_k", "2", "--draft_ckpt_dir", str(tmp_path)],
                     ["--spec_k", "2", "--draft_model_kwargs", "{}"],
                     ["--spec_k", "16"],
                     ["--spec_k", "-1"]):
            bad = make_parser().parse_args(
                ["serve", "--model", "llama-tiny", "--ckpt_dir",
                 str(tmp_path)] + argv)
            with pytest.raises(SystemExit):
                bad.fn(bad)
        assert conf_mod.SERVE_SPEC_K == "tony.serve.spec-k"

    @pytest.mark.slow
    def test_replica_spec_engine_parity(self, tmp_path):
        """Train → ckpt → two replicas off the same save (plain and
        speculative with a model draft restored through the same elastic
        path) → identical greedy token streams."""
        import optax

        from tony_tpu import ckpt, train
        from tony_tpu.models import get_model
        from tony_tpu.serve import Request
        from tony_tpu.serve.replica import Replica
        from tony_tpu.serve.spec import SpecEngine

        model = get_model("llama-tiny", n_layers=2)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 256, (4, 16)), jnp.int32)
        state = train.create_train_state(
            model, optax.adamw(1e-3), tokens, jax.random.PRNGKey(0))
        step = train.make_train_step(
            loss_of=lambda logits, b: train.next_token_loss(
                logits, b["x"]), donate=False)
        state, _ = step(state, {"x": tokens})
        mgr = ckpt.AsyncCheckpointer(tmp_path / "ckpt")
        mgr.save(state, step=1)
        mgr.wait()
        mgr.close()

        common = dict(model_name="llama-tiny",
                      model_kwargs={"n_layers": 2},
                      ckpt_dir=str(tmp_path / "ckpt"),
                      dtype_policy="bf16", ctx_max=64, block_size=8,
                      q_block=16, max_running=4, keep_logits=False)
        plain = Replica(**common)
        spec = Replica(**common, spec_k=4, draft_model_name="llama-tiny",
                       draft_model_kwargs={"n_layers": 2}, tag="spec")
        assert isinstance(spec.engine, SpecEngine)
        assert spec.draft_restored_step == 1
        prompts = [[int(x) for x in rng.randint(0, 256, n)]
                   for n in (6, 11)]

        def run(replica):
            eng = replica.engine
            for i, p in enumerate(prompts):
                eng.submit(Request(rid=i, tokens=p, max_new_tokens=5))
            return {c.rid: c.tokens for c in eng.run()}

        base = run(plain)
        out = run(spec)
        assert base == out
        # Draft == target (same ckpt): total acceptance.
        assert spec.engine.spec_accepted == spec.engine.spec_proposed > 0
        # The heartbeat file a spec replica publishes carries the
        # effective-throughput fields end to end.
        stats_path = tmp_path / "stats.json"
        spec.engine.write_stats(str(stats_path))
        from tony_tpu.executor import read_serve_stats

        read = read_serve_stats(stats_path)
        assert read["acceptance_rate"] == 1.0
        assert read["tokens_per_seq_round"] > 1.0
