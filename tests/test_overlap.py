"""Overlap-engine tier (comm/compute overlap tentpole): the GradBuckets
planner, bucketed-accumulation numerics vs the monolithic step, the XLA
flag merge, the bench leg, and the profiler's plan records — on the virtual
8-device CPU mesh. The 1F1B-vs-GPipe numerical pins live in
test_pipeline.py next to the schedule they pin."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu import parallel as par
from tony_tpu import profiler, train
from tony_tpu.models import get_model
from tony_tpu.parallel import overlap
from tony_tpu.parallel.overlap import (DEFAULT_BUCKET_BYTES, GradBuckets,
                                       MULTISLICE_XLA_FLAGS,
                                       OVERLAP_XLA_FLAGS, microbatch_grads,
                                       overlap_xla_flags)


def _tree():
    k = jax.random.split(jax.random.PRNGKey(0), 4)
    return {
        "a": jax.random.normal(k[0], (128, 64)),
        "b": {"w": jax.random.normal(k[1], (256, 256)),
              "bias": jax.random.normal(k[2], (256,))},
        "c": jax.random.normal(k[3], (40,)),
    }


class TestGradBuckets:
    def test_partitions_every_leaf_exactly_once(self):
        tree = _tree()
        plan = GradBuckets.plan(tree, bucket_bytes=64 * 1024)
        seen = sorted(i for b in plan.buckets for i in b)
        assert seen == list(range(len(jax.tree.leaves(tree))))

    def test_respects_byte_threshold(self):
        plan = GradBuckets.plan(_tree(), bucket_bytes=64 * 1024)
        for idxs, nbytes in zip(plan.buckets, plan.bucket_nbytes):
            # A multi-leaf bucket must fit; only a single oversized leaf
            # may exceed (it has nowhere smaller to go).
            assert nbytes <= plan.threshold or len(idxs) == 1
        total = sum(l.size * l.dtype.itemsize
                    for l in jax.tree.leaves(_tree()))
        assert sum(plan.bucket_nbytes) == total

    def test_one_dtype_per_bucket(self):
        tree = dict(_tree(), ints=jnp.zeros((100,), jnp.int32))
        plan = GradBuckets.plan(tree, bucket_bytes=1 << 30)
        for idxs in plan.buckets:
            assert len({plan.dtypes[i] for i in idxs}) == 1

    def test_pack_unpack_roundtrip(self):
        tree = _tree()
        plan = GradBuckets.plan(tree, bucket_bytes=64 * 1024)
        out = plan.unpack(plan.pack(tree))
        for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(out)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_plan_under_eval_shape(self):
        abstract = jax.eval_shape(_tree)
        plan = GradBuckets.plan(abstract, bucket_bytes=64 * 1024)
        assert plan.n_buckets >= 1

    def test_rejects_nonpositive_threshold(self):
        with pytest.raises(ValueError, match="positive"):
            GradBuckets.plan(_tree(), bucket_bytes=0)

    def test_rejects_empty_pytree(self):
        """Satellite pin: an empty grad tree must fail at plan time with a
        clear message, not later inside pack/unpack with an opaque
        concatenate error."""
        for empty in ({}, [], {"a": {}}):
            with pytest.raises(ValueError, match="empty"):
                GradBuckets.plan(empty)

    def test_reduce_scatter_pads_group_indivisible_buckets(self):
        """Satellite pin: bucket payloads NOT divisible by the sync group
        (prime-ish leaf sizes) take the padding path and still match the
        per-leaf psum exactly."""
        from jax.sharding import PartitionSpec as P

        from tony_tpu.compat import shard_map

        k = jax.random.split(jax.random.PRNGKey(3), 3)
        tree = {"a": jax.random.normal(k[0], (37,)),
                "b": jax.random.normal(k[1], (13, 7)),
                "c": jax.random.normal(k[2], (5,))}
        mesh = par.make_mesh()
        axes = overlap.sync_axes(mesh)
        # Tiny threshold: several buckets, each needing its own padding.
        plan = GradBuckets.plan(tree, bucket_bytes=256)
        assert plan.n_buckets > 1
        assert any(n % 8 for n in plan.bucket_numel)
        specs = jax.tree.map(lambda _: P(), tree)

        def spmd(t):
            r = jax.lax.axis_index("data").astype(jnp.float32) + 1.0
            t = jax.tree.map(lambda l: l * r, t)
            want = jax.tree.map(lambda l: jax.lax.psum(l, axes), t)
            got = plan.reduce(t, axes, op="reduce_scatter", group_size=8)
            return want, got

        want, got = jax.jit(shard_map(
            spmd, mesh, in_specs=(specs,), out_specs=(specs, specs)))(tree)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)


class TestSyncAxes:
    """Satellite pins: the sync-group helpers on meshes that don't carry
    every DP axis (manual meshes from user code)."""

    def test_mesh_missing_fsdp(self):
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(4, 2), ("data", "model"))
        assert overlap.sync_axes(mesh) == ("data",)
        assert overlap.sync_size(mesh) == 4
        assert overlap.ici_axes(mesh) == ("data",)
        assert overlap.dcn_axis(mesh) is None

    def test_mesh_missing_data(self):
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(2, 4), ("fsdp", "model"))
        assert overlap.sync_axes(mesh) == ("fsdp",)
        assert overlap.sync_size(mesh) == 2

    def test_mesh_with_neither_dp_axis(self):
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()).reshape(8,), ("model",))
        assert overlap.sync_axes(mesh) == ()
        assert overlap.sync_size(mesh) == 1

    def test_slice_axis_in_sync_group_but_not_ici(self):
        mesh = par.make_mesh(slices=2)
        assert overlap.sync_axes(mesh) == ("slice", "data", "fsdp")
        assert overlap.sync_size(mesh) == 8
        assert overlap.ici_axes(mesh) == ("data", "fsdp")
        assert overlap.dcn_axis(mesh) == "slice"

    def test_single_slice_mesh_has_no_dcn(self):
        assert overlap.dcn_axis(par.make_mesh()) is None

    @pytest.mark.parametrize("op", ["all_reduce", "reduce_scatter"])
    def test_reduce_matches_tree_psum(self, op):
        """Per-bucket reduction must equal the monolithic per-leaf psum —
        for both the allreduce and the RS+AG split (padded buckets)."""
        from tony_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = par.make_mesh()
        axes = ("data", "fsdp")
        tree = _tree()
        plan = GradBuckets.plan(tree, bucket_bytes=64 * 1024)
        specs = jax.tree.map(lambda _: P(), tree)

        def spmd(t):
            # Give each replica distinct values so the sum is a real test.
            r = jax.lax.axis_index("data").astype(jnp.float32) + 1.0
            t = jax.tree.map(lambda l: l * r, t)
            want = jax.tree.map(lambda l: jax.lax.psum(l, axes), t)
            got = plan.reduce(t, axes, op=op, group_size=8)
            return want, got

        want, got = jax.jit(shard_map(
            spmd, mesh, in_specs=(specs,), out_specs=(specs, specs)))(tree)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)


def _mnist_setup(batch=32, hidden=64):
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, 784))
    y = jax.random.randint(ky, (batch,), 0, 10)
    state = train.create_train_state(model, optax.sgd(0.1), x, kr)
    return state, {"x": x, "y": y}


@pytest.mark.parametrize("op", ["all_reduce", "reduce_scatter"])
def test_accum_step_matches_monolithic(op):
    """THE acceptance pin: bucketed-accumulation loss/grad-norm/params must
    match the monolithic make_train_step within 1e-5 on the 8-device DP
    mesh."""
    mesh = par.make_mesh()
    state, batch = _mnist_setup()
    mono = train.make_train_step(mesh=mesh, donate=False)
    accum = train.make_accum_train_step(
        mesh=mesh, microbatches=4, bucket_bytes=32 * 1024, reduce_op=op,
        donate=False)
    s1, m1 = mono(state, batch)
    s2, m2 = accum(state, batch)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    assert abs(float(m1["grad_norm"]) - float(m2["grad_norm"])) < 1e-5
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_accum_step_trains():
    mesh = par.make_mesh()
    state, batch = _mnist_setup()
    step = train.make_accum_train_step(mesh=mesh, microbatches=4)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_accum_step_rejects_indivisible_batch():
    mesh = par.make_mesh()
    state, _ = _mnist_setup()
    bad = {"x": jnp.zeros((24, 784)), "y": jnp.zeros((24,), jnp.int32)}
    step = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                       donate=False)
    with pytest.raises(ValueError, match="24.*not divisible.*32"):
        step(state, bad)


def test_accum_step_requires_mesh():
    with pytest.raises(ValueError, match="mesh"):
        train.make_accum_train_step(microbatches=4)


def test_microbatch_grads_single_bucket_and_many():
    """Bucketing must not change grads: one giant bucket vs per-leaf-ish
    tiny buckets agree with each other."""
    mesh = par.make_mesh()
    state, batch = _mnist_setup()

    def loss_fn(params, mb):
        logits = state.apply_fn({"params": params}, mb["x"])
        return train.cross_entropy_loss(logits, mb["y"])

    def run(bucket_bytes):
        return microbatch_grads(loss_fn, state.params, batch, mesh,
                                microbatches=4, bucket_bytes=bucket_bytes)

    loss_a, grads_a = jax.jit(lambda: run(1 << 30))()
    loss_b, grads_b = jax.jit(lambda: run(1024))()
    assert abs(float(loss_a) - float(loss_b)) < 1e-6
    for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_accum_step_reduce_scatter_pads_odd_shapes():
    """Satellite pin: hidden=52 yields bias/logit leaves whose bucket
    payloads don't divide the 8-way sync group — the in-scan
    reduce_scatter padding path must still match the monolithic step."""
    mesh = par.make_mesh()
    state, batch = _mnist_setup(hidden=52)
    mono = train.make_train_step(mesh=mesh, donate=False)
    accum = train.make_accum_train_step(
        mesh=mesh, microbatches=4, bucket_bytes=1024,
        reduce_op="reduce_scatter", donate=False)
    s1, m1 = mono(state, batch)
    s2, m2 = accum(state, batch)
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    assert abs(float(m1["grad_norm"]) - float(m2["grad_norm"])) < 1e-5
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_hierarchical_requires_multislice_mesh():
    mesh = par.make_mesh()
    state, batch = _mnist_setup()
    step = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                       hierarchy="hierarchical",
                                       donate=False)
    with pytest.raises(ValueError, match="multi-slice"):
        step(state, batch)
    with pytest.raises(ValueError, match="hierarchy"):
        train.make_accum_train_step(mesh=mesh, microbatches=4,
                                    hierarchy="bogus",
                                    donate=False)(state, batch)


def _zero3_state(state, mesh):
    """Shard the MLP state into the ZeRO-3 layout on ``mesh``."""
    from tony_tpu.benchmark import fsdp_shard_state
    return fsdp_shard_state(state, mesh)


def test_zero3_accum_matches_replicated_and_monolithic():
    """THE ZeRO-3 acceptance pin: fsdp-sharded params auto-detected, grads
    psum_scatter-ed straight into the shard layout, loss/grad-norm/params
    match both the replicated accum step and the monolithic step within
    1e-5 — and the updated params STAY in the shard layout."""
    mesh = par.make_mesh(fsdp=4)           # data=2 x fsdp=4
    state, batch = _mnist_setup()
    mono = train.make_train_step(mesh=mesh, donate=False)
    s1, m1 = mono(state, batch)
    repl = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                       bucket_bytes=32 * 1024,
                                       donate=False)
    s2, m2 = repl(state, batch)
    zstate = _zero3_state(state, mesh)
    zstep = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                        bucket_bytes=32 * 1024,
                                        donate=False)
    s3, m3 = zstep(zstate, batch)
    for m in (m2, m3):
        assert abs(float(m1["loss"]) - float(m["loss"])) < 1e-5
        assert abs(float(m1["grad_norm"]) - float(m["grad_norm"])) < 1e-5
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s3.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    # Sharding inspection: every updated leaf kept its fsdp placement
    # (specs compared with trailing-None dims normalized away).
    def norm(spec):
        t = tuple(spec)
        while t and t[-1] is None:
            t = t[:-1]
        return t

    for old, new in zip(jax.tree.leaves(zstate.params),
                        jax.tree.leaves(s3.params)):
        assert norm(new.sharding.spec) == norm(old.sharding.spec)


def test_zero3_grads_never_leave_shard_layout():
    """Sharding inspection on the grads themselves: microbatch_grads with
    param_specs returns grads carrying the fsdp spec (scatter path), and
    the profiler records the scatter-bucket plan."""
    from jax.sharding import PartitionSpec as P

    mesh = par.make_mesh(fsdp=4)
    state, batch = _mnist_setup()
    zstate = _zero3_state(state, mesh)
    specs = overlap.fsdp_param_specs(zstate.params, mesh)
    assert specs is not None

    def loss_fn(params, mb):
        logits = zstate.apply_fn({"params": params}, mb["x"])
        return train.cross_entropy_loss(logits, mb["y"])

    profiler.reset_overlap_records()
    with jax.sharding.Mesh(mesh.devices, mesh.axis_names):
        loss, grads = jax.jit(lambda p, b: microbatch_grads(
            loss_fn, p, b, mesh, microbatches=4, bucket_bytes=32 * 1024,
            param_specs=specs))(zstate.params, batch)
    spec_leaves = jax.tree.leaves(specs,
                                  is_leaf=lambda x: isinstance(x, P))
    sharded = 0
    for g, spec in zip(jax.tree.leaves(grads), spec_leaves):
        if any("fsdp" in str(e) for e in tuple(spec)):
            assert "fsdp" in str(g.sharding.spec)
            sharded += 1
    assert sharded >= 4
    rec = profiler.overlap_report()["accum_step"]
    assert rec["zero3"] is True
    assert rec["n_scatter_buckets"] >= 1
    assert any(l["op"] == "psum_scatter" and l["axes"] == ["fsdp"]
               for l in rec["levels"])


class TestUnevenZero3:
    """ROADMAP follow-on: leaves whose sharded dim doesn't divide the fsdp
    axis used to raise in plan_sharded — now they pad into dedicated
    scatter buckets and unpad on the way out."""

    def _tree_specs(self):
        from jax.sharding import PartitionSpec as P

        k = jax.random.split(jax.random.PRNGKey(7), 3)
        params = {"w": jax.random.normal(k[0], (8, 16)),     # 8 % 4 == 0
                  "v": jax.random.normal(k[1], (6, 16)),     # 6 % 4 != 0
                  "b": jax.random.normal(k[2], (16,))}
        specs = {"w": P("fsdp"), "v": P("fsdp"), "b": P()}
        return params, specs

    def test_plan_pads_into_own_scatter_bucket(self):
        params, specs = self._tree_specs()
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=1 << 20)
        # b=replicated, v=padded scatter, w=even scatter — three buckets,
        # and the padded one is separate from the even one.
        assert plan.n_scatter_buckets == 2
        assert sum(plan.bucket_padded) == 1
        i_v = 1                                    # flatten order: b, v, w
        assert plan.shard_pads[i_v] == 2           # 6 → 8 rows
        assert plan.padded_shape(i_v) == (8, 16)
        assert plan.shard_shape(i_v) == (2, 16)
        # The padded extent rides the collective and is budgeted.
        [b_v] = [b for b in range(plan.n_buckets) if plan.bucket_padded[b]]
        assert plan.bucket_nbytes[b_v] == 8 * 16 * 4

    def test_pack_gathered_roundtrip(self):
        """pack (shard-major, zero-padded) → leaf_buffers(gathered) is the
        identity on the uneven leaf — the unpad really unpads."""
        params, specs = self._tree_specs()
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=1 << 20)
        bufs = plan.pack(params)
        leaves = jax.tree.leaves(params)
        for b in range(plan.n_buckets):
            if not plan.bucket_padded[b]:
                continue
            out = plan.leaf_buffers(b, bufs[b], layout="gathered")
            for i, v in out.items():
                np.testing.assert_array_equal(np.asarray(v),
                                              np.asarray(leaves[i]))

    def test_microbatch_grads_match_full_batch(self, caplog):
        """Numerics pin: uneven ZeRO-3 grads (padded scatter + tail
        gather/unpad) match plain full-batch jax.grad within 1e-5; even
        leaves still exit in the shard layout, uneven ones whole — and
        the lost per-leaf memory saving is warned about loudly."""
        params, specs = self._tree_specs()
        mesh = par.make_mesh(fsdp=4)               # data=2 x fsdp=4
        kb = jax.random.split(jax.random.PRNGKey(8), 2)
        batch = {"x": jax.random.normal(kb[0], (32, 16)),
                 "y": jax.random.normal(kb[1], (32, 6))}

        def loss_fn(p, mb):
            out = mb["x"] @ (p["w"].T @ jnp.ones((8, 6)) @ p["v"]
                             + jnp.diag(p["b"]))
            return jnp.mean((out[:, :6] - mb["y"]) ** 2)

        profiler.reset_overlap_records()
        loss, grads = microbatch_grads(
            loss_fn, params, batch, mesh, microbatches=4,
            bucket_bytes=1 << 20, param_specs=specs)
        ref_loss, ref = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(params)
        assert abs(float(loss) - float(ref_loss)) < 1e-5
        assert grads["v"].shape == (6, 16)          # whole, unpadded
        assert "fsdp" in str(grads["w"].sharding.spec)
        # Grad magnitudes run ~5e2 here: 1e-4 abs ≈ 2e-7 relative.
        np.testing.assert_allclose(np.asarray(grads["v"]),
                                   np.asarray(ref["v"]), atol=1e-4)
        np.testing.assert_allclose(np.asarray(grads["b"]),
                                   np.asarray(ref["b"]), atol=1e-4)
        np.testing.assert_allclose(np.asarray(jax.device_get(grads["w"])),
                                   np.asarray(ref["w"]), atol=1e-4)
        rec = profiler.overlap_report()["accum_step"]
        assert rec["n_padded_buckets"] == 1
        assert "fsdp-indivisible" in caplog.text

    @pytest.mark.multislice
    def test_uneven_hierarchical_multislice(self):
        """The same pin on a 2-slice mesh: the padded bucket's in-scan
        psum_scatter + DCN allreduce + tail gather still sums over the
        whole sync group."""
        params, specs = self._tree_specs()
        mesh = par.make_mesh(slices=2, fsdp=4)     # slice=2 x fsdp=4
        kb = jax.random.split(jax.random.PRNGKey(9), 2)
        batch = {"x": jax.random.normal(kb[0], (32, 16)),
                 "y": jax.random.normal(kb[1], (32, 6))}

        def loss_fn(p, mb):
            out = mb["x"] @ (p["w"].T @ jnp.ones((8, 6)) @ p["v"]
                             + jnp.diag(p["b"]))
            return jnp.mean((out[:, :6] - mb["y"]) ** 2)

        loss, grads = microbatch_grads(
            loss_fn, params, batch, mesh, microbatches=2,
            bucket_bytes=1 << 20, param_specs=specs)
        ref_loss, ref = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(params)
        assert abs(float(loss) - float(ref_loss)) < 1e-5
        # Grad magnitudes run ~5e2 here: 1e-4 abs ≈ 2e-7 relative.
        np.testing.assert_allclose(np.asarray(grads["v"]),
                                   np.asarray(ref["v"]), atol=1e-4)


def test_fsdp_param_specs_detection():
    """Replicated params, fsdp=1 meshes, and non-array leaves all decline
    detection; a llama state created on an fsdp mesh through the logical
    rules opts in automatically."""
    mesh_dp = par.make_mesh()
    state, _ = _mnist_setup()
    assert overlap.fsdp_param_specs(state.params, mesh_dp) is None
    mesh_f = par.make_mesh(fsdp=4)
    assert overlap.fsdp_param_specs(state.params, mesh_f) is None
    assert overlap.fsdp_param_specs(
        {"w": np.zeros((4, 4))}, mesh_f) is None
    zstate = _zero3_state(state, mesh_f)
    specs = overlap.fsdp_param_specs(zstate.params, mesh_f)
    assert specs is not None


def test_profiler_records_bucket_plan():
    profiler.reset_overlap_records()
    mesh = par.make_mesh()
    state, batch = _mnist_setup()
    step = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                       bucket_bytes=32 * 1024, donate=False)
    step(state, batch)
    rec = profiler.overlap_report()
    assert "accum_step" in rec
    assert rec["accum_step"]["n_buckets"] >= 1
    assert sum(rec["accum_step"]["bucket_nbytes"]) == sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(state.params))
    assert rec["accum_step"]["microbatches"] == 4


class TestOverlapXlaFlags:
    def test_all_flags_present_on_empty(self):
        out = overlap_xla_flags()
        for f in OVERLAP_XLA_FLAGS:
            assert f in out

    def test_multislice_adds_dcn_set(self):
        out = overlap_xla_flags(multislice=True)
        for f in OVERLAP_XLA_FLAGS + MULTISLICE_XLA_FLAGS:
            assert f in out
        assert MULTISLICE_XLA_FLAGS[0] not in overlap_xla_flags()

    def test_user_flag_wins(self):
        user = "--xla_tpu_enable_latency_hiding_scheduler=false"
        out = overlap_xla_flags(user)
        assert "--xla_tpu_enable_latency_hiding_scheduler=false" in out
        assert "--xla_tpu_enable_latency_hiding_scheduler=true" not in out

    def test_unrelated_user_flags_kept(self):
        out = overlap_xla_flags("--xla_force_host_platform_device_count=8")
        assert "--xla_force_host_platform_device_count=8" in out
        assert "--xla_tpu_enable_async_collective_fusion=true" in out

    def test_idempotent(self):
        once = overlap_xla_flags()
        assert overlap_xla_flags(once) == once


def test_record_failure_logs_debug_once(monkeypatch, caplog):
    """Satellite pin: a broken profiler wiring must neither sink the step
    nor stay silent — one DEBUG line on the first failure, then quiet."""
    import logging

    monkeypatch.setattr(profiler, "_SAFE_RECORD_FAILED", set())

    def boom(*a, **kw):
        raise RuntimeError("profiler wired wrong")

    monkeypatch.setattr(profiler, "record_overlap", boom)
    with caplog.at_level(logging.DEBUG, logger="tony_tpu.profiler"):
        overlap._record("t1", n=1)      # must not raise
        overlap._record("t2", n=2)
    hits = [r for r in caplog.records if "profiler record" in r.message]
    assert len(hits) == 1
    assert hits[0].levelno == logging.DEBUG


def test_train_step_seq_axis_keeps_ring_sharding():
    """Satellite pin: make_train_step(seq_axis=True) constrains the batch
    with the sequence dim on the ring axis (it used to re-constrain
    long-context batches OFF it) and still trains. The rank-1 "w" leaf
    pins the leaf-rank guard: the (batch, seq) spec must not be forced
    onto labels/weights."""
    mesh = par.make_mesh(sp=2)
    model = get_model("llama-tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-2), tokens, jax.random.PRNGKey(0))
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
        mesh=mesh, seq_axis=True, donate=False)
    _, metrics = step(state, {"x": tokens, "w": jnp.ones((8,))})
    assert np.isfinite(float(metrics["loss"]))


def test_run_overlap_bench_reports_and_matches():
    """Acceptance: the bench leg on the 8-device CPU mesh reports numerics
    matching the monolithic step and emits per-bucket bytes."""
    import os

    from tony_tpu.benchmark import run_overlap_bench

    os.environ["BENCH_WINDOWS"] = "1"
    try:
        r = run_overlap_bench(batch=64, hidden=64, steps=1,
                              bucket_bytes=32 * 1024)
    finally:
        del os.environ["BENCH_WINDOWS"]
    assert r["numerics_ok"]
    assert r["loss_delta"] < 1e-5 and r["grad_norm_delta"] < 1e-5
    assert r["n_buckets"] == len(r["bucket_nbytes"]) >= 1
    assert all(b > 0 for b in r["bucket_nbytes"])
    assert r["mono_step_s"] > 0 and r["accum_step_s"] > 0
    assert r["overlap_records"]["accum_step"]["n_buckets"] == r["n_buckets"]
