"""Disaggregated prefill/decode legs (tony_tpu.serve.disagg, PR 15):
the KV-block wire tier (export/import with per-block CRC, adoption of
shipped shared-prefix stems), the prefill-only engine mode, the
decode-side handoff admission, the role-aware router dispatch with its
OSError-vs-HandoffError failover split, the widened heartbeat schema
(role + handoff counters), and the BITWISE pins of every disaggregated
path against the colocated PR 10/12/13 engine."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.disagg


# ---------------------------------------------------------------------------
# Shared tiny model + params (serving is read-only on params).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


def run_requests(eng, prompts, max_new=4):
    from tony_tpu.serve import Request

    done = {}
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, tokens=list(p), max_new_tokens=max_new))
    done.update({c.rid: c for c in eng.run()})
    return done


def disagg_requests(tiny, prompts, max_new=4, *, prefill_kw=None,
                    decode_kw=None, spec_k=0):
    """Prefill engine -> KV handoff -> decode engine, per request;
    returns (completions, prefill_engine, decode_engine)."""
    from tony_tpu.serve import EngineFront, SpecEngine
    from tony_tpu.serve.disagg import DecodeFront, PrefillFront

    pf_eng = make_engine(tiny, role="prefill", **(prefill_kw or {}))
    if spec_k:
        model, params = tiny
        dc_eng = SpecEngine(model, params, spec_k=spec_k, role="decode",
                            ctx_max=64, block_size=8, q_block=16,
                            decode_buckets=(2, 4), max_running=4,
                            keep_logits=True, **(decode_kw or {}))
    else:
        dc_eng = make_engine(tiny, role="decode", **(decode_kw or {}))
    pf = PrefillFront(EngineFront(pf_eng))
    dc = DecodeFront(EngineFront(dc_eng))
    done = {i: pf.prefill_handoff(p, max_new, rid=i, decode=dc)
            for i, p in enumerate(prompts)}
    return done, pf_eng, dc_eng


def assert_bitwise_equal(got, ref):
    """Token streams AND per-token logits of two completion maps."""
    assert sorted(got) == sorted(ref)
    for rid in ref:
        assert got[rid].tokens == ref[rid].tokens, rid
        assert len(got[rid].logits) == len(ref[rid].logits)
        for a, b in zip(got[rid].logits, ref[rid].logits):
            assert np.array_equal(a, b), rid


def cache_snapshot(c):
    return (dict(c._refs), list(c._free), c.cached_blocks(),
            {s: list(t) for s, t in c.owned_blocks().items()})


# ---------------------------------------------------------------------------
# The KV wire tier (kvcache export/import)
# ---------------------------------------------------------------------------

class TestWireTier:
    def _pool(self, n_blocks=8, block_size=4):
        from tony_tpu.serve import PagedKVCache

        return PagedKVCache(2, 4, n_blocks=n_blocks,
                            block_size=block_size)

    def _fill(self, c, sid, length):
        """Reserve + write recognizable bytes for ``length`` positions."""
        c.reserve(sid, length)
        for b in c.table(sid):
            c.k = c.k.at[:, b].set(float(b + 1))
            c.v = c.v.at[:, b].set(float(-(b + 1)))
        return c.table(sid)

    def test_export_import_round_trips_bytes(self):
        from tony_tpu.serve import prefix

        src = self._pool()
        self._fill(src, "s", 7)
        blocks = src.export_blocks("s", 7)
        assert len(blocks) == 2 and all("crc" in b for b in blocks)
        dst = self._pool()
        keys = prefix.chain_keys(list(range(7)), 4)
        adopted = dst.import_blocks("d", 11, blocks, keys=keys, offset=0)
        assert adopted == 0 and dst.imported_total == 2
        # Bytes land verbatim, position for position.
        st, dt = src.table("s"), dst.table("d")
        for i in range(2):
            assert np.array_equal(np.asarray(src.k[:, st[i]]),
                                  np.asarray(dst.k[:, dt[i]]))
            assert np.array_equal(np.asarray(src.v[:, st[i]]),
                                  np.asarray(dst.v[:, dt[i]]))
        assert len(dt) == dst.blocks_for(11)

    def test_corrupt_crc_is_typed_and_state_unchanged(self):
        from tony_tpu.serve import HandoffError

        src = self._pool()
        self._fill(src, "s", 8)
        blocks = src.export_blocks("s", 8)
        blocks[1] = dict(blocks[1], crc=(blocks[1]["crc"] ^ 1))
        dst = self._pool()
        snap = cache_snapshot(dst)
        k0, v0 = dst.k, dst.v
        with pytest.raises(HandoffError) as ei:
            dst.import_blocks("d", 8, blocks)
        assert not ei.value.retryable
        assert cache_snapshot(dst) == snap
        # Device bytes untouched too — validation runs before any write.
        assert dst.k is k0 and dst.v is v0

    def test_pool_pressure_is_admission_error_state_unchanged(self):
        from tony_tpu.serve import AdmissionError

        src = self._pool()
        self._fill(src, "s", 8)
        blocks = src.export_blocks("s", 8)
        dst = self._pool(n_blocks=4)
        dst.reserve("hog", 12)          # 3 of 4 blocks
        snap = cache_snapshot(dst)
        with pytest.raises(AdmissionError) as ei:
            dst.import_blocks("d", 8, blocks)
        assert ei.value.retryable
        assert cache_snapshot(dst) == snap
        dst.free_seq("hog")
        assert dst.import_blocks("d", 8, blocks) == 0   # heals

    def test_import_adopts_offered_stem_not_rewritten(self):
        from tony_tpu.serve import prefix

        stem = list(range(8))           # 2 full blocks of 4
        keys = prefix.chain_keys(stem, 4)
        src = self._pool()
        self._fill(src, "s", 10)
        blocks = src.export_blocks("s", 10)
        dst = self._pool()
        # Publish the stem on the receiving pool (an earlier handoff).
        dst.import_blocks("prior", 8, blocks[:2])
        for i, key in enumerate(keys):
            dst.publish_block("prior", i, key)
        imported_before = dst.imported_total
        # The offer/import handshake: offset = receiver's match.
        offset = len(dst.match_prefix(keys))
        assert offset == 2
        adopted = dst.import_blocks("d", 12, blocks[offset:], keys=keys,
                                    offset=offset)
        assert adopted == 2
        assert dst.imported_total - imported_before == 1   # only the tail
        # The adopted blocks are SHARED with the prior holder — and the
        # COW contract keeps them read-only for the importer.
        t_prior, t_d = dst.table("prior"), dst.table("d")
        assert t_d[:2] == t_prior[:2]
        assert all(dst.ref(b) == 2 for b in t_d[:2])
        w = dst.write_index("d", 0)     # write into an adopted block
        assert dst.table("d")[0] != t_prior[0], "COW must repoint"
        assert dst.ref(t_prior[0]) == 1

    def test_evaporated_offer_is_retryable_with_matched_count(self):
        from tony_tpu.serve import HandoffError, prefix

        src = self._pool()
        self._fill(src, "s", 8)
        blocks = src.export_blocks("s", 8)
        keys = prefix.chain_keys(list(range(8)), 4)
        dst = self._pool()
        snap = cache_snapshot(dst)
        with pytest.raises(HandoffError) as ei:
            dst.import_blocks("d", 8, blocks[2:], keys=keys, offset=2)
        assert ei.value.retryable and ei.value.matched == 0
        assert cache_snapshot(dst) == snap

    def test_geometry_mismatch_is_non_retryable(self):
        from tony_tpu.serve import HandoffError, PagedKVCache

        src = self._pool()
        self._fill(src, "s", 4)
        blocks = src.export_blocks("s", 4)
        dst = PagedKVCache(2, 8, n_blocks=8, block_size=4)  # wider kv
        with pytest.raises(HandoffError) as ei:
            dst.import_blocks("d", 4, blocks)
        assert not ei.value.retryable
        assert src.wire_header() != dst.wire_header()

    def test_shipper_bounded_retry_reships_missing_tail(self):
        """The offer/import handshake under churn: the receiver's match
        shrinks between offer and import; the shipper re-ships exactly
        the missing tail (the HandoffError's matched count), bounded."""
        from tony_tpu.serve import HandoffError, KVShipper

        calls = []

        class FlakyDecode:
            def kv_offer(self, keys):
                return 2                      # stale promise

            def kv_import(self, payload):
                calls.append((payload["offset"], len(payload["blocks"])))
                if len(calls) == 1:
                    raise HandoffError("evaporated", matched=1)
                return {"rid": payload.get("rid"), "tokens": [1]}

        handoff = {"keys": ["a", "b", "c"],
                   "blocks": [{"n": i} for i in range(3)]}
        out, shipped = KVShipper(max_attempts=3, backoff_s=0.0).ship(
            handoff, FlakyDecode())
        assert out["tokens"] == [1]
        assert shipped == 2                   # the final attempt's wire
        assert calls == [(2, 1), (1, 2)]      # re-shipped the lost block

        class AlwaysFull:
            def kv_offer(self, keys):
                return 0

            def kv_import(self, payload):
                raise HandoffError("pool full")

        with pytest.raises(HandoffError) as ei:
            KVShipper(max_attempts=3, backoff_s=0.0).ship(
                handoff, AlwaysFull())
        assert not ei.value.retryable
        assert "after 3 attempt(s)" in str(ei.value)

        class Corrupt:
            def kv_offer(self, keys):
                return 0

            def kv_import(self, payload):
                raise HandoffError("crc mismatch", retryable=False)

        with pytest.raises(HandoffError) as ei:
            KVShipper(max_attempts=3, backoff_s=0.0).ship(
                handoff, Corrupt())
        assert "after 1 attempt(s)" in str(ei.value), \
            "a non-retryable break must report the REAL attempt count"


# ---------------------------------------------------------------------------
# Engine-level bitwise pins vs the colocated engine
# ---------------------------------------------------------------------------

class TestDisaggBitwise:
    def test_ragged_lengths_bitwise_vs_colocated(self, tiny):
        """Prompt lengths spanning block boundaries (7/8/9/15/17):
        token streams AND per-token logits identical to the colocated
        engine's — the handoff's device->wire->device round trip is
        lossless and the decode resumes exactly where a colocated
        prefill would."""
        rng = np.random.RandomState(0)
        prompts = [list(rng.randint(0, 256, n)) for n in (7, 8, 9, 15, 17)]
        ref = run_requests(make_engine(tiny), prompts, max_new=5)
        got, pf_eng, dc_eng = disagg_requests(tiny, prompts, max_new=5)
        assert_bitwise_equal(got, ref)
        assert dc_eng.handoffs_in == len(prompts)
        assert dc_eng.cache.imported_total > 0
        # Both pools drain: the prefill gang frees at export, decode at
        # eviction — a leak would starve the fleet under load.
        assert pf_eng.cache.free_blocks == pf_eng.cache.n_blocks
        assert dc_eng.cache.free_blocks == dc_eng.cache.n_blocks

    def test_chunked_prefill_family_bitwise(self, tiny):
        """The prefill side runs the chunked (1, chunk) launch family —
        the same program the route config pins — and the split point
        cannot change a bit."""
        rng = np.random.RandomState(1)
        prompts = [list(rng.randint(0, 256, n)) for n in (9, 17, 33)]
        ref = run_requests(make_engine(tiny), prompts, max_new=4)
        got, pf_eng, _ = disagg_requests(
            tiny, prompts, max_new=4, prefill_kw={"prefill_chunk": 16})
        assert_bitwise_equal(got, ref)
        assert pf_eng.prefill_chunks >= 4

    def test_hit_and_miss_admissions_bitwise(self, tiny):
        """Prefix caching armed on BOTH sides: the prefill gang adopts
        published stems (hits skip prefill launches), the decode pool
        adopts the shipped stem instead of re-importing it — and the
        shipper provably re-transfers nothing for the adopted extent."""
        rng = np.random.RandomState(2)
        stem = list(rng.randint(0, 256, 16))    # 2 full blocks of 8
        prompts = [stem + list(rng.randint(0, 256, 5)),
                   stem + list(rng.randint(0, 256, 9)),
                   list(rng.randint(0, 256, 11)),   # miss
                   stem[:8] + list(rng.randint(0, 256, 3))]
        ref = run_requests(make_engine(tiny), prompts, max_new=5)
        got, pf_eng, dc_eng = disagg_requests(
            tiny, prompts, max_new=5,
            prefill_kw={"prefix_cache": True},
            decode_kw={"prefix_cache": True})
        assert_bitwise_equal(got, ref)
        assert pf_eng.prefix_hit_blocks > 0, "prefill-side hits"
        assert dc_eng.cache.adopted_total > 0, "decode-side adoption"
        # Shipped strictly fewer blocks than the prompts cover: the
        # stem crossed the wire once, later requests offered it away.
        covered = sum(pf_eng.cache.blocks_for(len(p)) for p in prompts)
        assert pf_eng.blocks_shipped < covered

    def test_spec_lane_on_decode_side_bitwise(self, tiny):
        """The speculative lane rides the decode side of the split:
        draft-and-verify over imported KV, greedy outputs pinned to the
        plain colocated engine's."""
        rng = np.random.RandomState(3)
        prompts = [list(rng.randint(0, 256, n)) for n in (7, 12, 17)]
        ref = run_requests(make_engine(tiny), prompts, max_new=6)
        got, _, dc_eng = disagg_requests(tiny, prompts, max_new=6,
                                         spec_k=4)
        assert_bitwise_equal(got, ref)
        assert dc_eng.verify_launches > 0
        assert dc_eng.cache.free_blocks == dc_eng.cache.n_blocks

    def test_mismatched_chain_keys_reject_before_poisoning_index(self, tiny):
        """The shipped keys index imported blocks into the SHARED
        prefix tier — a key-scheme-skewed shipper must reject typed
        and state-unchanged, not silently poison future adoptions."""
        from tony_tpu.serve import EngineFront, HandoffError
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront
        from tony_tpu.serve.engine import Request

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode", prefix_cache=True)
        pf = PrefillFront(EngineFront(pf_eng))
        dc = DecodeFront(EngineFront(dc_eng))
        rng = np.random.RandomState(14)
        p = list(rng.randint(0, 256, 17))
        with pf.front._drive:
            payload = pf_eng.prefill_only(
                Request(rid="r", tokens=p, max_new_tokens=4))
        payload["keys"] = ["deadbeef" * 2] * len(payload["keys"])
        snap = cache_snapshot(dc_eng.cache)
        with pytest.raises(HandoffError) as ei:
            dc.kv_import(payload)
        assert not ei.value.retryable
        assert cache_snapshot(dc_eng.cache) == snap
        assert dc_eng.cache.match_prefix(payload["keys"]) == [], \
            "nothing may have been indexed under the bogus keys"

    def test_corrupt_logits_rejects_typed_and_state_unchanged(self, tiny):
        """logits_b64 rides outside the per-block CRC: a corrupt row
        must reject BEFORE the import mutates the pool — no leaked
        table, imports_failed counted, typed error."""
        from tony_tpu.serve import EngineFront, HandoffError
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode")
        pf = PrefillFront(EngineFront(pf_eng))
        dc = DecodeFront(EngineFront(dc_eng))
        rng = np.random.RandomState(12)
        p = list(rng.randint(0, 256, 9))
        from tony_tpu.serve.engine import Request

        with pf.front._drive:
            payload = pf_eng.prefill_only(
                Request(rid="r", tokens=p, max_new_tokens=4))
        payload["logits_b64"] = payload["logits_b64"][:-3]   # corrupt
        snap = cache_snapshot(dc_eng.cache)
        with pytest.raises(HandoffError) as ei:
            dc.kv_import(payload)
        assert not ei.value.retryable
        assert dc_eng.imports_failed == 1
        assert cache_snapshot(dc_eng.cache) == snap, \
            "a rejected handoff must leak no pool state"

    def test_max_new_one_degenerate_handoff(self, tiny):
        """max_new_tokens == 1: the prefill side already produced the
        only token; the decode side admits, completes immediately, and
        leaks nothing."""
        rng = np.random.RandomState(4)
        prompts = [list(rng.randint(0, 256, 9))]
        ref = run_requests(make_engine(tiny), prompts, max_new=1)
        got, _, dc_eng = disagg_requests(tiny, prompts, max_new=1)
        assert_bitwise_equal(got, ref)
        assert dc_eng.cache.free_blocks == dc_eng.cache.n_blocks
        assert dc_eng.forwards == 0, \
            "a one-token handoff must cost the decode side zero launches"


# ---------------------------------------------------------------------------
# Failure semantics: bounded retry, fallback, the failover split
# ---------------------------------------------------------------------------

class TestHandoffFailure:
    def test_pressure_rejects_state_unchanged_then_heals(self, tiny):
        from tony_tpu.serve import EngineFront, HandoffError, KVShipper
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode", n_blocks=4)
        dc = DecodeFront(EngineFront(dc_eng))
        dc_eng.cache.reserve("hog", 16)     # 2 of 4 blocks
        rng = np.random.RandomState(5)
        p = list(rng.randint(0, 256, 12))   # 3-block total extent
        snap = cache_snapshot(dc_eng.cache)
        pf = PrefillFront(EngineFront(pf_eng),
                          shipper=KVShipper(max_attempts=3, backoff_s=0.0))
        with pytest.raises(HandoffError) as ei:
            pf.prefill_handoff(p, 5, rid="r", decode=dc)
        assert not ei.value.retryable
        assert dc_eng.imports_failed == 3, "every bounded attempt counted"
        assert cache_snapshot(dc_eng.cache) == snap, "state unchanged"
        # The prefill gang is NOT wedged: its pool is clean and the next
        # prompt prefills immediately.
        assert pf_eng.cache.free_blocks == pf_eng.cache.n_blocks
        dc_eng.cache.free_seq("hog")
        out = pf.prefill_handoff(p, 5, rid="r2", decode=dc)
        ref = run_requests(make_engine(tiny), [p], max_new=5)
        assert out.tokens == ref[0].tokens

    def test_prefill_pool_pressure_falls_back_colocated(self, tiny):
        """Transient PREFILL-pool pressure: prefill_only has no queue
        to park the request in (a colocated engine absorbs the same
        pressure by leaving it queued), so the shipper side re-types
        the retryable AdmissionError as a non-retryable HandoffError
        and the router's dispatch falls back to COLOCATED prefill on
        the decode replica — identical tokens, no hard failure.
        Never-fits still propagates as the request-level rejection."""
        from tony_tpu.serve import (AdmissionError, EngineFront,
                                    HandoffError, RequestRouter)
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        pf_eng = make_engine(tiny, role="prefill", n_blocks=4)
        dc_eng = make_engine(tiny, role="decode")
        pf_eng.cache.reserve("hog", 24)     # 3 of 4 blocks
        pf = PrefillFront(EngineFront(pf_eng))
        dc = DecodeFront(EngineFront(dc_eng))
        rng = np.random.RandomState(7)
        p = list(rng.randint(0, 256, 12))   # needs 2 blocks, 1 free
        with pytest.raises(HandoffError) as ei:
            pf.prefill_handoff(p, 5, rid="r", decode=dc)
        assert not ei.value.retryable
        router = RequestRouter(block_size=8)
        router.upsert_replica("prefill:0", client=pf,
                              stats=pf_eng.stats())
        router.upsert_replica("decode:0", client=dc,
                              stats=dc_eng.stats())
        out = router.dispatch(p, 5, rid="r2")
        assert out["replica"] == "decode:0"
        assert router.stats()["handoff_fallbacks"] == 1
        assert router.stats()["failovers"] == 0, \
            "pool pressure must not down-mark the prefill replica"
        ref = run_requests(make_engine(tiny), [p], max_new=5)
        assert out["tokens"] == ref[0].tokens
        # Over the whole pool outright: the non-retryable
        # AdmissionError propagates, exactly like colocated submit.
        big = list(rng.randint(0, 256, 40))  # 5 blocks > 4-block pool
        with pytest.raises(AdmissionError) as ei2:
            pf.prefill_handoff(big, 5, rid="r3", decode=dc)
        assert not ei2.value.retryable

    def test_missing_payload_field_rejects_typed(self, tiny):
        """A version-skewed payload missing (or mistyping) a required
        field is the same typed, counted, state-unchanged rejection as
        every other malformed field — never a bare KeyError escaping
        the (AdmissionError, HandoffError) failover split."""
        from tony_tpu.serve import HandoffError

        dc_eng = make_engine(tiny, role="decode")
        snap = cache_snapshot(dc_eng.cache)
        base = {"rid": "r", "tokens": [1, 2, 3], "max_new_tokens": 4,
                "first_token": 5, "length": 3, "keys": [], "blocks": [],
                **dc_eng.cache.wire_header()}
        bad = []
        for missing in ("rid", "tokens", "max_new_tokens", "first_token"):
            payload = dict(base)
            del payload[missing]
            bad.append(payload)
        bad.append(dict(base, tokens=None))          # mistyped
        for payload in bad:
            with pytest.raises(HandoffError) as ei:
                dc_eng.admit_handoff(payload)
            assert not ei.value.retryable
        assert dc_eng.imports_failed == len(bad), "every rejection counted"
        assert cache_snapshot(dc_eng.cache) == snap, "state unchanged"

    def test_truncated_blocks_reject_typed(self, tiny):
        """A payload whose blocks field is truncated or absent passes
        every per-block check (CRC only guards blocks that ARE
        present) — the admission must still reject typed rather than
        decode the uncovered prompt extent from uninitialized pool
        blocks, silently wrong."""
        from tony_tpu.serve import EngineFront, HandoffError
        from tony_tpu.serve.engine import Request

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode")
        rng = np.random.RandomState(9)
        p = list(rng.randint(0, 256, 12))
        front = EngineFront(pf_eng)
        with front._drive:
            payload = pf_eng.prefill_only(
                Request(rid="r", tokens=p, max_new_tokens=4))
        snap = cache_snapshot(dc_eng.cache)
        for bad in (dict(payload, blocks=payload["blocks"][:-1]),
                    {k: v for k, v in payload.items() if k != "blocks"}):
            with pytest.raises(HandoffError) as ei:
                dc_eng.admit_handoff(bad)
            assert not ei.value.retryable
        assert cache_snapshot(dc_eng.cache) == snap, "state unchanged"

    def test_rid_collision_rejects_typed_and_minted_rids_unique(
            self, tiny):
        """Minted rids carry a per-front namespace (a prefill front's
        rid lands on a decode engine that also mints its own), and a
        caller-supplied duplicate rejects typed BEFORE the import —
        not as the cache's bare fresh-admission ValueError escaping
        the failover split."""
        from tony_tpu.serve import EngineFront, HandoffError
        from tony_tpu.serve.engine import Request

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode")
        f1, f2 = EngineFront(pf_eng), EngineFront(dc_eng)
        rids = {f1.fresh_rid() for _ in range(4)} \
            | {f2.fresh_rid() for _ in range(4)}
        assert len(rids) == 8, "two fronts must not share a namespace"
        rng = np.random.RandomState(10)
        p = list(rng.randint(0, 256, 12))
        with f1._drive:
            payload = pf_eng.prefill_only(
                Request(rid="dup", tokens=p, max_new_tokens=4))
        dc_eng.cache.reserve("dup", 8)     # a live holder of the rid
        snap = cache_snapshot(dc_eng.cache)
        with pytest.raises(HandoffError) as ei:
            dc_eng.admit_handoff(payload)
        assert not ei.value.retryable
        assert cache_snapshot(dc_eng.cache) == snap

    def test_router_falls_back_to_colocated_on_decode(self, tiny):
        """A decode pool under pressure: every bounded shipping attempt
        is rejected, and the router's dispatch falls back to COLOCATED
        prefill on the decode replica — identical tokens, one fallback
        counted, fleet not down-marked."""
        from tony_tpu.serve import (AdmissionError, EngineFront,
                                    KVShipper, RequestRouter)
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode")

        class PressuredDecode(DecodeFront):
            """Rejects every import retryably (the wire form of a pool
            under sustained pressure — the deterministic stand-in for
            the engine-level rejection test_pressure_rejects pins)."""

            imports = 0

            def kv_import(self, payload):
                PressuredDecode.imports += 1
                raise AdmissionError("decode pool exhausted",
                                     needed_blocks=3, free_blocks=0)

        router = RequestRouter(block_size=8)
        router.upsert_replica(
            "prefill:0",
            client=PrefillFront(EngineFront(pf_eng),
                                shipper=KVShipper(max_attempts=2,
                                                  backoff_s=0.0)),
            stats=pf_eng.stats())
        router.upsert_replica(
            "decode:0", client=PressuredDecode(EngineFront(dc_eng)),
            stats=dc_eng.stats())
        rng = np.random.RandomState(6)
        p = list(rng.randint(0, 256, 12))
        out = router.dispatch(p, 5, rid="r")
        assert PressuredDecode.imports == 2, "bounded shipping budget"
        assert out["replica"] == "decode:0"
        assert router.stats()["handoff_fallbacks"] == 1
        assert router.stats()["failovers"] == 0, \
            "a request-level rejection must not down-mark the fleet"
        ref = run_requests(make_engine(tiny), [p], max_new=5)
        assert out["tokens"] == ref[0].tokens

    def test_prefill_transport_fault_fails_over(self, tiny):
        """The PR 13 failover split, kept: a DEAD prefill replica
        (OSError) is down-marked and the request re-dispatches to the
        live prefill replica; request-level errors still propagate."""
        from tony_tpu.serve import (AdmissionError, EngineFront,
                                    RequestRouter)
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        class DeadPrefill:
            def prefill_handoff(self, tokens, max_new_tokens, rid=None,
                                decode=None, conv=None):
                raise ConnectionRefusedError("replica gone")

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode")
        router = RequestRouter(block_size=8)
        router.upsert_replica("prefill:0", client=DeadPrefill(),
                              stats={"role": "prefill",
                                     "queue_depth": 0.0})
        # The live prefill replica scores WORSE (deeper queue), so the
        # dead one wins the first route and the dispatch must fail over.
        router.upsert_replica("prefill:1",
                              client=PrefillFront(EngineFront(pf_eng)),
                              stats={**pf_eng.stats(),
                                     "queue_depth": 2.0})
        router.upsert_replica("decode:0",
                              client=DecodeFront(EngineFront(dc_eng)),
                              stats=dc_eng.stats())
        rng = np.random.RandomState(7)
        p = list(rng.randint(0, 256, 9))
        out = router.dispatch(p, 4, rid="r", session_id="s")
        assert out["prefill_replica"] == "prefill:1"
        assert router.stats()["failovers"] >= 1
        assert not [v for v in router.replicas()
                    if v.name == "prefill:0"][0].alive
        # Request-level error: an oversized prompt propagates untouched
        # (never fits the decode extent), fleet stays up.
        with pytest.raises(AdmissionError):
            router.dispatch(list(rng.randint(0, 256, 30)), 60, rid="r2")
        assert [v for v in router.replicas()
                if v.name == "prefill:1"][0].alive


# ---------------------------------------------------------------------------
# Role-aware routing decisions
# ---------------------------------------------------------------------------

class TestRouterRoles:
    def _mk(self, **stats):
        base = {"queue_depth": 0.0, "running": 0.0, "p99_ms": 0.0}
        base.update(stats)
        return base

    def test_route_split_scores_prefill_by_overlap_decode_by_queue(self):
        from tony_tpu.serve import RequestRouter
        from tony_tpu.serve import prefix

        router = RequestRouter(block_size=4)
        toks = list(range(12))
        keys = prefix.chain_keys(toks, 4)
        router.upsert_replica("prefill:0", address="h:1", stats=self._mk(
            role="prefill", prefix_digest=keys[:2]))
        router.upsert_replica("prefill:1", address="h:2", stats=self._mk(
            role="prefill"))
        router.upsert_replica("decode:0", address="h:3", stats=self._mk(
            role="decode", queue_depth=3.0))
        router.upsert_replica("decode:1", address="h:4", stats=self._mk(
            role="decode", queue_depth=1.0))
        pf, dc = router.route_split(toks)
        assert (pf, dc) == ("prefill:0", "decode:1")

    def test_sticky_pair_affinity_and_repin(self):
        from tony_tpu.serve import RequestRouter

        router = RequestRouter(block_size=4)
        for n, r in (("prefill:0", "prefill"), ("prefill:1", "prefill"),
                     ("decode:0", "decode"), ("decode:1", "decode")):
            router.upsert_replica(n, address=f"h:{n}",
                                  stats=self._mk(role=r))
        pf1, dc1 = router.route_split([1, 2, 3], session_id="s")
        # Load changes do not move a pinned session...
        router.upsert_replica(dc1, address=f"h:{dc1}", stats=self._mk(
            role="decode", queue_depth=9.0))
        assert router.route_split([1, 2, 3], session_id="s") == (pf1, dc1)
        assert router.affinity_hits == 1
        # ...until a half retires: the pair re-routes and re-pins.
        router.retire_replica(dc1)
        pf2, dc2 = router.route_split([1, 2, 3], session_id="s")
        assert dc2 != dc1
        assert router.route_split([1, 2, 3], session_id="s") == (pf2, dc2)

    def test_colocated_fleet_has_no_split(self, tiny):
        from tony_tpu.serve import EngineFront, RequestRouter

        eng = make_engine(tiny)
        router = RequestRouter(block_size=8)
        router.upsert_replica("serve:0", client=EngineFront(eng),
                              stats=eng.stats())
        assert router.route_split([1, 2, 3]) == (None, None)
        rng = np.random.RandomState(8)
        p = list(rng.randint(0, 256, 9))
        out = router.dispatch(p, 4, rid="r")
        assert out["replica"] == "serve:0"
        assert "prefill_replica" not in out
        assert router.stats()["handoffs"] == 0

    def test_split_dissolving_mid_retry_serves_colocated(self, tiny):
        """The whole prefill gang dies mid-dispatch: the failover
        down-marks it, the split dissolves, and the SAME request still
        completes on the surviving decode replica's colocated path —
        a lost gang costs a retry, never the request."""
        from tony_tpu.serve import EngineFront, RequestRouter
        from tony_tpu.serve.disagg import DecodeFront

        class DeadPrefill:
            def prefill_handoff(self, tokens, max_new_tokens, rid=None,
                                decode=None, conv=None):
                raise ConnectionRefusedError("gang gone")

        dc_eng = make_engine(tiny, role="decode")
        router = RequestRouter(block_size=8)
        router.upsert_replica("prefill:0", client=DeadPrefill(),
                              stats={"role": "prefill",
                                     "queue_depth": 0.0})
        router.upsert_replica("decode:0",
                              client=DecodeFront(EngineFront(dc_eng)),
                              stats=dc_eng.stats())
        rng = np.random.RandomState(13)
        p = list(rng.randint(0, 256, 9))
        out = router.dispatch(p, 4, rid="r")
        assert out["replica"] == "decode:0"
        assert "prefill_replica" not in out, "served colocated"
        assert router.stats()["failovers"] == 1
        ref = run_requests(make_engine(tiny), [p], max_new=4)
        assert out["tokens"] == ref[0].tokens

    def test_split_dissolved_falls_back_to_colocated_path(self, tiny):
        """Only a prefill gang is live (decode gang lost): dispatch runs
        the plain colocated path on whatever serves — no wedge."""
        from tony_tpu.serve import EngineFront, RequestRouter
        from tony_tpu.serve.disagg import PrefillFront

        pf_eng = make_engine(tiny, role="prefill")
        front = EngineFront(pf_eng)
        router = RequestRouter(block_size=8)
        router.upsert_replica("prefill:0", client=PrefillFront(front),
                              stats=pf_eng.stats())
        rng = np.random.RandomState(9)
        p = list(rng.randint(0, 256, 9))
        out = router.dispatch(p, 4, rid="r")
        assert out["replica"] == "prefill:0"


# ---------------------------------------------------------------------------
# The widened heartbeat schema: stats file -> heartbeat -> session ->
# router ingestion, and the scaling matrix pinned under the new fields.
# ---------------------------------------------------------------------------

class TestHeartbeatSchema:
    NEW_FIELDS = ("blocks_shipped", "handoff_ms", "imports_failed")

    def test_stats_fields_present_and_zero_on_colocated(self, tiny):
        eng = make_engine(tiny)
        s = eng.stats()
        assert s["role"] == "colocated"
        for f in self.NEW_FIELDS:
            assert s[f] == 0.0, f

    def test_prefill_role_reports_load(self, tiny):
        """A prefill replica's heartbeat must show its handoff load —
        handoffs never queue or join the running batch, so without the
        prefill_only completion event the gang would report
        qps=0/p99=0 forever and the per-gang autoscaler (and the
        router's load scoring) could never see a prefill burst."""
        from tony_tpu.serve import EngineFront
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode")
        pf = PrefillFront(EngineFront(pf_eng))
        dc = DecodeFront(EngineFront(dc_eng))
        rng = np.random.RandomState(11)
        for i in range(2):
            pf.prefill_handoff(list(rng.randint(0, 256, 12)), 3,
                               rid=f"r{i}", decode=dc)
        s = pf_eng.stats()
        assert s["completed"] == 2.0
        assert s["qps"] > 0.0 and s["p99_ms"] > 0.0

    def test_round_trip_stats_file_to_router(self, tiny, tmp_path):
        """The full ingestion chain a fleet runs: engine stats file ->
        executor reader -> heartbeat RPC -> session -> serve_endpoints
        -> router view, with the role STRING and handoff counters
        surviving every hop."""
        from tony_tpu.conf import TonyConfig, serve_role_key
        from tony_tpu.executor import read_serve_stats
        from tony_tpu.rpc import ApplicationRpcHandler
        from tony_tpu.serve import RequestRouter
        from tony_tpu.session import TonySession

        eng = make_engine(tiny, role="prefill", prefix_cache=True)
        eng.blocks_shipped = 7
        eng.handoff_ms = 12.5
        path = tmp_path / "stats.json"
        eng.write_stats(str(path), extra={"rpc_port": 4242})
        stats = read_serve_stats(path)
        assert stats["role"] == "prefill"
        assert stats["blocks_shipped"] == 7.0
        assert stats["handoff_ms"] == 12.5

        conf = TonyConfig({"tony.prefill.instances": "1",
                           "tony.prefill.command": "x",
                           "tony.decode.instances": "1",
                           "tony.decode.command": "x",
                           serve_role_key("prefill"): "prefill",
                           serve_role_key("decode"): "decode"})
        session = TonySession(conf, "app_disagg")
        handler = ApplicationRpcHandler(session)
        session.on_registered("prefill", 0, "hostA", 1)
        session.on_registered("decode", 0, "hostB", 2)
        handler.rpc_heartbeat("prefill", 0, serve=stats)
        dec = make_engine(tiny, role="decode")
        handler.rpc_heartbeat("decode", 0, serve={
            **dec.stats(), "rpc_port": 4243})
        assert set(session.serve_job_types()) == {"prefill", "decode"}
        eps = handler.rpc_serve_endpoints()
        assert {e["job_type"] for e in eps} == {"prefill", "decode"}
        router = RequestRouter(block_size=8)
        router.refresh_from_task_infos(eps)
        views = {v.name: v for v in router.replicas()}
        assert views["prefill:0"].role == "prefill"
        assert views["prefill:0"].address == "hostA:4242"
        assert views["decode:0"].role == "decode"

    def test_scaling_decision_matrix_pinned_under_new_fields(self):
        """ScalingPolicy.decide is UNCHANGED by role/handoff fields:
        the same matrix the PR 12/13 tests pin, with the new keys
        riding along."""
        from tony_tpu.serve.scaling import ScalingPolicy, decide

        policy = ScalingPolicy(min_replicas=1, max_replicas=3,
                               queue_high=8.0, queue_low=1.0,
                               cooldown_s=30.0)
        extra = {"role": "decode", "blocks_shipped": 100.0,
                 "handoff_ms": 5.0, "imports_failed": 2.0}
        mk = lambda qd: {"queue_depth": qd, "p99_ms": 0.0, **extra}
        assert decide(policy, 0, [], now=0.0) == 1          # floor repair
        assert decide(policy, 1, [mk(20.0)], now=100.0) == 1    # hot
        assert decide(policy, 2, [mk(0.0), mk(0.0)], now=100.0) == -1
        assert decide(policy, 2, [mk(4.0), mk(4.0)], now=100.0) == 0
        assert decide(policy, 2, [mk(20.0)], now=10.0,
                      last_action=0.0) == 0                 # cooldown

    def test_cli_role_builds_heterogeneous_jobtypes(self):
        from tony_tpu import conf as conf_mod
        from tony_tpu.cli import make_parser

        args = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--ckpt_dir", "/tmp/ck",
            "--role", "prefill=2,decode=3", "--prefill_chunk", "32"])
        # Build the conf exactly as cmd_serve does, without submitting.
        captured = {}

        class FakeClient:
            def __init__(self, cfg, **kw):
                captured["cfg"] = cfg

            def run(self, timeout=None):
                return 0

        import tony_tpu.client as client_mod
        real = client_mod.TonyClient
        client_mod.TonyClient = FakeClient
        try:
            assert args.fn(args) == 0
        finally:
            client_mod.TonyClient = real
        cfg = captured["cfg"]
        assert cfg.get_int(conf_mod.instances_key("prefill"), 0) == 2
        assert cfg.get_int(conf_mod.instances_key("decode"), 0) == 3
        assert cfg.get(conf_mod.serve_role_key("prefill")) == "prefill"
        assert cfg.get(conf_mod.serve_role_key("decode")) == "decode"
        assert cfg.get(conf_mod.instances_key("serve")) is None
        for jt in ("prefill", "decode"):
            assert cfg.get(conf_mod.command_key(jt)) \
                == "python -m tony_tpu.serve.replica"

    def test_cli_role_validation(self):
        from tony_tpu.cli import make_parser

        for bad in ("warble=2", "prefill=0,decode=1", "prefill=2",
                    "prefill=x,decode=1"):
            args = make_parser().parse_args([
                "serve", "--model", "m", "--ckpt_dir", "/tmp/ck",
                "--role", bad])
            with pytest.raises(SystemExit):
                args.fn(args)


class TestFleetCeiling:
    """One ``--max_replicas`` is a FLEET ceiling on a split fleet: the
    per-gang policy maxes can never sum past it — two gangs must not
    each inflate to the whole budget."""

    def test_apportion_fleet_max(self):
        from tony_tpu.serve.scaling import apportion_fleet_max

        assert apportion_fleet_max({"prefill": 2, "decode": 4}, 12) == \
            {"prefill": 4, "decode": 8}
        # No headroom (or a ceiling below the floors): floors stand.
        assert apportion_fleet_max({"prefill": 2, "decode": 4}, 6) == \
            {"prefill": 2, "decode": 4}
        assert apportion_fleet_max({"prefill": 2, "decode": 4}, 0) == \
            {"prefill": 2, "decode": 4}
        # Largest-remainder headroom: shares sum exactly to the ceiling.
        assert apportion_fleet_max({"prefill": 2, "decode": 4}, 9) == \
            {"prefill": 3, "decode": 6}
        got = apportion_fleet_max({"a": 1, "b": 2}, 5)
        assert sum(got.values()) == 5 and got["a"] >= 1 and got["b"] >= 2

    def test_split_fleet_policies_respect_one_ceiling(self):
        from tony_tpu.conf import (SERVE_REPLICAS_MAX, TonyConfig,
                                   serve_replicas_max_key)
        from tony_tpu.serve.scaling import ScalingPolicy

        cfg = TonyConfig()
        cfg.set(SERVE_REPLICAS_MAX, "12")
        floors = {"prefill": 2, "decode": 4}
        pols = {jt: ScalingPolicy.from_conf(cfg, floors[jt], job_type=jt,
                                            fleet_floors=floors)
                for jt in floors}
        assert pols["prefill"].max_replicas == 4
        assert pols["decode"].max_replicas == 8
        assert sum(p.max_replicas for p in pols.values()) == 12
        # Per-gang override wins over the apportioned share.
        cfg.set(serve_replicas_max_key("decode"), "10")
        pol = ScalingPolicy.from_conf(cfg, 4, job_type="decode",
                                      fleet_floors=floors)
        assert pol.max_replicas == 10
        # A colocated fleet (one serve jobtype) keeps the classic
        # whole-budget semantics.
        pol = ScalingPolicy.from_conf(cfg, 2, job_type="serve",
                                      fleet_floors={"serve": 2})
        assert pol.max_replicas == 12


# ---------------------------------------------------------------------------
# The RPC wire end to end (slow: real servers, three replicas)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestDisaggOverRpc:
    def test_fleet_e2e_over_rpc_with_handoff(self, tiny):
        """The full wire: router (RPC dial) -> prefill replica RPC ->
        replica-to-replica KV ship (kv_offer/kv_import verbs) -> decode
        replica drives to completion. Token identity vs the colocated
        engine; handoff counters visible in serve_stats."""
        from tony_tpu.rpc import RpcServer
        from tony_tpu.serve import EngineFront, RequestRouter
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront
        from tony_tpu.serve.replica import _ReplicaRpcHandler

        class MiniReplica:
            """The request-path surface of serve.replica.Replica,
            without the ckpt restore (the e2e restore path is pinned by
            tests/test_serve.py)."""

            def __init__(self, eng):
                self.engine = eng
                self._front = EngineFront(eng)
                self._prefill_front = PrefillFront(self._front)
                self._decode_front = DecodeFront(self._front)

            def generate(self, tokens, max_new_tokens, rid=None,
                         conv=None, tenant=None):
                return self._front.generate(tokens, max_new_tokens,
                                            rid=rid, conv=conv,
                                            tenant=tenant)

            def prefill_handoff(self, tokens, max_new_tokens, rid=None,
                                decode=None, conv=None, tenant=None):
                return self._prefill_front.prefill_handoff(
                    tokens, max_new_tokens, rid=rid, decode=decode,
                    conv=conv, tenant=tenant)

            def kv_offer(self, keys):
                return self._decode_front.kv_offer(keys)

            def kv_import(self, payload):
                return self._decode_front.kv_import(payload)

        pf_eng = make_engine(tiny, role="prefill", prefill_chunk=16,
                             keep_logits=False)
        dc_eng = make_engine(tiny, role="decode", keep_logits=False)
        servers = []
        try:
            addrs = {}
            for name, eng in (("prefill:0", pf_eng), ("decode:0", dc_eng)):
                srv = RpcServer(
                    _ReplicaRpcHandler(MiniReplica(eng)),
                    host="127.0.0.1", port=0)
                srv.start()
                servers.append(srv)
                addrs[name] = srv.address
            router = RequestRouter(block_size=8, dial_timeout_s=5.0)
            router.upsert_replica("prefill:0", address=addrs["prefill:0"],
                                  stats={**pf_eng.stats()})
            router.upsert_replica("decode:0", address=addrs["decode:0"],
                                  stats={**dc_eng.stats()})
            rng = np.random.RandomState(10)
            prompts = [list(rng.randint(0, 256, n)) for n in (9, 17)]
            outs = [router.dispatch(p, 5, rid=f"r{i}")
                    for i, p in enumerate(prompts)]
            ref_eng = make_engine(tiny, keep_logits=False)
            ref = run_requests(ref_eng, prompts, max_new=5)
            for i, out in enumerate(outs):
                assert out["tokens"] == ref[i].tokens
                assert out["replica"] == "decode:0"
                assert out["prefill_replica"] == "prefill:0"
            assert pf_eng.blocks_shipped > 0
            assert pf_eng.handoff_ms > 0
            assert dc_eng.handoffs_in == 2
            assert pf_eng.cache.free_blocks == pf_eng.cache.n_blocks
            assert dc_eng.cache.free_blocks == dc_eng.cache.n_blocks
        finally:
            for srv in servers:
                srv.stop()

    def test_long_prompt_handoff_bitwise(self, tiny):
        """A prompt near the context extent crosses many blocks through
        chunked prefill and a multi-block ship — the handoff byte math
        at its worst case, still bit-for-bit."""
        rng = np.random.RandomState(11)
        prompts = [list(rng.randint(0, 256, 57))]   # 8 blocks of 8
        ref = run_requests(make_engine(tiny), prompts, max_new=4)
        got, pf_eng, dc_eng = disagg_requests(
            tiny, prompts, max_new=4, prefill_kw={"prefill_chunk": 16})
        assert_bitwise_equal(got, ref)
        assert pf_eng.blocks_shipped == 8
        assert dc_eng.cache.imported_total == 8
