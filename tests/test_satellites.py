"""Satellite-module tests: azkaban job-file shim, TPU discovery, TPU-VM
scheduler command construction (SURVEY.md §2.2 satellites + §2.1 GPU
discovery analogue)."""

import io
from pathlib import Path

from tony_tpu import conf as conf_mod
from tony_tpu.azkaban import job_file_conf, parse_job_file
from tony_tpu.cli import main as cli_main
from tony_tpu.discovery import TpuTopology, _chips_from_env, discover_tpus
from tony_tpu.scheduler import ContainerLaunch, TpuVmScheduler

WORKLOADS = Path(__file__).parent / "workloads"


def test_parse_job_file_properties_format(tmp_path):
    job = tmp_path / "train.job"
    job.write_text(
        "# a comment\n"
        "! another\n"
        "type=TonYJob\n"
        "job.name=nightly-train\n"
        "executes=python train.py \\\n"
        "  --epochs 3\n"
        "tony.worker.instances=4\n"
        "tony.worker.tpus=2\n")
    props = parse_job_file(job)
    assert props["type"] == "TonYJob"
    assert props["executes"] == "python train.py --epochs 3"
    assert props["tony.worker.instances"] == "4"


def test_job_file_conf_translation(tmp_path):
    job = tmp_path / "train.job"
    job.write_text(
        "job.name=nightly\n"
        "framework=jax\n"
        "src.dir=/data/src\n"
        "executes=python train.py\n"
        "tony.worker.instances=2\n")
    cfg, src_dir = job_file_conf(job)
    assert src_dir == "/data/src"
    assert cfg.get(conf_mod.APPLICATION_NAME) == "nightly"
    assert cfg.get(conf_mod.APPLICATION_FRAMEWORK) == "jax"
    assert cfg.get("tony.application.executes") == "python train.py"
    assert cfg.instances("worker") == 2


def test_azkaban_cli_submits_end_to_end(tmp_path):
    job = tmp_path / "smoke.job"
    job.write_text(
        "framework=standalone\n"
        f"src.dir={WORKLOADS}\n"
        "executes=python exit_0.py\n"
        "tony.worker.instances=1\n"
        "tony.task.heartbeat-interval-ms=200\n")
    rc = cli_main(["azkaban", str(job), "--workdir", str(tmp_path / "jobs"),
                   "--timeout", "90"])
    assert rc == 0


def test_discovery_env_paths():
    assert _chips_from_env({"TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1"}) == 4
    assert _chips_from_env({"TPU_VISIBLE_DEVICES": "0,1,2"}) == 3
    assert _chips_from_env({}) is None
    topo = discover_tpus()
    assert isinstance(topo, TpuTopology)
    assert topo.num_chips >= 0


def test_am_rejects_tpu_ask_on_chipless_host(tmp_path, monkeypatch):
    """tpus>0 with zero discovered chips must fail loudly, not become an
    unlimited-scheduler launch; tony.scheduler.total-tpus overrides."""
    import pytest
    from tony_tpu.am import ApplicationMaster
    from tony_tpu.conf import TonyConfig
    import tony_tpu.discovery as disc
    monkeypatch.setattr(disc, "discover_tpus",
                        lambda use_jax=False: disc.TpuTopology(0, "none"))
    props = {"tony.worker.instances": "1", "tony.worker.tpus": "4",
             "tony.application.framework": "standalone"}
    with pytest.raises(ValueError, match="no TPU chips"):
        ApplicationMaster(TonyConfig(props), "app_t", tmp_path / "j")
    am = ApplicationMaster(
        TonyConfig({**props, "tony.scheduler.total-tpus": "8"}),
        "app_t2", tmp_path / "j2")
    assert am.scheduler.total_tpus == 8


def test_tpuvm_scheduler_fake_ssh_e2e(tmp_path):
    """The multi-host path end-to-end with ssh faked as a local shim: conf +
    src stage over the tar|ssh pipeline, the executor launches 'remotely',
    registers, runs the workload, and the job succeeds."""
    import os
    import stat
    import sys

    from tony_tpu.am import ApplicationMaster
    from tony_tpu.conf import TonyConfig
    from tony_tpu.minipod import MiniPodJob
    from tony_tpu.util import PKG_ROOT

    fake = tmp_path / "fakessh.sh"
    fake.write_text("#!/bin/sh\nshift\nexec sh -c \"$*\"\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    conf = TonyConfig({
        "tony.application.framework": "standalone",
        "tony.worker.instances": "1",
        "tony.application.executes": "python exit_0.py",
        "tony.task.heartbeat-interval-ms": "200",
    })
    job_dir = tmp_path / "job"
    (job_dir / "src").mkdir(parents=True)
    import shutil
    for wl in ("exit_0.py",):
        shutil.copy(WORKLOADS / wl, job_dir / "src" / wl)
    sched = TpuVmScheduler(
        hosts=["localhost"], ssh_cmd=str(fake),
        remote_python=sys.executable,
        remote_workdir=str(tmp_path / "remote"),
        remote_pythonpath=PKG_ROOT)
    am = ApplicationMaster(conf, app_id="app_tpuvm", job_dir=job_dir,
                           scheduler=sched)
    job = MiniPodJob(am).start()
    assert job.wait(timeout=90) == 0
    # The remote workdir really was staged and used.
    assert (tmp_path / "remote" / "src" / "exit_0.py").is_file()
    assert (tmp_path / "remote" / "conf" / "tony-job.json").is_file()


def test_scheduler_from_conf_backends(tmp_path):
    import pytest
    from tony_tpu.conf import TonyConfig
    from tony_tpu.scheduler import scheduler_from_conf
    # local (default) → None: caller builds LocalProcessScheduler.
    assert scheduler_from_conf(TonyConfig(), tmp_path) is None
    # tpu-vm honors hosts and the node blacklist.
    sched = scheduler_from_conf(TonyConfig({
        "tony.scheduler.backend": "tpu-vm",
        "tony.scheduler.hosts": "10.0.0.1,10.0.0.2,10.0.0.3",
        "tony.application.node-blacklist": "10.0.0.2",
    }), tmp_path)
    assert isinstance(sched, TpuVmScheduler)
    assert sched.hosts == ["10.0.0.1", "10.0.0.3"]
    with pytest.raises(ValueError, match="needs tony.scheduler.hosts"):
        scheduler_from_conf(TonyConfig({
            "tony.scheduler.backend": "tpu-vm"}), tmp_path)
    with pytest.raises(ValueError, match="unknown tony.scheduler.backend"):
        scheduler_from_conf(TonyConfig({
            "tony.scheduler.backend": "k8s"}), tmp_path)


def test_tpuvm_scheduler_remote_command():
    sched = TpuVmScheduler(hosts=["10.0.0.1", "10.0.0.2"],
                           remote_workdir="/tmp/tt")
    launch = ContainerLaunch(job_type="worker", index=0,
                             env={"TONY_JOB_NAME": "worker",
                                  "TONY_AM_ADDRESS": "10.0.0.9:1234"})
    argv = sched.build_remote_command(launch, "10.0.0.1", cid="c01")
    assert argv[0] == "ssh" and argv[1] == "10.0.0.1"
    remote = argv[2]
    assert "mkdir -p /tmp/tt" in remote
    assert "export TONY_AM_ADDRESS=10.0.0.9:1234;" in remote
    assert "export TONY_EXECUTOR_HOST=10.0.0.1;" in remote
    # Remote lifecycle contract: setsid + pidfile so a second ssh exec can
    # kill the remote process group; wait propagates the exit code.
    assert "setsid python3 -m tony_tpu.executor" in remote
    assert "pids/c01.pid" in remote
    assert "wait $pid" in remote


def test_tpuvm_chip_accounting_and_venv_rewrite(tmp_path):
    sched = TpuVmScheduler(hosts=["a", "b"], remote_workdir="/tmp/tt",
                           host_tpus=4)
    # 4-chip asks land on distinct hosts; a third cannot fit anywhere.
    l4 = ContainerLaunch(job_type="worker", index=0, env={}, tpus=4)
    h1 = sched._host_for(l4)
    h2 = sched._host_for(l4)
    assert {h1, h2} == {"a", "b"}
    import pytest
    with pytest.raises(RuntimeError, match="unsatisfiable"):
        sched._host_for(l4)
    with pytest.raises(RuntimeError, match="unsatisfiable"):
        sched._host_for(ContainerLaunch(
            job_type="worker", index=9, env={}, tpus=8))
    # Venv paths rewrite to the staged worker-side copy (dir vs archive).
    venv_dir = tmp_path / "venv"
    venv_dir.mkdir()
    argv = sched.build_remote_command(ContainerLaunch(
        job_type="w", index=0, env={"TONY_VENV": str(venv_dir)}), "a")
    assert "export TONY_VENV=/tmp/tt/venv-stage;" in argv[2]
    venv_zip = tmp_path / "venv.tar.gz"
    venv_zip.write_bytes(b"x")
    argv = sched.build_remote_command(ContainerLaunch(
        job_type="w", index=0, env={"TONY_VENV": str(venv_zip)}), "a")
    assert "export TONY_VENV=/tmp/tt/venv-stage/venv.tar.gz;" in argv[2]
    # tony.containers.resources: the staged dir rewrites to the worker copy.
    argv = sched.build_remote_command(ContainerLaunch(
        job_type="w", index=0,
        env={"TONY_RESOURCES_DIR": str(tmp_path)}), "a")
    assert "export TONY_RESOURCES_DIR=/tmp/tt/resources;" in argv[2]


def test_docker_wrap_command_unit():
    import pytest
    from tony_tpu.conf import TonyConfig
    from tony_tpu.scheduler import docker_wrap_command
    argv = ["python", "-m", "tony_tpu.executor"]
    # Disabled (default): passthrough untouched.
    assert docker_wrap_command(TonyConfig(), argv) == argv
    # Enabled: wrapped in docker run with the curated env (-e), job-dir
    # bind mount (-v), container workdir (-w), and the configured image —
    # the YARN launch-context contract, not a bare image invocation.
    conf = TonyConfig({"tony.docker.enabled": "true",
                       "tony.docker.containers.image": "img:1"})
    wrapped = docker_wrap_command(
        conf, argv, env={"TONY_AM_ADDRESS": "h:1", "TONY_JOB_NAME": "w"},
        workdir="/jobs/app1/containers/c1", mounts=["/jobs/app1"])
    assert wrapped[:2] == ["docker", "run"]
    assert wrapped[-3:] == argv
    img_at = wrapped.index("img:1")
    head = wrapped[:img_at]
    assert "-v" in head and "/jobs/app1:/jobs/app1" in head
    assert "-w" in head and "/jobs/app1/containers/c1" in head
    assert "TONY_AM_ADDRESS=h:1" in head and "TONY_JOB_NAME=w" in head
    # Host environ must NOT leak into the container env.
    assert not any(a.startswith("PATH=") for a in head)
    # Enabled without an image: loud failure, not a silent no-op.
    with pytest.raises(ValueError, match="tony.docker.containers.image"):
        docker_wrap_command(
            TonyConfig({"tony.docker.enabled": "true"}), argv)


def test_remote_interpreter_site_flag_gated_on_pythonpath():
    """-S (the sitecustomize latency cut) is legal remotely ONLY when
    tony_tpu arrives via remote_pythonpath; a pip-installed remote needs
    the site import to find tony_tpu at all."""
    launch = ContainerLaunch(job_type="w", index=0, env={})
    with_pp = TpuVmScheduler(hosts=["a"], remote_workdir="/tmp/tt",
                             remote_pythonpath="/opt/tony")
    assert "-S -m tony_tpu.executor" in with_pp.build_remote_command(
        launch, "a")[2]
    without_pp = TpuVmScheduler(hosts=["a"], remote_workdir="/tmp/tt")
    remote = without_pp.build_remote_command(launch, "a")[2]
    assert "-S" not in remote and "-m tony_tpu.executor" in remote
