"""Test harness config.

Control-plane tests are pure Python. Compute-plane tests (models/, parallel/)
run JAX on a virtual 8-device CPU mesh — the MiniYARNCluster analogue for
sharding (SURVEY.md §4): multi-chip layouts compile and execute without TPU
hardware. The env vars must be set before jax initializes its backends, hence
the sitecustomize-style assignment at import time here.
"""

import os
import sys
from pathlib import Path

# Force (not setdefault): the session env pins JAX_PLATFORMS to the real TPU
# plugin; tests must run on the virtual CPU mesh regardless. The site
# customization imports jax at interpreter start, which latches JAX_PLATFORMS
# into jax's config before this file runs — so update the config directly
# too (safe: backends aren't initialized until first use).
os.environ["JAX_PLATFORMS"] = "cpu"
existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in existing:
    os.environ["XLA_FLAGS"] = (
        existing + " --xla_force_host_platform_device_count=8").strip()
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover — jax is baked into this image
    pass

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
