"""Test harness config.

Control-plane tests are pure Python. Compute-plane tests (models/, parallel/)
run JAX on a virtual 8-device CPU mesh — the MiniYARNCluster analogue for
sharding (SURVEY.md §4): multi-chip layouts compile and execute without TPU
hardware. The env vars must be set before jax initializes its backends, hence
the sitecustomize-style assignment at import time here.
"""

import os
import sys
from pathlib import Path

# Force (not setdefault): the session env pins JAX_PLATFORMS to the real TPU
# plugin; tests must run on the virtual CPU mesh regardless. The site
# customization imports jax at interpreter start, which latches JAX_PLATFORMS
# into jax's config before this file runs — so update the config directly
# too (safe: backends aren't initialized until first use).
os.environ["JAX_PLATFORMS"] = "cpu"
existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in existing:
    os.environ["XLA_FLAGS"] = (
        existing + " --xla_force_host_platform_device_count=8").strip()
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover — jax is baked into this image
    pass

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import pytest  # noqa: E402

# Suite tiers (VERDICT r4 weak #5: 176 tests had outgrown a single
# undifferentiated run). Marked per MODULE — a test's cost class is set by
# its harness (pure logic vs jax compiles vs live subprocesses), which is
# per-file here. Measured on this host, one pytest process:
#   quick ≈ 35s | jit ≈ 6min (compiles) | e2e ≈ 8min (real processes)
_TIER_BY_MODULE = {
    "test_conf": "quick", "test_session": "quick", "test_rpc": "quick",
    "test_runtimes": "quick", "test_security": "quick",
    "test_executor": "quick", "test_satellites": "quick",
    "test_checkpoint": "jit", "test_ckpt": "jit", "test_data": "jit",
    "test_ops": "jit", "test_fused_optim": "jit", "test_quant": "jit",
    "test_models": "jit",
    "test_moe": "jit", "test_batchnorm": "jit", "test_parallel": "jit",
    "test_pipeline": "jit", "test_overlap": "jit", "test_multislice": "jit",
    "test_sched": "jit",
    "test_analysis": "jit",
    "test_concurrency": "jit",
    "test_serve": "jit",
    "test_spec": "jit",
    "test_route": "jit",
    "test_disagg": "jit",
    "test_kvtier": "jit",
    "test_aot": "jit",
    "test_qos": "jit",
    "test_elastic": "jit",
    "test_publish": "jit",
    "test_e2e": "e2e", "test_client_cli": "e2e",
}


def pytest_collection_modifyitems(items):
    for item in items:
        # Unmapped modules default to the jit tier (still selected by the
        # documented full tiers) rather than silently carrying no marker —
        # a marker-filtered run must never skip a new file with no signal.
        tier = _TIER_BY_MODULE.get(item.module.__name__, "jit")
        item.add_marker(getattr(pytest.mark, tier))


# ---------------------------------------------------------------------------
# Thread-leak guard (the concurrency-analysis plane's test-side half):
# every test must leave no stray NON-daemon thread behind — a non-daemon
# survivor outlives pytest silently and is exactly the shutdown-hygiene
# drift the static audit polices in the package. Daemon threads are not
# policed here (the interpreter reaps them; the audit still requires the
# construction site to declare them), and neither are the long-lived
# helpers below, discovered while landing the guard.
# ---------------------------------------------------------------------------

_THREAD_ALLOWLIST_PREFIXES = (
    # concurrent.futures keeps idle non-daemon workers for reuse and joins
    # them at interpreter exit; the AM's launch pool ("launch_*") is
    # shut down per attempt but its last workers unwind asynchronously.
    "ThreadPoolExecutor",
    "launch",
    # jax/XLA host runtime helpers (platform-dependent; created once per
    # process on first compile, never per test).
    "jax_",
)


@pytest.fixture(autouse=True)
def _thread_leak_guard():
    import threading
    import time

    # Thread OBJECTS, not idents: CPython reuses a dead thread's ident,
    # so an ident snapshot could silently exclude a genuine leak.
    before = set(threading.enumerate())
    yield

    def strays():
        return [t for t in threading.enumerate()
                if t.is_alive() and not t.daemon
                and t not in before
                and t is not threading.current_thread()
                and not any(t.name.startswith(p)
                            for p in _THREAD_ALLOWLIST_PREFIXES)]

    # Grace window: teardown that signalled its threads deserves one
    # scheduler beat to see them unwind before the verdict.
    leaked = strays()
    deadline = time.monotonic() + 2.0
    while leaked and time.monotonic() < deadline:
        for t in leaked:
            t.join(timeout=0.2)
        leaked = strays()
    assert not leaked, (
        f"test leaked non-daemon thread(s): "
        f"{[t.name for t in leaked]} — join them on a teardown path, "
        f"or extend the conftest allowlist with an audited reason")
