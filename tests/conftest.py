"""Test harness config.

Control-plane tests are pure Python. Compute-plane tests (models/, parallel/)
run JAX on a virtual 8-device CPU mesh — the MiniYARNCluster analogue for
sharding (SURVEY.md §4): multi-chip layouts compile and execute without TPU
hardware. The env vars must be set before jax initializes its backends, hence
the sitecustomize-style assignment at import time here.
"""

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in existing:
    os.environ["XLA_FLAGS"] = (
        existing + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
