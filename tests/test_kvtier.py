"""KV-memory-hierarchy legs (tony_tpu.serve PR 16): the host-offload
tier (demote/promote with bytes verbatim, CRC-guarded host payloads,
the extended free/LRU/host partition), conversation parking pinned
BITWISE vs a never-parked engine (ragged lengths, prefix-cache / spec /
disagg composition, typed pool-pressure degrades that never wedge), and
the persistent prefix store (stage-and-rename commit, engine/replica
stem adoption)."""

from __future__ import annotations

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.kvtier


# ---------------------------------------------------------------------------
# Shared tiny model + params (built once; serving is read-only on params).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


def assert_bitwise(got, ref, what):
    assert got.tokens == ref.tokens, f"{what}: token streams differ"
    assert got.logits is not None and ref.logits is not None
    assert len(got.logits) == len(ref.logits)
    for j, (g, r) in enumerate(zip(got.logits, ref.logits)):
        assert np.array_equal(g, r), (
            f"{what}: logits row {j} differs "
            f"(max abs diff {np.max(np.abs(np.asarray(g) - np.asarray(r)))})")


def run_conversation(eng, turns, conv, max_new=4):
    """Drive a multi-turn conversation: each turn's prompt is the FULL
    history (prior prompt + generated tokens) plus the new user tokens —
    the chat-completion wire shape. Returns the per-turn completions."""
    from tony_tpu.serve import EngineFront

    front = EngineFront(eng)
    history: list = []
    outs = []
    for t in turns:
        prompt = history + [int(x) for x in t]
        kw = {} if conv is None else {"conv": conv}
        c = front.generate(prompt, max_new, **kw)
        outs.append(c)
        history = prompt + list(c.tokens)
    return outs


def cache_snapshot(c):
    return (dict(c._refs), list(c._free), c.cached_blocks(),
            {s: list(t) for s, t in c.owned_blocks().items()},
            list(c.host_keys()), list(c.parked_ids()))


def check_partition(c):
    """The pool partition, host tier included: free + cached + owned
    cover the device ids exactly; host keys never shadow device keys;
    parked ids never alias live tables."""
    owned = {}
    for t in c.owned_blocks().values():
        for b in t:
            owned[b] = owned.get(b, 0) + 1
    free, lru = set(c._free), set(c.cached_blocks())
    assert not free & lru
    assert not (free | lru) & set(owned)
    assert free | lru | set(owned) == set(range(c.n_blocks))
    assert not set(c.host_keys()) & set(c._index)
    assert c.host_blocks_used <= max(0, c.host_blocks)
    assert not set(c.parked_ids()) & set(c.owned_blocks())


# ---------------------------------------------------------------------------
# Host tier: demote / promote / park / resume at the pool level
# ---------------------------------------------------------------------------

class TestHostTier:
    def _pool(self, n_blocks=8, block_size=4, host_blocks=8, **kw):
        from tony_tpu.serve import PagedKVCache

        return PagedKVCache(2, 8, n_blocks=n_blocks,
                            block_size=block_size,
                            host_blocks=host_blocks, **kw)

    def _keys(self, tokens, bs=4):
        from tony_tpu.serve import prefix

        return prefix.chain_keys(tokens, bs)

    def _publish(self, c, sid, tokens):
        keys = self._keys(tokens, c.block_size)
        c.admit_shared(sid, len(tokens), keys)
        for i, key in enumerate(keys):
            c.write_index(sid, i * c.block_size)
            c.publish_block(sid, i, key)
        return keys

    def test_demote_promote_round_trip_bytes_verbatim(self):
        c = self._pool()
        toks = list(range(8))
        keys = self._publish(c, "a", toks)
        c.free_seq("a")                    # refcount-0 cached tier
        assert c.cached_blocks()
        # Capture device bytes before demotion for the verbatim check.
        before = {k: (np.asarray(c.k[:, c._index[k]]),
                      np.asarray(c.v[:, c._index[k]])) for k in keys}
        assert c.demote(len(keys)) == len(keys)
        assert set(c.host_keys()) == set(keys)
        assert c.demoted_total == len(keys)
        assert not set(keys) & set(c._index), \
            "a demoted key must leave the device index"
        check_partition(c)
        # Promotion re-stages the chain and the bytes come back verbatim.
        assert c.promote(keys) == len(keys)
        assert c.host_keys() == []
        assert c.promoted_total == len(keys)
        for k in keys:
            b = c._index[k]
            assert np.array_equal(np.asarray(c.k[:, b]), before[k][0])
            assert np.array_equal(np.asarray(c.v[:, b]), before[k][1])
        # Promoted blocks sit refcount-0 in the cached tier: a shared
        # admission adopts them like any published stem.
        assert c.match_prefix(keys) and len(c.match_prefix(keys)) == \
            len(keys)
        check_partition(c)

    def test_promote_consumes_lifo_tier_only(self):
        """Promotion under device pressure degrades (truncates to the
        free list) instead of allocating through LRU eviction — which
        could evict, or re-demote, the very chain being promoted."""
        c = self._pool(n_blocks=4, block_size=4)
        keys = self._publish(c, "a", list(range(8)))   # 2 blocks
        c.free_seq("a")
        assert c.demote(2) == 2
        c.reserve("hog", 16)               # all 4 device blocks owned
        assert c.promote(keys) == 0, \
            "no free block: promote must degrade, not evict"
        assert set(c.host_keys()) == set(keys)
        c.free_seq("hog")
        assert c.promote(keys) == 2
        check_partition(c)

    def test_host_crc_corruption_rejected_state_unchanged(self):
        from tony_tpu.serve import HandoffError

        c = self._pool()
        keys = self._publish(c, "a", list(range(8)))
        c.free_seq("a")
        c.demote(len(keys))
        c._host_index[keys[0]]["crc"] ^= 1
        snap = cache_snapshot(c)
        with pytest.raises(HandoffError) as ei:
            c.promote(keys)
        assert not ei.value.retryable
        assert cache_snapshot(c) == snap, \
            "a corrupt host payload must reject with BOTH tiers unchanged"
        # The poison entry discards cleanly; the chain recomputes fresh.
        assert c.discard_host(keys) == len(keys)
        assert c.host_keys() == []

    def test_host_tier_budget_reclaims_stems_never_parked(self):
        c = self._pool(n_blocks=12, block_size=4, host_blocks=3)
        keys = self._publish(c, "a", list(range(8)))   # 2 stem blocks
        c.free_seq("a")
        assert c.demote(2) == 2
        c.reserve("p", 12)                             # 3 blocks
        # Parking 3 blocks forces the 2 stems out (they are the only
        # legal victims) — and a SECOND park must then fail typed.
        from tony_tpu.serve import AdmissionError

        assert c.park("p", 12, keys=self._keys(list(range(12)))) == 3
        assert c.host_keys() == [], "stems are the reclaim victims"
        assert c.host_blocks_used == 3
        c.reserve("q", 4)
        with pytest.raises(AdmissionError) as ei:
            c.park("q", 4, keys=self._keys(list(range(4))))
        assert ei.value.retryable
        assert "q" in c.owned_blocks(), "failed park leaves the seq live"
        assert "p" in c.parked_ids()
        del keys

    def test_park_resume_round_trip_sync(self):
        c = self._pool()
        toks = list(range(10))             # 2 full blocks + partial tail
        keys = self._keys(toks)[:2]
        c.reserve("s", 12)
        for i in range(3):
            c.write_index("s", i * 4)
        want = [(np.asarray(c.k[:, b]), np.asarray(c.v[:, b]))
                for b in c.table("s")]
        assert c.park("s", 10, keys=keys) == 3
        assert "s" not in c.owned_blocks()
        assert c.parked_ids() == ["s"]
        check_partition(c)
        adopted = c.resume("s2", 14, "s")
        assert c.parked_ids() == []
        t = c.table("s2")
        for i in range(3):
            assert np.array_equal(np.asarray(c.k[:, t[i]]), want[i][0])
            assert np.array_equal(np.asarray(c.v[:, t[i]]), want[i][1])
        assert c.parked_total == 1 and c.resumed_total == 1
        assert adopted >= 0
        check_partition(c)

    def test_park_async_offload_worker_and_close(self):
        """The async double-buffer path: encode happens off-thread, the
        ready event gates the resume, and close() joins the worker (the
        thread-hygiene contract the conftest guard polices)."""
        c = self._pool(async_offload=True)
        try:
            assert any(t.name == "tony-kv-offload"
                       for t in threading.enumerate())
            toks = list(range(8))
            c.reserve("s", 8)
            for i in range(2):
                c.write_index("s", i * 4)
            want = [(np.asarray(c.k[:, b]), np.asarray(c.v[:, b]))
                    for b in c.table("s")]
            c.park("s", 8, keys=self._keys(toks))
            c.resume("s2", 12, "s")        # waits on the ready event
            t = c.table("s2")
            for i in range(2):
                assert np.array_equal(np.asarray(c.k[:, t[i]]),
                                      want[i][0])
        finally:
            c.close()
        assert not any(t.name == "tony-kv-offload"
                       for t in threading.enumerate())

    def test_parked_crc_corruption_rejected_record_kept(self):
        from tony_tpu.serve import HandoffError

        c = self._pool()
        c.reserve("s", 8)
        for i in range(2):
            c.write_index("s", i * 4)
        c.park("s", 8, keys=self._keys(list(range(8))))
        rec = c._parked["s"]
        rec["blocks"][0]["crc"] ^= 1
        snap = cache_snapshot(c)
        with pytest.raises(HandoffError):
            c.resume("s2", 12, "s")
        assert cache_snapshot(c) == snap, \
            "a corrupt resume must leave pool AND record unchanged"
        rec["blocks"][0]["crc"] ^= 1       # restore: record still good
        assert c.resume("s2", 12, "s") >= 0

    def test_park_tier_off_typed_state_unchanged(self):
        from tony_tpu.serve import AdmissionError

        c = self._pool(host_blocks=0)
        c.reserve("s", 8)
        snap = cache_snapshot(c)
        with pytest.raises(AdmissionError):
            c.park("s", 8, keys=self._keys(list(range(8))))
        assert cache_snapshot(c) == snap

    def test_park_validates_geometry(self):
        c = self._pool()
        c.reserve("s", 8)
        with pytest.raises(ValueError):
            c.park("s", 8, keys=[])        # needs 2 chain keys
        with pytest.raises(ValueError):
            c.park("s", 99, keys=[])       # beyond the held extent
        with pytest.raises(KeyError):
            c.resume("x", 8, "never-parked")
        assert c.unpark("never-parked") == 0


# ---------------------------------------------------------------------------
# Conversation parking: bitwise parity vs a never-parked engine
# ---------------------------------------------------------------------------

class TestParkingParity:
    def test_two_turn_resume_bitwise_and_counted(self, tiny):
        """The core contract: turn 2 of a parked conversation resumes
        from the host tier — zero prefill launches for the shared
        history — and its token stream AND per-token logits are bitwise
        identical to a never-parked engine's."""
        parked = make_engine(tiny, host_blocks=64)
        plain = make_engine(tiny)
        rng = np.random.RandomState(21)
        turns = [list(rng.randint(0, 256, 11)),
                 list(rng.randint(0, 256, 5))]
        got = run_conversation(parked, turns, conv="c1")
        ref = run_conversation(plain, turns, conv=None)
        for g, r in zip(got, ref):
            assert_bitwise(g, r, "two-turn parked vs never-parked")
        assert parked.park_hits == 1 and parked.park_lookups == 2
        s = parked.stats()
        assert s["park_hit_rate"] == 0.5
        assert s["parked_seqs"] == 1.0      # turn 2 re-parked on finish
        assert parked.parked_digest() == ["c1"]
        # The resumed turn skipped the shared-history prefill rows.
        assert parked.prefill_rows < plain.prefill_rows

    @pytest.mark.slow
    def test_park_resume_bitwise_ragged_lengths(self, tiny):
        """Ragged turn-1 lengths around the block/row-block boundaries:
        7/8/9/15/17 — partial tail blocks, exact block fits, and the
        q_block boundary all park and resume bitwise."""
        rng = np.random.RandomState(22)
        for n in (7, 8, 9, 15, 17):
            parked = make_engine(tiny, host_blocks=64)
            plain = make_engine(tiny)
            turns = [list(rng.randint(0, 256, n)),
                     list(rng.randint(0, 256, 4))]
            got = run_conversation(parked, turns, conv=f"c{n}")
            ref = run_conversation(plain, turns, conv=None)
            for g, r in zip(got, ref):
                assert_bitwise(g, r, f"ragged turn-1 length {n}")
            assert parked.park_hits == 1, f"length {n} must resume"
            parked.cache.close()

    def test_three_turn_conversation_reparks(self, tiny):
        parked = make_engine(tiny, host_blocks=64)
        plain = make_engine(tiny)
        rng = np.random.RandomState(23)
        turns = [list(rng.randint(0, 256, 9)),
                 list(rng.randint(0, 256, 3)),
                 list(rng.randint(0, 256, 5))]
        got = run_conversation(parked, turns, conv="c3", max_new=3)
        ref = run_conversation(plain, turns, conv=None, max_new=3)
        for g, r in zip(got, ref):
            assert_bitwise(g, r, "three-turn conversation")
        assert parked.park_hits == 2

    def test_diverged_turn_drops_record_and_reprefills(self, tiny):
        """An edited conversation (the second turn does not extend the
        parked history) must drop the record and admit fresh — correct
        output, no resume, no leak."""
        parked = make_engine(tiny, host_blocks=64)
        plain = make_engine(tiny)
        rng = np.random.RandomState(24)
        t1 = list(rng.randint(0, 256, 9))
        run_conversation(parked, [t1], conv="d1")
        edited = list(rng.randint(0, 256, 13))
        edited[0] = (t1[0] + 1) % 256               # not an extension
        from tony_tpu.serve import EngineFront

        got = EngineFront(parked).generate(edited, 4, conv="d1")
        ref = EngineFront(plain).generate(edited, 4)
        assert_bitwise(got, ref, "diverged turn")
        assert parked.park_hits == 0
        assert parked.cache.resumed_total == 0

    def test_park_composes_with_prefix_cache_shared_stem(self, tiny):
        """Parking + prefix caching: the resumed turn's blocks publish
        back into the prefix tier, a SECOND conversation sharing the
        stem adopts them (no COW, no stranded published block), and
        both stay bitwise vs prefix-only engines."""
        parked = make_engine(tiny, host_blocks=64, prefix_cache=True)
        plain = make_engine(tiny, prefix_cache=True)
        rng = np.random.RandomState(25)
        stem = list(rng.randint(0, 256, 8))
        turns = [stem + list(rng.randint(0, 256, 3)),
                 list(rng.randint(0, 256, 4))]
        got = run_conversation(parked, turns, conv="p1")
        ref = run_conversation(plain, turns, conv=None)
        for g, r in zip(got, ref):
            assert_bitwise(g, r, "parked+prefix vs prefix-only")
        # A second conversation over the same stem adopts the published
        # blocks on BOTH engines — sharing stays shared through a park.
        t2 = [stem + list(rng.randint(0, 256, 5))]
        got2 = run_conversation(parked, t2, conv="p2")
        ref2 = run_conversation(plain, t2, conv=None)
        assert_bitwise(got2[0], ref2[0], "second conv over shared stem")
        assert parked.prefix_hit_blocks > 0
        check_partition(parked.cache)
        # Nothing strands: dropping every parked record and the cached
        # tier returns the whole pool.
        for conv in list(parked._parked):
            rec = parked._parked.pop(conv)
            parked.cache.unpark(rec["rid"])
        assert parked.cache.free_blocks == parked.cache.n_blocks

    def test_spec_engine_parks_and_resumes_bitwise(self, tiny):
        """The speculative lane rides the host tier through the same
        ctor kwargs; greedy parity holds across a park/resume."""
        from tony_tpu.serve import SpecEngine

        model, params = tiny
        spec = SpecEngine(model, params, spec_k=2, ctx_max=64,
                          block_size=8, q_block=16, decode_buckets=(2, 4),
                          max_running=4, keep_logits=True,
                          host_blocks=64)
        plain = make_engine(tiny)
        rng = np.random.RandomState(26)
        turns = [list(rng.randint(0, 256, 9)),
                 list(rng.randint(0, 256, 4))]
        got = run_conversation(spec, turns, conv="s1")
        ref = run_conversation(plain, turns, conv=None)
        for g, r in zip(got, ref):
            assert_bitwise(g, r, "spec parked vs plain never-parked")
        assert spec.park_hits == 1

    def test_pool_pressure_on_resume_degrades_to_reprefill(self, tiny):
        """Device pressure at resume time: the typed AdmissionError is
        counted (host_degraded), the record is dropped, and the turn
        re-prefills — bitwise correct, never wedged."""
        parked = make_engine(tiny, host_blocks=64)
        plain = make_engine(tiny)
        rng = np.random.RandomState(27)
        turns = [list(rng.randint(0, 256, 9))]
        run_conversation(parked, turns, conv="g1")
        run_conversation(plain, turns, conv=None)
        hist_parked = parked._parked["g1"]["tokens"]
        # Hog the device pool so the resume's reservation cannot fit:
        # the request must DEGRADE (typed, counted) and stay queued —
        # never wedge — then complete once the pressure clears.
        hog_extent = parked.cache.free_blocks * parked.cache.block_size
        parked.cache.reserve("hog", hog_extent)
        from tony_tpu.serve import Request

        turn2 = hist_parked + list(rng.randint(0, 256, 4))
        parked.submit(Request(rid="g1t2", tokens=turn2,
                              max_new_tokens=4, conv="g1"))
        # step() directly: run(max_steps=) bounds the engine's LIFETIME
        # step counter, which turn 1 already advanced past any small N.
        for _ in range(3):
            assert parked.step() == []
        assert parked.host_degraded == 1, "the degrade is counted"
        assert parked._parked == {}, "a failed resume drops the record"
        parked.cache.free_seq("hog")
        got = parked.run()
        from tony_tpu.serve import EngineFront

        ref = EngineFront(plain).generate(turn2, 4)
        assert len(got) == 1
        assert_bitwise(got[0], ref, "post-degrade re-prefill")

    def test_host_tier_off_engine_never_parks(self, tiny):
        eng = make_engine(tiny)
        rng = np.random.RandomState(28)
        run_conversation(eng, [list(rng.randint(0, 256, 9))], conv="x")
        assert eng._parked == {} and eng.cache.parked_total == 0
        assert eng.stats()["parked_seqs"] == 0.0


# ---------------------------------------------------------------------------
# Disaggregated composition: the decode replica parks, the returning
# turn resumes through the colocated fallback path
# ---------------------------------------------------------------------------

class TestDisaggParking:
    @pytest.mark.slow
    def test_decode_side_park_resume_bitwise(self, tiny):
        """Turn 1 rides the prefill→decode handoff (conv on the wire
        payload); the decode engine parks it at eviction. Turn 2 lands
        on the decode replica's colocated-fallback generate with the
        same conv and RESUMES — bitwise vs a never-parked colocated
        engine, with zero prefill launches for the parked extent."""
        from tony_tpu.serve import EngineFront
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        pf_eng = make_engine(tiny, role="prefill")
        dc_eng = make_engine(tiny, role="decode", host_blocks=64)
        plain = make_engine(tiny)
        pf = PrefillFront(EngineFront(pf_eng))
        dc = DecodeFront(EngineFront(dc_eng))
        rng = np.random.RandomState(29)
        t1 = list(rng.randint(0, 256, 9))
        out1 = pf.prefill_handoff(t1, 4, rid="h1", decode=dc,
                                  conv="dconv")
        ref1 = EngineFront(plain).generate(t1, 4)
        assert out1.tokens == ref1.tokens, "disagg turn 1 tokens"
        assert dc_eng.parked_digest() == ["dconv"], \
            "the decode engine holds the parked conversation"
        # Turn 2: full history + new tokens through the decode
        # replica's own front (the router's colocated fallback path).
        hist = t1 + list(out1.tokens)
        t2 = hist + list(rng.randint(0, 256, 6))
        rows_before = dc_eng.prefill_rows
        out2 = dc.generate(t2, 4, rid="h2", conv="dconv")
        plain_rows_before = plain.prefill_rows
        ref2 = EngineFront(plain).generate(t2, 4)
        assert_bitwise(out2, ref2, "disagg turn 2 resume")
        assert dc_eng.park_hits == 1
        # Only the tail past the parked extent prefilled: strictly
        # fewer padded rows than the never-parked full prefill.
        assert dc_eng.prefill_rows - rows_before \
            < plain.prefill_rows - plain_rows_before


# ---------------------------------------------------------------------------
# Persistent prefix store
# ---------------------------------------------------------------------------

class TestPrefixStore:
    def _pool_with_stem(self, tokens):
        from tony_tpu.serve import PagedKVCache, prefix

        c = PagedKVCache(2, 8, n_blocks=8, block_size=4)
        keys = prefix.chain_keys(tokens, 4)
        c.admit_shared("a", len(tokens), keys)
        for i, key in enumerate(keys):
            c.write_index("a", i * 4)
            c.publish_block("a", i, key)
        return c, keys

    def test_put_get_round_trip_idempotent(self, tmp_path):
        from tony_tpu.serve import PrefixStore

        c, keys = self._pool_with_stem(list(range(8)))
        blocks = c.export_keys(keys)
        store = PrefixStore(str(tmp_path / "stems"))
        assert store.stems() == []
        assert store.put(keys, blocks, c.wire_header()) is True
        assert store.put(keys, blocks, c.wire_header()) is False, \
            "a committed stem is idempotent"
        assert store.stems() == [keys[-1]]
        rec = store.get(keys[-1])
        assert rec is not None
        assert rec["keys"] == list(keys)
        assert rec["header"] == c.wire_header()
        # The wire-form payloads round-trip byte-exact (CRC included).
        for got, want in zip(rec["blocks"], blocks):
            assert got["crc"] == want["crc"]
            assert got["k"] == want["k"] and got["v"] == want["v"]

    def test_put_validates_and_get_rejects_corruption(self, tmp_path):
        from tony_tpu.serve import PrefixStore

        c, keys = self._pool_with_stem(list(range(8)))
        blocks = c.export_keys(keys)
        store = PrefixStore(str(tmp_path / "stems"))
        with pytest.raises(ValueError):
            store.put(keys[:1], blocks, c.wire_header())   # len mismatch
        bad = [dict(b) for b in blocks]
        bad[0]["crc"] ^= 1
        with pytest.raises(ValueError):
            store.put(keys, bad, c.wire_header())          # pre-write CRC
        store.put(keys, blocks, c.wire_header())
        # On-disk corruption: flip one byte of the chunk file — get()
        # returns None (the replica recomputes), never bad bytes.
        blob = tmp_path / "stems" / f"stem_{keys[-1]}" / "blocks.bin"
        raw = bytearray(blob.read_bytes())
        raw[3] ^= 1
        blob.write_bytes(bytes(raw))
        assert store.get(keys[-1]) is None

    def test_tmp_staging_is_invisible(self, tmp_path):
        from tony_tpu.serve import PrefixStore

        root = tmp_path / "stems"
        store = PrefixStore(str(root))
        (root / "stem_deadbeef.tmp").mkdir(parents=True)
        assert store.stems() == [], \
            "a crashed staging dir must never be listed as committed"
        assert store.get("deadbeef") is None

    def test_engine_export_adopt_round_trip_bitwise(self, tiny,
                                                    tmp_path):
        """The full loop: a hot stem (proved shared by a second prompt)
        exports to the store; a FRESH engine adopts it on start (the
        replica `_load_stems` path, duck-typed) and serves the stem's
        prompt with prefix hits — bitwise vs a cold engine."""
        from tony_tpu.serve import EngineFront, PrefixStore
        from tony_tpu.serve.replica import Replica

        src = make_engine(tiny, prefix_cache=True)
        rng = np.random.RandomState(31)
        stem = list(rng.randint(0, 256, 16))
        front = EngineFront(src)
        front.generate(stem + list(rng.randint(0, 256, 3)), 3)
        front.generate(stem + list(rng.randint(0, 256, 4)), 3)
        store = PrefixStore(str(tmp_path / "stems"))
        with front._drive:
            wrote = src.export_stems(store)
        assert wrote >= 1 and store.stems(), \
            "a twice-proved stem must persist"
        # A fresh replica adopts from the store on start.
        fresh = make_engine(tiny, prefix_cache=True)
        stub = Replica.__new__(Replica)
        stub.engine = fresh
        stub._store = store
        Replica._load_stems(stub)
        assert fresh.store_adopted > 0
        check_partition(fresh.cache)
        # The warmed engine serves the stem's NEXT prompt with prefix
        # hits and stays bitwise vs a cold engine.
        cold = make_engine(tiny, prefix_cache=True)
        probe = stem + list(rng.randint(0, 256, 5))
        got = EngineFront(fresh).generate(probe, 4)
        ref = EngineFront(cold).generate(probe, 4)
        assert_bitwise(got, ref, "store-warmed vs cold engine")
        assert fresh.prefix_hit_blocks > 0, \
            "the adopted stem must actually be hit"

    def test_load_stems_skips_geometry_mismatch(self, tiny, tmp_path):
        from tony_tpu.serve import PrefixStore
        from tony_tpu.serve.replica import Replica

        c, keys = self._pool_with_stem(list(range(8)))
        store = PrefixStore(str(tmp_path / "stems"))
        store.put(keys, c.export_keys(keys), c.wire_header())
        eng = make_engine(tiny, prefix_cache=True)   # different geometry
        stub = Replica.__new__(Replica)
        stub.engine = eng
        stub._store = store
        Replica._load_stems(stub)
        assert eng.store_adopted == 0, \
            "a geometry-skewed stem must be skipped, not imported"
        assert eng.cache.free_blocks == eng.cache.n_blocks

    def test_adopt_stem_rejects_bad_input_quietly(self, tiny):
        eng = make_engine(tiny, prefix_cache=True)
        assert eng.adopt_stem([], []) == 0
        assert eng.adopt_stem(["aa"], []) == 0        # length mismatch
        off = make_engine(tiny)                       # prefix cache off
        assert off.adopt_stem(["aa"], [{}]) == 0


# ---------------------------------------------------------------------------
# Stats surface (the uniform fleet schema's host-tier half)
# ---------------------------------------------------------------------------

class TestTierStats:
    def test_stats_count_tier_activity(self, tiny):
        eng = make_engine(tiny, host_blocks=64)
        rng = np.random.RandomState(32)
        turns = [list(rng.randint(0, 256, 9)),
                 list(rng.randint(0, 256, 4))]
        run_conversation(eng, turns, conv="st")
        s = eng.stats()
        assert s["parked_seqs"] == 1.0
        assert s["host_blocks"] >= 1.0
        assert s["park_hit_rate"] == 0.5
        assert set(eng.parked_digest()) == {"st"}

    def test_write_stats_carries_parked_digest(self, tiny, tmp_path):
        import json

        eng = make_engine(tiny, host_blocks=64)
        rng = np.random.RandomState(33)
        run_conversation(eng, [list(rng.randint(0, 256, 9))], conv="wd")
        path = tmp_path / "serve-stats.json"
        eng.write_stats(str(path))
        payload = json.loads(path.read_text())
        assert payload["parked_digest"] == ["wd"]
        assert payload["parked_seqs"] == 1.0
