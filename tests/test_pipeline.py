"""Pipeline-parallel tier (SURVEY.md §2.3 PP): the GPipe combinator on the
8-device CPU mesh — sequential equivalence, autodiff (reverse pipeline),
composition with data parallelism, and a pipelined llama-tiny block stack."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model
from tony_tpu.parallel import gpipe, gpipe_1f1b, stage_split


def _stage_fn(p, x):
    # One dense "layer" per stage slice: params [L_local, D, D].
    def body(h, w):
        return jnp.tanh(h @ w), None
    h, _ = jax.lax.scan(body, x, p)
    return h


def _sequential(params, x):
    def body(h, w):
        return jnp.tanh(h @ w), None
    h, _ = jax.lax.scan(body, x, params)
    return h


@pytest.mark.parametrize("pp,microbatches", [(2, 4), (4, 8)])
def test_gpipe_matches_sequential(pp, microbatches):
    mesh = par.MeshSpec(pp=pp).build(jax.devices())
    d, batch, layers = 16, 16, 4
    params = jax.random.normal(
        jax.random.PRNGKey(0), (layers, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    staged = stage_split(params, pp)
    y = jax.jit(lambda p, x: gpipe(
        _stage_fn, p, x, mesh, microbatches=microbatches))(staged, x)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_grads_match_sequential():
    """The backward pass is the autodiff reverse pipeline; grads must equal
    the unpipelined model's."""
    mesh = par.MeshSpec(pp=2).build(jax.devices())  # dp auto-fills to 4
    d, batch = 8, 16
    params = jax.random.normal(jax.random.PRNGKey(0), (2, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))

    def loss_pp(staged):
        return gpipe(_stage_fn, staged, x, mesh, microbatches=2).sum()

    def loss_seq(p):
        return _sequential(p, x).sum()

    g_pp = jax.jit(jax.grad(loss_pp))(stage_split(params, 2))
    g_seq = jax.grad(loss_seq)(params)
    np.testing.assert_allclose(
        np.asarray(g_pp.reshape(g_seq.shape)), np.asarray(g_seq),
        rtol=1e-4, atol=1e-5)


def test_gpipe_composes_with_dp():
    """dp=4 × pp=2: each DP group pipelines its own batch shard; the result
    must still equal the sequential reference on the full batch."""
    mesh = par.MeshSpec(dp=4, pp=2).build(jax.devices())
    d, batch = 8, 16
    params = jax.random.normal(jax.random.PRNGKey(0), (2, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    y = jax.jit(lambda p, x: gpipe(
        _stage_fn, p, x, mesh, microbatches=2))(stage_split(params, 2), x)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_rejects_indivisible_dp_batch():
    """A global batch that doesn't divide by the DP group count used to be
    silently truncated (floor division dropped the remainder rows); it must
    raise, naming both numbers."""
    mesh = par.MeshSpec(dp=4, pp=2).build(jax.devices())
    params = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (15, 8))
    with pytest.raises(ValueError, match="15.*4"):
        gpipe(_stage_fn, stage_split(params, 2), x, mesh, microbatches=1)
    with pytest.raises(ValueError, match="15.*4"):
        gpipe_1f1b(_stage_fn, stage_split(params, 2), x, mesh,
                   microbatches=1)


def test_gpipe_1f1b_matches_gpipe_4_stages():
    """THE numerical pin (acceptance): the 1F1B schedule's outputs equal
    the reference GPipe schedule's on a 4-stage mesh."""
    mesh = par.MeshSpec(pp=4).build(jax.devices())
    d, batch, layers = 16, 16, 8
    params = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    staged = stage_split(params, 4)
    y_ref = jax.jit(lambda p, x: gpipe(
        _stage_fn, p, x, mesh, microbatches=8))(staged, x)
    y = jax.jit(lambda p, x: gpipe_1f1b(
        _stage_fn, p, x, mesh, microbatches=8))(staged, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_1f1b_grads_match_gpipe_4_stages():
    """Backward pin (acceptance): the explicitly scheduled reverse
    pipeline (custom_vjp, stage-granularity remat) produces the same param
    AND input grads as gpipe's autodiff backward on a 4-stage mesh."""
    mesh = par.MeshSpec(pp=4).build(jax.devices())
    d, batch, layers = 16, 16, 8
    params = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    staged = stage_split(params, 4)

    def loss(which, p, xx):
        fn = gpipe if which == "ref" else gpipe_1f1b
        return (fn(_stage_fn, p, xx, mesh, microbatches=8) ** 2).sum()

    gp_ref, gx_ref = jax.jit(jax.grad(
        lambda p, xx: loss("ref", p, xx), argnums=(0, 1)))(staged, x)
    gp, gx = jax.jit(jax.grad(
        lambda p, xx: loss("1f1b", p, xx), argnums=(0, 1)))(staged, x)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gp_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-4, atol=1e-5)


def test_gpipe_1f1b_composes_with_dp_and_trains():
    """dp=2 × pp=4: per-group pipelines with the cross-group param-grad
    psum — grads must equal the unpipelined sequential model's, and a
    simple SGD loop must reduce the loss."""
    mesh = par.MeshSpec(dp=2, pp=4).build(jax.devices())
    d, batch, layers = 8, 16, 4
    params = jax.random.normal(jax.random.PRNGKey(0), (layers, d, d)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
    staged = stage_split(params, 4)

    def loss_pp(p):
        return (gpipe_1f1b(_stage_fn, p, x, mesh, microbatches=4)
                ** 2).sum()

    def loss_seq(p):
        return (_sequential(p, x) ** 2).sum()

    g_pp = jax.jit(jax.grad(loss_pp))(staged)
    g_seq = jax.grad(loss_seq)(params)
    np.testing.assert_allclose(np.asarray(g_pp.reshape(g_seq.shape)),
                               np.asarray(g_seq), rtol=1e-4, atol=1e-5)

    losses = []
    p = staged
    grad = jax.jit(jax.value_and_grad(loss_pp))
    for _ in range(5):
        l, g = grad(p)
        p = p - 0.01 * g
        losses.append(float(l))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_pipelined_llama_blocks_match_and_train():
    """llama-tiny's scanned block stack split into 2 pipeline stages:
    logits match the plain model, and a pipelined train step reduces the
    loss (PP composed with DP on a dp=4 × pp=2 mesh)."""
    from tony_tpu.parallel import pipelined_lm_logits

    mesh = par.MeshSpec(dp=4, pp=2).build(jax.devices())
    model = get_model("llama-tiny")
    cfg = model.cfg
    tokens = jax.random.randint(jax.random.PRNGKey(1), (16, 16), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-2), tokens, jax.random.PRNGKey(0))

    lp = jax.jit(lambda p: pipelined_lm_logits(
        p, tokens, cfg, mesh, n_stages=2, microbatches=4))(state.params)
    # Reference: the unmodified model forward on the same params.
    ls = jax.jit(lambda p: model.apply({"params": p}, tokens))(state.params)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(ls),
                               rtol=5e-2, atol=5e-2)

    def loss_fn(params):
        logits = pipelined_lm_logits(params, tokens, cfg, mesh,
                                     n_stages=2, microbatches=4)
        return train.next_token_loss(logits, tokens)

    @jax.jit
    def step(state):
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    losses = []
    for _ in range(5):
        state, loss = step(state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
