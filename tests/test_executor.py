"""Executor-side unit tests (reference tier: TaskExecutor/TaskMonitor unit
tests, SURVEY.md §4). The full lifecycle is covered by the MiniPod e2e tier;
these pin the pieces with failure modes too narrow to stage end-to-end."""

import os
import threading
import time

from tony_tpu.executor import TaskMonitor
from tony_tpu.rpc import RpcClient


class FlakyClient:
    """metrics_report sink that fails its first ``fail_first`` calls —
    a transient AM outage (e.g. an AM-relaunch window)."""

    def __init__(self, fail_first: int):
        self.fail_first = fail_first
        self.calls = 0
        self.delivered = []
        self.got_samples = threading.Event()

    def call(self, method, **params):
        self.calls += 1
        if self.calls <= self.fail_first:
            raise ConnectionError("AM unreachable (simulated)")
        self.delivered.append(params["metrics"])
        if len(self.delivered) >= 2:
            self.got_samples.set()


def test_task_monitor_survives_transient_rpc_failures():
    """VERDICT r3 #6: a failed metrics RPC must not kill the monitor —
    after the AM comes back, samples flow again."""
    client = FlakyClient(fail_first=3)
    mon = TaskMonitor(os.getpid(), client, "worker", 0, interval_s=0.02)
    mon.start()
    try:
        assert client.got_samples.wait(timeout=20), (
            f"no samples after AM recovery; {client.calls} calls, "
            f"{len(client.delivered)} delivered")
    finally:
        mon.stop()
    assert client.calls >= 5  # the 3 failures were retried through, not fatal


def test_task_monitor_backoff_resets_on_success():
    client = FlakyClient(fail_first=2)
    mon = TaskMonitor(os.getpid(), client, "worker", 0, interval_s=0.02)
    # Drive _run's loop logic synchronously via sample+call to keep the
    # timing assertion deterministic: after a success the wait interval
    # must drop back to the configured cadence.
    mon.start()
    try:
        assert client.got_samples.wait(timeout=20)
        n = len(client.delivered)
        time.sleep(0.5)
        # ≥ a handful of new samples in 0.5s proves backoff was reset
        # (stuck backoff would cap this near 0.5/interval_backoff ≈ 1).
        assert len(client.delivered) - n >= 3
    finally:
        mon.stop()


def test_rpc_client_worst_case_call_bound():
    """The client's AM-relaunch grace is derived from this bound; it must
    dominate the retry window plus one last blocking connect+recv."""
    assert RpcClient.worst_case_call_s(1.0) == 1.0 + 2.0 * 1.0
    # Long-timeout clients stay capped at the socket timeout per op.
    assert RpcClient.worst_case_call_s(60.0) == 60.0 + 2.0 * 10.0


def test_link_tree_localizes_by_hardlink(tmp_path):
    """Venv/src localization links instead of copying (metadata-only per
    container — the submit→all-running latency lever); content identical,
    falls back to copy only across filesystems."""
    from tony_tpu.executor import _link_tree

    src = tmp_path / "venv"
    (src / "bin").mkdir(parents=True)
    (src / "bin" / "python").write_text("#!/bin/sh\n")
    (src / "lib.py").write_text("x = 1\n")
    dest = tmp_path / "localized"
    _link_tree(src, dest)
    assert (dest / "bin" / "python").read_text() == "#!/bin/sh\n"
    assert (dest / "lib.py").stat().st_ino == (src / "lib.py").stat().st_ino


def test_heartbeat_reports_committed_ckpt_step(tmp_path):
    """The executor half of the checkpoint control plane: with a
    tony.ckpt.dir configured, the heartbeat loop scans the COMMITTED steps
    (never the .tmp staging dirs) and piggybacks the newest on the RPC."""
    import json

    from tony_tpu import constants
    from tony_tpu.conf import TonyConfig
    from tony_tpu.executor import TaskExecutor
    from tony_tpu.rpc import ApplicationRpcHandler, RpcServer
    from tony_tpu.session import TonySession

    ckpt_dir = tmp_path / "ckpt"
    # A committed step and a torn staging dir (only the former may count).
    committed = ckpt_dir / "step_00000005"
    committed.mkdir(parents=True)
    (committed / "manifest.json").write_text("{}")
    (ckpt_dir / "step_00000006.tmp").mkdir()

    conf = TonyConfig({"tony.worker.instances": "1",
                       "tony.ckpt.dir": str(ckpt_dir)})
    session = TonySession(conf, app_id="app_ckpt_hb")
    session.on_registered("worker", 0, "127.0.0.1", 4000)
    server = RpcServer(ApplicationRpcHandler(session),
                       host="127.0.0.1").start()
    conf_path = tmp_path / "conf.json"
    conf_path.write_text(json.dumps(dict(conf.items())))
    try:
        executor = TaskExecutor(env={
            constants.ENV_JOB_NAME: "worker",
            constants.ENV_TASK_INDEX: "0",
            constants.ENV_AM_ADDRESS: server.address,
            constants.ENV_CONF_PATH: str(conf_path),
        })
        t = threading.Thread(target=executor._heartbeat_loop,
                             args=(0.05,), daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline \
                and session.task("worker", 0).ckpt_step != 5:
            time.sleep(0.05)
        executor._hb_stop.set()
        t.join(timeout=5)
        assert session.task("worker", 0).ckpt_step == 5
        assert session.last_committed_step() == 5
    finally:
        server.stop()
