"""Checkpoint-subsystem tier (tony_tpu.ckpt): format crash consistency,
async overlap, elastic cross-topology restore — on the virtual 8-device CPU
mesh. The compat-shim surface pins live in test_checkpoint.py; the e2e
gang-restart resume in test_e2e.py."""

import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu import ckpt
from tony_tpu import parallel as par
from tony_tpu import profiler, train
from tony_tpu.benchmark import fsdp_shard_state
from tony_tpu.ckpt import format as fmt
from tony_tpu.models import get_model

pytestmark = pytest.mark.ckpt


def _state(mesh=None, hidden=32, key=0):
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(key), 3)
    x = jax.random.normal(kx, (16, 784), jnp.float32)
    y = jax.random.randint(ky, (16,), 0, 10)
    state = train.create_train_state(
        model, optax.sgd(0.1, momentum=0.9), x, kr)
    return state, {"x": x, "y": y}


def _leaves_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if hasattr(y, "shape"):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)))


class TestFormat:
    def test_commit_is_atomic_rename(self, tmp_path):
        tree = {"w": jnp.arange(12.0).reshape(3, 4), "n": jnp.int32(7)}
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(tree, step=5, block=True)
        c.close()
        assert fmt.committed_steps(tmp_path) == [5]
        manifest = fmt.read_manifest(tmp_path, 5)
        assert manifest["format"] == fmt.FORMAT_VERSION
        assert {m["path"] for m in manifest["leaves"]} \
            == {"['n']", "['w']"}
        # Every chunk checksummed; every file listed.
        assert all("crc32" in ch for ch in manifest["chunks"])
        assert manifest["files"][0]["file"] == fmt.shard_file_name(0)

    def test_latest_step_ignores_staging_and_garbage(self, tmp_path):
        tree = {"w": jnp.ones((2, 2))}
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(tree, step=1, block=True)
        c.close()
        # A torn tmp dir from a crashed writer and a committed-looking dir
        # without a manifest must both be invisible.
        (tmp_path / "step_00000002.tmp").mkdir()
        (tmp_path / "step_00000002.tmp" / "shards_00000.bin").write_bytes(
            b"torn")
        (tmp_path / "step_00000003").mkdir()
        assert ckpt.latest_step(tmp_path) == 1
        restored = ckpt.restore_pytree(tmp_path, {"w": np.zeros((2, 2))})
        np.testing.assert_array_equal(restored["w"], np.ones((2, 2)))

    def test_same_step_recommit_replaces_without_loss_window(self, tmp_path):
        """Re-saving an already-committed step swaps via rename-aside (no
        rmtree-then-replace window where the only copy is gone): the new
        payload wins and no .old residue is left behind."""
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save({"w": jnp.ones((2, 2))}, step=1, block=True)
        c.save({"w": jnp.full((2, 2), 5.0)}, step=1, block=True)
        c.close()
        assert fmt.committed_steps(tmp_path) == [1]
        assert not list(Path(tmp_path).glob("*.old"))
        restored = ckpt.restore_pytree(tmp_path, {"w": np.zeros((2, 2))})
        np.testing.assert_array_equal(restored["w"],
                                      np.full((2, 2), 5.0))

    def test_host_numpy_leaf_snapshot_is_a_copy(self, tmp_path):
        """The snapshot contract for HOST leaves: mutating the live array
        after save() returns must not leak into the committed bytes."""
        live = np.ones((64, 64), np.float32)
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save({"w": live}, step=1)          # async: write still in flight
        live[:] = -1.0                        # train loop mutates in place
        c.wait()
        c.close()
        restored = ckpt.restore_pytree(tmp_path,
                                       {"w": np.zeros((64, 64),
                                                      np.float32)})
        np.testing.assert_array_equal(restored["w"],
                                      np.ones((64, 64), np.float32))

    def test_keep_prunes_old_steps(self, tmp_path):
        tree = {"w": jnp.ones((2,))}
        c = ckpt.AsyncCheckpointer(tmp_path, keep=2)
        for s in (1, 2, 3, 4):
            c.save(jax.tree.map(lambda x: x * s, tree), step=s, block=True)
        c.close()
        assert fmt.committed_steps(tmp_path) == [3, 4]
        restored = ckpt.restore_pytree(tmp_path, {"w": np.zeros((2,))})
        np.testing.assert_array_equal(restored["w"], 4 * np.ones((2,)))

    def test_keep_gc_ignores_inflight_tmp(self, tmp_path):
        """tony.ckpt.keep GC contract: only the newest K COMMITTED step
        dirs survive a save, and an in-flight .tmp staging dir neither
        counts toward K nor gets deleted by the prune."""
        c = ckpt.AsyncCheckpointer(tmp_path, keep=2)
        for s in (1, 2, 3):
            c.save({"w": jnp.ones((2,)) * s}, step=s, block=True)
        assert fmt.committed_steps(tmp_path) == [2, 3]
        # Simulate a sibling's in-flight save: staged shards, no commit.
        inflight = tmp_path / "step_00000005.tmp"
        inflight.mkdir()
        (inflight / fmt.shard_file_name(0)).write_bytes(b"staging")
        c.save({"w": jnp.ones((2,)) * 4}, step=4, block=True)
        c.close()
        # K counts committed steps only; the .tmp neither displaced a
        # committed survivor nor was reclaimed by prune.
        assert fmt.committed_steps(tmp_path) == [3, 4]
        assert inflight.is_dir()
        assert (inflight / fmt.shard_file_name(0)).read_bytes() \
            == b"staging"
        # Direct prune: same contract without a save in the way.
        assert fmt.prune(tmp_path, 1) == [3]
        assert fmt.committed_steps(tmp_path) == [4]
        assert inflight.is_dir()

    def test_corrupt_payload_raises_crc(self, tmp_path):
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save({"w": jnp.ones((8, 8))}, step=1, block=True)
        c.close()
        shard = fmt.step_dir(tmp_path, 1) / fmt.shard_file_name(0)
        raw = bytearray(shard.read_bytes())
        raw[3] ^= 0xFF
        shard.write_bytes(bytes(raw))
        with pytest.raises(IOError, match="CRC mismatch"):
            ckpt.restore_pytree(tmp_path, {"w": np.zeros((8, 8))})
        # verify=False trusts the payload (operator override).
        ckpt.restore_pytree(tmp_path, {"w": np.zeros((8, 8))},
                            verify=False)

    def test_shape_mismatch_raises(self, tmp_path):
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save({"w": jnp.ones((4, 4))}, step=1, block=True)
        c.close()
        with pytest.raises(ValueError, match="different model"):
            ckpt.restore_pytree(tmp_path, {"w": np.zeros((8, 8))})

    def test_bf16_roundtrip(self, tmp_path):
        tree = {"w": jnp.arange(16, dtype=jnp.bfloat16).reshape(4, 4)}
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(tree, step=1, block=True)
        c.close()
        restored = ckpt.restore_pytree(
            tmp_path, {"w": jnp.zeros((4, 4), jnp.bfloat16)})
        assert restored["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))


class TestAsync:
    def test_async_save_snapshots_before_return(self, tmp_path):
        """save() must copy device→host BEFORE returning: later updates to
        the state (or donation) cannot leak into the committed bytes."""
        state, batch = _state()
        step_fn = train.make_train_step()
        state, _ = step_fn(state, batch)
        saved_params = jax.device_get(state.params)
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(state.params, step=1)        # async — returns pre-commit
        for _ in range(3):                  # keep training over the write
            state, _ = step_fn(state, batch)
        c.wait()
        assert c.latest_step() == 1
        restored = ckpt.restore_pytree(
            tmp_path, jax.tree.map(
                lambda a: np.zeros(a.shape, a.dtype)
                if hasattr(a, "shape") else a, saved_params))
        _leaves_equal(restored, saved_params)

    def test_writer_error_surfaces_on_wait(self, tmp_path):
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        # Point the writer at an impossible path (a path THROUGH a file —
        # fails for root too, unlike permission bits).
        blocker = tmp_path / "blocker"
        blocker.write_text("not a dir")
        c.directory = blocker / "nope"
        c.save({"w": jnp.ones((2,))}, step=1)
        with pytest.raises(RuntimeError, match="writer failed"):
            c.wait()
        c.close()

    def test_profiler_records_stall_and_write(self, tmp_path):
        profiler.reset_ckpt_records()
        state, _ = _state()
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(state, step=1, block=True)
        c.close()
        rec = profiler.ckpt_report()["async_save"]
        assert rec["step"] == 1
        assert rec["nbytes"] > 0 and rec["n_chunks"] >= 1
        assert rec["stall_s"] >= 0 and rec["write_s"] > 0

    @pytest.mark.slow
    def test_large_state_async_stall_beats_blocking(self, tmp_path):
        """The overlap claim on a state big enough to measure (~50 MB):
        the async save's caller stall must undercut the blocking save."""
        state, batch = _state(hidden=4096)
        step_fn = train.make_train_step()
        state, _ = step_fn(state, batch)
        c = ckpt.AsyncCheckpointer(tmp_path / "b", keep=2)
        import time
        t0 = time.perf_counter()
        c.save(state, step=1, block=True)
        blocking_s = time.perf_counter() - t0
        c.close()
        a = ckpt.AsyncCheckpointer(tmp_path / "a", keep=2)
        a.save(state, step=1)
        stall_s = a.stats["stall_s"][0]
        state, _ = step_fn(state, batch)     # ride the write
        a.wait()
        restored = ckpt.restore_pytree(
            tmp_path / "a", jax.tree.map(
                lambda x: np.zeros(x.shape, x.dtype)
                if hasattr(x, "shape") else x, jax.device_get(state)))
        a.close()
        assert stall_s < blocking_s
        assert jax.tree.leaves(restored)     # committed and readable


class TestMultiProcessBarrier:
    def test_nonzero_process_blocks_until_global_commit(self, tmp_path):
        """Host-simulated 2-process commit: process 1's blocking save must
        not return at 'my shards landed' — it returns only once process
        0's manifest rename makes the step globally durable, so
        latest_step never diverges across the gang."""
        import threading
        import time as time_mod

        from tony_tpu.ckpt.snapshot import extract_snapshot, write_snapshot

        tree = {"w": jnp.arange(8.0)}
        snap1 = extract_snapshot(tree, 1)
        done1 = threading.Event()

        def proc1():
            write_snapshot(tmp_path, snap1, process_index=1,
                           num_processes=2, barrier_timeout_s=30.0)
            done1.set()

        t = threading.Thread(target=proc1, daemon=True)
        t.start()
        time_mod.sleep(0.3)
        assert not done1.is_set()            # shards landed, commit hasn't
        assert ckpt.latest_step(tmp_path) is None
        snap0 = extract_snapshot(tree, 1)
        write_snapshot(tmp_path, snap0, process_index=0, num_processes=2,
                       barrier_timeout_s=30.0)
        assert done1.wait(timeout=30.0)      # released by the commit
        assert ckpt.latest_step(tmp_path) == 1
        manifest = fmt.read_manifest(tmp_path, 1)
        assert len(manifest["files"]) == 2   # both processes' shard files

    def test_commit_times_out_on_missing_process(self, tmp_path):
        from tony_tpu.ckpt.snapshot import extract_snapshot, write_snapshot

        snap = extract_snapshot({"w": jnp.ones((2,))}, 1)
        with pytest.raises(TimeoutError, match="did not finish"):
            write_snapshot(tmp_path, snap, process_index=0,
                           num_processes=2, barrier_timeout_s=0.3)


class TestCrashConsistency:
    def test_sigkill_mid_save_preserves_previous_step(self, tmp_path):
        """THE acceptance pin: kill -9 between shard write and manifest
        commit never loses the previously committed step — it restores
        bit-exact, and the torn staging dir is reclaimed."""
        script = textwrap.dedent("""
            import jax, jax.numpy as jnp, numpy as np, sys
            from tony_tpu import ckpt
            root, expect = sys.argv[1], sys.argv[2]
            tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                    "s": jnp.float32(3.5)}
            c = ckpt.AsyncCheckpointer(root, keep=3)
            c.save(tree, step=1, block=True)
            np.save(expect, np.asarray(tree["w"]))
            # Arm the fault injection for the SECOND save only: the env
            # hook SIGKILLs this process after the shard payload is
            # written but before the manifest commit rename.
            import os
            os.environ["TONY_CKPT_CRASH"] = "after_shards"
            c.save({"w": jnp.full((8, 8), 99.0),
                    "s": jnp.float32(9.9)}, step=2, block=True)
            print("UNREACHABLE")
        """)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=str(Path(__file__).resolve().parent.parent))
        env.pop("TONY_CKPT_CRASH", None)
        root = tmp_path / "d"
        expect = tmp_path / "expect.npy"
        proc = subprocess.run(
            [sys.executable, "-c", script, str(root), str(expect)],
            env=env, capture_output=True, text=True, timeout=180)
        assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                    proc.stdout,
                                                    proc.stderr)
        assert "UNREACHABLE" not in proc.stdout
        # Previous step intact and bit-exact; step 2 never committed.
        assert ckpt.latest_step(root) == 1
        assert (root / "step_00000002.tmp").is_dir()   # the torn write
        restored = ckpt.restore_pytree(
            root, {"w": np.zeros((8, 8), np.float32),
                   "s": np.float32(0)})
        np.testing.assert_array_equal(restored["w"], np.load(expect))
        assert float(restored["s"]) == 3.5
        # A new checkpointer incarnation sweeps the torn staging dir.
        c = ckpt.AsyncCheckpointer(root, keep=3)
        c.close()
        assert not (root / "step_00000002.tmp").exists()
        assert ckpt.latest_step(root) == 1

    def test_crash_before_commit_rename(self, tmp_path):
        """Same invariant at the later phase boundary: manifest staged in
        the tmp dir, rename not issued — still nothing committed."""
        calls = []

        def hook(phase):
            calls.append(phase)
            if phase == "before_commit":
                raise KeyboardInterrupt("simulated kill")

        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save({"w": jnp.ones((4,))}, step=1, block=True)
        fmt.CRASH_HOOK = hook
        try:
            with pytest.raises(RuntimeError, match="writer failed"):
                c.save({"w": jnp.full((4,), 2.0)}, step=2, block=True)
        finally:
            fmt.CRASH_HOOK = None
            c.close()
        assert "before_commit" in calls
        assert ckpt.latest_step(tmp_path) == 1


@pytest.mark.multislice
class TestElasticRestore:
    def test_cross_topology_2slice_to_1slice(self, tmp_path):
        """THE elastic acceptance pin: a ZeRO-3 state saved on a (host-
        simulated) 2-slice fsdp=2 mesh restores onto a 1-slice fsdp=4 mesh
        AND onto fsdp=2, bit-exact, with train-step numerics pinned within
        1e-6 against the original topology."""
        mesh_a = par.make_mesh(slices=2, fsdp=2)   # slice=2 x data=2 x fsdp=2
        state, batch = _state(hidden=64)
        zstate = fsdp_shard_state(state, mesh_a)
        step_a = train.make_train_step(mesh=mesh_a, donate=False)
        zstate, _ = step_a(zstate, batch)
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(zstate, step=1, block=True)
        c.close()
        manifest = fmt.read_manifest(tmp_path, 1)
        assert manifest["mesh"]["shape"]["slice"] == 2
        assert any(m["spec"] and "fsdp" in str(m["spec"])
                   for m in manifest["leaves"])
        host = jax.device_get(zstate)
        for spec_kw in ({"fsdp": 4}, {"fsdp": 2}):
            mesh_b = par.make_mesh(**spec_kw)      # 1-slice relayouts
            abstract = jax.tree.map(
                lambda a: np.zeros(a.shape, a.dtype)
                if hasattr(a, "shape") else a, host)
            restored = ckpt.restore_pytree(tmp_path, abstract, mesh=mesh_b)
            _leaves_equal(restored, host)
            # Manifest specs mapped onto the NEW mesh: still fsdp-sharded.
            kernel = restored.params["Dense_0"]["kernel"]
            assert "fsdp" in str(kernel.sharding.spec)
            assert kernel.sharding.mesh.shape["fsdp"] == spec_kw["fsdp"]
            step_b = train.make_train_step(mesh=mesh_b, donate=False)
            _, m_b = step_b(restored, batch)
            zs2, m_a = step_a(zstate, batch)
            assert abs(float(m_b["loss"]) - float(m_a["loss"])) < 1e-6
            assert abs(float(m_b["grad_norm"])
                       - float(m_a["grad_norm"])) < 1e-6

    def test_adapt_spec_degrades_missing_axes(self):
        from jax.sharding import PartitionSpec as P
        mesh = par.make_mesh(fsdp=4)
        # Unknown axis name → replicated dim; known-but-indivisible → same.
        assert ckpt.adapt_spec(P("oldaxis"), (8,), mesh) == P(None)
        assert ckpt.adapt_spec(P("fsdp"), (6,), mesh) == P(None)
        assert ckpt.adapt_spec(P("fsdp"), (8,), mesh) == P("fsdp")
        assert ckpt.adapt_spec(None, (8,), mesh) == P()

    def test_restore_targets_committed_sharding_wins(self, tmp_path):
        """A target whose leaves carry committed shardings restores INTO
        those shardings (the shim contract) — manifest specs only fill in
        for shardingless targets."""
        mesh = par.make_mesh(fsdp=2)
        state, _ = _state(hidden=32)
        zstate = fsdp_shard_state(state, mesh)
        c = ckpt.AsyncCheckpointer(tmp_path, keep=3)
        c.save(zstate, step=1, block=True)
        c.close()
        mesh_b = par.make_mesh(fsdp=4)
        target = fsdp_shard_state(state, mesh_b)
        restored = ckpt.restore_pytree(tmp_path, target)
        kernel = restored.params["Dense_0"]["kernel"]
        assert kernel.sharding == \
            target.params["Dense_0"]["kernel"].sharding
        _leaves_equal(restored.params, jax.device_get(zstate.params))


class TestTrainLoop:
    def test_plain_fold_without_ckpt_dir(self):
        state, batch = _state()
        step_fn = train.make_train_step()
        final, metrics = train.train_loop(state, step_fn, [batch] * 3,
                                          ckpt_dir=None)
        assert int(final.step) == 3 and jnp.isfinite(metrics["loss"])

    def test_save_every_and_resume(self, tmp_path, monkeypatch):
        """The control-plane contract end to end: attempt 1 trains 4 steps
        saving every 2 (async), 'dies'; attempt 2 re-enters the SAME loop
        code and resumes from the newest committed step via the TONY_CKPT_*
        env the JAXRuntime injects."""
        from tony_tpu import constants
        monkeypatch.setenv(constants.ENV_CKPT_DIR, str(tmp_path / "c"))
        monkeypatch.setenv(constants.ENV_CKPT_EVERY, "2")
        monkeypatch.setenv(constants.ENV_CKPT_KEEP, "2")
        state, batch = _state()
        step_fn = train.make_train_step()
        seen = []
        final, _ = train.train_loop(state, step_fn, [batch] * 4,
                                    on_step=lambda i, m: seen.append(i))
        assert int(final.step) == 4 and seen == [1, 2, 3, 4]
        assert ckpt.latest_step(tmp_path / "c") == 4
        # Attempt 2: fresh init, same loop — resumes at 4, trains 2 more.
        state2, _ = _state(key=1)
        final2, _ = train.train_loop(state2, step_fn, [batch] * 2)
        assert int(final2.step) == 6
        assert ckpt.latest_step(tmp_path / "c") == 6

    def test_restore_on_start_false_ignores_checkpoint(self, tmp_path):
        state, batch = _state()
        step_fn = train.make_train_step()
        train.train_loop(state, step_fn, [batch] * 2,
                         ckpt_dir=str(tmp_path), save_every=1)
        fresh, _ = _state(key=2)
        final, _ = train.train_loop(fresh, step_fn, [batch],
                                    ckpt_dir=str(tmp_path),
                                    restore_on_start=False,
                                    save_final=False)
        assert int(final.step) == 1
