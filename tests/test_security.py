"""CredentialProvider SPI unit tests (reference tier: the token plumbing
checks in TestTonyClient / TestUtils — SURVEY.md §2.1 Security)."""

import json

import pytest

from tony_tpu import security
from tony_tpu.conf import TonyConfig
from tony_tpu.rpc import ENV_JOB_TOKEN


def test_default_provider_is_token():
    p = security.provider_for(TonyConfig())
    assert isinstance(p, security.TokenCredentialProvider)
    creds = p.acquire(TonyConfig(), None)
    assert len(creds["token"]) == 32
    # Default executor env ships exactly the RPC token.
    assert p.executor_env(creds) == {ENV_JOB_TOKEN: creds["token"]}
    # Default refresh keeps the credential map.
    assert p.refresh(TonyConfig(), None, creds) is None


def test_provider_spec_validation():
    with pytest.raises(ValueError, match="module:Class"):
        security.provider_for(TonyConfig(
            {security.CREDENTIAL_PROVIDER: "not-a-path"}))
    with pytest.raises(ModuleNotFoundError):
        security.provider_for(TonyConfig(
            {security.CREDENTIAL_PROVIDER: "no_such_mod:Provider"}))
    with pytest.raises(TypeError, match="CredentialProvider"):
        # An importable class that is not a provider must be rejected.
        security.provider_for(TonyConfig(
            {security.CREDENTIAL_PROVIDER: "pathlib:Path"}))


def test_credentials_file_roundtrip(tmp_path):
    path = security.write_credentials(tmp_path, {"token": "t", "x": "1"})
    assert path.stat().st_mode & 0o777 == 0o600
    assert security.read_credentials(tmp_path) == {"token": "t", "x": "1"}
    assert json.loads(path.read_text())["x"] == "1"


def test_read_credentials_absent(tmp_path):
    assert security.read_credentials(tmp_path) is None


def test_am_rejects_tokenless_provider(tmp_path):
    """security.enabled with a provider that ships no 'token' must fail
    loudly at AM construction — never an unauthenticated RPC surface."""
    from tony_tpu.am import ApplicationMaster

    security.write_credentials(tmp_path, {"cert": "pem-bytes"})
    with pytest.raises(ValueError, match="no 'token'"):
        ApplicationMaster(
            TonyConfig({"tony.worker.instances": "1",
                        "tony.security.enabled": "true"}),
            app_id="app_x", job_dir=tmp_path)
