"""Config-system unit tests (reference tier: TestTonyConfigurationKeys/TestUtils)."""

import textwrap

import pytest

from tony_tpu import conf as C
from tony_tpu.conf import TonyConfig


def test_defaults_layer():
    cfg = TonyConfig()
    assert cfg.get(C.APPLICATION_FRAMEWORK) == "jax"
    assert cfg.get_int(C.TASK_MAX_MISSED_HEARTBEATS) == 25
    assert cfg.get_bool(C.DOCKER_ENABLED) is False


def test_xml_compat_load(tmp_path):
    xml = textwrap.dedent("""\
        <configuration>
          <property><name>tony.worker.instances</name><value>4</value></property>
          <property><name>tony.worker.memory</name><value>8g</value></property>
          <property><name>tony.application.framework</name><value>tensorflow</value></property>
        </configuration>""")
    p = tmp_path / "tony.xml"
    p.write_text(xml)
    cfg = TonyConfig.load(p)
    assert cfg.instances("worker") == 4
    assert cfg.get_memory_mb(C.memory_key("worker")) == 8192
    assert cfg.get(C.APPLICATION_FRAMEWORK) == "tensorflow"


def test_json_load_and_overrides(tmp_path):
    p = tmp_path / "job.json"
    p.write_text('{"tony.worker.instances": 2, "tony.worker.vcores": 3}')
    cfg = TonyConfig.load(p)
    cfg.merge_overrides({"tony.worker.vcores": "5"})
    assert cfg.get_int(C.vcores_key("worker")) == 5
    assert cfg.instances("worker") == 2


def test_open_jobtype_templating():
    # Any user-invented job type works without code changes (SURVEY.md §5.6).
    cfg = TonyConfig({
        "tony.chief.instances": "1",
        "tony.worker.instances": "2",
        "tony.evaluator.instances": "1",
        "tony.dbwriter.instances": "1",      # invented type
        "tony.dbwriter.memory": "512m",
    })
    assert cfg.job_types() == ["chief", "dbwriter", "evaluator", "worker"]
    assert cfg.total_tasks() == 5
    req = cfg.container_request("dbwriter")
    assert req.memory_mb == 512 and req.instances == 1


def test_reserved_segments_not_jobtypes():
    cfg = TonyConfig({"tony.worker.instances": "1",
                      "tony.am.instances": "9"})  # 'am' is reserved
    assert cfg.job_types() == ["worker"]


def test_untracked_jobtypes():
    cfg = TonyConfig({"tony.worker.instances": "1", "tony.ps.instances": "2"})
    assert not cfg.is_tracked("ps")
    assert cfg.is_tracked("worker")
    cfg.set(C.APPLICATION_UNTRACKED, "worker")
    assert not cfg.is_tracked("worker")
    assert cfg.is_tracked("ps")


def test_task_env_csv():
    cfg = TonyConfig({"tony.worker.instances": "1",
                      "tony.worker.env": "FOO=1,BAR=a=b"})
    assert cfg.task_env("worker") == {"FOO": "1", "BAR": "a=b"}


def test_validate_rejects_bad_framework():
    cfg = TonyConfig({"tony.worker.instances": "1",
                      C.APPLICATION_FRAMEWORK: "caffe"})
    with pytest.raises(ValueError, match="unknown"):
        cfg.validate()


def test_validate_requires_jobtype():
    with pytest.raises(ValueError, match="no job types"):
        TonyConfig().validate()


def test_json_roundtrip():
    cfg = TonyConfig({"tony.worker.instances": "3"})
    clone = TonyConfig.from_json(cfg.to_json())
    assert clone.instances("worker") == 3
    assert dict(clone.items()) == dict(cfg.items())


def test_job_types_chief_like_order_canonical():
    # 'master' inserted before 'chief' in the props: canonical order must
    # still be (chief, master, ...) regardless of dict insertion order.
    cfg = TonyConfig({"tony.master.instances": "1", "tony.chief.instances": "1",
                      "tony.worker.instances": "2"})
    assert cfg.job_types() == ["chief", "master", "worker"]
    # Round-trip through JSON (sorted keys) must agree.
    assert TonyConfig.from_json(cfg.to_json()).job_types() == cfg.job_types()


def test_validate_rejects_gpu_asks():
    # A GPU ask that scheduled in the reference must fail loudly on the
    # TPU substrate, not silently no-op (VERDICT r4 missing #5).
    cfg = TonyConfig({"tony.worker.instances": "2", "tony.worker.gpus": "4"})
    with pytest.raises(ValueError, match="tony.worker.gpus.*tpus"):
        cfg.validate()


def test_validate_accepts_tpu_asks():
    TonyConfig({"tony.worker.instances": "2",
                "tony.worker.tpus": "4"}).validate()
