"""Continuous-publication legs (tony_tpu.publish + tony_tpu.serve.swap
PR 20): the versioned pointer file's stage-and-rename crash sweep (old
pointer or new, never torn), resolve_target's pointer/pin/race rules,
the FleetSwapController rolling-swap policy on a fake clock, warm()'s
pad self-tuner, the prefix/host-tier flush on swap, the hot in-place
weight swap pinned BITWISE vs a fresh replica restored from the same
manifest with zero dropped requests under concurrent traffic, the
chaos sweep at every swap boundary (exactly one weight version per
replica — rolled back whole or committed whole), the router's
swap-window down-mark, `tony history bill --json/--csv --since/--until`,
`tony aot gc`, and the PUBLISH→SWAP jhist timeline."""

from __future__ import annotations

import json
import threading
import time
import types
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu import chaos
from tony_tpu import events as ev
from tony_tpu import history, publish
from tony_tpu.ckpt.format import MANIFEST_NAME, committed_steps, step_dir
from tony_tpu.serve.swap import (FleetSwapController, SwapError,
                                 derive_prefill_pads, resolve_target)

pytestmark = pytest.mark.publish


@pytest.fixture(autouse=True)
def _chaos_clean(monkeypatch):
    """No chaos schedule or hook leaks between tests."""
    for name in (chaos.ENV_KILL_STEP, chaos.ENV_HB_DROP,
                 chaos.ENV_RPC_DELAY_S, chaos.ENV_RPC_DELAY_CALLS,
                 chaos.ENV_CRASH):
        monkeypatch.delenv(name, raising=False)
    monkeypatch.setattr(chaos, "KILL_HOOK", None)
    monkeypatch.setattr(chaos, "CRASH_HOOK", None)
    monkeypatch.setattr(chaos, "SLEEP_HOOK", None)
    chaos.reset()
    yield
    chaos.reset()


def commit_fake_steps(root: Path, *steps: int) -> None:
    """Committed-looking step dirs: the pointer plane only reads the
    manifest's EXISTENCE (committed_steps), never its contents."""
    root.mkdir(parents=True, exist_ok=True)
    for s in steps:
        d = step_dir(root, s)
        d.mkdir(exist_ok=True)
        (d / MANIFEST_NAME).write_text("{}")


class _Crashed(RuntimeError):
    """CRASH_HOOK's in-process stand-in for SIGKILL."""


# ---------------------------------------------------------------------------
# The pointer file: publish_step / latest_publication
# ---------------------------------------------------------------------------

class TestPublishPointer:
    def test_roundtrip_versions_and_rollback(self, tmp_path):
        commit_fake_steps(tmp_path, 3, 7)
        rec = publish.publish_step(tmp_path)            # default: newest
        assert (rec["version"], rec["step"]) == (1, 7)
        assert rec["manifest"] == f"step_{7:08d}/{MANIFEST_NAME}"
        # Rollback: an OLDER step under a NEWER version — the fleet
        # compares versions, so the roll-back still propagates.
        rec = publish.publish_step(tmp_path, 3, note="bad eval")
        assert (rec["version"], rec["step"]) == (2, 3)
        assert rec["note"] == "bad eval"
        # Re-publishing the same step is a "converge again" push, not a
        # no-op: it mints version 3.
        rec = publish.publish_step(tmp_path, 3)
        assert (rec["version"], rec["step"]) == (3, 3)
        back = publish.latest_publication(tmp_path)
        assert (back["version"], back["step"]) == (3, 3)

    def test_uncommitted_or_empty_raises(self, tmp_path):
        with pytest.raises(publish.PublishError):
            publish.publish_step(tmp_path)              # nothing committed
        commit_fake_steps(tmp_path, 2)
        with pytest.raises(publish.PublishError):
            publish.publish_step(tmp_path, 5)           # never committed
        # A .tmp staging dir is NOT committed — publishing it must fail.
        (tmp_path / f"step_{9:08d}.tmp").mkdir()
        with pytest.raises(publish.PublishError):
            publish.publish_step(tmp_path, 9)

    def test_latest_publication_failure_silent(self, tmp_path):
        assert publish.latest_publication(tmp_path) is None
        (tmp_path).mkdir(exist_ok=True)
        (tmp_path / publish.PUBLISH_FILE).write_text("{ torn half-writ")
        assert publish.latest_publication(tmp_path) is None
        (tmp_path / publish.PUBLISH_FILE).write_text('{"version": "x"}')
        assert publish.latest_publication(tmp_path) is None

    @pytest.mark.parametrize("site", ["publish_before_stage",
                                      "publish_after_stage",
                                      "publish_after_replace"])
    def test_crash_sweep_old_or_new_never_torn(self, site, tmp_path,
                                               monkeypatch):
        commit_fake_steps(tmp_path, 3, 7)
        old = publish.publish_step(tmp_path, 3)         # v1 -> step 3

        def hook(where):
            raise _Crashed(where)

        monkeypatch.setattr(chaos, "CRASH_HOOK", hook)
        monkeypatch.setenv(chaos.ENV_CRASH, site)
        with pytest.raises(_Crashed):
            publish.publish_step(tmp_path, 7)
        rec = publish.latest_publication(tmp_path)
        assert rec is not None, f"crash at {site} left a torn pointer"
        if site == "publish_after_replace":
            assert (rec["version"], rec["step"]) == (2, 7)
        else:
            assert (rec["version"], rec["step"]) == \
                (old["version"], old["step"])
        # The crash's staging leftovers never poison the NEXT publish.
        monkeypatch.delenv(chaos.ENV_CRASH)
        nxt = publish.publish_step(tmp_path, 7)
        assert nxt["version"] == rec["version"] + 1 and nxt["step"] == 7

    def test_train_loop_publishes_on_save_cadence(self, tmp_path,
                                                  monkeypatch):
        from tony_tpu import constants
        from tony_tpu import train as tr

        monkeypatch.delenv(constants.ENV_PUBLISH_EVERY, raising=False)
        root = tmp_path / "ckpt"
        tr.train_loop({"w": np.zeros(2, np.float32)},
                      lambda state, batch: (state, {}), [{}] * 6,
                      ckpt_dir=str(root), save_every=2, publish_every=2)
        rec = publish.latest_publication(root)
        # Saves land at 2/4/6; every 2nd save publishes (step 4), and
        # the final save always publishes (step 6) — pointer at 6, v2.
        assert rec is not None
        assert (rec["version"], rec["step"]) == (2, 6)
        assert rec["step"] in committed_steps(root)


# ---------------------------------------------------------------------------
# resolve_target
# ---------------------------------------------------------------------------

class TestResolveTarget:
    def test_pointer_pin_and_race_rules(self, tmp_path):
        commit_fake_steps(tmp_path, 3, 7)
        with pytest.raises(SwapError):
            resolve_target(tmp_path)                    # no publication
        publish.publish_step(tmp_path, 7)               # v1 -> 7
        assert resolve_target(tmp_path) == (1, 7)
        assert resolve_target(tmp_path, version=1) == (1, 7)
        # Pointer raced past the version the caller saw: typed failure,
        # never a silent swap onto other weights.
        with pytest.raises(SwapError):
            resolve_target(tmp_path, version=99)
        # Explicit step pin: the pointer's version when it names that
        # step, the unpublished version 0 otherwise.
        assert resolve_target(tmp_path, step=7) == (1, 7)
        assert resolve_target(tmp_path, step=3) == (0, 3)
        with pytest.raises(SwapError):
            resolve_target(tmp_path, step=5)            # uncommitted


# ---------------------------------------------------------------------------
# FleetSwapController (fake clock: pure policy, no threads, no jax)
# ---------------------------------------------------------------------------

def _fleet(*rows):
    return [{"id": rid, "version": v, "standby": sb, "index": i}
            for rid, v, sb, i in rows]


class TestFleetSwapController:
    def _ctl(self, **kw):
        self.now = [0.0]
        kw.setdefault("timeout_s", 10.0)
        kw.setdefault("cooldown_s", 5.0)
        return FleetSwapController(clock=lambda: self.now[0], **kw)

    def test_standby_first_one_in_flight_version_skip(self):
        ctl = self._ctl()
        fleet = _fleet(("a", 1, False, 0), ("b", 1, True, 2),
                       ("c", 1, False, 1))
        assert ctl.next_replica(fleet) is None          # no target yet
        assert ctl.set_target(2, 10) is True
        assert ctl.set_target(2, 10) is False           # same version: no edge
        assert ctl.set_target(1, 5) is False            # older: never adopted
        # Warm standby first — the free dry run — then actives by index.
        assert ctl.next_replica(fleet) == "b"
        ctl.begin("b")
        assert ctl.next_replica(fleet) is None          # one in flight
        ctl.finish("b", True)
        fleet = _fleet(("a", 1, False, 0), ("b", 2, True, 2),
                       ("c", 1, False, 1))
        assert ctl.next_replica(fleet) == "a"
        ctl.begin("a"); ctl.finish("a", True)
        fleet = _fleet(("a", 2, False, 0), ("b", 2, True, 2),
                       ("c", 1, False, 1))
        assert ctl.next_replica(fleet) == "c"
        ctl.begin("c"); ctl.finish("c", True)
        # Everyone at target: converged, nothing to do.
        assert ctl.next_replica(_fleet(("a", 2, False, 0),
                                       ("b", 2, True, 2),
                                       ("c", 2, False, 1))) is None
        assert ctl.swapped == 3 and ctl.failed == 0

    def test_failure_cooldown_and_new_target_clears_it(self):
        ctl = self._ctl()
        ctl.set_target(2, 10)
        fleet = _fleet(("a", 1, False, 0))
        ctl.begin("a"); ctl.finish("a", False)
        assert ctl.failed == 1
        assert ctl.next_replica(fleet) is None          # cooling down
        self.now[0] = 4.9
        assert ctl.next_replica(fleet) is None
        self.now[0] = 5.1
        assert ctl.next_replica(fleet) == "a"           # cooldown over
        ctl.begin("a"); ctl.finish("a", False)
        # A NEWER publication may be the fix — it clears the cooldown.
        assert ctl.set_target(3, 11) is True
        assert ctl.next_replica(fleet) == "a"

    def test_timeout_reap_and_idempotent_late_finish(self):
        ctl = self._ctl()
        ctl.set_target(2, 10)
        ctl.begin("a")
        assert ctl.check_timeout() is None
        self.now[0] = 10.5
        assert ctl.check_timeout() == "a"               # wedged: reaped
        assert ctl.in_flight is None and ctl.failed == 1
        ctl.finish("a", True)                           # thread's late finish
        assert ctl.swapped == 0 and ctl.failed == 1     # no double count
        # The reap opened a cooldown window too.
        assert ctl.next_replica(_fleet(("a", 1, False, 0))) is None
        self.now[0] = 16.0
        assert ctl.next_replica(_fleet(("a", 1, False, 0))) == "a"

    def test_run_records_outcome(self):
        calls = []

        def swap_fn(rid):
            calls.append(rid)
            if rid == "bad":
                raise RuntimeError("poisoned manifest")

        ctl = FleetSwapController(swap_fn, clock=time.monotonic)
        ok, detail, wall = ctl.run("good")
        assert ok and detail == "" and wall >= 0.0
        ok, detail, _ = ctl.run("bad")
        assert not ok and "poisoned manifest" in detail
        assert calls == ["good", "bad"]
        assert ctl.swapped == 1 and ctl.failed == 1
        with pytest.raises(ValueError):
            FleetSwapController().run("x")              # policy-only mode


# ---------------------------------------------------------------------------
# warm() pad self-tuning
# ---------------------------------------------------------------------------

class TestDerivePrefillPads:
    def test_filters_ranks_and_sorts(self):
        records = [
            # jhist SERVE_WINDOW shape...
            {"type": ev.SERVE_WINDOW, "payload": {"stats": {"prompt_hist": {
                "16": 5.0, "48": 2.0, "33": 9.0}}}},
            # ...and a raw stats dict both parse.
            {"prompt_hist": {"16": 1.0, "32": 4.0, "128": 9.0,
                             "-16": 3.0, "x": 1.0}},
        ]
        # 33 not a q_block multiple, 128 > ctx_max, -16/x garbage.
        assert derive_prefill_pads(records, q_block=16, ctx_max=64) == \
            [16, 32, 48]
        # limit keeps the most-frequent pads, returned ascending.
        assert derive_prefill_pads(records, q_block=16, ctx_max=64,
                                   limit=2) == [16, 32]
        assert derive_prefill_pads([], q_block=16) == []
        assert derive_prefill_pads([{"payload": {}}], q_block=16) == []


# ---------------------------------------------------------------------------
# Swap hygiene: the prefix/host tiers flush, parked conversations stay
# ---------------------------------------------------------------------------

class TestFlushPrefix:
    def test_flush_unindexes_device_and_host_tiers(self):
        from tony_tpu.serve import PagedKVCache

        c = PagedKVCache(2, 8, n_blocks=8, block_size=4, host_blocks=4)
        t_a = c.reserve("a", 8)
        assert c.publish_block("a", 0, "k0")
        assert c.publish_block("a", 1, "k1")
        t_b = c.reserve("b", 4)
        assert c.publish_block("b", 0, "k2")
        c.free_seq("a")                 # k0/k1 -> refcount-0 cached tier
        assert c.demote(1) == 1         # coldest stem -> host tier
        assert len(c.host_keys()) == 1
        free_before = c.free_blocks
        # Three entries invalidated: one host stem + two indexed blocks
        # (k2's block is still OWNED by "b" — unindexed but not freed).
        assert c.flush_prefix() == 3
        assert c.host_keys() == [] and c.match_prefix(["k0", "k1"]) == []
        assert c.match_prefix(["k2"]) == []
        # The refcount-0 resident moved from the (already reclaimable)
        # LRU tier to the LIFO free list — the free_blocks total is
        # unchanged, the pool just lost its adoptable index entries.
        assert c.free_blocks == free_before == c.n_blocks - len(t_b)
        # ...and b's still-referenced block frees normally afterwards.
        owned = c.free_seq("b")
        assert owned == len(t_b) and c.free_blocks == c.n_blocks


# ---------------------------------------------------------------------------
# Shared tiny model + engine-level swap unit legs
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)

    def init(seed):
        p = nn.unbox(model.init(jax.random.PRNGKey(seed),
                                sample))["params"]
        return jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, p)

    return model, init(0), init(7)


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params, _ = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


class TestEngineSwap:
    def test_stats_schema_and_prompt_hist(self, tiny):
        from tony_tpu.serve import Request

        eng = make_engine(tiny)
        eng.submit(Request(rid="a", tokens=list(range(6)),
                           max_new_tokens=2))
        eng.submit(Request(rid="b", tokens=list(range(20)),
                           max_new_tokens=2))
        eng.run()
        s = eng.stats()
        assert s["weight_version"] == 0.0 and s["weight_step"] == 0.0
        assert s["weight_swaps"] == 0.0 and s["swapping"] == 0.0
        # Histogram keys are the PADDED prompt lengths (q_block=16).
        assert s["prompt_hist"] == {"16": 1.0, "32": 1.0}
        # The heartbeat normalizer passes the new keys through whole.
        from tony_tpu.util import normalize_serve_telemetry

        wire = normalize_serve_telemetry(json.loads(json.dumps(s)))
        assert wire["prompt_hist"] == {"16": 1.0, "32": 1.0}
        assert wire["weight_version"] == 0.0

    def test_swap_params_bitwise_and_zero_recompile(self, tiny):
        from tony_tpu.serve import Request

        model, params1, params2 = tiny
        eng = make_engine(tiny)
        prompt = list(range(5))
        eng.submit(Request(rid="pre", tokens=prompt, max_new_tokens=4))
        pre = eng.run()[0]
        fns = dict(eng._fns)
        eng.swap_params(params2, version=3, step=20)
        assert eng.weight_version == 3 and eng.weight_step == 20
        assert eng.weight_swaps == 1
        eng.submit(Request(rid="post", tokens=prompt, max_new_tokens=4))
        post = eng.run()[0]
        # Same geometry, same step programs: the swap compiled NOTHING.
        assert dict(eng._fns) == fns
        # Post-swap output is bitwise the params2 engine's, not params1's.
        ref = make_engine((model, params2, None))
        ref.submit(Request(rid="r", tokens=prompt, max_new_tokens=4))
        ref_c = ref.run()[0]
        assert post.tokens == ref_c.tokens
        assert all(np.array_equal(a, b)
                   for a, b in zip(post.logits, ref_c.logits))
        assert pre.tokens != post.tokens or not all(
            np.array_equal(a, b) for a, b in zip(pre.logits, post.logits))

    def test_swap_geometry_mismatch_rolls_back(self, tiny):
        model, params1, _ = tiny
        eng = make_engine(tiny)
        # A one-leaf tree never matches the model's treedef.
        with pytest.raises(SwapError):
            eng.swap_params({"w": jnp.zeros((2,), jnp.bfloat16)},
                            version=9, step=9)
        assert eng.weight_version == 0 and eng.weight_swaps == 0
        assert eng.params is params1    # old reference, untouched


# ---------------------------------------------------------------------------
# The replica hot swap: pointer-seeded startup, bitwise pin, zero drops,
# chaos at every boundary
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def two_step_ckpt(tmp_path_factory):
    """Two committed REAL checkpoints (different param values) the
    elastic restore can land: step 1 and step 2."""
    import optax

    from tony_tpu import ckpt, train
    from tony_tpu.models import get_model

    root = tmp_path_factory.mktemp("pub") / "ckpt"
    model = get_model("llama-tiny", n_layers=2)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 256, (4, 16)),
                         jnp.int32)
    mgr = ckpt.AsyncCheckpointer(root)
    for step, seed in ((1, 0), (2, 7)):
        state = train.create_train_state(
            model, optax.adamw(1e-3), tokens, jax.random.PRNGKey(seed))
        mgr.save(state, step=step, block=True)
    mgr.close()
    return str(root)


def _make_replica(root, **kw):
    from tony_tpu.serve.replica import Replica

    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return Replica(model_name="llama-tiny", model_kwargs={"n_layers": 2},
                   ckpt_dir=root, dtype_policy="bf16", **kw)


PROMPTS = [[int(x) for x in np.random.RandomState(s).randint(0, 256, n)]
           for s, n in ((1, 6), (2, 11), (3, 14))]


@pytest.mark.slow
class TestHotSwap:
    def test_startup_follows_pointer_not_latest(self, two_step_ckpt):
        rec = publish.publish_step(two_step_ckpt, 1)
        replica = _make_replica(two_step_ckpt)
        # The pointer outranks "latest committed": step 2 exists, the
        # publication names step 1, the replica serves step 1.
        assert replica.restored_step == 1
        assert replica.engine.weight_step == 1
        assert replica.engine.weight_version == rec["version"]

    def test_hot_swap_bitwise_vs_fresh_replica_zero_drops(
            self, two_step_ckpt):
        v1 = publish.publish_step(two_step_ckpt, 1)["version"]
        replica = _make_replica(two_step_ckpt)
        ref1 = {i: replica.generate(p, 4).tokens
                for i, p in enumerate(PROMPTS)}
        v2 = publish.publish_step(two_step_ckpt, 2,
                                  note="nightly eval passed")["version"]
        streams, errors = [], []

        def traffic(pi):
            try:
                for _ in range(5):
                    c = replica.generate(PROMPTS[pi], 4, rid=None)
                    streams.append((pi, list(c.tokens)))
            except Exception as e:   # noqa: BLE001 — any drop fails the pin
                errors.append(e)

        threads = [threading.Thread(target=traffic, args=(pi,))
                   for pi in range(len(PROMPTS))]
        for t in threads:
            t.start()
        out = replica.hot_swap()
        for t in threads:
            t.join()
        assert not errors, f"swap dropped traffic: {errors[0]!r}"
        assert out["ok"] and out["from_version"] == v1
        assert out["to_version"] == v2 and out["step"] == 2
        assert replica.engine.weight_version == v2
        assert replica.engine.weight_step == 2
        assert replica.restored_step == 2
        assert replica.engine.stats()["weight_swaps"] == 1.0
        # THE acceptance pin: post-swap streams are bitwise the fresh
        # replica's, restored from the same published manifest.
        fresh = _make_replica(two_step_ckpt)
        assert fresh.restored_step == 2
        ref2 = {i: fresh.generate(p, 4).tokens
                for i, p in enumerate(PROMPTS)}
        assert ref2 != ref1          # the two manifests really differ
        for i, p in enumerate(PROMPTS):
            assert replica.generate(p, 4).tokens == ref2[i]
        # Zero drops AND no mixed-version stream: every completion that
        # rode through the window is wholly old-weights or wholly new.
        assert len(streams) == 5 * len(PROMPTS)
        for pi, toks in streams:
            assert len(toks) == 4
            assert toks in (ref1[pi], ref2[pi]), (
                f"prompt {pi}: stream {toks} matches neither the "
                f"pre-swap ({ref1[pi]}) nor post-swap ({ref2[pi]}) "
                f"version — a mixed-version completion")

    @pytest.mark.parametrize("site", ["swap_before_restore",
                                      "swap_after_restore",
                                      "swap_before_flip",
                                      "swap_after_flip"])
    def test_chaos_sweep_exactly_one_weight_version(
            self, site, two_step_ckpt, monkeypatch):
        v1 = publish.publish_step(two_step_ckpt, 1)["version"]
        replica = _make_replica(two_step_ckpt)
        t1 = {i: replica.generate(p, 3).tokens
              for i, p in enumerate(PROMPTS[:2])}
        v2 = publish.publish_step(two_step_ckpt, 2)["version"]

        def hook(where):
            raise _Crashed(where)

        monkeypatch.setattr(chaos, "CRASH_HOOK", hook)
        monkeypatch.setenv(chaos.ENV_CRASH, site)
        with pytest.raises(_Crashed):
            replica.hot_swap()
        monkeypatch.delenv(chaos.ENV_CRASH)
        # The engine is never left wedged mid-quiesce...
        assert replica.engine.swapping is False
        got = {i: replica.generate(p, 3).tokens
               for i, p in enumerate(PROMPTS[:2])}
        if site == "swap_after_flip":
            # Crash AFTER the atomic flip: the new version committed.
            assert replica.engine.weight_version == v2
            fresh = _make_replica(two_step_ckpt)
            assert got == {i: fresh.generate(p, 3).tokens
                           for i, p in enumerate(PROMPTS[:2])}
        else:
            # Crash anywhere before: rolled back whole — the old
            # version, bitwise.
            assert replica.engine.weight_version == v1
            assert replica.engine.weight_step == 1
            assert got == t1

    def test_swap_rpc_verb_and_stale_version_pin(self, two_step_ckpt):
        publish.publish_step(two_step_ckpt, 1)
        replica = _make_replica(two_step_ckpt)
        handler = replica.rpc_handler()
        rec = publish.publish_step(two_step_ckpt, 2)
        out = handler.rpc_swap(version=rec["version"])
        assert out["ok"] and out["to_version"] == rec["version"]
        # A stale version pin (pointer moved past what the AM saw) is a
        # typed refusal with the current weights kept.
        publish.publish_step(two_step_ckpt, 1)
        with pytest.raises(SwapError):
            handler.rpc_swap(version=rec["version"])
        assert replica.engine.weight_version == rec["version"]


# ---------------------------------------------------------------------------
# THE HEADLINE PIN: a routed 2-replica fleet rolls onto a new publication
# one replica at a time — zero dropped requests, both replicas end
# bitwise on the new manifest, the router's down-mark covers each window.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_rolling_fleet_swap_zero_drops(two_step_ckpt):
    from tony_tpu.serve.router import RequestRouter

    v1 = publish.publish_step(two_step_ckpt, 1)["version"]
    replicas = {f"serve:{i}": _make_replica(two_step_ckpt)
                for i in range(2)}
    router = RequestRouter(block_size=8)
    for name in replicas:
        router.upsert_replica(name, address=f"fake:{name}")
    ref1 = {i: replicas["serve:0"].generate(p, 3).tokens
            for i, p in enumerate(PROMPTS)}
    v2 = publish.publish_step(two_step_ckpt, 2)["version"]

    stop = threading.Event()
    streams, errors = [], []

    def traffic():
        i = 0
        while not stop.is_set():
            pi = i % len(PROMPTS)
            i += 1
            try:
                name = router.route(PROMPTS[pi])
                c = replicas[name].generate(PROMPTS[pi], 3)
                streams.append((pi, list(c.tokens)))
            except Exception as e:   # noqa: BLE001 — drops fail the pin
                errors.append(e)
                return

    threads = [threading.Thread(target=traffic) for _ in range(2)]
    for t in threads:
        t.start()
    try:
        # The AM's rolling tick, inline: one replica at a time, router
        # down-marked for exactly the swap window.
        ctl = FleetSwapController(timeout_s=120.0)
        assert ctl.set_target(v2, 2)
        while True:
            fleet = [{"id": name, "version": r.engine.weight_version,
                      "standby": False, "index": int(name.split(":")[1])}
                     for name, r in replicas.items()]
            name = ctl.next_replica(fleet)
            if name is None:
                break
            router.retire_replica(name)        # the swap-window down-mark
            ctl.begin(name)
            out = replicas[name].hot_swap()
            ctl.finish(name, out["ok"])
            router.upsert_replica(name)        # heartbeat revival
        assert ctl.swapped == 2 and ctl.failed == 0
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, f"rolling swap dropped a request: {errors[0]!r}"
    # Both replicas converged on v2 and serve bitwise-identical streams
    # to a fresh replica restored from the same manifest.
    fresh = _make_replica(two_step_ckpt)
    ref2 = {i: fresh.generate(p, 3).tokens for i, p in enumerate(PROMPTS)}
    for name, r in replicas.items():
        assert r.engine.weight_version == v2, name
        for i, p in enumerate(PROMPTS):
            assert r.generate(p, 3).tokens == ref2[i], (name, i)
    # Every in-window stream was wholly one version — never mixed.
    assert streams, "traffic never landed"
    for pi, toks in streams:
        assert len(toks) == 3 and toks in (ref1[pi], ref2[pi]), (pi, toks)


# ---------------------------------------------------------------------------
# Router down-mark + session/heartbeat plumbing (jax-free)
# ---------------------------------------------------------------------------

class TestControlPlanePlumbing:
    def test_router_retires_swapping_replica_and_revives(self):
        from tony_tpu.serve.router import RequestRouter

        rt = RequestRouter(block_size=16)

        def infos(swapping):
            m = {"rpc_port": 7001, "queue_depth": 0.0}
            if swapping:
                m["swapping"] = 1.0
            return [{"job_type": "serve", "index": 0, "status": "RUNNING",
                     "host": "h0", "serve_metrics": m}]

        rt.refresh_from_task_infos(infos(False))
        assert [v.retired for v in rt.replicas()] == [False]
        rt.refresh_from_task_infos(infos(True))
        assert [v.retired for v in rt.replicas()] == [True]
        # The post-flip republish clears the flag; the next beat revives.
        rt.refresh_from_task_infos(infos(False))
        assert [v.retired for v in rt.replicas()] == [False]

    def test_session_heartbeat_carries_publication(self):
        from tony_tpu.conf import TonyConfig
        from tony_tpu.session import TonySession

        s = TonySession(TonyConfig({"tony.worker.instances": "1"}),
                        app_id="app_pub")
        s.on_registered("worker", 0, "h0", 4000)
        s.on_heartbeat("worker", 0, published={"version": 3, "step": 40})
        t = s.task("worker", 0)
        assert t.published == {"version": 3, "step": 40}
        assert t.to_info()["published"] == {"version": 3, "step": 40}
        # Malformed publication news is advisory, never liveness-fatal.
        s.on_heartbeat("worker", 0, published={"version": "x"})
        assert s.task("worker", 0).published == {"version": 3, "step": 40}


# ---------------------------------------------------------------------------
# jhist: the PUBLISH→SWAP timeline, bill --json/--csv --since/--until
# ---------------------------------------------------------------------------

class TestHistoryPlane:
    def test_publish_swap_events_rotation_proof(self):
        assert ev.PUBLISH not in ev._HIGH_RATE
        assert ev.SWAP not in ev._HIGH_RATE

    @pytest.fixture
    def pub_jhist(self, tmp_path, monkeypatch):
        clock = {"t": 1000.0}
        monkeypatch.setattr(
            ev, "time", types.SimpleNamespace(time=lambda: clock["t"]))
        from tony_tpu.conf import SERVE_QOS_TENANTS

        handler = ev.EventHandler(
            tmp_path, "app_pub_hist",
            conf_snapshot={SERVE_QOS_TENANTS: "gold:2"})
        handler.task_started("serve", 0, "host0")
        for t, rate in ((1000.0, 100.0), (1010.0, 100.0), (1020.0, 0.0)):
            clock["t"] = t
            handler.serve_window(
                "serve", 0,
                {"tenants": {"gold": {"tokens_per_s": rate}}})
        handler.publish(1, 5, note="nightly")
        handler.swap("serve", 1, 0, 1, 5, 2.5, True)
        handler.swap("serve", 0, 0, 1, 5, 130.0, False,
                     detail="swap RPC timed out")
        handler.application_finished("SUCCEEDED", "")
        handler.close()
        return tmp_path

    def test_timeline_reconstructs_from_history(self, pub_jhist):
        jobs = history.gather_jobs(pub_jhist)
        detail = history.job_detail(jobs[0])
        assert [p["version"] for p in detail["publications"]] == [1]
        assert [(s["index"], s["ok"]) for s in detail["swaps"]] == [
            (1, True), (0, False)]
        text = history.render_show(detail)
        assert "publication timeline:" in text
        assert "PUBLISH v1" in text and "step 5" in text
        assert "SWAP serve:1 v0→v1" in text
        assert "FAILED" in text and "swap RPC timed out" in text
        page = history._job_page(detail)
        assert "Publication timeline" in page

    def test_bill_window_clips_before_rollup(self, pub_jhist):
        jobs = history.gather_jobs(pub_jhist)
        # Full ledger: 100 tok/s × 20 s = 2000 tokens, weight 2.
        rows = history.bill_rows(jobs)
        assert rows == [{"app_id": "app_pub_hist", "tenant": "gold",
                         "tokens": pytest.approx(2000.0), "weight": 2.0,
                         "billed": pytest.approx(4000.0)}]
        # since drops the first window, until the last — half each.
        assert history.bill_rows(jobs, since=1005.0)[0]["tokens"] == \
            pytest.approx(1000.0)
        assert history.bill_rows(jobs, until=1015.0)[0]["tokens"] == \
            pytest.approx(1000.0)
        assert history.bill_rows(jobs, "nobody") == []

    def test_bill_cli_json_csv_and_parse_when(self, pub_jhist, capsys):
        args = types.SimpleNamespace(action="bill", app_id=None,
                                     history_dir=str(pub_jhist),
                                     json=True, csv=False,
                                     since=None, until="1015")
        assert history.main(args) == 0
        rows = json.loads(capsys.readouterr().out)
        assert rows[0]["tenant"] == "gold"
        assert rows[0]["tokens"] == pytest.approx(1000.0)
        args.json, args.csv = False, True
        assert history.main(args) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert out[0] == "app_id,tenant,tokens,weight,billed"
        assert out[1] == "app_pub_hist,gold,1000,2,2000"
        # Unparseable window: usage error, not a stack trace.
        args.until = "last tuesday"
        assert history.main(args) == 2
        assert "unparseable" in capsys.readouterr().out
        assert history.parse_when(None) is None
        assert history.parse_when("1015.5") == 1015.5
        assert history.parse_when("2026-08-07") == time.mktime(
            time.strptime("2026-08-07", "%Y-%m-%d"))


# ---------------------------------------------------------------------------
# tony aot gc + the CLI front doors
# ---------------------------------------------------------------------------

class TestAotGc:
    RT = {"jax": "0.9.9", "backend": "cpu", "n_devices": 1}

    def _entry(self, root, name, fp):
        d = root / name
        d.mkdir(parents=True)
        (d / "entry.json").write_text(json.dumps({"fingerprint": fp}))
        (d / "prog.bin").write_bytes(b"x" * 64)

    def test_gc_drops_only_unhittable_entries(self, tmp_path):
        from tony_tpu.ckpt.aot import AOTCache

        cache = AOTCache(str(tmp_path / "aot"))
        root = Path(cache.root)
        # Live: runtime matches — OTHER geometry/model is kept (that is
        # what a shared cache is FOR).
        self._entry(root, "aot_live1", {**self.RT, "kind": "decode"})
        self._entry(root, "aot_live2", {**self.RT, "kind": "prefill",
                                        "mesh": "fsdp4"})
        # Stranded: a runtime no live config can reproduce.
        self._entry(root, "aot_stale", {**self.RT, "jax": "0.1.0"})
        # Torn: unreadable entry.json == unhittable.
        (root / "aot_torn").mkdir()
        (root / "aot_torn" / "entry.json").write_text("{ half")
        # A crashed writer's staging dir is always reclaimed.
        self._entry(root, "aot_x.tmp123", {**self.RT})
        dropped, kept, freed = cache.gc(dry_run=True, runtime=self.RT)
        assert (dropped, kept) == (3, 2) and freed > 0
        assert sorted(p.name for p in root.iterdir() if
                      p.name.startswith("aot_")) == [
            "aot_live1", "aot_live2", "aot_stale", "aot_torn",
            "aot_x.tmp123"]          # dry run deleted nothing
        dropped, kept, freed2 = cache.gc(runtime=self.RT)
        assert (dropped, kept) == (3, 2) and freed2 == freed
        assert sorted(p.name for p in root.iterdir() if
                      p.name.startswith("aot_")) == [
            "aot_live1", "aot_live2"]
        # Idempotent: a second pass finds nothing stranded.
        assert cache.gc(runtime=self.RT) == (0, 2, 0)


class TestCli:
    def test_tony_publish(self, tmp_path, capsys):
        from tony_tpu.cli import main as cli_main

        root = tmp_path / "ckpt"
        commit_fake_steps(root, 4)
        assert cli_main(["publish", str(root)]) == 0
        assert "published v1 -> step 4" in capsys.readouterr().out
        assert cli_main(["publish", str(root), "--step", "9"]) == 1
        assert "not committed" in capsys.readouterr().out
        rec = publish.latest_publication(root)
        assert (rec["version"], rec["step"]) == (1, 4)

    def test_tony_aot_gc(self, tmp_path, capsys):
        from tony_tpu.cli import main as cli_main

        cache_dir = tmp_path / "aot"
        (cache_dir / "aot_orphan.tmp1").mkdir(parents=True)
        assert cli_main(["aot", "gc", "--cache", str(cache_dir),
                         "--dry-run"]) == 0
        assert "would drop 1" in capsys.readouterr().out
        assert (cache_dir / "aot_orphan.tmp1").is_dir()
        assert cli_main(["aot", "gc", "--cache", str(cache_dir)]) == 0
        assert "dropped 1" in capsys.readouterr().out
        assert not (cache_dir / "aot_orphan.tmp1").exists()

    def test_tony_serve_follow_resolves_ckpt_dir(self, tmp_path,
                                                 monkeypatch):
        import tony_tpu.client as client_mod
        from tony_tpu import conf as conf_mod
        from tony_tpu import constants
        from tony_tpu.cli import cmd_serve, make_parser
        from tony_tpu.conf import TonyConfig

        captured = {}

        class _FakeClient:
            def __init__(self, cfg, **kw):
                captured["cfg"] = cfg

            def run(self, timeout=None):
                return 0

        monkeypatch.setattr(client_mod, "TonyClient", _FakeClient)
        # --follow a JOB DIR: the followed train job's conf supplies the
        # ckpt root the publications land in, and follow mode is armed.
        jobdir = tmp_path / "job"
        jobdir.mkdir()
        ckpt = tmp_path / "ckpt"
        TonyConfig({conf_mod.CKPT_DIR: str(ckpt)}).save(
            jobdir / constants.TONY_JOB_JSON)
        args = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--follow", str(jobdir)])
        assert args.fn(args) == 0
        cfg = captured["cfg"]
        assert cfg.get(conf_mod.PUBLISH_FOLLOW) == "true"
        assert cfg.get(conf_mod.SERVE_CKPT_DIR) == str(ckpt.resolve())
        # A bare ckpt dir (no job conf inside) follows directly.
        args = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--follow", str(ckpt)])
        assert args.fn(args) == 0
        assert captured["cfg"].get(conf_mod.SERVE_CKPT_DIR) == \
            str(ckpt.resolve())
        # A jobdir whose conf names no ckpt dir is a clean usage error.
        empty = tmp_path / "job2"
        empty.mkdir()
        TonyConfig({}).save(empty / constants.TONY_JOB_JSON)
        args = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--follow", str(empty)])
        with pytest.raises(SystemExit, match="nothing to"):
            cmd_serve(args)
        # Neither --ckpt_dir nor --follow: same.
        args = make_parser().parse_args(["serve", "--model", "llama-tiny"])
        with pytest.raises(SystemExit, match="--ckpt_dir"):
            cmd_serve(args)
