"""Real ParameterServerStrategy training (graduation config ①, SURVEY.md §6;
reference: TestTonyE2E#testPSWorkerTrainingShouldPass). Role-switched on the
TF_CONFIG the TFRuntime injected: ps/worker run a tf.distribute.Server (they
hold variables / run replica fns until the AM tears them down on chief
success — the chief-done policy); the chief drives a ClusterCoordinator
training loop whose loss must decrease."""

import json
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import tensorflow as tf

tfc = json.loads(os.environ["TF_CONFIG"])
role, idx = tfc["task"]["type"], tfc["task"]["index"]

if role in ("ps", "worker"):
    server = tf.distribute.Server(tf.train.ClusterSpec(tfc["cluster"]),
                                  job_name=role, task_index=idx,
                                  protocol="grpc")
    server.join()  # forever; the AM kills us when the chief finishes
else:
    import numpy as np

    resolver = tf.distribute.cluster_resolver.TFConfigClusterResolver()
    strategy = tf.distribute.ParameterServerStrategy(resolver)
    coord = tf.distribute.coordinator.ClusterCoordinator(strategy)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(64, 4)).astype("float32")
    ys = xs @ rng.normal(size=(4, 1)).astype("float32")
    with strategy.scope():  # variables land on the ps
        w = tf.Variable(tf.zeros((4, 1)))
        opt = tf.keras.optimizers.SGD(0.1)

    @tf.function
    def step():
        def replica_fn():
            with tf.GradientTape() as tape:
                loss = tf.reduce_mean(
                    tf.square(tf.constant(xs) @ w - tf.constant(ys)))
            grads = tape.gradient(loss, [w])
            opt.apply_gradients(zip(grads, [w]))
            return loss

        return strategy.run(replica_fn)

    losses = [float(coord.fetch(coord.schedule(step))) for _ in range(20)]
    coord.join()
    assert losses[-1] < losses[0] * 0.5, losses
    with open("tf_ps_result.json", "w") as f:
        json.dump({"loss_first": losses[0], "loss_last": losses[-1]}, f)
    print(f"tf ps-strategy chief: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
