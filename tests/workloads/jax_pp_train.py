"""Distributed pipeline-parallel training stub: 2 processes form one pp=2
mesh; the GPipe ppermute ring crosses the process boundary (the class of
breakage single-process pipeline tests can't catch). Process 0 writes the
loss history."""

import json
import os
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import tony_tpu.distributed as dist

initialized = dist.initialize()
assert initialized, "expected multi-process TonY env"

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model
from tony_tpu.parallel import pipelined_lm_logits

mesh = par.MeshSpec(dp=jax.device_count() // 2, pp=2).build()
model = get_model("llama-tiny")
cfg = model.cfg

# 2 microbatches x 2 rows per DP group (the executor's device count is
# env-dependent, so size the batch from the mesh, not a constant).
glob = mesh.shape["data"] * 4
local_batch = glob // jax.process_count()
sample = jnp.zeros((glob, 16), jnp.int32)
state = train.create_train_state(
    model, optax.adam(1e-2), sample, jax.random.PRNGKey(0), mesh=mesh)


def loss_fn(params, tokens):
    logits = pipelined_lm_logits(params, tokens, cfg, mesh,
                                 n_stages=2, microbatches=2)
    return train.next_token_loss(logits, tokens)


import functools


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, tokens):
    loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
    return state.apply_gradients(grads=grads), loss


tokens_local = jax.random.randint(
    jax.random.PRNGKey(jax.process_index()), (local_batch, 16), 0, cfg.vocab)
tokens = train.global_batch(mesh, {"x": tokens_local})["x"]

losses = []
for _ in range(6):
    state, loss = step(state, tokens)
    losses.append(float(loss))

if jax.process_index() == 0:
    Path("pp_losses.json").write_text(json.dumps({
        "num_processes": jax.process_count(),
        "num_devices": jax.device_count(),
        "mesh": dict(mesh.shape),
        "losses": losses,
    }))
