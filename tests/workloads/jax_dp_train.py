"""Distributed JAX DP training stub: the SURVEY.md §7 step-5 milestone
workload. Each process joins the jax.distributed world wired by the
JAXRuntime env, builds a global 2-device mesh, and trains an MNIST-shaped
MLP where GSPMD psums gradients across processes. Process 0 writes the loss
history for the e2e test to assert on."""

import json
import os
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import tony_tpu.distributed as dist

initialized = dist.initialize()
assert initialized, "expected multi-process TonY env"

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model

mesh = par.MeshSpec(dp=jax.device_count()).build()
model = get_model("mnist-mlp", hidden=32)

local_batch = 8
key = jax.random.PRNGKey(jax.process_index())
x_local = jax.random.normal(key, (local_batch, 784), jnp.float32)
y_local = jax.random.randint(key, (local_batch,), 0, 10)

state = train.create_train_state(
    model, optax.adam(1e-2), jnp.zeros((1, 784)), jax.random.PRNGKey(0),
    mesh=mesh)
step = train.make_train_step(mesh=mesh)

losses = []
for _ in range(8):
    batch = train.global_batch(mesh, {"x": x_local, "y": y_local})
    state, metrics = step(state, batch)
    losses.append(float(metrics["loss"]))

assert all(jnp.isfinite(jnp.asarray(losses))), losses
assert losses[-1] < losses[0], losses
if jax.process_index() == 0:
    Path("dp_losses.json").write_text(json.dumps({
        "losses": losses,
        "num_processes": jax.process_count(),
        "num_devices": jax.device_count(),
    }))
print(f"rank {jax.process_index()}: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
