"""Stub workload for chief-like tasks: dump env to ./env.json, then wait
until N containers TOTAL (including this one) have written env.json
before exiting (reference fixture role: check_env_and_venv.py). Needed
because the chief-done success policy ends the job — and kills
still-running peers — the moment the chief exits, which would race
peers' env.json writes.
"""
import glob
import json
import os
import sys
import time

with open("env.json.tmp", "w") as f:
    json.dump(dict(os.environ), f)
os.rename("env.json.tmp", "env.json")

want = int(sys.argv[1]) if len(sys.argv) > 1 else 2
# Below MiniPod.run's 60s default timeout so a missing peer fails as a
# clean nonzero exit, not a harness TimeoutError.
deadline = time.time() + 45
# cwd is containers/<task_id>/src inside the shared job dir.
while len(glob.glob("../../*/src/env.json")) < want:
    if time.time() > deadline:
        sys.exit(3)
    time.sleep(0.05)
