"""Stub workload: dump the env the executor built into ./env.json
(reference fixture: check_env_and_venv.py). Written via tmp+rename so a
peer polling for the file (check_env_wait.py) never sees a partial write."""
import json
import os

with open("env.json.tmp", "w") as f:
    json.dump(dict(os.environ), f)
os.rename("env.json.tmp", "env.json")
