"""Stub workload: dump the env the executor built into ./env.json
(reference fixture: check_env_and_venv.py)."""
import json
import os

with open("env.json", "w") as f:
    json.dump(dict(os.environ), f)
