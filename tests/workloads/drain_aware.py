"""Elastic-resize-aware standalone workload: runs until the executor's
drain flag appears (the ``TONY_DRAIN_FILE`` path materialized when the
AM's heartbeat reply carries the drain directive), then exits
``EXIT_DRAINED`` — the minimal analogue of ``train_loop``'s drain poll
for e2e resize tests that don't need a real model."""

import os
import sys
import time

drain = os.environ.get("TONY_DRAIN_FILE", "")
while True:
    if drain and os.path.exists(drain):
        sys.exit(14)  # constants.EXIT_DRAINED
    time.sleep(0.05)
