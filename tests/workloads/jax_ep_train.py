"""Distributed expert-parallel training stub: each process joins the
jax.distributed world wired by the JAXRuntime env and trains the tiny MoE
model over an ep=2 mesh spanning BOTH processes — the GShard dispatch
all_to_all crosses the process boundary. Process 0 writes the result."""

import json
import os
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import tony_tpu.distributed as dist

initialized = dist.initialize()
assert initialized, "expected multi-process TonY env"

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model

mesh = par.MeshSpec(dp=jax.device_count() // 2, ep=2).build()
model = get_model("llama-moe-tiny")
cfg = model.cfg

local_batch = 4
tokens_local = jax.random.randint(
    jax.random.PRNGKey(jax.process_index()), (local_batch, 16), 0, cfg.vocab)

sample = jnp.zeros((local_batch * jax.process_count(), 16), jnp.int32)
state = train.create_train_state(
    model, optax.adam(1e-2), sample, jax.random.PRNGKey(0), mesh=mesh)
step = train.make_train_step(
    loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
    mesh=mesh)

losses, aux = [], []
for _ in range(6):
    batch = train.global_batch(mesh, {"x": tokens_local})
    state, metrics = step(state, batch)
    losses.append(float(metrics["loss"]))
    aux.append(float(metrics["aux_loss"]))

if jax.process_index() == 0:
    Path("ep_losses.json").write_text(json.dumps({
        "num_processes": jax.process_count(),
        "num_devices": jax.device_count(),
        "mesh": dict(mesh.shape),
        "losses": losses,
        "aux": aux,
    }))
