import time
while True:
    time.sleep(0.1)
