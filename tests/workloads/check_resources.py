"""Asserts tony.containers.resources entries were localized into the
container cwd (reference fixture role: check_env_and_venv.py for
LocalizableResource): a plain file, a directory, and an unpacked #archive
member. Writes what it saw for the test to inspect."""
import json
import sys
from pathlib import Path

seen = {
    "data": Path("data.txt").read_text().strip(),
    "dir_member": Path("extra/nested.txt").read_text().strip(),
    "archive_member": Path("inside_archive.txt").read_text().strip(),
}
Path("resources_check.json").write_text(json.dumps(seen))
sys.exit(0)
