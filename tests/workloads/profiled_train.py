"""Worker that starts the profiler server (via tony_tpu.distributed) and
keeps the backend busy long enough for the AM's automatic trace collection
to capture real events (SURVEY.md §5.1 collection half, e2e)."""

import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import tony_tpu.distributed as dist

dist.initialize()  # starts jax.profiler.start_server on TONY_PROFILER_PORT
assert os.environ.get("TONY_PROFILER_PORT"), "profiler port not assigned"

import jax.numpy as jnp

x = jnp.ones((256, 256))
deadline = time.time() + 25.0
while time.time() < deadline:
    x = (x @ x) / 256.0
    x.block_until_ready()
print("profiled workload done")
