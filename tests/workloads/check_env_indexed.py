"""Stub workload: dump the env into ./env.<task_index>.json — the
per-task variant of check_env.py for substrates where co-hosted
containers share a working directory (the tpu-vm remote workdir)."""
import json
import os

idx = os.environ.get("TONY_TASK_INDEX", "x")
tmp = f"env.{idx}.json.tmp"
with open(tmp, "w") as f:
    json.dump(dict(os.environ), f)
os.rename(tmp, f"env.{idx}.json")
