"""Real tf.distribute training across containers (graduation configs ①/②,
SURVEY.md §6; reference: TestTonyE2E#testPSWorkerTrainingShouldPass runs an
actually-training TF job, not an env check). MultiWorkerMirroredStrategy
forms its collective ring purely from the TF_CONFIG the TFRuntime injected;
a custom strategy.run loop (keras-3 fit no longer supports MWMS) trains a
linear model and loss must decrease — real cross-container allreduce."""

import json
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np
import tensorflow as tf

tfc = json.loads(os.environ["TF_CONFIG"])
assert tfc["task"]["type"] == "worker"
rank = tfc["task"]["index"]
n_workers = len(tfc["cluster"]["worker"])
assert n_workers >= 2, tfc

strategy = tf.distribute.MultiWorkerMirroredStrategy()
assert strategy.num_replicas_in_sync == n_workers

# Tiny synthetic linear regression; per-worker shards of a seeded dataset,
# so the allreduced gradient spans data this worker never saw.
rng = np.random.default_rng(0)
xs = rng.normal(size=(128, 8)).astype(np.float32)
w_true = rng.normal(size=(8, 1)).astype(np.float32)
ys = xs @ w_true
shard_x = xs[rank::n_workers]
shard_y = ys[rank::n_workers]

with strategy.scope():
    w = tf.Variable(tf.zeros((8, 1)), name="w")
    opt = tf.keras.optimizers.SGD(0.1)


@tf.function
def step(bx, by):
    def replica_step(x, y):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean(tf.square(x @ w - y))
        grads = tape.gradient(loss, [w])
        opt.apply_gradients(zip(grads, [w]))  # allreduced under MWMS
        return loss

    per_replica = strategy.run(replica_step, args=(bx, by))
    return strategy.reduce(tf.distribute.ReduceOp.MEAN, per_replica, axis=None)


losses = []
for _ in range(30):
    losses.append(float(step(tf.constant(shard_x), tf.constant(shard_y))))
assert losses[-1] < losses[0] * 0.5, losses  # really trained, not noise

with open(f"tf_rank{rank}.json", "w") as f:
    json.dump({"rank": rank, "n_workers": n_workers,
               "loss_first": losses[0], "loss_last": losses[-1]}, f)
print(f"tf worker {rank}/{n_workers}: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
