"""Fails on the first attempt, succeeds on the second (AM gang-restart test).
The marker lives in the STAGED src dir (shared across attempts), not the
per-container copy, so attempt 2 sees attempt 1's marker."""
import os
import sys

marker = os.path.join(os.environ["TONY_SRC_DIR"], "flaky.marker")
if os.path.exists(marker):
    sys.exit(0)
open(marker, "w").close()
sys.exit(1)
