"""Gang-restart resume workload (reference fixture analogue: the user
script that restores from its HDFS checkpoint dir after an AM restart).

Attempt 1: train 3 steps, save via Checkpointer, exit 1 (induced failure
-> whole-gang restart). Attempt 2: restore, assert the step survived,
train 2 more, save, write resume.json, exit 0.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from tony_tpu import train as tr
from tony_tpu.checkpoint import Checkpointer

ckpt_dir = os.environ["CKPT_DIR"]


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(x)


x = jnp.ones((2, 8))
y = jnp.zeros((2,), jnp.int32)
state = tr.create_train_state(Tiny(), optax.sgd(0.1), x, jax.random.PRNGKey(0))
ckpt = Checkpointer(ckpt_dir)
state = ckpt.restore_or(state)
start = int(state.step)

step = tr.make_train_step()
if start == 0:
    for _ in range(3):
        state, metrics = step(state, {"x": x, "y": y})
    ckpt.save(state)
    ckpt.close()
    sys.exit(1)  # induced failure: the AM must gang-restart

assert start == 3, f"expected to resume from step 3, got {start}"
for _ in range(2):
    state, metrics = step(state, {"x": x, "y": y})
    assert jnp.isfinite(metrics["loss"]), "post-resume loss is not finite"
ckpt.save(state)
ckpt.close()
with open("resume.json", "w") as f:
    json.dump({"resumed_from": start, "final_step": int(state.step)}, f)
sys.exit(0)
