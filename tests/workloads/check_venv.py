"""Asserts the shipped venv was localized and put on PATH (reference test
fixture analogue: ``check_env_and_venv.py``)."""

import json
import os
import shutil
from pathlib import Path

venv = os.environ.get("VIRTUAL_ENV")
assert venv, "VIRTUAL_ENV not set"
tool = shutil.which("tony-venv-marker")
assert tool, "venv bin/ not on PATH"
assert Path(tool).read_text().strip() == "#!/bin/sh"
Path("venv_check.json").write_text(json.dumps({"virtual_env": venv,
                                               "tool": tool}))
