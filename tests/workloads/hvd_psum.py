"""Horovod-semantics-on-ICI stub (graduation config ④, SURVEY.md §6): the
job sees the full HOROVOD_* contract, but its allreduce is an XLA
cross-process reduction over the coordinator triple the HorovodRuntime also
exported — the NCCL→ICI replacement, live."""

import json
import os
from pathlib import Path

rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])
assert os.environ["HOROVOD_CONTROLLER"] == "tony"
assert os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"]
assert int(os.environ["HOROVOD_LOCAL_SIZE"]) >= 1
assert int(os.environ["HOROVOD_CROSS_SIZE"]) >= 1

# The driver-served slot table is the source of truth; the env ranks the
# runtime computed independently must agree with it — one slot math, two
# transports (this is what hvd.init() would consume from the rendezvous).
from tony_tpu.runtime.horovod_driver import fetch_slots

rdv = (os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] + ":"
       + os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"])
table = fetch_slots(rdv)
assert table["ready"], table
my_slot = table["slots"][rank]
assert my_slot["rank"] == rank and my_slot["size"] == size, (my_slot, rank)
assert my_slot["local_rank"] == int(os.environ["HOROVOD_LOCAL_RANK"])
assert my_slot["local_size"] == int(os.environ["HOROVOD_LOCAL_SIZE"])
assert my_slot["cross_rank"] == int(os.environ["HOROVOD_CROSS_RANK"])
assert my_slot["cross_size"] == int(os.environ["HOROVOD_CROSS_SIZE"])

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import tony_tpu.distributed as dist

assert dist.initialize(), "coordinator triple missing"
assert dist.process_id() == rank and dist.num_processes() == size

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The ring-allreduce moment: every process contributes its rank; the jitted
# sum over the process-sharded global array is the cross-host collective.
mesh = Mesh(jax.devices(), ("data",))
n_local = jax.local_device_count()
local = jnp.full((n_local,), rank, jnp.int32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local)
total = int(jax.jit(
    jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr))
expected = sum(r * n_local for r in range(size))
assert total == expected, (total, expected)
Path(f"hvd_rank{rank}.json").write_text(json.dumps({
    "rank": rank, "size": size, "allreduce": total}))
print(f"hvd rank {rank}/{size}: allreduce={total}")
