import time, sys
time.sleep(3)
sys.exit(0)
