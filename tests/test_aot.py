"""Replica cold-start plane (PR 17): persisted AOT compile cache +
warm-standby pools.

Four claims under test:

* the cache itself (:mod:`tony_tpu.ckpt.aot`): round trip, corruption /
  truncation / fingerprint-drift each a COUNTED state-unchanged miss,
  concurrent populate first-writer-wins through the atomic rename;
* cache-hit engines are BITWISE the fresh-trace engine — token streams
  and per-token logits — across the serve/spec/route/disagg step
  families, and a cache-hit replica start executes ZERO fresh traces or
  compiles (counter-pinned, the machine-independent claim);
* the warm-standby pool policy: ``decide_warm`` matrix, the
  ``ScalingPolicy`` decision matrix pinned UNCHANGED under the widened
  sample schema, standby exclusion from the routable endpoint set, and
  the stats→heartbeat→session round trip of the +4 schema;
* the engine-loop demotion daemon: off by default, counted when armed.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.aot


@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


def run_requests(eng, prompts, max_new=4):
    from tony_tpu.serve import Request

    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, tokens=list(p), max_new_tokens=max_new))
    return {c.rid: c for c in eng.run()}


def assert_bitwise_equal(got, ref):
    """Token streams AND per-token logits of two completion maps."""
    assert sorted(got) == sorted(ref)
    for rid in ref:
        assert got[rid].tokens == ref[rid].tokens, rid
        assert len(got[rid].logits) == len(ref[rid].logits)
        for a, b in zip(got[rid].logits, ref[rid].logits):
            assert np.array_equal(a, b), rid


PROMPTS = [[3, 5, 7, 11, 13], [2, 4, 6], [1, 2, 3, 4, 5, 6, 7, 8, 9]]


# ---------------------------------------------------------------------------
# The cache itself
# ---------------------------------------------------------------------------

def _tiny_compiled():
    """A real ``jax.stages.Compiled`` cheap enough for unit tests."""
    x = jnp.arange(8, dtype=jnp.float32)
    return jax.jit(lambda a: a * 2 + 1).lower(x).compile(), x


class TestAOTCache:

    def test_round_trip_and_counters(self, tmp_path):
        from tony_tpu.ckpt import AOTCache, make_fingerprint

        cache = AOTCache(str(tmp_path))
        fp = make_fingerprint("unit", geometry={"n": 8})
        assert cache.get(fp) is None and cache.misses == 1
        compiled, x = _tiny_compiled()
        assert cache.put(fp, compiled) and cache.puts == 1
        loaded = cache.get(fp)
        assert loaded is not None and cache.hits == 1
        np.testing.assert_array_equal(np.asarray(loaded(x)),
                                      np.asarray(compiled(x)))
        # Idempotent second put: counted race, store unchanged.
        assert not cache.put(fp, compiled) and cache.put_races == 1
        assert len(cache.entries()) == 1

    def test_fingerprint_drift_is_counted_miss(self, tmp_path):
        from tony_tpu.ckpt import AOTCache, make_fingerprint

        cache = AOTCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        fp = make_fingerprint("unit", geometry={"b": 2, "t": 16})
        cache.put(fp, compiled)
        # Changed geometry: a different key, so simply absent.
        drifted = make_fingerprint("unit", geometry={"b": 4, "t": 16})
        assert cache.get(drifted) is None and cache.misses == 1
        # Changed jax version string with the SAME key (a hand-forced
        # address collision): the stored full fingerprint must reject.
        skewed = dict(fp, jax="0.0.0-drifted")
        d = cache._dir(fp)
        entry = json.loads((d / "entry.json").read_text())
        entry["fingerprint"] = dict(entry["fingerprint"],
                                    jax="0.0.0-stored")
        (d / "entry.json").write_text(json.dumps(entry))
        assert cache.get(fp) is None and cache.misses == 2
        assert cache.get(skewed) is None and cache.misses == 3
        # State unchanged: the entry is still on disk, untouched.
        assert len(cache.entries()) == 1

    @pytest.mark.parametrize("how", ["flip", "truncate", "entry"])
    def test_corruption_is_counted_miss_state_unchanged(self, tmp_path,
                                                        how):
        from tony_tpu.ckpt import AOTCache, make_fingerprint

        cache = AOTCache(str(tmp_path))
        compiled, _ = _tiny_compiled()
        fp = make_fingerprint("unit", geometry={"case": how})
        cache.put(fp, compiled)
        d = cache._dir(fp)
        if how == "flip":
            raw = bytearray((d / "payload.bin").read_bytes())
            raw[len(raw) // 2] ^= 0xFF
            (d / "payload.bin").write_bytes(bytes(raw))
        elif how == "truncate":
            raw = (d / "payload.bin").read_bytes()
            (d / "payload.bin").write_bytes(raw[:len(raw) // 2])
        else:
            (d / "entry.json").write_text("{not json")
        before = sorted(p.name for p in d.iterdir())
        assert cache.get(fp) is None
        assert cache.misses == 1 and cache.hits == 0
        # get never mutates the store: poison costs a recompile per
        # consult, not a crash and not a repair attempt.
        assert sorted(p.name for p in d.iterdir()) == before

    def test_concurrent_populate_first_writer_wins(self, tmp_path):
        from tony_tpu.ckpt import AOTCache, make_fingerprint

        compiled, x = _tiny_compiled()
        fp = make_fingerprint("unit", geometry={"race": 1})
        caches = [AOTCache(str(tmp_path)) for _ in range(4)]
        barrier = threading.Barrier(4)
        results = [None] * 4

        def writer(i):
            barrier.wait()
            results[i] = caches[i].put(fp, compiled)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(results) == 1            # exactly one commit
        assert sum(c.put_races for c in caches) == 3
        # The committed entry is whole and loads; no staging orphans
        # linger inside the committed dir listing.
        reader = AOTCache(str(tmp_path))
        assert len(reader.entries()) == 1
        loaded = reader.get(fp)
        assert loaded is not None
        np.testing.assert_array_equal(np.asarray(loaded(x)),
                                      np.asarray(compiled(x)))

    def test_payload_only_entry_needs_caller_trees(self, tmp_path,
                                                   monkeypatch):
        """An unpicklable treedef (the train state's optax tx) commits
        a payload-only entry: get without caller trees is a counted
        miss; with them, a working executable."""
        import pickle as _pickle

        from tony_tpu.ckpt import AOTCache, make_fingerprint
        from tony_tpu.ckpt import aot as aot_mod

        class _NoDumps:
            PicklingError = _pickle.PicklingError
            UnpicklingError = _pickle.UnpicklingError
            loads = staticmethod(_pickle.loads)

            @staticmethod
            def dumps(obj):
                raise _pickle.PicklingError("local object")

        monkeypatch.setattr(aot_mod, "pickle", _NoDumps)
        cache = AOTCache(str(tmp_path))
        compiled, x = _tiny_compiled()
        fp = make_fingerprint("unit", geometry={"trees": "none"})
        assert cache.put(fp, compiled)
        monkeypatch.undo()
        entry = json.loads(
            (cache._dir(fp) / "entry.json").read_text())
        assert entry["trees_b64"] is None
        assert cache.get(fp) is None and cache.misses == 1
        from jax.experimental import serialize_executable as se
        _, in_tree, out_tree = se.serialize(compiled)
        loaded = cache.get(fp, in_tree=in_tree, out_tree=out_tree)
        assert loaded is not None and cache.hits == 1
        np.testing.assert_array_equal(np.asarray(loaded(x)),
                                      np.asarray(compiled(x)))


# ---------------------------------------------------------------------------
# Bitwise parity + the zero-fresh-compiles pin (serve family)
# ---------------------------------------------------------------------------

class TestServeFamilyBitwise:

    def test_cache_hit_engine_is_bitwise_and_compiles_nothing(
            self, tiny, tmp_path):
        """THE acceptance pin: a replica starting on a populated cache
        executes ZERO fresh traces/compiles for the step family and its
        streams are bit-identical to a cold-trace engine's."""
        from tony_tpu.ckpt import AOTCache

        ref = run_requests(make_engine(tiny), PROMPTS)
        root = str(tmp_path / "aot")
        # First cache-armed engine: populates (counted misses).
        e1 = make_engine(tiny, aot_cache=AOTCache(root))
        e1.warm(prefill_pads=(16,))
        assert e1.aot_misses > 0 and e1.fresh_compiles > 0
        got1 = run_requests(e1, PROMPTS)
        assert_bitwise_equal(got1, ref)
        # Second engine, same family: every program deserializes.
        c2 = AOTCache(root)
        e2 = make_engine(tiny, aot_cache=c2)
        e2.warm(prefill_pads=(16,))
        got2 = run_requests(e2, PROMPTS)
        assert_bitwise_equal(got2, ref)
        assert e2.fresh_compiles == 0          # zero XLA compiles
        assert e2._fns == {}                   # zero fresh traces
        assert e2.aot_hits > 0 and e2.aot_misses == 0
        assert c2.hits == e2.aot_hits and c2.misses == 0
        assert e2.deserialize_ms >= 0.0 and e2.compile_ms == 0.0

    def test_corrupted_cache_degrades_to_fresh_trace_bitwise(
            self, tiny, tmp_path):
        from tony_tpu.ckpt import AOTCache

        root = str(tmp_path / "aot")
        e1 = make_engine(tiny, aot_cache=AOTCache(root))
        e1.warm(prefill_pads=(16,))
        ref = run_requests(e1, PROMPTS)
        # Poison every payload byte-flip style.
        for d in (tmp_path / "aot").iterdir():
            if d.is_dir():
                raw = bytearray((d / "payload.bin").read_bytes())
                raw[0] ^= 0xFF
                (d / "payload.bin").write_bytes(bytes(raw))
        e2 = make_engine(tiny, aot_cache=AOTCache(root))
        e2.warm(prefill_pads=(16,))
        got = run_requests(e2, PROMPTS)
        assert_bitwise_equal(got, ref)
        assert e2.aot_hits == 0 and e2.aot_misses > 0
        assert e2.fresh_compiles > 0           # recompiled, never wrong

    def test_default_engine_has_no_aot_surface(self, tiny):
        """No cache handle: the hot loop runs the raw jit dict exactly
        as before this PR — the parallel executable dict stays empty
        and the counters stay zero."""
        eng = make_engine(tiny)
        run_requests(eng, PROMPTS[:1])
        assert eng.aot_cache is None and eng._aot_fns == {}
        assert eng.aot_hits == 0 and eng.aot_misses == 0
        s = eng.stats()
        assert s["aot_hits"] == 0.0 and s["aot_misses"] == 0.0
        assert s["compile_ms"] == 0.0 and s["warm_standby"] == 0.0


@pytest.mark.slow
class TestOtherFamiliesBitwise:

    def test_route_family(self, tiny, tmp_path):
        """Prefix cache + chunked prefill (the route composition) under
        a populated cache: bitwise, with the chunk program cached."""
        from tony_tpu.ckpt import AOTCache

        kw = dict(prefix_cache=True, prefill_chunk=16)
        ref = run_requests(make_engine(tiny, **kw), PROMPTS)
        root = str(tmp_path / "aot")
        e1 = make_engine(tiny, aot_cache=AOTCache(root), **kw)
        e1.warm(prefill_pads=(16,))
        assert_bitwise_equal(run_requests(e1, PROMPTS), ref)
        e2 = make_engine(tiny, aot_cache=AOTCache(root), **kw)
        e2.warm(prefill_pads=(16,))
        assert_bitwise_equal(run_requests(e2, PROMPTS), ref)
        assert e2.fresh_compiles == 0 and e2._fns == {}

    def test_spec_family(self, tiny, tmp_path):
        from tony_tpu.ckpt import AOTCache
        from tony_tpu.serve import SpecEngine

        model, params = tiny
        kw = dict(spec_k=3, ctx_max=64, block_size=8, q_block=16,
                  decode_buckets=(2, 4), max_running=4, keep_logits=True)
        ref = run_requests(SpecEngine(model, params, **kw), PROMPTS)
        root = str(tmp_path / "aot")
        e1 = SpecEngine(model, params, aot_cache=AOTCache(root), **kw)
        assert_bitwise_equal(run_requests(e1, PROMPTS), ref)
        assert e1.aot_misses > 0
        e2 = SpecEngine(model, params, aot_cache=AOTCache(root), **kw)
        assert_bitwise_equal(run_requests(e2, PROMPTS), ref)
        assert e2.aot_hits > 0 and e2.fresh_compiles == 0

    def test_disagg_family(self, tiny, tmp_path):
        """Prefill→KV handoff→decode with BOTH halves cache-armed."""
        from tony_tpu.ckpt import AOTCache
        from tony_tpu.serve import EngineFront
        from tony_tpu.serve.disagg import DecodeFront, PrefillFront

        def handoff(aot_root):
            cache_kw = {}
            if aot_root:
                cache_kw = {"aot_cache": AOTCache(aot_root)}
            pf_eng = make_engine(tiny, role="prefill", **cache_kw)
            dc_eng = make_engine(tiny, role="decode", **cache_kw)
            pf = PrefillFront(EngineFront(pf_eng))
            dc = DecodeFront(EngineFront(dc_eng))
            done = {i: pf.prefill_handoff(list(p), 4, rid=i, decode=dc)
                    for i, p in enumerate(PROMPTS)}
            return done, pf_eng, dc_eng

        ref, _, _ = handoff(None)
        root = str(tmp_path / "aot")
        got1, _, _ = handoff(root)
        assert_bitwise_equal(got1, ref)
        got2, pf2, dc2 = handoff(root)
        assert_bitwise_equal(got2, ref)
        assert pf2.aot_hits + dc2.aot_hits > 0
        assert pf2.aot_misses == 0 and dc2.aot_misses == 0

    def test_train_step_cache_bitwise(self, tmp_path):
        """make_accum_train_step(aot_cache=): a second build of the
        same (topology, config, loss) family deserializes instead of
        compiling, and the stepped state is bit-identical."""
        import optax

        from tony_tpu import parallel as par
        from tony_tpu import train
        from tony_tpu.ckpt import AOTCache
        from tony_tpu.models import get_model

        mesh = par.make_mesh()
        model = get_model("mnist-mlp", hidden=32)
        kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
        x = jax.random.normal(kx, (32, 784))
        y = jax.random.randint(ky, (32,), 0, 10)
        state = train.create_train_state(model, optax.sgd(0.1), x, kr)
        batch = {"x": x, "y": y}
        plain = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                            donate=False)
        s0, m0 = plain(state, batch)
        root = str(tmp_path / "aot")
        c1 = AOTCache(root)
        first = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                            donate=False, aot_cache=c1)
        s1, m1 = first(state, batch)
        assert c1.misses == 1 and c1.puts == 1
        c2 = AOTCache(root)
        second = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                             donate=False, aot_cache=c2)
        s2, m2 = second(state, batch)
        assert c2.hits == 1 and c2.misses == 0
        assert float(m0["loss"]) == float(m1["loss"]) == float(m2["loss"])
        for a, b, c in zip(jax.tree.leaves(s0.params),
                           jax.tree.leaves(s1.params),
                           jax.tree.leaves(s2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        # inspect still hands the analysis plane the RAW jit, not the
        # deserialized executable — the audit surface cannot drift.
        assert second.inspect(state)["jitted"] is not None

    @pytest.mark.slow
    def test_train_step_optstate_reshard_recompiles(self, tmp_path):
        """Step 1's output re-shards the OPTIMIZER state (replicated
        adamw init -> the step's out_shardings) while the params keep
        their layout — the executable memo must key on every state
        leaf's sharding, or step 2 calls a stale Compiled and jax
        hard-fails on the input-sharding mismatch (raw jit would have
        silently re-traced)."""
        import optax

        from tony_tpu import parallel as par
        from tony_tpu import train
        from tony_tpu.ckpt import AOTCache
        from tony_tpu.models import get_model

        mesh = par.make_mesh(fsdp=4)
        model = get_model("llama-tiny", n_layers=2)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, 256, (16, 16)), jnp.int32)
        state = train.create_train_state(
            model, optax.adamw(1e-3), tokens, jax.random.PRNGKey(0),
            mesh=mesh)
        cache = AOTCache(str(tmp_path / "aot"))
        step = train.make_accum_train_step(
            loss_of=lambda logits, b: train.next_token_loss(
                logits, b["x"]),
            mesh=mesh, microbatches=2, donate=False, aot_cache=cache)
        state, m1 = step(state, {"x": tokens})
        state, m2 = step(state, {"x": tokens})      # re-sharded input
        assert np.isfinite(float(m2["loss"]))
        # Two distinct layouts -> two cache entries, both compiled.
        assert cache.misses == 2 and cache.puts == 2
        # Steady state: the third step hits the step-2 memo entry.
        state, _ = step(state, {"x": tokens})
        assert cache.misses == 2


# ---------------------------------------------------------------------------
# Warm-standby pool policy + schema
# ---------------------------------------------------------------------------

class TestWarmPoolPolicy:

    def test_decide_warm_matrix(self):
        from tony_tpu.serve import scaling

        p = scaling.ScalingPolicy(min_replicas=1, max_replicas=6,
                                  queue_high=4.0, queue_low=1.0,
                                  p99_high_ms=0.0, cooldown_s=0.0)
        cases = [
            # (target, active, warm) -> delta
            ((2, 1, 0), 2),     # empty pool: grant 2
            ((2, 1, 2), 0),     # at target: hold
            ((2, 1, 3), -1),    # over target: retire 1
            ((2, 5, 0), 1),     # ceiling caps: 6-5 leaves room for 1
            ((2, 6, 0), 0),     # full fleet: no standbys
            ((2, 6, 1), -1),    # full fleet drains the pool
            ((0, 3, 2), -2),    # pool off: drain everything
            ((4, 1, 1), 3),
        ]
        for (target, active, warm), want in cases:
            assert scaling.decide_warm(p, target, active, warm) == want, \
                (target, active, warm)

    def test_decide_matrix_pinned_under_new_fields(self):
        """The PR 15 ScalingPolicy decision matrix must not move when
        samples carry the +4 cold-start fields."""
        from tony_tpu.serve import scaling

        p = scaling.ScalingPolicy(min_replicas=1, max_replicas=4,
                                  queue_high=4.0, queue_low=1.0,
                                  p99_high_ms=100.0, cooldown_s=30.0)
        extra = {"aot_hits": 7.0, "aot_misses": 1.0,
                 "compile_ms": 1234.0, "warm_standby": 0.0,
                 "daemon_demotions": 2.0}
        cases = [
            (1, [{"queue_depth": 9.0, "p99_ms": 10.0}], None, 1),
            (2, [{"queue_depth": 0.2, "p99_ms": 10.0}] * 2, None, -1),
            (2, [{"queue_depth": 2.0, "p99_ms": 10.0}] * 2, None, 0),
            (0, [], None, 1),                       # floor repair
            (2, [{"queue_depth": 9.0, "p99_ms": 10.0}] * 2, 100.0, 0),
        ]
        now = 110.0
        for n, samples, last, want in cases:
            bare = scaling.decide(p, n, samples, now=now,
                                  last_action=last)
            widened = scaling.decide(p, n,
                                     [dict(s, **extra) for s in samples],
                                     now=now, last_action=last)
            assert bare == widened == want, (n, samples)

    def test_stats_schema_plus_four(self, tiny, tmp_path):
        """Engine stats carry the new keys (floats, zeros unarmed) and
        write_stats round-trips them through the executor reader."""
        from tony_tpu.executor import read_serve_stats

        eng = make_engine(tiny, warm_standby=True)
        s = eng.stats()
        for k in ("aot_hits", "aot_misses", "compile_ms",
                  "warm_standby", "daemon_demotions"):
            assert isinstance(s[k], float), k
        assert s["warm_standby"] == 1.0
        path = tmp_path / "stats.json"
        eng.write_stats(str(path), extra={"rpc_port": 4321})
        read = read_serve_stats(path)
        assert read["warm_standby"] == 1.0
        assert read["aot_hits"] == 0.0 and read["compile_ms"] == 0.0

    def test_heartbeat_round_trip_and_endpoint_exclusion(self, tmp_path):
        """Stats file → heartbeat RPC → session: the +4 fields land in
        serve_samples, and a live standby is NOT a routable endpoint
        until its heartbeat flips warm_standby off."""
        from tony_tpu import constants
        from tony_tpu.conf import TonyConfig
        from tony_tpu.executor import TaskExecutor
        from tony_tpu.rpc import ApplicationRpcHandler, RpcServer
        from tony_tpu.session import TonySession

        conf = TonyConfig({"tony.serve.instances": "1",
                           "tony.serve.command": "x"})
        session = TonySession(conf, app_id="app_aot_hb")
        session.on_registered("serve", 0, "127.0.0.1", 4000)
        server = RpcServer(ApplicationRpcHandler(session),
                           host="127.0.0.1").start()
        conf_path = tmp_path / "conf.json"
        conf_path.write_text(json.dumps(dict(conf.items())))
        payload = {"qps": 1.0, "p99_ms": 9.0, "queue_depth": 0.0,
                   "aot_hits": 5.0, "aot_misses": 1.0,
                   "compile_ms": 321.5, "warm_standby": 1.0,
                   "daemon_demotions": 0.0, "rpc_port": 5555}
        try:
            executor = TaskExecutor(env={
                constants.ENV_JOB_NAME: "serve",
                constants.ENV_TASK_INDEX: "0",
                constants.ENV_AM_ADDRESS: server.address,
                constants.ENV_CONF_PATH: str(conf_path),
                constants.ENV_LOG_DIR: str(tmp_path),
            })
            executor.serve_stats_path().write_text(json.dumps(payload))
            t = threading.Thread(target=executor._heartbeat_loop,
                                 args=(0.05,), daemon=True)
            t.start()
            task = session.task("serve", 0)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not task.serve_metrics:
                time.sleep(0.05)
            executor._hb_stop.set()
            t.join(timeout=5)
            got = task.serve_metrics
            assert got["aot_hits"] == 5.0 and got["aot_misses"] == 1.0
            assert got["compile_ms"] == 321.5
            assert got["warm_standby"] == 1.0
            # The sample reaches the autoscaler...
            assert session.serve_samples("serve")[0]["warm_standby"] \
                == 1.0
            # ...but a live standby is NOT routable.
            assert session.serve_endpoints("serve") == []
            # Promotion: the next heartbeat says warm_standby=0 and the
            # endpoint appears.
            session.on_heartbeat("serve", 0,
                                 serve=dict(payload, warm_standby=0.0))
            eps = session.serve_endpoints("serve")
            assert len(eps) == 1 and eps[0]["host"] == "127.0.0.1"
        finally:
            server.stop()

    def test_engine_promote_is_idempotent(self, tiny):
        eng = make_engine(tiny, warm_standby=True)
        assert eng.stats()["warm_standby"] == 1.0
        assert eng.promote() is True
        assert eng.promote() is False
        assert eng.stats()["warm_standby"] == 0.0


# ---------------------------------------------------------------------------
# The AM's warm-pool mechanics (fake scheduler, real session + RPC)
# ---------------------------------------------------------------------------

class _FakeContainer:
    def __init__(self, cid):
        self.container_id = cid
        self.is_running = True


class _FakeScheduler:
    def __init__(self):
        self.launched = []

    def launch(self, req):
        self.launched.append(req)
        return _FakeContainer(f"c{len(self.launched)}")

    def stop_container(self, c):
        c.is_running = False

    def poll_completed(self):
        return []

    def stop(self):
        pass


def _make_am(conf_pairs, tmp_path, app_id):
    from types import SimpleNamespace

    from tony_tpu.am import ApplicationMaster
    from tony_tpu.conf import TonyConfig
    from tony_tpu.session import TonySession

    conf = TonyConfig(conf_pairs)
    sched = _FakeScheduler()
    am = ApplicationMaster(conf, app_id, tmp_path, scheduler=sched)
    session = TonySession(conf, app_id)
    am.session = session
    am.handler = SimpleNamespace(_all_registered_fired=True)
    am.server = SimpleNamespace(port=1)
    return am, session, sched


class TestWarmPoolAM:

    def test_backfill_launches_standbys(self, tmp_path):
        """Pool below target: the AM grants elastic standbys without
        touching the active set (decide said hold)."""
        am, session, sched = _make_am(
            {"tony.serve.instances": "1", "tony.serve.command": "x",
             "tony.serve.replicas.max": "4",
             "tony.serve.warm-standby": "2"}, tmp_path, "app_warm_bf")
        session.on_registered("serve", 0, "h", 1)
        session.on_heartbeat("serve", 0, serve={
            "qps": 1.0, "p99_ms": 5.0, "queue_depth": 2.0})
        am._autoscale_serve(session)
        assert len(sched.launched) == 2
        assert session.task("serve", 1).elastic
        assert session.task("serve", 2).elastic
        # At target: the next tick holds.
        session.on_heartbeat("serve", 1, serve={"warm_standby": 1.0})
        session.on_heartbeat("serve", 2, serve={"warm_standby": 1.0})
        am._autoscale_serve(session)
        assert len(sched.launched) == 2

    def test_scale_up_promotes_standby_over_rpc(self, tmp_path):
        """Hot queue + a pooled standby: the AM's scale-up flips the
        standby active over its promote RPC instead of a cold grant —
        and the session's endpoint view flips with it this tick."""
        from tony_tpu.rpc import RpcServer

        class _PromoteHandler:
            def __init__(self):
                self.calls = 0

            def rpc_promote(self):
                self.calls += 1
                return True

        handler = _PromoteHandler()
        server = RpcServer(handler, host="127.0.0.1").start()
        try:
            am, session, sched = _make_am(
                {"tony.serve.instances": "1", "tony.serve.command": "x",
                 "tony.serve.replicas.max": "4",
                 "tony.serve.scale.cooldown-s": "0"},
                tmp_path, "app_warm_promo")
            session.on_registered("serve", 0, "127.0.0.1", 1)
            session.on_heartbeat("serve", 0, serve={
                "qps": 1.0, "p99_ms": 5.0, "queue_depth": 50.0})
            standby = session.add_task("serve")
            session.on_registered("serve", standby.index,
                                  "127.0.0.1", 2)
            session.on_heartbeat("serve", standby.index, serve={
                "warm_standby": 1.0, "rpc_port": float(server.port)})
            # Before promotion only the active replica is routable.
            assert len(session.serve_endpoints("serve")) == 1
            am._autoscale_serve(session)
            assert handler.calls == 1
            assert sched.launched == []        # promotion, not a grant
            assert standby.serve_metrics["warm_standby"] == 0.0
            assert len(session.serve_endpoints("serve")) == 2
        finally:
            server.stop()

    def test_promote_rpc_failure_falls_back_to_cold_grant(self,
                                                          tmp_path):
        am, session, sched = _make_am(
            {"tony.serve.instances": "1", "tony.serve.command": "x",
             "tony.serve.replicas.max": "4",
             "tony.serve.scale.cooldown-s": "0"},
            tmp_path, "app_warm_fb")
        session.on_registered("serve", 0, "127.0.0.1", 1)
        session.on_heartbeat("serve", 0, serve={
            "qps": 1.0, "p99_ms": 5.0, "queue_depth": 50.0})
        standby = session.add_task("serve")
        session.on_registered("serve", standby.index, "127.0.0.1", 2)
        # A dead promote port: dial fails, the AM cold-grants instead.
        session.on_heartbeat("serve", standby.index, serve={
            "warm_standby": 1.0, "rpc_port": 1.0})
        am._autoscale_serve(session)
        assert len(sched.launched) == 1
        assert standby.serve_metrics["warm_standby"] == 1.0

    def test_full_fleet_drains_pool(self, tmp_path):
        """Active set at the ceiling: decide_warm retires standbys —
        every budget slot serves traffic."""
        am, session, sched = _make_am(
            {"tony.serve.instances": "2", "tony.serve.command": "x",
             "tony.serve.replicas.max": "2",
             "tony.serve.warm-standby": "1"}, tmp_path, "app_warm_dr")
        session.on_registered("serve", 0, "h", 1)
        session.on_registered("serve", 1, "h", 2)
        for i in (0, 1):
            session.on_heartbeat("serve", i, serve={
                "qps": 1.0, "p99_ms": 5.0, "queue_depth": 2.0})
        standby = session.add_task("serve")
        session.on_registered("serve", standby.index, "h", 3)
        session.on_heartbeat("serve", standby.index,
                             serve={"warm_standby": 1.0})
        am._autoscale_serve(session)
        assert standby.status.is_terminal
        assert sched.launched == []


# ---------------------------------------------------------------------------
# Demotion daemon
# ---------------------------------------------------------------------------

class TestDemotionDaemon:

    def test_off_by_default(self, tiny):
        eng = make_engine(tiny, host_blocks=8, prefix_cache=True)
        run_requests(eng, PROMPTS)
        assert eng.demote_watermark == 0.0
        assert eng.daemon_demotions == 0
        assert eng.stats()["daemon_demotions"] == 0.0

    def test_watermark_demotes_published_stems(self, tiny):
        """Armed daemon: once pool occupancy crosses the watermark the
        step loop pre-drains refcount-0 (published) blocks into the
        host tier — counted, bitwise-invisible to the streams. The
        schedule staggers completions: r0 finishes early, publishing a
        refcount-0 stem that the daemon demotes while r1 keeps
        stepping."""
        from tony_tpu.serve import Request

        def staggered(eng):
            eng.submit(Request(rid="r0", tokens=[3, 5, 7, 11, 13, 17,
                                                 19, 23, 29],
                               max_new_tokens=2))
            eng.submit(Request(rid="r1", tokens=[2, 4, 6],
                               max_new_tokens=16))
            return {c.rid: c for c in eng.run()}

        ref = staggered(make_engine(tiny, prefix_cache=True))
        eng = make_engine(tiny, prefix_cache=True, host_blocks=16,
                          demote_watermark=0.05, demote_batch=2)
        got = staggered(eng)
        assert_bitwise_equal(got, ref)
        assert eng.daemon_demotions > 0
        assert eng.stats()["daemon_demotions"] \
            == float(eng.daemon_demotions)

    def test_watermark_validation(self, tiny):
        with pytest.raises(ValueError, match="demote_watermark"):
            make_engine(tiny, demote_watermark=1.5)
