"""Client + CLI + history + proxy tests (reference tiers: ``TonyClient`` unit
+ e2e paths of ``TestTonyE2E``, the tony-cli surface, and the history-server
parser/controller tests — SURVEY.md §4)."""

import io
import json
import urllib.request
from pathlib import Path

import pytest

from tony_tpu import constants
from tony_tpu.cli import main as cli_main
from tony_tpu.client import TonyClient
from tony_tpu.conf import TonyConfig
from tony_tpu.history import (HistoryServer, find_job, gather_jobs,
                              job_detail, render_list, render_show)
from tony_tpu.proxy import ProxyServer

WORKLOADS = Path(__file__).parent / "workloads"


def base_props(**over):
    props = {
        "tony.application.framework": "standalone",
        "tony.application.executes": "python exit_0.py",
        "tony.worker.instances": "1",
        "tony.task.heartbeat-interval-ms": "200",
    }
    props.update({k: str(v) for k, v in over.items()})
    return props


def run_client(tmp_path, stream=None, **over) -> TonyClient:
    client = TonyClient(TonyConfig(base_props(**over)), src_dir=WORKLOADS,
                        workdir=tmp_path / "jobs", stream=stream or io.StringIO())
    client.exit_code = client.run(timeout=90)
    return client


def test_client_submit_monitor_success(tmp_path):
    out = io.StringIO()
    client = run_client(tmp_path, stream=out)
    assert client.exit_code == 0
    assert client.final_status == "SUCCEEDED"
    text = out.getvalue()
    # The reference's monitor loop prints task transitions.
    assert "task worker:0 -> RUNNING" in text
    assert "task worker:0 -> SUCCEEDED" in text
    assert "finished: SUCCEEDED" in text


def test_client_failure_exit_code_contract(tmp_path):
    client = run_client(tmp_path, **{
        "tony.application.executes": "python exit_1.py"})
    assert client.exit_code == 1
    assert client.final_status == "FAILED"


def test_client_listener_sees_task_infos(tmp_path):
    seen = []
    client = TonyClient(TonyConfig(base_props()), src_dir=WORKLOADS,
                        workdir=tmp_path / "jobs", stream=io.StringIO())
    client.add_listener(lambda infos: seen.append(
        {i["job_type"] + ":" + str(i["index"]): i["status"] for i in infos}))
    assert client.run(timeout=90) == 0
    assert seen, "listener never invoked"
    assert any("worker:0" in snap for snap in seen)


def test_cli_submit_end_to_end(tmp_path, capsys):
    rc = cli_main([
        "submit", "--src_dir", str(WORKLOADS),
        "--executes", "python exit_0.py",
        "--framework", "standalone",
        "--workdir", str(tmp_path / "jobs"),
        "--conf", "tony.worker.instances=1",
        "--conf", "tony.task.heartbeat-interval-ms=200",
    ])
    assert rc == 0


def test_cli_conf_file_xml_layering(tmp_path):
    xml = tmp_path / "tony.xml"
    xml.write_text("""<configuration>
      <property><name>tony.worker.instances</name><value>1</value></property>
      <property><name>tony.application.framework</name><value>standalone</value></property>
      <property><name>tony.application.executes</name><value>python exit_1.py</value></property>
    </configuration>""")
    # --conf override beats the conf_file value (layering contract).
    rc = cli_main([
        "submit", "--src_dir", str(WORKLOADS), "--conf_file", str(xml),
        "--workdir", str(tmp_path / "jobs"),
        "--conf", "tony.application.executes=python exit_0.py",
        "--conf", "tony.task.heartbeat-interval-ms=200",
    ])
    assert rc == 0


def test_cli_version(capsys):
    assert cli_main(["version"]) == 0
    assert "tony-tpu" in capsys.readouterr().out


def test_cli_rejects_bad_conf_pair():
    with pytest.raises(SystemExit):
        cli_main(["submit", "--conf", "not-a-pair"])


def test_venv_shipped_and_on_path(tmp_path):
    """--python_venv stages the venv, executors localize it per container
    and put its bin/ on PATH with VIRTUAL_ENV set."""
    venv = tmp_path / "myvenv"
    (venv / "bin").mkdir(parents=True)
    marker = venv / "bin" / "tony-venv-marker"
    marker.write_text("#!/bin/sh\n")
    marker.chmod(0o755)
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.executes": "python check_venv.py",
            "tony.application.python-venv": str(venv)})),
        src_dir=WORKLOADS, workdir=tmp_path / "jobs", stream=io.StringIO())
    assert client.run(timeout=90) == 0
    [check] = Path(client.job_dir).glob("containers/*/src/venv_check.json")
    data = json.loads(check.read_text())
    assert data["virtual_env"].endswith("venv")
    assert "containers" in data["tool"]  # the per-container localized copy


def test_containers_resources_localized(tmp_path):
    """tony.containers.resources (VERDICT r4 missing #2): a plain file, a
    directory, and a #archive entry declared in the conf must be staged by
    the client and localized into every container's cwd (archive
    unpacked) — the reference's LocalizableResource passthrough."""
    import tarfile

    res = tmp_path / "inputs"
    res.mkdir()
    (res / "data.txt").write_text("tokenizer-bytes\n")
    extra = res / "extra"
    extra.mkdir()
    (extra / "nested.txt").write_text("nested-value\n")
    payload = tmp_path / "inside_archive.txt"
    payload.write_text("unpacked-ok\n")
    with tarfile.open(res / "bundle.tar.gz", "w:gz") as tf:
        tf.add(payload, arcname="inside_archive.txt")

    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.executes": "python check_resources.py",
            "tony.worker.instances": "2",
            "tony.containers.resources":
                f"{res/'data.txt'},{res/'extra'},{res/'bundle.tar.gz'}#archive",
        })),
        src_dir=WORKLOADS, workdir=tmp_path / "jobs", stream=io.StringIO())
    assert client.run(timeout=90) == 0
    checks = sorted(Path(client.job_dir).glob(
        "containers/*/src/resources_check.json"))
    assert len(checks) == 2          # EVERY container localized its copy
    for check in checks:
        data = json.loads(check.read_text())
        assert data == {"data": "tokenizer-bytes",
                        "dir_member": "nested-value",
                        "archive_member": "unpacked-ok"}
    # The client staged the entries next to src/venv.
    staged = Path(client.job_dir) / "resources"
    assert (staged / "data.txt").is_file()
    assert (staged / "bundle.tar.gz").is_file()


def test_containers_resources_missing_entry_fails_at_submit(tmp_path):
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.containers.resources": str(tmp_path / "nope.txt")})),
        src_dir=WORKLOADS, workdir=tmp_path / "jobs", stream=io.StringIO())
    with pytest.raises(FileNotFoundError, match="nope.txt"):
        client.stage()


def test_am_sigterm_graceful_teardown(tmp_path):
    """SIGTERM to the AM process (client kill fallback) must drain through
    normal teardown: containers reaped, final-status.json written KILLED."""
    import time
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.executes": "python forever.py"})),
        src_dir=WORKLOADS, workdir=tmp_path / "jobs", stream=io.StringIO())
    client.submit()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            addr = client._am_address()
            if addr is not None:
                from tony_tpu.rpc import RpcClient
                try:
                    with RpcClient(addr, timeout=2.0) as c:
                        infos = c.call("get_task_infos")
                    if any(i["status"] == "RUNNING" for i in infos):
                        break
                except Exception:
                    pass
            time.sleep(0.1)
        client.am_proc.terminate()          # SIGTERM, not SIGKILL
        rc = client.monitor(timeout=60)
        assert rc == 1
        assert client.final_status == "KILLED"
        assert "SIGTERM" in client.final_message
        # No orphaned executor/user processes: every container workdir's
        # processes died with the job (scheduler.stop ran in AM teardown).
        final = json.loads((client.job_dir / "final-status.json").read_text())
        assert final["status"] == "KILLED"
    finally:
        if client.am_proc.poll() is None:
            client.am_proc.kill()


def test_cli_kill_and_logs(tmp_path, capsys):
    """`tony kill` (yarn application -kill analogue) reaches a detached
    job's AM via finish_application; `tony logs` prints container logs."""
    import time

    workdir = tmp_path / "jobs"
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.executes": "python forever.py"})),
        src_dir=WORKLOADS, workdir=workdir, stream=io.StringIO())
    client.submit()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and not (client.job_dir / "am.address").is_file():
            time.sleep(0.1)
        assert (client.job_dir / "am.address").is_file()
        assert cli_main(["kill", client.app_id,
                         "--workdir", str(workdir),
                         "--reason", "cli-test"]) == 0
        assert client.monitor(timeout=60) == 1
        assert client.final_status == "KILLED"
        assert "tony kill" in client.final_message
    finally:
        if client.am_proc and client.am_proc.poll() is None:
            client.am_proc.kill()

    done = run_client(tmp_path, **{
        "tony.application.executes": "python -c 'print(\"log-marker\")'"})
    assert done.exit_code == 0
    assert cli_main(["logs", done.app_id, "--workdir",
                     str(tmp_path / "jobs"), "--tail", "5"]) == 0
    out = capsys.readouterr().out
    assert "log-marker" in out and "stdout.log" in out
    # Unknown app id fails loudly.
    assert cli_main(["logs", "app_nope", "--workdir",
                     str(tmp_path / "jobs")]) == 1
    assert cli_main(["kill", "app_nope", "--workdir",
                     str(tmp_path / "jobs")]) == 1


@pytest.mark.slow
def test_cli_profile_captures_trace(tmp_path, monkeypatch):
    """`tony profile` against a detached RUNNING job: endpoint fetched over
    the new get_task_callback_info verb, synchronized capture into the
    history dir. Relative --workdir on purpose — the logdir travels inside
    the profiler RPC and the server writes the xplane from a different
    cwd (the round-4 live bug)."""
    import time

    monkeypatch.chdir(tmp_path)
    src = Path("src")
    src.mkdir()
    # Stretch the busy window in THIS test's copy: the client-side poll
    # (endpoint registration + port-bind probe) can eat most of the
    # stock 25 s on a cold jax import, leaving the capture to race the
    # workload's exit — the flake this test was known for. The job is
    # killed in the finally either way, so the longer window never
    # lengthens a passing run.
    workload = (WORKLOADS / "profiled_train.py").read_text()
    stretched = workload.replace("deadline = time.time() + 25.0",
                                 "deadline = time.time() + 120.0")
    assert stretched != workload, \
        "busy-window anchor line changed in profiled_train.py — " \
        "re-anchor the stretch or the capture races the workload again"
    (src / "profiled_train.py").write_text(stretched)
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.framework": "jax",
            "tony.application.executes": "python profiled_train.py",
            "tony.task.profiler.enabled": "true",
            "tony.task.max-missed-heartbeats": "200"})),
        src_dir=src, workdir=Path("jobs"), stream=io.StringIO())
    client.submit()
    try:
        from tony_tpu.profiler import (_wait_reachable,
                                       endpoints_from_callback_info)
        from tony_tpu.rpc import RpcClient
        deadline = time.monotonic() + 60
        endpoints = {}
        while time.monotonic() < deadline and not endpoints:
            addr_file = client.job_dir / "am.address"
            if addr_file.is_file():
                try:
                    with RpcClient(addr_file.read_text().strip(),
                                   timeout=5) as c:
                        endpoints = endpoints_from_callback_info(
                            c.call("get_task_callback_info"))
                except Exception:
                    pass
            time.sleep(0.25)
        assert endpoints, "profiler endpoint never registered"
        # The endpoint is REGISTERED at user-process launch; the
        # jax.profiler server inside it only binds after the jax import
        # — and on some hosts/images it never binds at all (known
        # failing at HEAD: unreachable within collect_traces' 60 s).
        # Poll with bounded backoff and SKIP with the reason when the
        # port never opens: that is this environment's jax, not a
        # regression in the capture path this test pins.
        addr = next(iter(endpoints.values()))
        reachable, window = False, 2.0
        probe_deadline = time.monotonic() + 60
        while not reachable and time.monotonic() < probe_deadline:
            reachable = _wait_reachable(addr, window)
            window = min(8.0, window * 2)
        if not reachable:
            pytest.skip(
                f"jax profiler port {addr} never bound in this "
                f"environment (registered but unreachable for 60s); "
                f"cannot exercise trace capture here")
        assert cli_main(["profile", client.app_id, "--workdir", "jobs",
                         "--duration_ms", "1000"]) == 0
        traces = list((client.job_dir / "history" / "traces").rglob("*.pb"))
        assert traces and traces[0].stat().st_size > 0
    finally:
        cli_main(["kill", client.app_id, "--workdir", "jobs"])
        client.monitor(timeout=60)
        if client.am_proc and client.am_proc.poll() is None:
            client.am_proc.kill()


# -- history ---------------------------------------------------------------

def test_history_list_show_and_portal(tmp_path):
    client = run_client(tmp_path)
    history_dir = client.job_dir / "history"
    jobs = gather_jobs(history_dir)
    assert len(jobs) == 1
    assert jobs[0]["app_id"] == client.app_id
    assert jobs[0]["state"] == "finished"
    listing = render_list(jobs)
    assert client.app_id in listing

    job = find_job(client.app_id, history_dir)
    detail = job_detail(job)
    assert detail["final"]["status"] == "SUCCEEDED"
    assert any(t["job_type"] == "worker" for t in detail["tasks"])
    shown = render_show(detail)
    assert "SUCCEEDED" in shown and "worker:0" in shown

    server = HistoryServer(history_dir, host="127.0.0.1", port=0)
    import threading
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        index = urllib.request.urlopen(f"{base}/", timeout=10).read().decode()
        assert client.app_id in index
        page = urllib.request.urlopen(
            f"{base}/jobs/{client.app_id}", timeout=10).read().decode()
        assert "SUCCEEDED" in page and "worker:0" in page
        api = json.loads(urllib.request.urlopen(
            f"{base}/api/jobs", timeout=10).read())
        assert api[0]["app_id"] == client.app_id
        assert urllib.request.urlopen(
            f"{base}/jobs/nope", timeout=10).status  # pragma: no cover
    except urllib.error.HTTPError as e:
        assert e.code == 404  # the /jobs/nope probe
    finally:
        server.shutdown()


def test_stage_skips_nested_workdir(tmp_path):
    """`tony submit --src_dir . --workdir ./jobs` puts the workdir INSIDE
    src_dir; staging must prune it or copytree recurses into the copy
    being made until ENAMETOOLONG (found live in round 4)."""
    src = tmp_path / "proj"
    src.mkdir()
    (src / "train.py").write_text("print('hi')\n")
    client = TonyClient(TonyConfig(base_props()), src_dir=src,
                        workdir=src / "jobs", stream=io.StringIO())
    client.stage()
    staged = client.job_dir / "src"
    assert (staged / "train.py").is_file()
    assert not (staged / "jobs").exists()   # the workdir was pruned

    # Degenerate form: --workdir == --src_dir (job dir is a direct child).
    client2 = TonyClient(TonyConfig(base_props()), src_dir=src,
                         workdir=src, stream=io.StringIO())
    client2.stage()
    staged2 = client2.job_dir / "src"
    assert (staged2 / "train.py").is_file()
    assert not (staged2 / client2.app_id).exists()  # job dir pruned


def test_relative_workdir_venv_reaches_containers(tmp_path, monkeypatch):
    """A RELATIVE --workdir must not produce relative staged paths: the
    venv path resolved fine in the AM's cwd but localized nothing in the
    containers (found live in round 4). Also pins hardlink localization."""
    monkeypatch.chdir(tmp_path)
    src = Path("proj")
    src.mkdir()
    for name in ("check_venv.py",):
        (src / name).write_text((WORKLOADS / name).read_text())
    venv = Path("myvenv")
    (venv / "bin").mkdir(parents=True)
    marker = venv / "bin" / "tony-venv-marker"
    marker.write_text("#!/bin/sh")
    marker.chmod(0o755)
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.executes": "python check_venv.py",
            "tony.application.python-venv": "myvenv",
            "tony.worker.instances": "2"})),
        src_dir=src, workdir=Path("jobs"), stream=io.StringIO())
    assert client.run(timeout=90) == 0
    localized = sorted(client.job_dir.glob(
        "containers/*/venv/bin/tony-venv-marker"))
    assert len(localized) == 2
    staged_ino = (client.job_dir / "venv" / "bin"
                  / "tony-venv-marker").stat().st_ino
    assert all(p.stat().st_ino == staged_ino for p in localized)


def test_history_read_path_is_cached(tmp_path, monkeypatch):
    """VERDICT r3 #7: a second request over an unchanged history dir must do
    zero re-parsing (mtime/size-keyed cache), and long TASK_METRICS
    timelines render downsampled."""
    from tony_tpu import events as ev
    from tony_tpu.history import MAX_TIMELINE_SAMPLES

    h = ev.EventHandler(tmp_path, "app_cache_0001", app_name="cached")
    h.task_started("worker", 0, "127.0.0.1")
    for i in range(3 * MAX_TIMELINE_SAMPLES):
        h.task_metrics("worker", 0, {"cpu_pct": float(i)})
    h.task_finished("worker", 0, "SUCCEEDED", 0)
    h.application_finished("SUCCEEDED")
    h.close()

    calls = {"n": 0}
    real_parse = ev._parse_file

    def counting_parse(path):
        calls["n"] += 1
        return real_parse(path)

    monkeypatch.setattr(ev, "_parse_file", counting_parse)

    job = find_job("app_cache_0001", tmp_path)
    detail = job_detail(job)
    parses_cold = calls["n"]
    assert parses_cold >= 1

    # Unchanged dir → both the list scan and the detail page are served
    # entirely from cache.
    job2 = find_job("app_cache_0001", tmp_path)
    detail2 = job_detail(job2)
    assert calls["n"] == parses_cold
    assert detail2["final"] == detail["final"]

    # Timeline is downsampled to the cap, newest sample kept.
    tl = detail["metrics_timelines"]["worker:0"]
    assert len(tl) == MAX_TIMELINE_SAMPLES
    assert tl[-1]["cpu_pct"] == float(3 * MAX_TIMELINE_SAMPLES - 1)

    # A changed file (append) invalidates the cache entry.
    finished = Path(job["path"])
    with open(finished, "a", encoding="utf-8") as f:
        f.write(json.dumps({"type": "TASK_METRICS", "timestamp": 0.0,
                            "payload": {"job_type": "worker", "index": 0,
                                        "metrics": {"cpu_pct": -1.0}}}) + "\n")
    job_detail(find_job("app_cache_0001", tmp_path))
    assert calls["n"] == parses_cold + 1


# -- proxy -----------------------------------------------------------------

def test_proxy_roundtrip():
    import socket
    import threading

    # Upstream echo server.
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    upstream_port = srv.getsockname()[1]

    def echo_once():
        conn, _ = srv.accept()
        data = conn.recv(1024)
        conn.sendall(b"echo:" + data)
        conn.close()

    threading.Thread(target=echo_once, daemon=True).start()
    with ProxyServer("127.0.0.1", upstream_port) as proxy:
        c = socket.create_connection(("127.0.0.1", proxy.local_port), timeout=5)
        c.sendall(b"hello")
        assert c.recv(1024) == b"echo:hello"
        c.close()
    srv.close()


def test_client_reports_submit_to_running_latency(tmp_path):
    """BASELINE.md secondary metric: the client prints submit→all-RUNNING
    and keeps the number (shipped to the AM via TONY_SUBMIT_TS)."""
    out = io.StringIO()
    client = run_client(tmp_path, stream=out, **{
        "tony.application.executes": "python sleep_exit_0.py"})
    assert client.exit_code == 0
    assert client.all_running_latency_s is not None
    assert 0 < client.all_running_latency_s < 60
    assert "all tasks running" in out.getvalue()


@pytest.mark.slow
def test_client_relaunches_crashed_am(tmp_path):
    """AM-attempt restart end-to-end (reference: the RM relaunches the AM
    container up to yarn's am max-attempts): SIGKILL the live AM process;
    the client relaunches it, the orphaned attempt-1 executors
    self-terminate on heartbeat loss, and attempt 2's tasks come back
    RUNNING under the new AM."""
    import os
    import signal
    import threading
    import time

    from tony_tpu.rpc import RpcClient

    client = TonyClient(TonyConfig(base_props(**{
        "tony.application.executes": "python forever.py",
        "tony.am.max-attempts": "2",
        "tony.task.max-missed-heartbeats": "3",
    })), src_dir=WORKLOADS, workdir=tmp_path / "jobs", stream=io.StringIO())
    client.submit()
    mon = threading.Thread(
        target=lambda: setattr(client, "exit_code", client.monitor()),
        daemon=True)
    mon.start()

    def running_tasks():
        addr = client._am_address()
        if addr is None:
            return []
        try:
            with RpcClient(addr, token=client._token(), timeout=2.0) as c:
                infos = c.call("get_task_infos")
        except Exception:
            return []
        return [i for i in infos if i["status"] == "RUNNING"]

    def wait_for(pred, timeout, what):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = pred()
            if v:
                return v
            time.sleep(0.05)
        raise TimeoutError(what)

    def executor_pids():
        out = []
        for pid_dir in Path("/proc").glob("[0-9]*"):
            try:
                cwd = os.readlink(pid_dir / "cwd")
            except OSError:
                continue
            if str(client.job_dir / "containers") in cwd:
                out.append(int(pid_dir.name))
        return out

    wait_for(running_tasks, 60, "attempt-1 task never RUNNING")
    attempt1_pids = set(executor_pids())  # executor + its user child
    assert attempt1_pids
    pid1 = client.am_proc.pid
    os.killpg(pid1, signal.SIGKILL)  # AM + nothing else (executors setsid)
    wait_for(lambda: client.am_proc.pid != pid1, 30, "AM never relaunched")
    assert client._am_launches == 2
    wait_for(running_tasks, 90, "attempt-2 task never RUNNING")
    # Attempt-1's executor notices the dead AM and self-terminates (user
    # child included); attempt-2's processes are the only survivors.
    wait_for(lambda: not (attempt1_pids & set(executor_pids())), 30,
             f"orphaned attempt-1 processes remain: "
             f"{attempt1_pids & set(executor_pids())}")
    client.kill("test done")
    mon.join(timeout=60)
    assert not mon.is_alive()
    assert client.final_status == "KILLED"


def test_containers_resources_duplicate_basename_rejected(tmp_path):
    (tmp_path / "a").mkdir(); (tmp_path / "b").mkdir()
    (tmp_path / "a" / "vocab.txt").write_text("v1")
    (tmp_path / "b" / "vocab.txt").write_text("v2")
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.containers.resources":
                f"{tmp_path/'a'/'vocab.txt'},{tmp_path/'b'/'vocab.txt'}"})),
        src_dir=WORKLOADS, workdir=tmp_path / "jobs", stream=io.StringIO())
    with pytest.raises(ValueError, match="duplicate"):
        client.stage()


def test_resnet_bench_job_via_submit(tmp_path):
    """The north-star measurement path (BASELINE.md: "via tony-submit"):
    examples/resnet_bench_job runs the bench.py step INSIDE a submitted
    job and emits the same JSON schema; the jhist carries the
    submit->all-running latency. CPU-shape here; the real-chip numbers are
    recorded in the README."""
    example = Path(__file__).parent.parent / "examples" / "resnet_bench_job"
    client = TonyClient(
        TonyConfig(base_props(**{
            "tony.application.framework": "jax",
            "tony.application.executes": "python train.py",
            "tony.worker.env":
                "BENCH_BATCH=4,BENCH_IMAGE=32,BENCH_STEPS=2,BENCH_WINDOWS=1",
        })),
        src_dir=example, workdir=tmp_path / "jobs", stream=io.StringIO())
    assert client.run(timeout=240) == 0
    [result] = Path(client.job_dir).glob("containers/*/src/bench_result.json")
    data = json.loads(result.read_text())
    assert data["metric"] == "resnet50_mfu"
    assert data["images_per_sec_per_chip"] > 0
    assert data["task"] == "worker:0"
    # The latency metric exists in the event log (ALL_TASKS_RUNNING).
    from tony_tpu.events import read_events
    [jhist] = Path(client.job_dir).glob("history/finished/**/*.jhist")
    evs = read_events(jhist)
    all_running = [e for e in evs if e.get("type") == "ALL_TASKS_RUNNING"]
    assert all_running
    assert all_running[0]["payload"]["submit_to_running_s"] > 0
