"""Routed-serving legs (tony_tpu.serve PR 13): block-level prefix
caching (chain hashing, refcounted adoption, COW, ref-aware LRU over
the LIFO free tier), chunked prefill, the cross-replica request router
(overlap scoring, sticky affinity, failover), the widened heartbeat
schema, and the BITWISE pins of every new admission path against the
unrouted PR 10/12 engine."""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.route


# ---------------------------------------------------------------------------
# Shared tiny model + params (serving is read-only on params).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import flax.linen as nn

    from tony_tpu.models import get_model

    model = get_model("llama-tiny", n_layers=2)
    sample = jnp.zeros((1, 16), jnp.int32)
    params = nn.unbox(model.init(jax.random.PRNGKey(0), sample))["params"]
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        params)
    return model, params


def make_engine(tiny, **kw):
    from tony_tpu.serve import ServeEngine

    model, params = tiny
    kw.setdefault("ctx_max", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("q_block", 16)
    kw.setdefault("decode_buckets", (2, 4))
    kw.setdefault("max_running", 4)
    kw.setdefault("keep_logits", True)
    return ServeEngine(model, params, **kw)


def run_requests(eng, prompts, max_new=4, stagger=True):
    """Submit + drive; staggered submission exercises mid-flight joins
    (live-donor sharing) the way real traffic would."""
    from tony_tpu.serve import Request

    done = {}
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, tokens=list(p), max_new_tokens=max_new))
        if stagger:
            done.update({c.rid: c for c in eng.step()})
    done.update({c.rid: c for c in eng.run()})
    return done


def assert_bitwise_equal(got, ref):
    """Token streams AND per-token logits of two completion maps."""
    assert sorted(got) == sorted(ref)
    for rid in ref:
        assert got[rid].tokens == ref[rid].tokens, rid
        assert len(got[rid].logits) == len(ref[rid].logits)
        for a, b in zip(got[rid].logits, ref[rid].logits):
            assert np.array_equal(a, b), rid


# ---------------------------------------------------------------------------
# Chain hashing (tony_tpu.serve.prefix)
# ---------------------------------------------------------------------------

class TestPrefixHashing:
    def test_chain_keys_cover_full_blocks_only(self):
        from tony_tpu.serve import prefix

        toks = list(range(21))
        keys = prefix.chain_keys(toks, 8)
        assert len(keys) == 2                       # 21 // 8
        assert prefix.chain_keys(toks[:16], 8) == keys
        assert prefix.chain_keys([], 8) == []

    def test_chain_keys_deterministic_and_prefix_sensitive(self):
        from tony_tpu.serve import prefix

        a = prefix.chain_keys([1, 2, 3, 4, 5, 6, 7, 8], 4)
        assert a == prefix.chain_keys([1, 2, 3, 4, 5, 6, 7, 8], 4)
        # Same second block under a different first block: the chain
        # key differs — a block is addressable only under its WHOLE
        # prefix, because its KV rows depend on every earlier token.
        b = prefix.chain_keys([9, 2, 3, 4, 5, 6, 7, 8], 4)
        assert a[0] != b[0] and a[1] != b[1]
        # prior= continues a chain without rehashing history.
        assert prefix.chain_keys([5, 6, 7, 8], 4, prior=a[0]) == [a[1]]

    def test_match_overlap_is_prefix_not_intersection(self):
        from tony_tpu.serve import prefix

        keys = ["k0", "k1", "k2"]
        assert prefix.match_overlap(keys, {"k0", "k1", "k2"}) == 3
        assert prefix.match_overlap(keys, {"k0", "k2"}) == 1
        assert prefix.match_overlap(keys, {"k1", "k2"}) == 0
        assert prefix.match_overlap([], {"k0"}) == 0


# ---------------------------------------------------------------------------
# The prefix tier of the paged KV cache
# ---------------------------------------------------------------------------

def _cache(n_blocks=12, block_size=4):
    from tony_tpu.serve import PagedKVCache

    return PagedKVCache(1, 4, n_blocks=n_blocks, block_size=block_size)


def _keys(tokens, bs=4):
    from tony_tpu.serve import prefix

    return prefix.chain_keys(tokens, bs)


def _publish_all(c, sid, tokens, bs=4):
    for i, key in enumerate(_keys(tokens, bs)):
        c.publish_block(sid, i, key)


def check_partition(c):
    """THE pool invariant: free tier + cached tier + refcounted
    ownership partition the block ids, and every refcount equals the
    number of tables holding the block."""
    owned = {}
    for t in c.owned_blocks().values():
        for b in t:
            owned[b] = owned.get(b, 0) + 1
    free, lru = set(c._free), set(c.cached_blocks())
    assert not free & lru
    assert not (free | lru) & set(owned)
    assert free | lru | set(owned) == set(range(c.n_blocks))
    assert {b: c.ref(b) for b in owned} == owned
    assert set(c._refs) == set(owned)


class TestPrefixKVCache:
    def test_admit_shared_adopts_and_partitions(self):
        c = _cache()
        toks = list(range(10))                  # 2 full blocks + tail
        c.reserve("a", 12)
        _publish_all(c, "a", toks)
        matched = c.admit_shared("b", 12, _keys(toks))
        assert matched == 2
        ta, tb = c.table("a"), c.table("b")
        assert tb[:2] == ta[:2] and tb[2] != ta[2]
        assert c.ref(ta[0]) == 2 and c.ref(ta[2]) == 1
        assert c.adopted_total == 2
        check_partition(c)

    def test_admit_shared_atomic_on_pressure(self):
        c = _cache(n_blocks=4)
        c.reserve("a", 8)                       # 2 of 4
        _publish_all(c, "a", list(range(8)))
        with pytest.raises(Exception) as exc:
            c.admit_shared("b", 20, _keys(list(range(8))))  # needs 3 fresh
        from tony_tpu.serve import AdmissionError

        assert isinstance(exc.value, AdmissionError)
        assert c.table("b") == [] and c.ref(c.table("a")[0]) == 1
        check_partition(c)

    def test_cow_never_mutates_shared_block(self):
        c = _cache()
        toks = list(range(8))
        c.reserve("a", 8)
        _publish_all(c, "a", toks)
        # Distinguishable device bytes in a's block 0.
        c.k = c.k.at[:, c.table("a")[0]].set(7.0)
        c.admit_shared("b", 8, _keys(toks))
        shared = c.table("a")[0]
        assert c.table("b")[0] == shared and c.ref(shared) == 2
        idx = c.write_index("b", 1)             # first divergent write
        priv = c.table("b")[0]
        assert priv != shared, "COW must repoint, never mutate"
        assert idx == priv * c.block_size + 1
        assert c.ref(shared) == 1 and c.ref(priv) == 1
        # The copy carried the donor's rows; the donor still owns its
        # original bytes.
        assert float(c.k[0, priv, 0, 0]) == 7.0
        assert float(c.k[0, shared, 0, 0]) == 7.0
        assert c.cow_total == 1
        # Writes into an exclusively-owned block never copy.
        assert c.write_index("a", 2) == shared * c.block_size + 2
        assert c.cow_total == 1
        check_partition(c)

    def test_free_retires_published_blocks_to_lru_and_revives(self):
        c = _cache()
        toks = list(range(8))
        c.reserve("a", 10)                      # 3 blocks, 2 publishable
        _publish_all(c, "a", toks)
        c.free_seq("a")
        assert len(c.cached_blocks()) == 2      # published pair, cached
        assert c.free_blocks == c.n_blocks      # both tiers count
        matched = c.admit_shared("b", 8, _keys(toks))
        assert matched == 2 and c.revived_total == 2
        assert not c.cached_blocks()
        check_partition(c)

    def test_lru_eviction_order_and_index_drop(self):
        c = _cache(n_blocks=5)
        c.reserve("a", 4)
        _publish_all(c, "a", [1, 2, 3, 4])
        c.free_seq("a")                         # block -> LRU
        c.reserve("b", 4)
        _publish_all(c, "b", [5, 6, 7, 8])
        c.free_seq("b")
        first, second = c.cached_blocks()
        # Drain the LIFO tier; the next allocation must reclaim the
        # LEAST recently freed cached block and unindex it.
        c.reserve("z", 3 * 4)
        t = c.reserve("y", 4)
        assert t == [first] and c.lru_evicted_total == 1
        assert c.match_prefix(_keys([1, 2, 3, 4])) == []
        assert c.match_prefix(_keys([5, 6, 7, 8])) == [second]
        check_partition(c)

    def test_spec_rollback_on_forked_sequence_keeps_shared_prefix(self):
        c = _cache()
        toks = list(range(8))
        c.reserve("a", 8)
        _publish_all(c, "a", toks)
        c.admit_shared("b", 8, _keys(toks))
        shared = c.table("b")[:2]
        c.spec_reserve("b", 14)                 # revocable extension
        assert len(c.table("b")) == 4
        c.commit("b", 9)                        # accept into block 2
        freed = c.rollback("b")
        assert freed == 1                       # the block above the cursor
        assert c.table("b")[:2] == shared
        assert all(c.ref(b) == 2 for b in shared), \
            "rollback must never strand or release a shared block"
        assert c.committed_len("b") == 9
        check_partition(c)

    def test_randomized_admit_fork_write_evict_interleave(self):
        """Satellite pin: ≥300 randomized ops over a small pool —
        refcounts + free tiers + tables partition the pool at EVERY
        step, COW never hands out a shared block for writing, and spec
        rollback on forked sequences never touches an adopted prefix."""
        from tony_tpu.serve import AdmissionError

        rng = np.random.RandomState(0)
        c = _cache(n_blocks=16, block_size=4)
        stems = [list(rng.randint(0, 50, 8)) for _ in range(3)]
        seqs = {}                               # sid -> token list
        sid_n = 0
        for opno in range(340):
            op = rng.choice(["admit", "write", "spec", "free"])
            if op == "admit":
                sid_n += 1
                sid = f"s{sid_n}"
                toks = list(stems[rng.randint(3)][:rng.choice([4, 8])]) \
                    + list(rng.randint(0, 50, rng.randint(0, 6)))
                try:
                    c.admit_shared(sid, len(toks) + 4, _keys(toks))
                except AdmissionError:
                    check_partition(c)
                    continue
                seqs[sid] = toks
                # Publish what a prefill would: every full prompt block.
                _publish_all(c, sid, toks)
            elif op == "write" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                span = len(c.table(sid)) * c.block_size
                pos = rng.randint(span)
                try:
                    idx = c.write_index(sid, pos)
                except AdmissionError:
                    check_partition(c)
                    continue
                b = c.table(sid)[pos // c.block_size]
                assert idx == b * c.block_size + pos % c.block_size
                assert c.ref(b) == 1, \
                    "a write target must be exclusively owned"
            elif op == "spec" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                table_before = list(c.table(sid))
                extent = len(table_before) * c.block_size
                try:
                    c.spec_reserve(sid, extent + rng.randint(1, 9))
                except AdmissionError:
                    check_partition(c)
                    continue
                accepted = rng.randint(extent + 1)
                c.commit(sid, accepted)
                c.rollback(sid)
                assert c.table(sid)[:len(table_before)] == table_before, \
                    "rollback must leave the pre-speculation table intact"
            elif op == "free" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                del seqs[sid]
                c.free_seq(sid)
                assert c.free_seq(sid) == 0     # idempotent
            check_partition(c)
        assert c.adopted_total > 0 and c.cow_total > 0, \
            "the interleave must actually exercise sharing and COW"
        for sid in list(seqs):
            c.free_seq(sid)
        check_partition(c)
        assert c.free_blocks == c.n_blocks


# ---------------------------------------------------------------------------
# Engine-level bitwise pins vs the unrouted PR 10 engine
# ---------------------------------------------------------------------------

class TestPrefixEngineBitwise:
    def test_hit_and_miss_admissions_bitwise_vs_plain(self, tiny):
        """Shared-prefix admissions (hits), unrelated admissions
        (misses): token streams AND per-token logits identical to the
        prefix-cache-off engine's."""
        rng = np.random.RandomState(0)
        shared = list(rng.randint(0, 256, 24))      # 3 full blocks of 8
        prompts = [shared + list(rng.randint(0, 256, 5)),
                   shared + list(rng.randint(0, 256, 9)),
                   list(rng.randint(0, 256, 11)),   # miss
                   shared[:8] + list(rng.randint(0, 256, 3))]
        ref = run_requests(make_engine(tiny), prompts)
        eng = make_engine(tiny, prefix_cache=True)
        got = run_requests(eng, prompts)
        assert_bitwise_equal(got, ref)
        assert eng.prefix_hit_blocks > 0
        assert eng.stats()["prefix_cache_hit_rate"] > 0
        assert eng.cache.free_blocks == eng.cache.n_blocks

    def test_cow_divergence_mid_block_and_at_boundary(self, tiny):
        """The acceptance matrix: a follow-up prompt that diverges from
        the cached conversation MID-block (the diverged block misses,
        recompute from the boundary) and one that diverges exactly AT a
        block boundary (maximal reuse), plus the full-cover repeat whose
        tail re-computation COWs a live donor's block."""
        rng = np.random.RandomState(1)
        base = list(rng.randint(0, 256, 16))        # 2 full blocks
        prompts = [base,
                   base[:12] + list(rng.randint(0, 256, 7)),   # mid-block
                   base[:8] + list(rng.randint(0, 256, 5)),    # boundary
                   list(base)]                      # full-cover repeat
        ref = run_requests(make_engine(tiny), prompts, max_new=5)
        eng = make_engine(tiny, prefix_cache=True)
        got = run_requests(eng, prompts, max_new=5)
        assert_bitwise_equal(got, ref)
        assert eng.cache.cow_total >= 1, \
            "the full-cover repeat against a live donor must COW"
        assert eng.cache.adopted_total >= 4

    def test_recently_evicted_prefix_revives(self, tiny):
        """Multi-turn after eviction: the first turn completes and
        evicts; the follow-up prompt (history + new tokens) adopts the
        cached-tier blocks — prefill rows drop, bits do not change."""
        rng = np.random.RandomState(2)
        turn1 = list(rng.randint(0, 256, 17))
        eng = make_engine(tiny, prefix_cache=True)
        first = run_requests(eng, [turn1], max_new=4)[0]
        assert eng.cache.cached_blocks(), "evicted blocks must be cached"
        turn2 = turn1 + first.tokens + list(rng.randint(0, 256, 4))
        rows_before = eng.prefill_rows
        got = run_requests(eng, [turn2], max_new=4)
        assert eng.cache.revived_total > 0
        # The adopted turn-1 blocks were not re-prefilled.
        assert eng.prefill_rows - rows_before < -(-len(turn2) // 16) * 16
        ref = run_requests(make_engine(tiny), [turn2], max_new=4)
        assert_bitwise_equal(got, ref)

    def test_spec_engine_rides_prefix_cache_bitwise(self, tiny):
        """The speculative lane composes with sharing: forked sequences
        verify through COW-aware writes and roll back without touching
        the shared prefix; greedy outputs stay pinned to the plain
        engine's."""
        from tony_tpu.serve import Request, SpecEngine

        model, params = tiny
        rng = np.random.RandomState(3)
        shared = list(rng.randint(0, 256, 16))
        prompts = [shared + list(rng.randint(0, 256, n)) for n in (0, 3, 7)]
        ref = run_requests(make_engine(tiny), prompts, max_new=6)
        eng = SpecEngine(model, params, spec_k=4, ctx_max=64,
                         block_size=8, q_block=16, decode_buckets=(2, 4),
                         max_running=4, keep_logits=True,
                         prefix_cache=True)
        got = run_requests(eng, prompts, max_new=6)
        assert_bitwise_equal(got, ref)
        assert eng.cache.adopted_total > 0
        assert eng.cache.free_blocks == eng.cache.n_blocks


# ---------------------------------------------------------------------------
# Chunked prefill
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_chunked_vs_monolithic_bitwise_ragged(self, tiny):
        """Ragged prompt lengths spanning the chunk boundary (chunk=16:
        7/15/16/17/23) — chunked streams and logits are bit-identical
        to monolithic prefill's."""
        rng = np.random.RandomState(4)
        prompts = [list(rng.randint(0, 256, n)) for n in (7, 15, 16, 17, 23)]
        ref = run_requests(make_engine(tiny), prompts)
        eng = make_engine(tiny, prefill_chunk=16)
        got = run_requests(eng, prompts)
        assert_bitwise_equal(got, ref)
        assert eng.prefill_chunks >= 7      # 1+1+1+2+2 chunk launches
        assert eng.stats()["prefill_chunks"] == float(eng.prefill_chunks)

    def test_chunked_composes_with_prefix_cache(self, tiny):
        rng = np.random.RandomState(5)
        shared = list(rng.randint(0, 256, 24))
        prompts = [shared + list(rng.randint(0, 256, n)) for n in (2, 6, 13)]
        ref = run_requests(make_engine(tiny), prompts, max_new=3)
        eng = make_engine(tiny, prefix_cache=True, prefill_chunk=16)
        got = run_requests(eng, prompts, max_new=3)
        assert_bitwise_equal(got, ref)
        assert eng.prefix_hit_blocks > 0 and eng.prefill_chunks > 0

    @pytest.mark.slow
    def test_long_prompt_does_not_stall_decode(self, tiny):
        """The latency property chunking buys: while a long prompt
        prefills chunk by chunk, the already-running sequence keeps
        emitting a token EVERY iteration — with monolithic prefill the
        admission step stalls it for the whole prompt."""
        from tony_tpu.serve import Request

        eng = make_engine(tiny, ctx_max=128, prefill_chunk=16,
                          keep_logits=False)
        rng = np.random.RandomState(6)
        eng.submit(Request(rid="short", tokens=[1, 2, 3],
                           max_new_tokens=8))
        eng.step()
        long_prompt = list(rng.randint(0, 256, 60))   # 4 chunks
        eng.submit(Request(rid="long", tokens=long_prompt,
                           max_new_tokens=2))
        grew = []
        done = {}
        for _ in range(4):
            before = len(next(s for s in eng._running
                              if s.rid == "short").tokens)
            done.update({c.rid: c for c in eng.step()})
            running = {s.rid: s for s in eng._running}
            if "short" in running:
                grew.append(len(running["short"].tokens) - before)
        assert all(g == 1 for g in grew), \
            f"decode stalled during chunked prefill: {grew}"
        done.update({c.rid: c for c in eng.run()})
        # Token-stream sanity against the monolithic engine.
        mono = make_engine(tiny, ctx_max=128, keep_logits=False)
        mono.submit(Request(rid="short", tokens=[1, 2, 3],
                            max_new_tokens=8))
        mono.step()
        mono.submit(Request(rid="long", tokens=long_prompt,
                            max_new_tokens=2))
        mref = {c.rid: c for c in mono.run()}
        assert done["short"].tokens == mref["short"].tokens
        assert done["long"].tokens == mref["long"].tokens

    def test_chunk_validation(self, tiny):
        with pytest.raises(ValueError, match="prefill_chunk"):
            make_engine(tiny, prefill_chunk=12)      # not a q_block multiple
        with pytest.raises(ValueError, match="prefill_chunk"):
            make_engine(tiny, prefill_chunk=-16)


# ---------------------------------------------------------------------------
# Heartbeat/stats schema (satellite): engine -> stats file -> heartbeat
# -> session -> router
# ---------------------------------------------------------------------------

class TestStatsSchema:
    def test_stats_fields_present_and_zero_when_off(self, tiny):
        from tony_tpu.serve import Request

        eng = make_engine(tiny, keep_logits=False)
        eng.submit(Request(rid="r", tokens=[1, 2, 3], max_new_tokens=2))
        eng.run()
        stats = eng.stats()
        assert stats["prefix_cache_hit_rate"] == 0.0
        assert stats["blocks_shared"] == 0.0
        assert stats["prefill_chunks"] == 0.0
        # KV-tier fields ship as zeros on engines without the host tier:
        # the fleet schema stays uniform so the router and autoscaler
        # never branch on schema presence.
        for key in ("host_blocks", "parked_seqs", "demotions",
                    "promotions", "park_hit_rate"):
            assert stats[key] == 0.0
        assert eng.prefix_digest() == []
        assert eng.parked_digest() == []

    def test_spec_engine_publishes_schema_zeros(self, tiny):
        from tony_tpu.serve import Request, SpecEngine

        model, params = tiny
        eng = SpecEngine(model, params, spec_k=2, ctx_max=64,
                         block_size=8, q_block=16, decode_buckets=(2,),
                         max_running=2)
        eng.submit(Request(rid="r", tokens=[1, 2, 3], max_new_tokens=3))
        eng.run()
        stats = eng.stats()
        for key in ("prefix_cache_hit_rate", "blocks_shared",
                    "prefill_chunks", "host_blocks", "parked_seqs",
                    "demotions", "promotions", "park_hit_rate"):
            assert stats[key] == 0.0

    def test_stats_file_carries_digest_and_rpc_port(self, tiny, tmp_path):
        from tony_tpu.executor import read_serve_stats
        from tony_tpu.serve import Request, prefix

        eng = make_engine(tiny, prefix_cache=True, keep_logits=False)
        toks = list(np.random.RandomState(7).randint(0, 256, 19))
        eng.submit(Request(rid="r", tokens=toks, max_new_tokens=3))
        eng.run()
        path = tmp_path / "serve-stats.json"
        eng.write_stats(str(path), extra={"rpc_port": 4321})
        read = read_serve_stats(path)
        assert read["rpc_port"] == 4321.0
        keys = prefix.chain_keys(toks, eng.block_size)
        assert set(keys) <= set(read["prefix_digest"])
        assert read["prefix_cache_hit_rate"] == 0.0

    def test_executor_heartbeat_round_trips_new_schema(self, tmp_path):
        """Stats file → heartbeat RPC → session.serve_metrics, with the
        three new floats AND the digest list intact — the router's
        whole input path."""
        from tony_tpu import constants
        from tony_tpu.conf import TonyConfig
        from tony_tpu.executor import TaskExecutor
        from tony_tpu.rpc import ApplicationRpcHandler, RpcServer
        from tony_tpu.serve.router import RequestRouter
        from tony_tpu.session import TonySession

        conf = TonyConfig({"tony.serve.instances": "1",
                           "tony.serve.command": "x"})
        session = TonySession(conf, app_id="app_route_hb")
        session.on_registered("serve", 0, "127.0.0.1", 4000)
        server = RpcServer(ApplicationRpcHandler(session),
                           host="127.0.0.1").start()
        conf_path = tmp_path / "conf.json"
        conf_path.write_text(json.dumps(dict(conf.items())))
        payload = {"qps": 1.0, "p99_ms": 12.0, "queue_depth": 2.0,
                   "prefix_cache_hit_rate": 0.75, "blocks_shared": 6.0,
                   "prefill_chunks": 3.0, "rpc_port": 5555,
                   "host_blocks": 4.0, "parked_seqs": 2.0,
                   "demotions": 5.0, "promotions": 3.0,
                   "park_hit_rate": 0.5,
                   "prefix_digest": ["aa", "bb"],
                   "parked_digest": ["conv-1", "conv-2"]}
        try:
            executor = TaskExecutor(env={
                constants.ENV_JOB_NAME: "serve",
                constants.ENV_TASK_INDEX: "0",
                constants.ENV_AM_ADDRESS: server.address,
                constants.ENV_CONF_PATH: str(conf_path),
                constants.ENV_LOG_DIR: str(tmp_path),
            })
            executor.serve_stats_path().write_text(json.dumps(payload))
            t = threading.Thread(target=executor._heartbeat_loop,
                                 args=(0.05,), daemon=True)
            t.start()
            deadline = time.monotonic() + 10.0
            task = session.task("serve", 0)
            while time.monotonic() < deadline and not task.serve_metrics:
                time.sleep(0.05)
            executor._hb_stop.set()
            t.join(timeout=5)
            got = task.serve_metrics
            assert got["prefix_cache_hit_rate"] == 0.75
            assert got["blocks_shared"] == 6.0
            assert got["prefill_chunks"] == 3.0
            assert got["host_blocks"] == 4.0
            assert got["parked_seqs"] == 2.0
            assert got["demotions"] == 5.0
            assert got["promotions"] == 3.0
            assert got["park_hit_rate"] == 0.5
            assert got["prefix_digest"] == ["aa", "bb"]
            assert got["parked_digest"] == ["conv-1", "conv-2"]
            assert got["rpc_port"] == 5555.0
            # serve_endpoints exposes the routable wire form...
            eps = session.serve_endpoints("serve")
            assert len(eps) == 1 and eps[0]["host"] == "127.0.0.1"
            # ...and the router ingests it end to end.
            router = RequestRouter(block_size=8)
            router.refresh_from_task_infos(eps)
            views = router.replicas()
            assert views[0].address == "127.0.0.1:5555"
            assert views[0].digest == frozenset(["aa", "bb"])
            assert views[0].parked == frozenset(["conv-1", "conv-2"])
        finally:
            server.stop()

    def test_scaling_decide_unchanged_by_new_fields(self):
        from tony_tpu.serve import scaling

        pol = scaling.ScalingPolicy(min_replicas=1, max_replicas=4,
                                    queue_high=8.0, queue_low=1.0)
        hot = [{"queue_depth": 12.0, "p99_ms": 100.0,
                "prefix_cache_hit_rate": 0.9, "blocks_shared": 50.0,
                "prefill_chunks": 7.0, "prefix_digest": ["aa"],
                "host_blocks": 4.0, "parked_seqs": 2.0,
                "demotions": 5.0, "promotions": 3.0,
                "park_hit_rate": 0.5, "parked_digest": ["conv-1"]}]
        assert scaling.decide(pol, 1, hot, now=0.0) == 1


# ---------------------------------------------------------------------------
# Router scoring / affinity / failover (pure + in-process)
# ---------------------------------------------------------------------------

class TestRouter:
    def _keys(self, toks):
        from tony_tpu.serve import prefix

        return prefix.chain_keys(toks, 16)

    def test_score_prefers_overlap_then_load(self):
        from tony_tpu.serve.router import (ReplicaView, RouterPolicy,
                                           score)

        pol = RouterPolicy()
        toks = list(range(48))
        keys = self._keys(toks)
        warm = ReplicaView(name="warm", address="x",
                           digest=frozenset(keys))
        cold = ReplicaView(name="cold", address="x")
        busy = ReplicaView(name="busy", address="x",
                           digest=frozenset(keys), queue_depth=16.0)
        assert score(pol, warm, keys) > score(pol, cold, keys)
        assert score(pol, cold, keys) > score(pol, busy, keys), \
            "a deep queue must outweigh cache overlap"

    def test_policy_validation(self):
        from tony_tpu.serve.router import RouterPolicy

        with pytest.raises(ValueError):
            RouterPolicy(cache_weight=-1.0)

    def test_sticky_affinity_and_retirement_failover(self):
        from tony_tpu.serve.router import RequestRouter

        calls = {"a": 0, "b": 0}

        class Client:
            def __init__(self, name):
                self.name = name

            def generate(self, tokens, max_new_tokens, rid=None,
                         conv=None):
                calls[self.name] += 1
                return {"rid": rid, "tokens": [0], "latency_ms": 1.0}

        rt = RequestRouter(block_size=16)
        rt.upsert_replica("a", client=Client("a"),
                          stats={"queue_depth": 0.0})
        rt.upsert_replica("b", client=Client("b"),
                          stats={"queue_depth": 5.0})
        first = rt.dispatch(list(range(16)), 2, session_id="s1")
        assert first["replica"] == "a"          # lighter load wins
        rt.upsert_replica("a", stats={"queue_depth": 50.0})
        again = rt.dispatch(list(range(16)), 2, session_id="s1")
        assert again["replica"] == "a", "affinity must out-pin load"
        assert rt.affinity_hits == 1
        rt.retire_replica("a")
        moved = rt.dispatch(list(range(16)), 2, session_id="s1")
        assert moved["replica"] == "b", "retirement must re-dispatch"
        assert calls == {"a": 2, "b": 1}

    def test_parked_digest_repins_returning_conversation(self):
        """A returning turn with no affinity pin (router restart) lands
        on the replica holding its PARKED KV — the host-tier resume
        beats any overlap score — and the pin re-establishes."""
        from tony_tpu.serve.router import RequestRouter

        seen = []

        class Client:
            def __init__(self, name):
                self.name = name

            def generate(self, tokens, max_new_tokens, rid=None,
                         conv=None):
                seen.append((self.name, conv))
                return {"rid": rid, "tokens": [0], "latency_ms": 1.0}

        rt = RequestRouter(block_size=16)
        # "cold" scores better on load; "warm" holds the parked conv.
        rt.upsert_replica("cold", client=Client("cold"),
                          stats={"queue_depth": 0.0})
        rt.upsert_replica("warm", client=Client("warm"),
                          stats={"queue_depth": 5.0,
                                 "parked_digest": ["turnful"]})
        out = rt.dispatch(list(range(16)), 2, session_id="turnful")
        assert out["replica"] == "warm"
        assert rt.stats()["park_pins"] == 1.0
        # conv rides the dispatch so the engine can resume under it.
        assert seen == [("warm", "turnful")]
        # The re-pin is sticky: the next turn is an affinity hit, not
        # another parked-digest scan.
        rt.dispatch(list(range(16)), 2, session_id="turnful")
        assert rt.affinity_hits == 1 and rt.stats()["park_pins"] == 1.0
        # Sessionless dispatch ships NO conv kwarg (stub back-compat).
        class Legacy:
            def generate(self, tokens, max_new_tokens, rid=None):
                return {"rid": rid, "tokens": [1], "latency_ms": 1.0}

        rt.upsert_replica("cold", client=Legacy(),
                          stats={"queue_depth": 0.0})
        assert rt.dispatch([1, 2], 2)["tokens"] == [1]

    def test_dead_replica_fails_over_and_revives_on_heartbeat(self):
        from tony_tpu.serve.router import RequestRouter

        class Dead:
            def generate(self, *a, **k):
                raise ConnectionError("gone")

        class Live:
            def generate(self, tokens, max_new_tokens, rid=None,
                         conv=None):
                return {"rid": rid, "tokens": [0], "latency_ms": 1.0}

        rt = RequestRouter(block_size=16)
        rt.upsert_replica("x", client=Dead(), stats={"queue_depth": 0.0})
        rt.upsert_replica("y", client=Live(), stats={"queue_depth": 1.0})
        out = rt.dispatch(list(range(16)), 2, session_id="s")
        assert out["replica"] == "y" and rt.failovers == 1
        # A fresh heartbeat is the liveness source of truth.
        rt.upsert_replica("x", stats={"queue_depth": 0.0})
        assert rt.route(list(range(16))) == "x"

    def test_no_replica_error(self):
        from tony_tpu.serve.router import NoReplicaError, RequestRouter

        rt = RequestRouter()
        with pytest.raises(NoReplicaError):
            rt.route([1, 2, 3])

    def test_request_level_error_does_not_poison_fleet(self):
        """A bad REQUEST (oversized prompt → AdmissionError) must
        propagate to its caller, not mark healthy replicas down — one
        misbehaving client must never render the fleet unroutable."""
        from tony_tpu.serve import AdmissionError
        from tony_tpu.serve.router import RequestRouter

        class Healthy:
            def generate(self, tokens, max_new_tokens, rid=None):
                if len(tokens) > 4:
                    raise AdmissionError("too big", retryable=False)
                return {"rid": rid, "tokens": [0], "latency_ms": 1.0}

        rt = RequestRouter(block_size=16)
        rt.upsert_replica("a", client=Healthy(),
                          stats={"queue_depth": 0.0})
        with pytest.raises(AdmissionError):
            rt.dispatch(list(range(10)), 2)
        assert rt.failovers == 0
        assert rt.replicas()[0].alive, \
            "a request-level error must not down-mark the replica"
        assert rt.dispatch([1, 2], 2)["tokens"] == [0]

    def test_cache_aware_routing_wins_on_digest(self, tiny):
        """In-process fleet: the replica that served the conversation
        advertises its blocks; the router sends the follow-up there."""
        from tony_tpu.serve import EngineFront
        from tony_tpu.serve.router import RequestRouter

        e1 = make_engine(tiny, prefix_cache=True, keep_logits=False)
        e2 = make_engine(tiny, prefix_cache=True, keep_logits=False)
        rt = RequestRouter(block_size=8)
        rt.upsert_replica("r1", client=EngineFront(e1))
        rt.upsert_replica("r2", client=EngineFront(e2))
        rng = np.random.RandomState(8)
        convo = list(rng.randint(0, 256, 17))
        first = rt.dispatch(convo, 4)
        served_by = first["replica"]
        eng = e1 if served_by == "r1" else e2
        # Heartbeat tick: each replica advertises queue + digest.
        rt.upsert_replica("r1", stats={**e1.stats(),
                                       "prefix_digest": e1.prefix_digest()})
        rt.upsert_replica("r2", stats={**e2.stats(),
                                       "prefix_digest": e2.prefix_digest()})
        follow = convo + list(first["tokens"]) + [5, 6, 7]
        assert rt.route(follow) == served_by, \
            "overlap must route the follow-up to the warm replica"
        assert rt.dispatch(follow, 2)["replica"] == served_by
        assert rt.cache_routed >= 1


# ---------------------------------------------------------------------------
# Routed multi-replica serving vs one unrouted replica (the fleet pin)
# ---------------------------------------------------------------------------

class TestRoutedServing:
    @pytest.mark.slow
    def test_two_replica_routed_bitwise_vs_single(self, tiny):
        """The acceptance pin: the SAME request set served through the
        router over TWO replicas (sessions sticky, shared prefixes
        cached) emits token streams identical to one unrouted PR 10
        engine serving everything."""
        from tony_tpu.serve import EngineFront
        from tony_tpu.serve.router import RequestRouter

        rng = np.random.RandomState(9)
        stems = [list(rng.randint(0, 256, 16)) for _ in range(2)]
        requests = []                           # (session, prompt, n)
        for i in range(10):
            stem = stems[i % 2]
            requests.append((f"sess{i % 3}",
                             stem + list(rng.randint(0, 256, 1 + i % 5)),
                             3 + i % 3))
        # Reference: one unrouted engine, sequential.
        ref_eng = make_engine(tiny, max_running=8, keep_logits=False)
        ref_front = EngineFront(ref_eng)
        ref = [ref_front.generate(p, n).tokens
               for (_, p, n) in requests]
        # Fleet: two prefix-cache replicas behind the router.
        e1 = make_engine(tiny, max_running=8, prefix_cache=True,
                         keep_logits=False)
        e2 = make_engine(tiny, max_running=8, prefix_cache=True,
                         keep_logits=False)
        rt = RequestRouter(block_size=8)
        rt.upsert_replica("r1", client=EngineFront(e1))
        rt.upsert_replica("r2", client=EngineFront(e2))
        got = []
        for sess, p, n in requests:
            got.append(rt.dispatch(p, n, session_id=sess)["tokens"])
            for name, e in (("r1", e1), ("r2", e2)):
                rt.upsert_replica(name, stats={
                    **e.stats(), "prefix_digest": e.prefix_digest()})
        assert got == ref
        assert e1.forwards > 0 and e2.forwards > 0, \
            "the router must actually spread the fleet"
        stats = rt.stats()
        assert stats["dispatched"] == len(requests)

    @pytest.mark.slow
    def test_router_server_over_rpc_with_failover(self, tiny):
        """The network front: two RPC replicas behind a RouterServer;
        killing one mid-trace re-dispatches without losing a request."""
        from tony_tpu.rpc import RpcClient, RpcServer
        from tony_tpu.serve import EngineFront
        from tony_tpu.serve.router import RequestRouter, RouterServer

        class Handler:
            def __init__(self, front):
                self.front = front

            def rpc_generate(self, tokens, max_new_tokens=16, rid=None,
                             conv=None, tenant=None):
                c = self.front.generate(tokens, max_new_tokens, rid=rid)
                return {"rid": c.rid, "tokens": c.tokens,
                        "latency_ms": round(1e3 * c.latency_s, 3)}

        e1 = make_engine(tiny, keep_logits=False)
        e2 = make_engine(tiny, keep_logits=False)
        f1, f2 = EngineFront(e1), EngineFront(e2)
        # Warm the jit shapes OUTSIDE the RPC window: the client's
        # per-op socket cap (10 s) is for transport, not CPU compiles.
        f1.generate([7, 7], 3)
        f2.generate([7, 7], 3)
        s1 = RpcServer(Handler(f1), host="127.0.0.1").start()
        s2 = RpcServer(Handler(f2), host="127.0.0.1").start()
        router = RequestRouter(block_size=8, dial_timeout_s=2.0)
        router.upsert_replica("r1", address=f"127.0.0.1:{s1.port}",
                              stats={"queue_depth": 0.0})
        router.upsert_replica("r2", address=f"127.0.0.1:{s2.port}",
                              stats={"queue_depth": 1.0})
        try:
            with RouterServer(router, host="127.0.0.1") as front:
                with RpcClient(front.address, timeout=120.0) as client:
                    out = client.call("generate", tokens=[1, 2, 3, 4],
                                      max_new_tokens=3,
                                      session_id="sess")
                    assert out["replica"] == "r1"
                    ref = out["tokens"]
                    s1.stop()               # the pinned replica dies
                    out2 = client.call("generate", tokens=[1, 2, 3, 4],
                                       max_new_tokens=3,
                                       session_id="sess")
                    assert out2["replica"] == "r2"
                    assert out2["tokens"] == ref, \
                        "failover must reproduce the greedy stream"
                    stats = client.call("router_stats")
                    assert stats["failovers"] >= 1
        finally:
            s2.stop()

    def test_cli_route_parser_and_serve_flags(self, tmp_path):
        from tony_tpu.cli import make_parser

        args = make_parser().parse_args([
            "route", "--am", "127.0.0.1:9999", "--block_size", "8"])
        assert args.fn.__name__ == "cmd_route"
        assert args.cache_weight == 4.0
        sv = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--ckpt_dir",
            str(tmp_path), "--prefix_cache", "--prefill_chunk", "32",
            "--host_blocks", "64", "--prefix_store",
            str(tmp_path / "stems")])
        assert sv.prefix_cache and sv.prefill_chunk == 32
        assert sv.host_blocks == 64
        assert sv.prefix_store == str(tmp_path / "stems")
        from tony_tpu.cli import cmd_serve

        bad = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--ckpt_dir",
            str(tmp_path), "--prefill_chunk", "12"])
        with pytest.raises(SystemExit, match="prefill_chunk"):
            cmd_serve(bad)
        bad_tier = make_parser().parse_args([
            "serve", "--model", "llama-tiny", "--ckpt_dir",
            str(tmp_path), "--host_blocks", "-1"])
        with pytest.raises(SystemExit, match="host_blocks"):
            cmd_serve(bad_tier)


# ---------------------------------------------------------------------------
# The eighth analyze config
# ---------------------------------------------------------------------------

class TestAnalyzeRoute:
    def test_analyze_route_config_clean_with_pin(self):
        """The acceptance gate: `tony analyze --config route` is clean
        with zero waivers against the committed pin — chunked prefill
        introduces no compiled step shape beyond the declared chunk
        geometry, with zero inter-chip collectives and donated KV
        pools (also covered by the test_analysis parametrization; this
        is the route lane's named copy)."""
        from tony_tpu.analysis import cli as acli

        report = acli.run_config(
            "route", signature_path=str(
                Path(__file__).parent / "signatures" / "route.json"))
        assert report.ok, report.summary()
        assert not report.waived
        assert report.signature["collectives"] == {}
        assert report.config["prefill_chunk"] == 32
