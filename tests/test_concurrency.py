"""Concurrency-analysis legs (tony_tpu.analysis.concurrency): the
lock-discipline lint with its '# lockfree:' blessings, the static +
witnessed lock-order graph with cycle detection (a seeded inversion is a
NAMED finding, not a hung CI job), the thread-hygiene audit, the
committed blessings baseline, the profiler's lock-witness registry — and
the genuinely multi-threaded randomized kvcache interleave: concurrent
admit/fork/write/spec/evict from N threads over one shared pool with the
refcount/free/LRU partition pinned at every quiescent point."""

from __future__ import annotations

import json
import textwrap
import threading
from pathlib import Path

import numpy as np
import pytest

from tony_tpu import profiler
from tony_tpu.analysis import concurrency as conc

pytestmark = pytest.mark.conc

REPO = Path(__file__).resolve().parent.parent


def lint(src: str, rel: str = "mod.py"):
    return conc.lint_source(textwrap.dedent(src), rel, rel)


@pytest.fixture()
def fresh_witness():
    conc.reset_witness()
    yield
    conc.reset_witness()


# ---------------------------------------------------------------------------
# Rule 1: lock discipline
# ---------------------------------------------------------------------------

class TestLockDiscipline:
    GUARDED = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def drop(self):
                self._items.pop()
    """

    def test_unguarded_write_fires_with_provenance(self):
        findings, _ = lint(self.GUARDED)
        assert len(findings) == 1
        f = findings[0]
        assert (f.rule, f.kind) == ("lock_discipline", "unguarded_write")
        assert f.provenance == "C.drop._items"
        assert not f.blessed
        assert "C._lock" in f.message and ".pop()" in f.message
        assert "drop()" in f.message

    def test_lockfree_pragma_blesses_with_reason(self):
        findings, _ = lint(self.GUARDED.replace(
            "self._items.pop()",
            "# lockfree: drop() is documented driver-thread-only\n"
            "                self._items.pop()"))
        active = [f for f in findings if not f.blessed]
        blessed = [f for f in findings if f.blessed]
        assert not active
        assert len(blessed) == 1
        assert blessed[0].blessed_by == \
            "drop() is documented driver-thread-only"

    def test_bare_pragma_is_itself_a_finding(self):
        findings, _ = lint(self.GUARDED.replace(
            "self._items.pop()",
            "self._items.pop()   # lockfree:"))
        assert len(findings) == 1
        assert findings[0].kind == "bare_pragma"
        assert not findings[0].blessed

    def test_init_is_construction_not_violation(self):
        # __init__ assigns the guarded attr bare — before any
        # concurrency exists; must not fire.
        findings, _ = lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1
        """)
        assert findings == []

    def test_closure_under_lock_is_not_guard_evidence(self):
        # The closure's body runs later (another thread, after the
        # with exited) — the lexically enclosing lock is NOT held, so
        # it neither witnesses a guard nor gets flagged.
        findings, _ = lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def spawn(self):
                    with self._lock:
                        def worker():
                            self._n += 1
                        return worker

                def bump(self):
                    self._n += 1
        """)
        assert findings == []

    def test_helper_lock_method_counts_as_guard(self):
        # ``with self._part_lock(key):`` — a per-key lock table behind
        # a helper (the TpuVmScheduler staging idiom).
        findings, _ = lint("""
            import threading

            class C:
                def __init__(self):
                    self._staged = set()

                def _part_lock(self, key):
                    return threading.Lock()

                def stage(self, key):
                    with self._part_lock(key):
                        self._staged.add(key)

                def unstage(self, key):
                    self._staged.discard(key)
        """)
        assert len(findings) == 1
        assert findings[0].kind == "unguarded_write"
        assert findings[0].provenance == "C.unstage._staged"
        assert "_part_lock()" in findings[0].message

    def test_subclass_mutation_of_base_guarded_attr_fires(self):
        # Same-file inheritance: the base declares the lock and the
        # guard discipline; a subclass method that forgets the lock is
        # exactly the drift the pass exists to catch (the SpecEngine/
        # ServeEngine-style hierarchy).
        findings, _ = lint("""
            import threading

            class Base:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._events = []

                def add(self, x):
                    with self._lock:
                        self._events.append(x)

            class Sub(Base):
                def drain(self):
                    self._events.clear()
        """)
        assert len(findings) == 1
        assert findings[0].provenance == "Sub.drain._events"

    def test_subclass_with_over_base_lock_is_guard_evidence(self):
        # The subclass holds the BASE-declared lock: that's a real hold
        # (and real guard evidence), not an unknown context manager.
        findings, _ = lint("""
            import threading

            class Base:
                def __init__(self):
                    self._lock = threading.Lock()

            class Sub(Base):
                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def drain(self):
                    self._items.clear()
        """)
        assert len(findings) == 1
        assert findings[0].provenance == "Sub.drain._items"

    def test_augassign_subscript_and_del_count_as_mutations(self):
        findings, _ = lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._m = {}

                def put(self, k, v):
                    with self._lock:
                        self._m[k] = v

                def evict(self, k):
                    del self._m[k]
        """)
        assert len(findings) == 1
        assert findings[0].provenance == "C.evict._m"

    def test_reads_are_not_flagged(self):
        findings, _ = lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def peek(self):
                    return len(self._items)
        """)
        assert findings == []

    def test_engine_events_ring_is_guarded_at_head(self):
        # Regression pin for the race this PR fixed: the stats
        # publisher thread iterates ServeEngine._events while the drive
        # thread appends — both sides now hold ServeEngine._lock, and
        # the pass must SEE that (the guarded-elsewhere inference is
        # what would catch the next drift).
        import ast

        src = (REPO / "tony_tpu" / "serve" / "engine.py").read_text()
        cls = next(n for n in ast.walk(ast.parse(src))
                   if isinstance(n, ast.ClassDef)
                   and n.name == "ServeEngine")
        scan = conc._scan_class(cls, "serve/engine.py")
        assert "_events" in scan.guarded
        assert scan.guarded["_events"][0] == "_lock"

    def test_ckpt_writer_error_slot_is_guarded_at_head(self):
        # Same pin for AsyncCheckpointer._err: the writer thread banks,
        # the caller swap-reads — both under _err_lock since this PR.
        import ast

        src = (REPO / "tony_tpu" / "ckpt" / "snapshot.py").read_text()
        cls = next(n for n in ast.walk(ast.parse(src))
                   if isinstance(n, ast.ClassDef)
                   and n.name == "AsyncCheckpointer")
        scan = conc._scan_class(cls, "ckpt/snapshot.py")
        assert "_err" in scan.guarded
        assert scan.guarded["_err"][0] == "_err_lock"


# ---------------------------------------------------------------------------
# Rule 2: lock order (static graph + cycle detection)
# ---------------------------------------------------------------------------

class TestStaticLockOrder:
    def test_nested_with_extracts_edges(self):
        _, edges = lint("""
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass
        """, rel="m.py")
        assert [(s, d) for s, d, _ in edges] == [("C._a", "C._b")]
        assert edges[0][2].startswith("m.py:")

    def test_multi_item_with_orders_left_to_right(self):
        _, edges = lint("""
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a, self._b:
                        pass
        """)
        assert [(s, d) for s, d, _ in edges] == [("C._a", "C._b")]

    def test_cycle_named_with_both_sites(self):
        edges = [("C._a", "C._b", "m.py:10"), ("C._b", "C._a", "m.py:20")]
        findings = conc.check_lock_order(edges, observed=[])
        assert len(findings) == 1
        f = findings[0]
        assert (f.rule, f.kind) == ("lock_order", "inversion")
        assert f.provenance == "C._a -> C._b -> C._a"
        assert "m.py:10" in f.message and "m.py:20" in f.message

    def test_consistent_order_is_clean(self):
        edges = [("A", "B", "x:1"), ("B", "C", "x:2"), ("A", "C", "x:3")]
        assert conc.check_lock_order(edges, observed=[]) == []

    def test_find_cycles_dedups_rotations(self):
        cycles = conc.find_cycles([("a", "b"), ("b", "c"), ("c", "a")])
        assert cycles == [["a", "b", "c", "a"]]


# ---------------------------------------------------------------------------
# The runtime witness
# ---------------------------------------------------------------------------

class TestWitness:
    def test_nested_acquire_records_edge_and_banks(self, fresh_witness):
        a, b = conc.Lock("w.a"), conc.Lock("w.b")
        with a:
            with b:
                pass
        edges = conc.observed_edges()
        assert [(e["src"], e["dst"]) for e in edges] == [("w.a", "w.b")]
        assert edges[0]["count"] == 1
        assert edges[0]["threads"] == [threading.current_thread().name]
        assert "test_concurrency" in edges[0]["where"]
        rec = profiler.lock_report()["witness"]
        assert [(e["src"], e["dst"]) for e in rec["edges"]] \
            == [("w.a", "w.b")]
        assert rec["locks"] == ["w.a", "w.b"]

    def test_reentrant_rlock_never_self_edges(self, fresh_witness):
        r = conc.RLock("w.r")
        with r:
            with r:
                pass
        assert conc.observed_edges() == []

    def test_witness_catches_seeded_inversion(self, fresh_witness):
        """THE acceptance pin: two threads acquire the same two locks in
        opposite orders (at different times, so nothing actually
        deadlocks) and the merged-graph cycle check names the inversion
        instead of CI hanging on the real interleaving."""
        a, b = conc.Lock("inv.a"), conc.Lock("inv.b")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab, name="t-ab")
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba, name="t-ba")
        t2.start()
        t2.join()
        findings = conc.check_lock_order([])
        assert len(findings) == 1
        f = findings[0]
        assert (f.rule, f.kind) == ("lock_order", "inversion")
        assert f.provenance == "inv.a -> inv.b -> inv.a"
        assert "witness" in f.message
        assert "t-ab" in f.message or "t-ba" in f.message

    def test_static_and_witness_edges_merge_into_one_cycle(
            self, fresh_witness):
        # Half the cycle only the AST sees, half only the runtime saw —
        # the point of merging before cycle detection.
        a, b = conc.Lock("m.a"), conc.Lock("m.b")
        with a:
            with b:
                pass
        findings = conc.check_lock_order([("m.b", "m.a", "seeded.py:1")])
        assert len(findings) == 1
        assert findings[0].provenance == "m.a -> m.b -> m.a"
        assert "static seeded.py:1" in findings[0].message

    def test_condition_wait_drops_and_reacquires_one_hold(
            self, fresh_witness):
        c = conc.Condition("w.cond")
        with c:
            assert conc._held_stack().count("w.cond") == 1
            c.wait(timeout=0.01)
            # wait() released for its sleep and re-recorded on wake —
            # exactly one hold, no duplicate stack entry.
            assert conc._held_stack().count("w.cond") == 1
        assert conc._held_stack() == []

    def test_timeout_failed_acquire_records_nothing(self, fresh_witness):
        a = conc.Lock("w.t")
        a.acquire()
        grabbed = []

        def try_it():
            grabbed.append(a.acquire(blocking=False))

        t = threading.Thread(target=try_it)
        t.start()
        t.join()
        assert grabbed == [False]
        a.release()
        assert conc._held_stack() == []


# ---------------------------------------------------------------------------
# Rule 3: thread hygiene
# ---------------------------------------------------------------------------

class TestThreadHygiene:
    def test_non_daemon_unjoined_thread_fires(self):
        findings, _ = lint("""
            import threading

            class C:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
        """)
        assert len(findings) == 1
        f = findings[0]
        assert (f.rule, f.kind) == ("thread_hygiene", "unjoined_thread")
        assert f.provenance == "C.start.self._t"
        assert "non-daemon" in f.message

    def test_daemon_true_passes(self):
        findings, _ = lint("""
            import threading

            class C:
                def start(self):
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)
                    self._t.start()
        """)
        assert findings == []

    def test_joined_self_thread_passes_across_methods(self):
        findings, _ = lint("""
            import threading

            class C:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def close(self):
                    self._t.join(timeout=5)
        """)
        assert findings == []

    def test_joined_local_thread_passes(self):
        findings, _ = lint("""
            import threading

            def run():
                t = threading.Thread(target=work)
                t.start()
                t.join()
        """)
        assert findings == []

    def test_unjoined_local_and_unassigned_fire(self):
        findings, _ = lint("""
            import threading

            def fire_and_forget():
                threading.Thread(target=work).start()
        """)
        assert len(findings) == 1
        assert findings[0].provenance == "fire_and_forget.<unassigned>"

    def test_non_literal_daemon_fires(self):
        findings, _ = lint("""
            import threading

            def run(flag):
                t = threading.Thread(target=work, daemon=flag)
                t.start()
        """)
        assert len(findings) == 1
        assert "daemon is not a literal True" in findings[0].message

    def test_threadlife_pragma_blesses(self):
        findings, _ = lint("""
            import threading

            def run():
                # threadlife: joined by the supervisor at job end
                t = threading.Thread(target=work)
                t.start()
        """)
        active = [f for f in findings if not f.blessed]
        assert not active
        assert findings and findings[0].blessed_by == \
            "joined by the supervisor at job end"


# ---------------------------------------------------------------------------
# Baseline (the committed blessings file)
# ---------------------------------------------------------------------------

class TestBaseline:
    def test_round_trip_blesses_by_fingerprint(self, tmp_path):
        findings, _ = lint(TestLockDiscipline.GUARDED)
        assert len(findings) == 1
        base = tmp_path / "concurrency.json"
        conc.write_baseline(base, findings, reason="audited: test-only")
        loaded = conc.load_baseline(base)
        assert loaded == {findings[0].fingerprint(): "audited: test-only"}
        active, blessed = conc.apply_baseline(findings, loaded)
        assert active == []
        assert blessed[0].blessed_by == "audited: test-only"

    def test_fingerprint_survives_line_churn(self):
        f1, _ = lint(TestLockDiscipline.GUARDED)
        f2, _ = lint("\n\n\n" + textwrap.dedent(TestLockDiscipline.GUARDED))
        assert f1[0].fingerprint() == f2[0].fingerprint()
        assert f1[0].line != f2[0].line

    def test_missing_baseline_is_empty(self, tmp_path):
        assert conc.load_baseline(tmp_path / "absent.json") == {}

    def test_main_update_baseline_then_clean(self, tmp_path):
        mod = tmp_path / "seeded.py"
        mod.write_text(textwrap.dedent(TestLockDiscipline.GUARDED))
        base = tmp_path / "base.json"
        assert conc.main([str(mod), "--baseline", str(base)]) == 1
        assert conc.main([str(mod), "--baseline", str(base),
                          "--update-baseline"]) == 0
        assert json.loads(base.read_text())["blessed"]
        assert conc.main([str(mod), "--baseline", str(base)]) == 0

    def test_main_missing_path_fails_loudly(self, tmp_path):
        assert conc.main([str(tmp_path / "nope")]) == 2

    def test_blessing_is_per_method_not_per_attribute(self):
        # Two unlocked mutations of the SAME guarded attribute in
        # different methods must carry distinct fingerprints — blessing
        # one audited site must not green-light the next call site that
        # forgets the lock.
        findings, _ = lint(TestLockDiscipline.GUARDED.replace(
            "def drop(self):",
            "def also(self):\n"
            "                self._items.pop()\n\n"
            "            def drop(self):"))
        fps = {f.fingerprint() for f in findings}
        assert len(findings) == 2 and len(fps) == 2

    def test_update_baseline_preserves_existing_reasons(self, tmp_path):
        # The regen must keep a still-firing blessing's audited reason
        # (not blow the baseline away and re-word everything), add the
        # new finding, and prune stale fingerprints.
        mod = tmp_path / "seeded.py"
        mod.write_text(textwrap.dedent(TestLockDiscipline.GUARDED))
        base = tmp_path / "base.json"
        findings, _ = conc.analyze_tree(mod)
        conc.write_baseline(base, findings, reason="audited: original")
        # Grow a second violation in another method, regen.
        mod.write_text(textwrap.dedent(TestLockDiscipline.GUARDED.replace(
            "def drop(self):",
            "def also(self):\n"
            "                self._items.pop()\n\n"
            "            def drop(self):")))
        assert conc.main([str(mod), "--baseline", str(base),
                          "--update-baseline"]) == 0
        loaded = conc.load_baseline(base)
        assert len(loaded) == 2
        old_fp = findings[0].fingerprint()
        assert loaded[old_fp] == "audited: original"
        assert conc.main([str(mod), "--baseline", str(base)]) == 0
        # Stale entries prune once the violation is gone.
        mod.write_text(textwrap.dedent(TestLockDiscipline.GUARDED))
        assert conc.main([str(mod), "--baseline", str(base),
                          "--update-baseline"]) == 0
        assert set(conc.load_baseline(base)) == {old_fp}


# ---------------------------------------------------------------------------
# The package tree at HEAD + the CLI verbs
# ---------------------------------------------------------------------------

class TestTreeCleanAtHead:
    def test_package_tree_is_clean(self, fresh_witness):
        report = conc.analyze_concurrency(
            REPO / "tony_tpu",
            baseline_path=REPO / "tests" / "signatures"
            / "concurrency.json")
        assert report.ok, "\n".join(str(f) for f in report.findings)

    def test_summary_banked_in_analysis_report(self, fresh_witness):
        profiler.reset_analysis_records()
        conc.analyze_concurrency(REPO / "tony_tpu")
        rec = profiler.analysis_report()["concurrency"]
        assert rec["findings"] == 0
        profiler.reset_analysis_records()

    def test_make_lint_invocation_is_clean(self, fresh_witness):
        assert conc.main(
            [str(REPO / "tony_tpu"), "--baseline",
             str(REPO / "tests" / "signatures" / "concurrency.json")]
        ) == 0

    def test_tony_analyze_concurrency_verb(self, fresh_witness, capsys):
        from types import SimpleNamespace

        from tony_tpu.analysis import cli as analysis_cli

        rc = analysis_cli.main(SimpleNamespace(
            concurrency=True, signatures=str(REPO / "tests"
                                             / "signatures"),
            update_signatures=False, config=None, json=None, lint=False))
        assert rc == 0
        assert "CLEAN" in capsys.readouterr().out

    def test_concurrency_json_report_written(self, fresh_witness,
                                             tmp_path):
        from types import SimpleNamespace

        from tony_tpu.analysis import cli as analysis_cli

        out = tmp_path / "conc.json"
        rc = analysis_cli.main(SimpleNamespace(
            concurrency=True, signatures=None, update_signatures=False,
            config=None, json=str(out), lint=False))
        assert rc == 0
        data = json.loads(out.read_text())
        assert data["concurrency"]["findings"] == []
        assert "static_edges" in data["concurrency"]

    def test_update_signatures_needs_dir(self):
        from types import SimpleNamespace

        from tony_tpu.analysis import cli as analysis_cli

        rc = analysis_cli.main(SimpleNamespace(
            concurrency=True, signatures=None, update_signatures=True,
            config=None, json=None, lint=False))
        assert rc == 2

    def test_explicit_config_with_concurrency_is_rejected(self, capsys):
        # --concurrency replaces the jaxpr configs; silently skipping a
        # requested one would read as "serve analyzed clean".
        from types import SimpleNamespace

        from tony_tpu.analysis import cli as analysis_cli

        rc = analysis_cli.main(SimpleNamespace(
            concurrency=True, signatures=None, update_signatures=False,
            config="serve", json=None, lint=False))
        assert rc == 2
        assert "INSTEAD" in capsys.readouterr().out

    def test_concurrency_module_is_jax_free(self):
        # Same layering contract as srclint: `make lint` and the
        # gateway-side `tony analyze --concurrency` must not pull jax.
        import subprocess
        import sys

        code = ("import sys; import tony_tpu.analysis.concurrency; "
                "sys.exit(1 if 'jax' in sys.modules else 0)")
        proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                              capture_output=True)
        assert proc.returncode == 0, proc.stderr.decode()


# ---------------------------------------------------------------------------
# Profiler registry
# ---------------------------------------------------------------------------

class TestLockRegistry:
    def test_record_report_reset(self):
        profiler.reset_lock_records()
        profiler.record_locks("t", locks=["a"], edges=[])
        assert profiler.lock_report() == {"t": {"locks": ["a"],
                                                "edges": []}}
        profiler.reset_lock_records()
        assert profiler.lock_report() == {}

    def test_safe_record_routes_locks(self):
        profiler.reset_lock_records()
        profiler.safe_record("locks", "t", locks=["x"], edges=[])
        assert profiler.lock_report()["t"]["locks"] == ["x"]
        profiler.reset_lock_records()


# ---------------------------------------------------------------------------
# The genuinely multi-threaded kvcache interleave (the PR 13 randomized
# stress, now driven from N threads through the lock witness)
# ---------------------------------------------------------------------------

def _cache(n_blocks=16, block_size=4, **kw):
    from tony_tpu.serve import PagedKVCache

    return PagedKVCache(1, 4, n_blocks=n_blocks, block_size=block_size,
                        **kw)


def _keys(tokens, bs=4):
    from tony_tpu.serve import prefix

    return prefix.chain_keys(tokens, bs)


def check_partition(c):
    """THE pool invariant (same as test_route's): free tier + cached
    tier + refcounted ownership partition the block ids, and every
    refcount equals the number of tables holding the block. With the
    PR 16 host tier: host keys are disjoint from the device index (a
    promoted or re-published key leaves the host shadow), the tier
    stays inside its budget, and parked ids never alias live tables."""
    owned = {}
    for t in c.owned_blocks().values():
        for b in t:
            owned[b] = owned.get(b, 0) + 1
    free, lru = set(c._free), set(c.cached_blocks())
    assert not free & lru
    assert not (free | lru) & set(owned)
    assert free | lru | set(owned) == set(range(c.n_blocks))
    assert {b: c.ref(b) for b in owned} == owned
    assert set(c._refs) == set(owned)
    assert not set(c.host_keys()) & set(c._index), \
        "a chain key must live on exactly one tier"
    assert c.host_blocks_used <= max(0, c.host_blocks)
    assert not set(c.parked_ids()) & set(c.owned_blocks()), \
        "a parked id must not alias a live table"


@pytest.mark.slow
class TestThreadedKvcacheInterleave:
    N_THREADS = 4
    ROUNDS = 6
    OPS_PER_ROUND = 24

    def test_concurrent_interleave_partition_pinned(self, fresh_witness):
        """N threads hammer one shared pool with randomized
        admit/fork(shared-prefix)/write(COW)/spec(reserve-commit-
        rollback)/evict — and, PR 16, demote/promote/park/resume
        through the host tier — under the witnessed pool lock; at
        every quiescent point (a barrier each round) the
        refcount/free/LRU/host-tier partition is pinned exactly as the
        single-threaded PR 13 interleave pins it — and the witness
        graph of the run is cycle-free."""
        from tony_tpu.serve import AdmissionError

        c = _cache(n_blocks=16, block_size=4, host_blocks=8)
        pool_lock = conc.Lock("kvcache.pool")
        stats_lock = conc.Lock("kvcache.stats")
        stems = [list(np.random.RandomState(7).randint(0, 50, 8))
                 for _ in range(3)]
        barrier = threading.Barrier(self.N_THREADS + 1)
        errors = []
        stats = {"ops": 0, "admitted": 0}

        def one_op(rng, tid, seqs, parked, sid_n):
            op = rng.choice(["admit", "write", "spec", "free",
                             "handoff", "demote", "promote", "park",
                             "resume"])
            if op == "admit":
                sid = f"t{tid}-s{sid_n[0]}"
                sid_n[0] += 1
                toks = list(stems[rng.randint(3)][:rng.choice([4, 8])]) \
                    + list(rng.randint(0, 50, rng.randint(0, 6)))
                try:
                    c.admit_shared(sid, len(toks) + 4, _keys(toks))
                except AdmissionError:
                    return
                seqs[sid] = toks
                for i, key in enumerate(_keys(toks)):
                    c.publish_block(sid, i, key)
                # Consistent nesting pool -> stats: the witness sees a
                # real cross-lock edge, and it must stay acyclic.
                with stats_lock:
                    stats["admitted"] += 1
            elif op == "write" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                pos = rng.randint(len(c.table(sid)) * c.block_size)
                try:
                    c.write_index(sid, pos)
                except AdmissionError:
                    return
                b = c.table(sid)[pos // c.block_size]
                assert c.ref(b) == 1, \
                    "a write target must be exclusively owned"
            elif op == "spec" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                before = list(c.table(sid))
                extent = len(before) * c.block_size
                try:
                    c.spec_reserve(sid, extent + rng.randint(1, 9))
                except AdmissionError:
                    return
                c.commit(sid, rng.randint(extent + 1))
                c.rollback(sid)
                assert c.table(sid)[:len(before)] == before
            elif op == "free" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                del seqs[sid]
                c.free_seq(sid)
                assert c.free_seq(sid) == 0
            elif op == "handoff" and seqs:
                # The PR 15 wire tier under the same witnessed lock:
                # export a live sequence's prompt blocks and import
                # them as a new sequence — the self-handoff exercises
                # the receiver path (offer-matched adoption + fresh
                # byte writes) exactly as an RPC receiver thread would
                # drive it, and the partition stays pinned.
                from tony_tpu.serve import HandoffError

                src = list(seqs)[rng.randint(len(seqs))]
                toks = seqs[src]
                bs = c.block_size
                exp_len = rng.randint(1, len(toks) + 1)
                blocks = c.export_blocks(src, exp_len)
                keys = _keys(toks)[:exp_len // bs]
                offset = len(c.match_prefix(keys))
                sid = f"t{tid}-h{sid_n[0]}"
                sid_n[0] += 1
                if blocks[offset:] and rng.rand() < 0.25:
                    # Seeded corruption: the import must reject typed
                    # and state-unchanged (the partition check below
                    # pins "unchanged").
                    bad = [dict(b) for b in blocks[offset:]]
                    bad[0]["crc"] ^= 1
                    try:
                        c.import_blocks(sid, exp_len, bad, keys=keys,
                                        offset=offset)
                        raise AssertionError("corrupt import accepted")
                    except HandoffError:
                        return
                try:
                    adopted = c.import_blocks(
                        sid, exp_len + 4, blocks[offset:], keys=keys,
                        offset=offset)
                except AdmissionError:
                    return
                assert adopted == offset
                # Imported bytes are read-only until the engine's write
                # path COWs them: adopted blocks stay referenced (>= 2
                # with a live donor, 1 when revived from the cached
                # tier), fresh imports privately owned — and the write
                # op's exclusivity assert above covers the COW half.
                t_new = c.table(sid)
                for b in t_new[:adopted]:
                    assert c.ref(b) >= 1
                if blocks[offset:]:
                    i = adopted + rng.randint(len(blocks) - offset)
                    want_k, _ = c._decode_block(blocks[i])
                    assert np.array_equal(
                        np.asarray(c.k[:, t_new[i]]), want_k), \
                        "imported block bytes must land verbatim"
                seqs[sid] = list(toks[:exp_len])
            elif op == "demote":
                # PR 16 host tier: cold cached-tier blocks drop to host
                # payloads; the pool partition below pins the books.
                c.demote(rng.randint(1, 4))
            elif op == "promote" and c.host_keys():
                from tony_tpu.serve import HandoffError

                hk = c.host_keys()
                key = hk[rng.randint(len(hk))]
                payload = dict(c._host_index[key])
                # The corruption probe needs a free slot: with the LIFO
                # tier empty promote degrades to 0 BEFORE decoding (by
                # design — it never allocates through LRU eviction), so
                # the poison would go untested and leak to a later op.
                if rng.rand() < 0.25 and c._free:
                    # Seeded host-tier corruption: promote must reject
                    # typed with BOTH tiers unchanged (the partition
                    # check each round pins "unchanged"), and the
                    # poison entry discards cleanly.
                    before_free = list(c._free)
                    c._host_index[key]["crc"] ^= 1
                    try:
                        c.promote([key])
                        raise AssertionError("corrupt promote accepted")
                    except HandoffError:
                        pass
                    assert list(c._free) == before_free
                    assert c.discard_host([key]) == 1
                    return
                if c.promote([key]):
                    b = c._index[key]
                    want_k, want_v = c._decode_block(payload)
                    assert np.array_equal(np.asarray(c.k[:, b]),
                                          want_k) \
                        and np.array_equal(np.asarray(c.v[:, b]),
                                           want_v), \
                        "demoted bytes must promote back verbatim"
            elif op == "park" and seqs:
                sid = list(seqs)[rng.randint(len(seqs))]
                toks = seqs[sid]
                length = rng.randint(1, len(toks) + 1)
                try:
                    c.park(sid, length,
                           keys=_keys(toks)[:length // c.block_size])
                except AdmissionError:
                    return          # host tier full: plain evict path
                del seqs[sid]
                c.free_seq(sid)     # park already freed: idempotent 0
                pid = f"t{tid}-p{sid_n[0]}"
                sid_n[0] += 1
                parked[pid] = (sid, length, list(toks))
            elif op == "resume" and parked:
                from tony_tpu.serve import HandoffError

                pid = list(parked)[rng.randint(len(parked))]
                old_sid, length, toks = parked[pid]
                rec = c._parked[old_sid]
                rec["ready"].wait()
                # The probe must poison a block the resume will DECODE:
                # a stem block still published on device (another
                # thread's copy of the shared stem) is adopted without
                # touching its host payload, so corrupting it proves
                # nothing — match the prefix under the same lock the
                # resume will and corrupt the first decoded block.
                m = len(c.match_prefix(rec["keys"]))
                if rng.rand() < 0.25 and m < len(rec["blocks"]):
                    # Seeded CRC corruption on a parked payload: the
                    # resume must reject typed and state-unchanged —
                    # record intact, pool untouched — then restore.
                    rec["blocks"][m]["crc"] ^= 1
                    try:
                        c.resume(f"t{tid}-x", length + 4, old_sid)
                        raise AssertionError("corrupt resume accepted")
                    except HandoffError:
                        pass
                    assert old_sid in c._parked
                    rec["blocks"][m]["crc"] ^= 1
                    return
                sid = f"t{tid}-r{sid_n[0]}"
                sid_n[0] += 1
                try:
                    c.resume(sid, length + 4, old_sid)
                except AdmissionError:
                    return          # record kept: retryable next round
                del parked[pid]
                seqs[sid] = list(toks[:length])

        def worker(tid):
            rng = np.random.RandomState(100 + tid)
            seqs, parked, sid_n = {}, {}, [0]
            try:
                for _ in range(self.ROUNDS):
                    for _ in range(self.OPS_PER_ROUND):
                        with pool_lock:
                            one_op(rng, tid, seqs, parked, sid_n)
                            stats["ops"] += 1
                    barrier.wait()          # quiescent point reached
                    barrier.wait()          # main finished the check
                with pool_lock:
                    for sid in list(seqs):
                        c.free_seq(sid)
                    for _, (old_sid, _, _) in parked.items():
                        c.unpark(old_sid)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)
                barrier.abort()

        threads = [threading.Thread(target=worker, args=(i,),
                                    name=f"kv-stress-{i}", daemon=True)
                   for i in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for _ in range(self.ROUNDS):
            # A worker failure aborts the barrier: fall through to the
            # error assert below, which names the REAL exception.
            try:
                barrier.wait()
                check_partition(c)          # every quiescent point
                barrier.wait()
            except threading.BrokenBarrierError:
                break
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads)
        check_partition(c)
        assert c.free_blocks == c.n_blocks
        assert c.adopted_total > 0 and c.cow_total > 0, \
            "the interleave must actually exercise sharing and COW"
        assert c.imported_total > 0, \
            "the interleave must actually exercise the handoff wire tier"
        assert c.demoted_total > 0 and c.promoted_total > 0, \
            "the interleave must actually exercise the host tier"
        assert c.parked_total > 0 and c.resumed_total > 0, \
            "the interleave must actually exercise park/resume"
        assert stats["ops"] == self.N_THREADS * self.ROUNDS \
            * self.OPS_PER_ROUND
        # The witness watched the whole run: the pool->stats edge was
        # observed from multiple threads, and the merged order graph is
        # acyclic — a seeded inversion in this same harness IS caught
        # (TestWitness.test_witness_catches_seeded_inversion).
        edges = conc.observed_edges()
        assert [(e["src"], e["dst"]) for e in edges] \
            == [("kvcache.pool", "kvcache.stats")]
        assert len(edges[0]["threads"]) > 1
        assert conc.check_lock_order([]) == []

    def test_seeded_inversion_in_stress_harness_is_named(
            self, fresh_witness):
        """The same two stress locks acquired once in the WRONG order
        (from a thread that nests stats -> pool) turn the previous
        test's clean graph into a named deadlock finding."""
        pool_lock = conc.Lock("kvcache.pool")
        stats_lock = conc.Lock("kvcache.stats")
        with pool_lock:
            with stats_lock:
                pass

        def inverted():
            with stats_lock:
                with pool_lock:
                    pass

        t = threading.Thread(target=inverted, name="kv-inverted")
        t.start()
        t.join()
        findings = conc.check_lock_order([])
        assert len(findings) == 1
        assert findings[0].kind == "inversion"
        assert findings[0].provenance == \
            "kvcache.pool -> kvcache.stats -> kvcache.pool"
