"""End-to-end tests on the MiniPod: real AM thread, real executor processes,
stub python workloads (reference tier: ``TestTonyE2E`` on MiniYARNCluster —
SURVEY.md §4). Every failure semantic is exercised live, not mocked."""

import json
import os
import signal
import time
from pathlib import Path

import pytest


# jaxlib's CPU client gained cross-process collectives after the 0.4 line;
# on older wheels any multi-process GSPMD computation aborts with
# "Multiprocess computations aren't implemented on the CPU backend", so the
# jax-distributed e2e milestones cannot execute regardless of TonY's own
# correctness (the control-plane path they ride is covered by the
# standalone/tf/pytorch e2e tests). Version gate, not a runtime probe: the
# probe would itself need a second process and a jax import.
import jax as _jax

needs_cpu_multiprocess = pytest.mark.skipif(
    _jax.__version_info__ < (0, 5),
    reason="jaxlib CPU backend lacks multi-process computations")

from tony_tpu import constants
from tony_tpu.minipod import MiniPod
from tony_tpu.session import JobStatus, TaskStatus

WORKLOADS = Path(__file__).parent / "workloads"


def wl(name: str) -> str:
    return f"python {name}"


@pytest.fixture
def pod(tmp_path):
    return MiniPod(tmp_path)


def props(**over):
    base = {
        "tony.application.framework": "standalone",
        "tony.application.executes": wl("exit_0.py"),
    }
    base.update({k: str(v) for k, v in over.items()})
    return base


def test_single_task_success(pod):
    job = pod.run(props(**{"tony.worker.instances": "1"}),
                  src_dir=WORKLOADS)
    assert job.exit_code == 0
    assert job.session.job_status is JobStatus.SUCCEEDED
    t = job.session.task("worker", 0)
    assert t.status is TaskStatus.SUCCEEDED and t.exit_code == 0


def test_two_worker_gang_success(pod):
    job = pod.run(props(**{"tony.worker.instances": "2"}),
                  src_dir=WORKLOADS)
    assert job.exit_code == 0
    assert all(t.status is TaskStatus.SUCCEEDED for t in job.session.tasks())


def test_tracked_failure_fails_fast(pod):
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.sleeper.instances": "1",
        "tony.worker.command": wl("exit_1.py"),
        "tony.sleeper.command": wl("forever.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 1
    assert job.session.job_status is JobStatus.FAILED
    assert job.session.task("worker", 0).status is TaskStatus.FAILED
    # The forever-sleeper was torn down, not left running.
    assert job.session.task("sleeper", 0).status is TaskStatus.KILLED
    assert not job.scheduler.running()


def test_untracked_crash_ignored(pod):
    # ps is untracked by default: its crash must not fail the job. The
    # worker sleeps so the ps failure deterministically lands while the job
    # is still running (not during teardown).
    job = pod.run(props(**{
        "tony.application.framework": "tensorflow",
        "tony.worker.instances": "1",
        "tony.worker.command": wl("sleep_exit_0.py"),
        "tony.ps.instances": "1",
        "tony.ps.command": wl("exit_1.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    assert job.session.job_status is JobStatus.SUCCEEDED
    assert job.session.task("ps", 0).status is TaskStatus.FAILED


def test_chief_done_tears_down_workers(pod):
    job = pod.run(props(**{
        "tony.chief.instances": "1",
        "tony.worker.instances": "1",
        "tony.chief.command": wl("exit_0.py"),
        "tony.worker.command": wl("forever.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    assert job.session.job_status is JobStatus.SUCCEEDED
    assert job.session.task("worker", 0).status is TaskStatus.KILLED
    assert not job.scheduler.running()


def test_heartbeat_timeout_marks_lost(pod):
    job = pod.submit(props(**{
        "tony.worker.instances": "1",
        "tony.application.executes": wl("forever.py"),
        "tony.task.max-missed-heartbeats": "4",   # 4 * 200ms = 800ms expiry
    }), src_dir=WORKLOADS)
    # Wait until the task is live, then freeze the whole executor process
    # group: alive but silent -> missed heartbeats -> LOST.
    job.wait_for(lambda: job.session is not None
                 and job.session.task("worker", 0).status is TaskStatus.RUNNING,
                 what="worker running")
    [container] = job.scheduler.running()
    os.killpg(container._proc.pid, signal.SIGSTOP)
    try:
        assert job.wait(timeout=30) == 1
    finally:
        try:
            os.killpg(container._proc.pid, signal.SIGCONT)
        except ProcessLookupError:
            pass
    t = job.session.task("worker", 0)
    assert t.status is TaskStatus.LOST
    assert t.exit_code == constants.EXIT_LOST_TASK
    assert "heartbeat" in job.session.final_message


def test_env_contract_reaches_user_process(pod, tmp_path):
    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("check_env.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    env_files = list(Path(job.am.job_dir).glob("containers/*/src/env.json"))
    assert len(env_files) == 2
    envs = [json.loads(p.read_text()) for p in env_files]
    ranks = sorted(int(e[constants.ENV_PROCESS_ID]) for e in envs)
    assert ranks == [0, 1]
    for e in envs:
        assert e[constants.ENV_NUM_PROCESSES] == "2"
        spec = json.loads(e[constants.ENV_DIST_SPEC])
        assert len(spec["worker"]) == 2
        # Coordinator is worker:0's registered spec for every process.
        assert e[constants.ENV_COORDINATOR_ADDRESS] == spec["worker"][0]


def test_preemption_relaunches_task(pod):
    job = pod.submit(props(**{
        "tony.worker.instances": "2",
        "tony.application.executes": wl("forever.py"),
    }), src_dir=WORKLOADS)
    job.wait_for(lambda: job.session is not None and all(
        t.status is TaskStatus.RUNNING for t in job.session.tasks()),
        what="all running")
    victim = job.session.task("worker", 0)
    assert job.scheduler.preempt(victim.container_id)
    # Task must come back: re-registered and RUNNING again, retry counted.
    # Generous deadline: relaunch = process spawn + re-registration + gang
    # barrier, which under CPU contention (parallel suite runs) can take
    # far longer than the idle-machine norm — the assertion is about the
    # relaunch happening, not how fast.
    job.wait_for(lambda: victim.preemption_retries == 1
                 and victim.status is TaskStatus.RUNNING,
                 timeout=180, what="preempted task relaunched")
    assert job.session.job_status is JobStatus.RUNNING
    job.kill()
    assert job.wait(timeout=120) == 1
    assert job.session.job_status is JobStatus.KILLED


def test_preemption_retries_exhausted_fails(pod):
    job = pod.submit(props(**{
        "tony.worker.instances": "1",
        "tony.application.executes": wl("forever.py"),
        "tony.container.preemption.max-retries": "0",
    }), src_dir=WORKLOADS)
    job.wait_for(lambda: job.session is not None
                 and job.session.task("worker", 0).status is TaskStatus.RUNNING,
                 what="worker running")
    assert job.scheduler.preempt(job.session.task("worker", 0).container_id)
    assert job.wait(timeout=30) == 1
    t = job.session.task("worker", 0)
    assert t.status is TaskStatus.FAILED
    assert t.exit_code == constants.EXIT_PREEMPTED


def test_am_gang_restart_retries_whole_attempt(pod):
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.application.executes": wl("flaky_once.py"),
        "tony.am.retry-count": "1",
    }), src_dir=WORKLOADS)
    # Attempt 1 fails (marker created), attempt 2 succeeds.
    assert job.exit_code == 0
    assert job.session.attempt_id == 2
    assert job.session.job_status is JobStatus.SUCCEEDED


def test_execution_timeout_kills_user_process(pod):
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.application.executes": wl("forever.py"),
        "tony.task.executor.execution-timeout-ms": "500",
    }), src_dir=WORKLOADS)
    assert job.exit_code == 1
    t = job.session.task("worker", 0)
    assert t.status is TaskStatus.FAILED
    assert "timed out" in t.diagnostics


@pytest.mark.slow
def test_wide_gang_e2e(pod):
    """Scale sanity: a 16-task gang (3 jobtypes) through the full
    client→AM→executor path — registration storm, gang barrier, success
    policy over mixed types, event log completeness."""
    job = pod.run(props(**{
        "tony.worker.instances": "12",
        "tony.evaluator.instances": "3",
        "tony.ps.instances": "1",
        "tony.ps.command": wl("sleep_exit_0.py"),
        "tony.application.untracked.jobtypes": "ps",
        "tony.am.gang-allocation-timeout-ms": "120000",
    }), src_dir=WORKLOADS, timeout=240)
    assert job.exit_code == 0
    tasks = list(job.session.tasks())
    assert len(tasks) == 16
    tracked = [t for t in tasks if t.tracked]
    assert len(tracked) == 15
    assert all(t.status is TaskStatus.SUCCEEDED for t in tracked)
    # Every tracked task made it into the finished event log.
    from tony_tpu import events as ev
    [jhist] = (Path(job.am.job_dir) / "history" / "finished").glob("*.jhist")
    finished = {f"{r['payload']['job_type']}:{r['payload']['index']}"
                for r in ev.read_events(jhist)
                if r["type"] == "TASK_FINISHED"}
    assert {t.task_id for t in tracked} <= finished


def test_docker_wrapped_executor_e2e(pod, tmp_path, monkeypatch):
    """tony.docker.enabled wraps every executor launch in `docker run`; a
    fake docker shim on PATH records the invocation and execs the wrapped
    command, so the whole job must still pass through it."""
    shim_dir = tmp_path / "shims"
    shim_dir.mkdir()
    marker = tmp_path / "docker_calls.log"
    shim = shim_dir / "docker"
    shim.write_text(
        "#!/bin/sh\n"
        f"echo \"$@\" >> {marker}\n"
        # Drop everything up to and including the image token (run --rm
        # --network=host -v ... -w ... -e KEY=V ... <image>), then exec
        # the wrapped command on the host.
        "while [ \"$1\" != \"tony-test-img:latest\" ]; do shift; done\n"
        "shift\n"
        "exec \"$@\"\n")
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{shim_dir}:{os.environ['PATH']}")
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.docker.enabled": "true",
        "tony.docker.containers.image": "tony-test-img:latest",
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    calls = marker.read_text().strip().splitlines()
    assert len(calls) == 1
    assert calls[0].startswith("run --rm --network=host -v ")
    assert " tony-test-img:latest " in calls[0]
    assert " -e TONY_AM_ADDRESS=" in calls[0]  # curated env rode -e


def test_security_token_plumbed_end_to_end(pod):
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.security.enabled": "true",
        "tony.application.executes": wl("check_env.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    [env_file] = Path(job.am.job_dir).glob("containers/*/src/env.json")
    env = json.loads(env_file.read_text())
    token = (Path(job.am.job_dir) / "am.token").read_text()
    assert env["TONY_JOB_TOKEN"] == token


def test_custom_credential_provider_e2e(pod, tmp_path, monkeypatch):
    """CredentialProvider SPI (VERDICT r4 missing #1): a CUSTOM provider —
    resolved from tony.security.credential-provider — supplies the RPC
    token AND ships an extra credential into every container's env, and
    the AM's refresh hook rewrites credentials.json on its interval."""
    import sys

    prov_dir = tmp_path / "plugins"
    prov_dir.mkdir()
    (prov_dir / "my_creds.py").write_text(
        "from pathlib import Path\n"
        "from tony_tpu.security import CredentialProvider\n\n"
        "class Provider(CredentialProvider):\n"
        "    name = 'custom'\n"
        "    def acquire(self, conf, job_dir):\n"
        "        return {'token': 'tok-fixed-by-test', 'sesame': 'open'}\n"
        "    def refresh(self, conf, job_dir, current):\n"
        "        n = int(current.get('renewals', '0')) + 1\n"
        "        return dict(current, renewals=str(n))\n"
        "    def executor_env(self, creds):\n"
        "        env = super().executor_env(creds)\n"
        "        env['MY_CREDENTIAL'] = creds['sesame']\n"
        "        return env\n")
    monkeypatch.syspath_prepend(str(prov_dir))
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.security.enabled": "true",
        "tony.security.credential-provider": "my_creds:Provider",
        "tony.security.credential-refresh-interval-ms": "200",
        "tony.application.executes": wl("check_env.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    [env_file] = Path(job.am.job_dir).glob("containers/*/src/env.json")
    env = json.loads(env_file.read_text())
    # The provider's token authenticated the whole RPC path (the job ran),
    # and its extra credential reached the user process.
    assert env["TONY_JOB_TOKEN"] == "tok-fixed-by-test"
    assert env["MY_CREDENTIAL"] == "open"
    from tony_tpu import security
    creds = security.read_credentials(Path(job.am.job_dir))
    assert creds["token"] == "tok-fixed-by-test"
    assert int(creds.get("renewals", "0")) >= 1   # refresh hook fired


@needs_cpu_multiprocess
def test_jax_distributed_dp_training(pod):
    """The SURVEY.md §7 step-5 milestone: `--framework=jax` runs 2-process
    data-parallel training where jax.distributed rendezvous comes from the
    JAXRuntime env and GSPMD psums grads across the processes."""
    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("jax_dp_train.py"),
        "tony.am.gang-allocation-timeout-ms": "120000",
        "tony.task.max-missed-heartbeats": "100",  # slow CPU compile ≫ 200ms
    }), src_dir=WORKLOADS, timeout=240)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    assert job.exit_code == 0
    [result] = Path(job.am.job_dir).glob("containers/*/src/dp_losses.json")
    data = json.loads(result.read_text())
    # Device count = 2 processes × inherited host-device count (the test
    # env's 8-device XLA flag leaks into executors — harmless for DP).
    assert data["num_processes"] == 2
    assert data["num_devices"] >= 2
    assert data["losses"][-1] < data["losses"][0]


@needs_cpu_multiprocess
def test_jax_distributed_expert_parallel_training(pod):
    """Expert parallelism across processes: 2 executors form one ep=2 mesh;
    the MoE dispatch all_to_all crosses the process boundary and the aux
    loss flows back through the train harness."""
    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("jax_ep_train.py"),
        "tony.am.gang-allocation-timeout-ms": "120000",
        "tony.task.max-missed-heartbeats": "100",  # slow CPU compile
    }), src_dir=WORKLOADS, timeout=240)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    assert job.exit_code == 0
    [result] = Path(job.am.job_dir).glob("containers/*/src/ep_losses.json")
    data = json.loads(result.read_text())
    assert data["num_processes"] == 2
    assert data["mesh"]["expert"] == 2
    assert data["losses"][-1] < data["losses"][0]
    assert all(a > 0 for a in data["aux"])


@needs_cpu_multiprocess
def test_jax_distributed_pipeline_parallel_training(pod):
    """Pipeline parallelism across processes: 2 executors form one pp=2
    mesh; the GPipe ppermute ring crosses the process boundary."""
    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("jax_pp_train.py"),
        "tony.am.gang-allocation-timeout-ms": "120000",
        "tony.task.max-missed-heartbeats": "100",  # slow CPU compile
    }), src_dir=WORKLOADS, timeout=240)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    assert job.exit_code == 0
    [result] = Path(job.am.job_dir).glob("containers/*/src/pp_losses.json")
    data = json.loads(result.read_text())
    assert data["num_processes"] == 2
    assert data["mesh"]["pipe"] == 2
    assert data["losses"][-1] < data["losses"][0]


def test_tf_config_contract_e2e(pod):
    """Graduation configs ①/② (SURVEY.md §6): a tensorflow-framework job's
    executors build a correct TF_CONFIG over ps/worker/chief, live."""
    job = pod.run(props(**{
        "tony.application.framework": "tensorflow",
        "tony.chief.instances": "1",
        "tony.worker.instances": "1",
        "tony.ps.instances": "1",
        "tony.application.executes": wl("check_env.py"),
        # Chief-done policy kills peers on chief exit; make the chief wait
        # for the worker's env.json so the assertion below can't race it.
        "tony.chief.command": wl("check_env_wait.py 2"),
        "tony.ps.command": wl("sleep_exit_0.py"),
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    envs = {}
    for p in Path(job.am.job_dir).glob("containers/*/src/env.json"):
        e = json.loads(p.read_text())
        envs[f"{e['TONY_JOB_NAME']}:{e['TONY_TASK_INDEX']}"] = e
    tf_config = json.loads(envs["worker:0"]["TF_CONFIG"])
    assert set(tf_config["cluster"]) == {"chief", "worker", "ps"}
    assert tf_config["task"] == {"type": "worker", "index": 0}
    chief_cfg = json.loads(envs["chief:0"]["TF_CONFIG"])
    assert chief_cfg["task"]["type"] == "chief"
    # All members agree on the cluster map.
    assert chief_cfg["cluster"] == tf_config["cluster"]


@pytest.mark.slow
def test_tf_mwms_real_training_e2e(pod):
    """VERDICT r3 #3 / graduation config ②: REAL tf.distribute training —
    MultiWorkerMirroredStrategy forms its collective ring from the injected
    TF_CONFIG across 2 containers and the loss decreases."""
    job = pod.run(props(**{
        "tony.application.framework": "tensorflow",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("tf_mwms_train.py"),
        "tony.task.max-missed-heartbeats": "200",   # TF import is slow
    }), src_dir=WORKLOADS, timeout=300)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    results = sorted(Path(job.am.job_dir).glob(
        "containers/*/src/tf_rank*.json"))
    assert len(results) == 2
    for p in results:
        data = json.loads(p.read_text())
        assert data["n_workers"] == 2
        assert data["loss_last"] < data["loss_first"] * 0.5


@pytest.mark.slow
def test_tf_ps_strategy_real_training_e2e(pod):
    """VERDICT r3 #3 / graduation config ①: REAL ParameterServerStrategy —
    ps+worker run tf.distribute.Servers, the chief's ClusterCoordinator
    trains through them, chief-done policy ends the job."""
    job = pod.run(props(**{
        "tony.application.framework": "tensorflow",
        "tony.chief.instances": "1",
        "tony.ps.instances": "1",
        "tony.worker.instances": "1",
        "tony.application.executes": wl("tf_ps_train.py"),
        # worker runs a server forever; only the chief's exit decides.
        "tony.application.untracked.jobtypes": "ps,worker",
        "tony.task.max-missed-heartbeats": "200",
    }), src_dir=WORKLOADS, timeout=300)
    assert job.exit_code == 0, job.session.final_message
    assert job.session.task("chief", 0).status is TaskStatus.SUCCEEDED
    [result] = Path(job.am.job_dir).glob(
        "containers/*/src/tf_ps_result.json")
    data = json.loads(result.read_text())
    assert data["loss_last"] < data["loss_first"] * 0.5


@pytest.mark.slow
def test_pytorch_ddp_example_e2e(pod):
    """Graduation config ③: real torch.distributed DDP (gloo) across two
    MiniPod containers via the PyTorchRuntime env — the example itself is
    the workload."""
    examples = Path(__file__).parent.parent / "examples"
    job = pod.run(props(**{
        "tony.application.framework": "pytorch",
        "tony.worker.instances": "2",
        "tony.application.executes": "python pytorch_mnist_ddp.py",
        "tony.task.max-missed-heartbeats": "100",
    }), src_dir=examples, timeout=240)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    [result] = Path(job.am.job_dir).glob("containers/*/src/result.json")
    data = json.loads(result.read_text())
    assert data["world_size"] == 2


@needs_cpu_multiprocess
def test_horovod_on_ici_psum_e2e(pod):
    """Graduation config ④: HOROVOD_* contract + XLA cross-process reduce
    as the NCCL→ICI replacement, 2 live processes."""
    job = pod.run(props(**{
        "tony.application.framework": "horovod",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("hvd_psum.py"),
        "tony.task.max-missed-heartbeats": "100",
    }), src_dir=WORKLOADS, timeout=240)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    results = sorted(Path(job.am.job_dir).glob(
        "containers/*/src/hvd_rank*.json"))
    assert len(results) == 2
    for p in results:
        data = json.loads(p.read_text())
        assert data["size"] == 2
        # Independent check of the cross-process reduce: sum over ranks of
        # rank * local_device_count (the test env leaks an 8-device flag
        # into executors, so derive n_local from the result itself).
        n_local = data["allreduce"]  # == 0*n + 1*n == n for 2 ranks
        assert n_local > 0
        assert data["allreduce"] == sum(
            r * n_local for r in range(data["size"]))


def test_events_written_and_finalized(pod):
    from tony_tpu import events as ev
    job = pod.run(props(**{"tony.worker.instances": "1"}), src_dir=WORKLOADS)
    history = Path(job.am.job_dir) / "history"
    finished = list((history / "finished").glob("*.jhist"))
    assert len(finished) == 1
    records = ev.read_events(finished[0])
    types = [r["type"] for r in records]
    assert types[0] == "METADATA"
    assert "APPLICATION_INITED" in types
    assert "TASK_STARTED" in types
    assert "TASK_FINISHED" in types
    assert types[-1] == "APPLICATION_FINISHED"
    assert records[-1]["payload"]["status"] == "SUCCEEDED"
    meta = ev.job_metadata(finished[0])
    assert meta["app_id"] == job.am.app_id


# ---------------------------------------------------------------------------
# TPU-VM substrate e2e: the multi-host scheduler driven through a fake-ssh
# shim (a local script standing in for `ssh host cmd`), so the full
# gang/placement/preemption/kill matrix runs against the remote code path —
# staging pipeline, setsid+pidfile lifecycle, remote process-group kill —
# without a pod (SURVEY.md §4: multi-node without a real cluster).
# ---------------------------------------------------------------------------

import subprocess
import sys

from tony_tpu.util import PKG_ROOT


class TpuVmHarness:
    """Builds tpu-vm-backend jobs over a fake ssh shim in a temp dir."""

    def __init__(self, tmp_path):
        self.fake = tmp_path / "fakessh.sh"
        self.fake.write_text('#!/bin/sh\nshift\nexec sh -c "$*"\n')
        self.fake.chmod(0o755)
        self.remote = tmp_path / "remote"
        self.pod = MiniPod(tmp_path)

    def props(self, **over):
        base = {
            "tony.application.framework": "standalone",
            "tony.application.executes": wl("exit_0.py"),
            "tony.scheduler.backend": "tpu-vm",
            "tony.scheduler.hosts": "127.0.0.1,localhost",
            "tony.scheduler.ssh-command": str(self.fake),
            "tony.scheduler.remote-python": sys.executable,
            "tony.scheduler.remote-workdir": str(self.remote),
            "tony.scheduler.remote-pythonpath": PKG_ROOT,
        }
        base.update({k: str(v) for k, v in over.items()})
        return base

    def orphaned_executors(self):
        """Processes whose cwd is the 'remote' workdir — anything here
        after a job ended is a leaked remote process."""
        out = []
        for pid_dir in Path("/proc").glob("[0-9]*"):
            try:
                if os.readlink(pid_dir / "cwd") == str(self.remote):
                    out.append(int(pid_dir.name))
            except OSError:
                continue
        return out


@pytest.fixture
def tpuvm(tmp_path):
    return TpuVmHarness(tmp_path)


def test_tpuvm_gang_placement_respects_host_chips(tpuvm):
    """Two 4-chip tasks on two 4-chip hosts must land one per host (the
    r2 round-robin ignored capacity); both see the staged src and succeed."""
    job = tpuvm.pod.run(tpuvm.props(**{
        "tony.worker.instances": "2",
        "tony.worker.tpus": "4",
        "tony.scheduler.host-tpus": "4",
    }), src_dir=WORKLOADS, timeout=120)
    assert job.exit_code == 0, job.session.final_message
    assert all(t.status is TaskStatus.SUCCEEDED for t in job.session.tasks())
    # Placement used both hosts (a single host cannot carry 8 chips).
    sched = job.scheduler
    assert set(sched._host_tasks) == {"127.0.0.1", "localhost"}
    assert all(v == 0 for v in sched._host_chips.values())  # all freed
    assert (tpuvm.remote / "src" / "exit_0.py").is_file()
    assert not tpuvm.orphaned_executors()


def test_tpuvm_oversubscribed_chips_fails_loudly(tpuvm):
    """Three 4-chip tasks on two 4-chip hosts: unsatisfiable, and the AM
    fails the job instead of crashing."""
    job = tpuvm.pod.run(tpuvm.props(**{
        "tony.worker.instances": "3",
        "tony.worker.tpus": "4",
        "tony.scheduler.host-tpus": "4",
    }), src_dir=WORKLOADS, timeout=120)
    assert job.exit_code == 1
    assert job.session.job_status is JobStatus.FAILED
    assert "launch failed" in " ".join(
        t.diagnostics or "" for t in job.session.tasks())


def test_tpuvm_preemption_relaunches_via_remote_kill(tpuvm):
    """Preempt reaches the remote process group through the pidfile; the
    AM re-requests and the task comes back RUNNING."""
    job = tpuvm.pod.submit(tpuvm.props(**{
        "tony.worker.instances": "2",
        "tony.application.executes": wl("forever.py"),
    }), src_dir=WORKLOADS)
    job.wait_for(lambda: job.session is not None and all(
        t.status is TaskStatus.RUNNING for t in job.session.tasks()),
        timeout=60, what="all running on tpu-vm substrate")
    victim = job.session.task("worker", 0)
    assert job.scheduler.preempt(victim.container_id)
    job.wait_for(lambda: victim.preemption_retries == 1
                 and victim.status is TaskStatus.RUNNING,
                 timeout=60, what="preempted task relaunched")
    job.kill()
    assert job.wait(timeout=60) == 1
    assert job.session.job_status is JobStatus.KILLED
    job.wait_for(lambda: not tpuvm.orphaned_executors(), timeout=30,
                 what="no orphaned remote processes after kill")
    assert not list((tpuvm.remote / "pids").glob("*.pid"))


def test_tpuvm_kill_leaves_no_orphans(tpuvm):
    """Tearing down forever-running tasks must reap executor AND user
    process on the 'remote' side — the r2 substrate only killed the local
    ssh client."""
    job = tpuvm.pod.submit(tpuvm.props(**{
        "tony.worker.instances": "2",
        "tony.application.executes": wl("forever.py"),
    }), src_dir=WORKLOADS)
    job.wait_for(lambda: job.session is not None and all(
        t.status is TaskStatus.RUNNING for t in job.session.tasks()),
        timeout=60, what="all running")
    assert tpuvm.orphaned_executors()   # running tasks live in the workdir
    job.kill()
    assert job.wait(timeout=60) == 1
    job.wait_for(lambda: not tpuvm.orphaned_executors(), timeout=30,
                 what="remote processes reaped")


def test_tpuvm_venv_staged_and_activated(tpuvm, tmp_path):
    """--python_venv on the tpu-vm path: the venv dir is staged to the
    worker and activated for the user process (ADVICE r2: it was silently
    dropped)."""
    venv = tmp_path / "myvenv"
    (venv / "bin").mkdir(parents=True)
    (venv / "bin" / "tony-venv-marker").write_text("#!/bin/sh")
    (venv / "bin" / "tony-venv-marker").chmod(0o755)
    job = tpuvm.pod.run(tpuvm.props(**{
        "tony.worker.instances": "1",
        "tony.application.executes": wl("check_venv.py"),
        "tony.application.python-venv": str(venv),
    }), src_dir=WORKLOADS, timeout=120)
    assert job.exit_code == 0, job.session.final_message
    assert (tpuvm.remote / "venv-stage" / "bin" / "tony-venv-marker").is_file()


def test_tpuvm_staging_failure_fails_job_not_am(tpuvm):
    """A broken transfer pipeline (ssh that always fails) must fail the
    job with a staging diagnostic — not hang the gang or crash the AM
    (ADVICE r2: failures were check=False-swallowed)."""
    tpuvm.fake.write_text("#!/bin/sh\nexit 42\n")
    job = tpuvm.pod.run(tpuvm.props(**{
        "tony.worker.instances": "1",
    }), src_dir=WORKLOADS, timeout=120)
    assert job.exit_code == 1
    assert job.session.job_status is JobStatus.FAILED
    diags = " ".join(t.diagnostics or "" for t in job.session.tasks())
    assert "staging" in diags and "failed" in diags


def test_tpuvm_concurrent_gang_stages_each_host_once(tpuvm):
    """The AM launches gangs concurrently (r4) and staging serializes PER
    HOST: 4 workers on 2 hosts must stage conf+src exactly once per host —
    no double transfers, no torn trees."""
    log = tpuvm.fake.parent / "ssh_calls.log"
    tpuvm.fake.write_text(
        "#!/bin/sh\n"
        f"echo \"$@\" >> {log}\n"
        'shift\nexec sh -c "$*"\n')
    job = tpuvm.pod.run(tpuvm.props(**{
        "tony.worker.instances": "4",
    }), src_dir=WORKLOADS, timeout=120)
    assert job.exit_code == 0, job.session.final_message
    calls = log.read_text().splitlines()
    # Staging commands carry 'tar -xf' on the remote side; one conf + one
    # src transfer per distinct host.
    stage_calls = [c for c in calls if "tar -xf" in c]
    per_host = {}
    for c in stage_calls:
        host = c.split()[0]
        per_host[host] = per_host.get(host, 0) + 1
    assert set(per_host) == {"127.0.0.1", "localhost"}, per_host
    assert all(v == 2 for v in per_host.values()), per_host  # conf + src


@needs_cpu_multiprocess
def test_tpuvm_jax_distributed_dp_training(tpuvm):
    """VERDICT r3 #4: the closest this environment gets to the v4-32 story —
    two 'hosts' behind the SSH substrate run REAL jax.distributed DP
    training end to end: tar-over-ssh staging, remote env rewrite, the
    jax coordinator formed across 'hosts', GSPMD grad psum, and a clean
    remote teardown with zero orphans."""
    job = tpuvm.pod.run(tpuvm.props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "2",
        "tony.application.executes": wl("jax_dp_train.py"),
        "tony.am.gang-allocation-timeout-ms": "120000",
        "tony.task.max-missed-heartbeats": "100",  # slow CPU compile
    }), src_dir=WORKLOADS, timeout=240)
    for t in job.session.tasks():
        assert t.status is TaskStatus.SUCCEEDED, (t.task_id, t.diagnostics)
    assert job.exit_code == 0
    # Placement spanned both 'hosts' (the coordinator crossed the
    # substrate): the REGISTERED executor hosts, not the scheduler's
    # pre-populated host table.
    assert {t.host for t in job.session.tasks()} == \
        {"127.0.0.1", "localhost"}
    data = json.loads((tpuvm.remote / "src" / "dp_losses.json").read_text())
    assert data["num_processes"] == 2
    assert data["losses"][-1] < data["losses"][0]
    assert not tpuvm.orphaned_executors()
    assert not list((tpuvm.remote / "pids").glob("*.pid"))


def test_metrics_timeline_and_latency_events(pod, monkeypatch):
    """VERDICT r2 #5/#8: TaskMonitor samples must survive as a TASK_METRICS
    timeline in the jhist (not just the final snapshot), and the gang
    barrier must record the submit→all-RUNNING latency."""
    import time as _time

    from tony_tpu import events as ev
    from tony_tpu.history import job_detail, _job_page

    monkeypatch.setenv(constants.ENV_SUBMIT_TS, repr(_time.time()))
    job = pod.run(props(**{
        "tony.worker.instances": "1",
        "tony.application.executes": wl("sleep_exit_0.py"),
        "tony.task.metrics-interval-ms": "150",
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    # In-session timeline: multiple bounded samples, monotone timestamps.
    t = job.session.task("worker", 0)
    assert len(t.metrics_history) >= 2
    assert t.metrics_history == sorted(t.metrics_history,
                                       key=lambda s: s["ts"])
    assert job.session.all_running_latency_s is not None
    assert 0 < job.session.all_running_latency_s < 60
    # jhist timeline + latency event.
    [jhist] = (Path(job.am.job_dir) / "history" / "finished").glob("*.jhist")
    records = ev.read_events(jhist)
    samples = [r for r in records if r["type"] == ev.TASK_METRICS]
    assert len(samples) >= 2
    assert all(r["payload"]["job_type"] == "worker" for r in samples)
    assert "rss_mb" in samples[0]["payload"]["metrics"] or \
        samples[0]["payload"]["metrics"]  # at least one metric key
    [running] = [r for r in records if r["type"] == ev.ALL_TASKS_RUNNING]
    assert running["payload"]["submit_to_running_s"] > 0
    # Portal render: the job page shows the per-task history, not one row.
    detail = job_detail({"app_id": job.am.app_id, "state": "finished",
                         "path": str(jhist), "metadata": {}})
    assert len(detail["metrics_timelines"]["worker:0"]) >= 2
    page = _job_page(detail)
    assert "Metrics timeline" in page and "samples" in page
    assert "submit→all-running" in page


def test_callback_info_dispatched_to_am(pod):
    """VERDICT r2 #7: registerCallbackInfo must reach the AM (dead SPI in
    r2). The JAX runtime's consumer: executors push their bound profiler
    endpoint."""
    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "1",
        "tony.application.executes": wl("sleep_exit_0.py"),
        "tony.task.profiler.enabled": "true",
    }), src_dir=WORKLOADS)
    assert job.exit_code == 0
    info = job.session.task_callback_info
    assert "worker:0" in info
    payload = json.loads(info["worker:0"])
    # Executor-reserved ephemeral port (fixed base+rank collided across
    # overlapping jobs on one host).
    host, _, port = payload["profiler"].rpartition(":")
    assert host and 1024 < int(port) < 65536


@pytest.mark.slow
def test_profiler_trace_collection(pod):
    """VERDICT r3 #5: the collection half of SURVEY §5.1 — the AM fetches a
    real trace from each rank's profiler endpoint into the history dir,
    and the portal lists it."""
    from tony_tpu.history import job_detail, render_show, _job_page
    from tony_tpu.profiler import list_traces

    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "1",
        "tony.application.executes": wl("profiled_train.py"),
        "tony.task.profiler.enabled": "true",
        "tony.task.profiler.collect-after-s": "0.5",
        "tony.task.profiler.collect-duration-ms": "1000",
    }), src_dir=WORKLOADS, timeout=180)
    assert job.exit_code == 0, job.session.final_message
    history = Path(job.am.job_dir) / "history"
    traces = list_traces(history, job.am.app_id)
    assert "worker_0" in traces, f"no trace collected: {traces}"
    assert any(f["bytes"] > 0 and str(f["file"]).endswith(".xplane.pb")
               for f in traces["worker_0"]), traces["worker_0"]
    # Portal surfaces: the show page and the HTML job page list the trace.
    [jhist] = (history / "finished").glob("*.jhist")
    detail = job_detail({"app_id": job.am.app_id, "state": "finished",
                         "path": str(jhist), "metadata": {}})
    assert detail["traces"] == traces
    assert "traces:" in render_show(detail)
    assert "Profiler traces" in _job_page(detail)


@pytest.mark.slow
def test_checkpoint_resume_across_gang_restart(pod, tmp_path):
    """The reference's whole recovery story (SURVEY.md §5.4): attempt 1
    trains and checkpoints, dies; the gang restarts; attempt 2 restores
    from the Checkpointer and continues from the saved step."""
    ckpt_dir = tmp_path / "ckpt"
    job = pod.run(props(**{
        "tony.application.framework": "jax",
        "tony.worker.instances": "1",
        "tony.application.executes": wl("train_resume.py"),
        "tony.worker.env": f"CKPT_DIR={ckpt_dir}",
        "tony.am.retry-count": "1",
        "tony.task.max-missed-heartbeats": "100",
    }), src_dir=WORKLOADS, timeout=180)
    assert job.exit_code == 0, job.session.final_message
    assert job.session.attempt_id == 2      # attempt 1 failed, 2 resumed
    results = list(Path(job.am.job_dir).glob("containers/*/src/resume.json"))
    assert len(results) == 1                # only attempt 2 wrote it
    data = json.loads(results[0].read_text())
    assert data["resumed_from"] == 3
    assert data["final_step"] == 5


def test_tpuvm_resources_and_subdivision_env(tpuvm, tmp_path):
    """Remote-substrate passthroughs, live over fake-ssh: (a) a
    tony.containers.resources file staged by the CLIENT reaches the
    remote container cwd via the {wd}/resources rewrite; (b) two jax
    workers subdividing one host emit the full libtpu process-grid env
    (the contract pinned by unit tests, here proven end-to-end)."""
    import io

    from tony_tpu.client import TonyClient
    from tony_tpu.conf import TonyConfig

    data = tmp_path / "lookup.txt"
    data.write_text("resource-bytes\n")
    props = tpuvm.props(**{
        "tony.application.framework": "jax",
        "tony.application.executes": "python check_env_indexed.py",
        "tony.worker.instances": "2",
        "tony.worker.tpus": "2",
        "tony.scheduler.hosts": "127.0.0.1",
        "tony.scheduler.host-tpus": "4",
        "tony.scheduler.total-tpus": "4",
        "tony.containers.resources": str(data),
        "tony.task.heartbeat-interval-ms": "200",
    })
    client = TonyClient(TonyConfig(props), src_dir=WORKLOADS,
                        workdir=tmp_path / "jobs", stream=io.StringIO())
    assert client.run(timeout=120) == 0
    # (a) the resource landed next to the remote src copy.
    assert (tpuvm.remote / "resources" / "lookup.txt").is_file()
    assert (tpuvm.remote / "src" / "lookup.txt").read_text() \
        == "resource-bytes\n"
    # (b) both tasks saw the uniform-subdivision libtpu env.
    for idx in (0, 1):
        env = json.loads((tpuvm.remote / "src" / f"env.{idx}.json")
                         .read_text())
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
        assert env["TPU_PROCESS_BOUNDS"] == "2,1,1"
        assert env["CLOUD_TPU_TASK_ID"] == str(idx)
        assert env["TPU_PROCESS_PORT"] == str(8476 + idx)
        assert env["TPU_PROCESS_ADDRESSES"] == \
            "127.0.0.1:8476,127.0.0.1:8477"
        assert env["TONY_RESOURCES_DIR"].endswith("/resources")
