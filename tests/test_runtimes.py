"""Runtime-adapter unit tests: buildTaskEnv output given a fake cluster spec
(reference tier: TestHorovodRuntime etc., SURVEY.md §4)."""

import json

import pytest

from tony_tpu import constants
from tony_tpu.conf import TonyConfig
from tony_tpu.runtime import TaskContext, get_framework
from tony_tpu.runtime.horovod_driver import HorovodDriver, compute_slots, fetch_slots
from tony_tpu.runtime.horovod_runtime import CALLBACK_RENDEZVOUS_ADDR

SPEC = {
    "chief": ["h0:4000"],
    "worker": ["h0:4001", "h1:4002", "h1:4003"],
}


def ctx_for(framework, job_type, index, spec=None, conf_extra=None, callback=None):
    props = {"tony.chief.instances": "1", "tony.worker.instances": "3",
             "tony.application.framework": framework}
    props.update(conf_extra or {})
    return TaskContext(
        conf=TonyConfig(props), job_type=job_type, index=index,
        cluster_spec=spec or SPEC, am_address="am:9000",
        app_id="app_1_0001", callback_info=callback or {})


def test_common_env():
    env = get_framework("standalone").task_adapter().build_task_env(
        ctx_for("standalone", "worker", 1))
    assert env[constants.ENV_JOB_TYPE] == "worker"
    assert env[constants.ENV_TASK_INDEX_USER] == "1"
    assert env[constants.ENV_TASK_NUM] == "4"
    assert json.loads(env[constants.ENV_DIST_SPEC]) == SPEC
    assert env[constants.ENV_AM_ADDRESS] == "am:9000"


def test_tf_config():
    env = get_framework("tensorflow").task_adapter().build_task_env(
        ctx_for("tensorflow", "worker", 2))
    tf_config = json.loads(env[constants.ENV_TF_CONFIG])
    assert tf_config["cluster"] == SPEC
    assert tf_config["task"] == {"type": "worker", "index": 2}


def test_tf_config_excludes_sidecars():
    spec = dict(SPEC, tensorboard=["h9:5000"])
    env = get_framework("tensorflow").task_adapter().build_task_env(
        ctx_for("tensorflow", "chief", 0, spec=spec,
                conf_extra={"tony.tensorboard.instances": "1"}))
    assert "tensorboard" not in json.loads(env[constants.ENV_TF_CONFIG])["cluster"]


def test_pytorch_ddp_env():
    env = get_framework("pytorch").task_adapter().build_task_env(
        ctx_for("pytorch", "worker", 1))
    # Coordinator is global rank 0 = chief:0.
    assert env[constants.ENV_MASTER_ADDR] == "h0"
    assert env[constants.ENV_MASTER_PORT] == "4000"
    assert env[constants.ENV_WORLD_SIZE] == "4"
    assert env[constants.ENV_RANK] == "2"          # chief=0, worker0=1, worker1=2
    assert env[constants.ENV_LOCAL_RANK] == "0"    # first task on h1
    assert env[constants.ENV_INIT_METHOD] == "tcp://h0:4000"


def test_jax_coordinator_env():
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0))
    assert env[constants.ENV_COORDINATOR_ADDRESS] == "h0:4000"
    assert env[constants.ENV_PROCESS_ID] == "1"
    assert env[constants.ENV_NUM_PROCESSES] == "4"
    # libtpu contract: worker id is the PER-HOST id, hostnames one per HOST.
    assert env[constants.ENV_TPU_WORKER_ID] == "0"      # worker:0 is on h0
    assert env[constants.ENV_TPU_WORKER_HOSTNAMES] == "h0,h1"


def test_jax_chip_pinning():
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 2, conf_extra={"tony.worker.tpus": "2"}))
    # worker:2 is the second task on h1 -> local_rank 1 -> chips 2,3
    assert env[constants.ENV_TPU_VISIBLE_DEVICES] == "2,3"


def test_jax_host_subdivision_contract():
    """The documented libtpu env for tasks subdividing a host, with the
    expected values WRITTEN DOWN (VERDICT r4 weak #3: this contract is
    untestable on a 1-chip host, so the emitted values are pinned here).

    Topology: chief+worker0 share h0, worker1+worker2 share h1; every task
    asks tpus=2, so each host contributes 4 chips in a 2x2 grid, split
    into two 1x2 processes."""
    conf_extra = {"tony.chief.tpus": "2", "tony.worker.tpus": "2"}
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 2, conf_extra=conf_extra))
    assert env[constants.ENV_TPU_WORKER_ID] == "1"          # host h1
    assert env[constants.ENV_TPU_WORKER_HOSTNAMES] == "h0,h1"
    assert env[constants.ENV_TPU_CHIPS_PER_PROCESS_BOUNDS] == "1,2,1"
    # 2x2 host grid / 1x2 per-process grid = 2x1 processes, on 2 hosts.
    assert env[constants.ENV_TPU_PROCESS_BOUNDS] == "2,1,2"
    assert env[constants.ENV_TPU_PROCESS_ADDRESSES] == \
        "h0:8476,h0:8477,h1:8478,h1:8479"
    assert env[constants.ENV_TPU_PROCESS_PORT] == "8479"    # base + rank 3
    assert env[constants.ENV_CLOUD_TPU_TASK_ID] == "3"
    assert env[constants.ENV_TPU_VISIBLE_DEVICES] == "2,3"


def test_jax_subdivision_env_absent_when_not_subdividing():
    # One task per host: the process-grid env must NOT be emitted (libtpu
    # then derives the topology from worker id/hostnames alone).
    spec = {"worker": ["h0:4000", "h1:4001"]}
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 1, spec=spec,
                conf_extra={"tony.worker.instances": "2",
                            "tony.chief.instances": "0",
                            "tony.worker.tpus": "4"}))
    assert constants.ENV_TPU_PROCESS_BOUNDS not in env
    assert constants.ENV_TPU_PROCESS_ADDRESSES not in env
    assert env[constants.ENV_TPU_WORKER_ID] == "1"


def test_jax_uneven_host_packing_withholds_bounds_everywhere():
    """Hosts with unequal task counts have no rectangular process grid;
    EVERY task must withhold the grid env (an inconsistent emit would hang
    libtpu init) — including tasks on the crowded host."""
    spec = {"worker": ["h0:4000", "h0:4001", "h1:4002"]}
    conf_extra = {"tony.worker.instances": "3", "tony.chief.instances": "0",
                  "tony.worker.tpus": "2"}
    for idx in (0, 1, 2):
        env = get_framework("jax").task_adapter().build_task_env(
            ctx_for("jax", "worker", idx, spec=spec, conf_extra=conf_extra))
        assert constants.ENV_TPU_PROCESS_BOUNDS not in env, idx
        assert constants.ENV_TPU_PROCESS_ADDRESSES not in env, idx


def test_jax_mixed_tpus_cohort_gets_pinning_but_no_bounds():
    # A mixed-tpus cohort has no legal rectangular encoding: chip pinning
    # still works, the process-grid env must be withheld.
    conf_extra = {"tony.chief.tpus": "4", "tony.worker.tpus": "2"}
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0, conf_extra=conf_extra))
    assert env[constants.ENV_TPU_VISIBLE_DEVICES] == "4,5"
    assert constants.ENV_TPU_PROCESS_BOUNDS not in env


def test_jax_injects_overlap_xla_flags_for_tpu_tasks():
    """TPU-resourced jax tasks get the comm/compute-overlap compiler knobs
    (latency-hiding scheduler + async collective fusion) by default."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0,
                conf_extra={"tony.worker.tpus": "2"}))
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
        in env[constants.ENV_XLA_FLAGS]
    assert "--xla_tpu_enable_async_collective_fusion=true" \
        in env[constants.ENV_XLA_FLAGS]


def test_jax_no_overlap_flags_without_tpus():
    """Non-TPU tasks must NOT get the xla_tpu_* set: XLA aborts the
    process on flags its build doesn't know (measured on the CPU wheel),
    so default-injecting would kill every CPU-backend job."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0))
    assert constants.ENV_XLA_FLAGS not in env


def test_jax_overlap_flags_forced_on_by_conf():
    """Whole-host TPU jobs don't set tony.<jobtype>.tpus; explicit conf
    true forces injection."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0,
                conf_extra={"tony.jax.overlap-xla-flags": "true"}))
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
        in env[constants.ENV_XLA_FLAGS]


def test_jax_overlap_flags_user_value_wins():
    """A flag the user set via tony.<jobtype>.env keeps ITS value; only
    missing flags are appended."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0, conf_extra={
            "tony.worker.tpus": "2",
            "tony.worker.env":
                "XLA_FLAGS=--xla_tpu_enable_latency_hiding_scheduler"
                "=false"}))
    flags = env[constants.ENV_XLA_FLAGS]
    assert "--xla_tpu_enable_latency_hiding_scheduler=false" in flags
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" not in flags
    assert "--xla_tpu_overlap_compute_collective_tc=true" in flags


def test_jax_overlap_flags_conf_gated_off():
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0,
                conf_extra={"tony.worker.tpus": "2",
                            "tony.jax.overlap-xla-flags": "false"}))
    assert constants.ENV_XLA_FLAGS not in env


def test_jax_ckpt_env_exported_from_conf():
    """tony.ckpt.dir/every/keep reach the user process as TONY_CKPT_* —
    train_loop's defaults — with every/keep defaulted when unset; no
    ckpt env at all when the dir isn't configured."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0,
                conf_extra={"tony.ckpt.dir": "/mnt/durable/ckpt",
                            "tony.ckpt.every": "50"}))
    assert env[constants.ENV_CKPT_DIR] == "/mnt/durable/ckpt"
    assert env[constants.ENV_CKPT_EVERY] == "50"
    assert env[constants.ENV_CKPT_KEEP] == "3"
    bare = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0))
    assert constants.ENV_CKPT_DIR not in bare


def test_jax_data_seed_env_exported_from_conf():
    """tony.data.seed reaches the user process as TONY_DATA_SEED (the
    Dataset default seed — the whole gang, and every restart of it, must
    derive the identical example stream); absent when unset."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0,
                conf_extra={"tony.data.seed": "1234"}))
    assert env[constants.ENV_DATA_SEED] == "1234"
    bare = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0))
    assert constants.ENV_DATA_SEED not in bare


def test_jax_ckpt_env_not_exported_to_sidecars():
    """Sidecars are outside the SPMD world: they must not inherit the
    checkpoint wiring (a tensorboard task scanning/saving into the train
    job's directory would be wrong in both directions)."""
    spec = dict(SPEC, tensorboard=["h9:5000"])
    env = get_framework("jax").task_adapter().framework_env(
        ctx_for("jax", "tensorboard", 0, spec=spec,
                conf_extra={"tony.tensorboard.instances": "1",
                            "tony.ckpt.dir": "/mnt/durable/ckpt"}))
    assert constants.ENV_CKPT_DIR not in env


def test_jax_sidecar_gets_no_overlap_flags():
    spec = dict(SPEC, tensorboard=["h9:5000"])
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "tensorboard", 0, spec=spec,
                conf_extra={"tony.tensorboard.instances": "1"}))
    assert constants.ENV_XLA_FLAGS not in env


def test_jax_rejects_ps():
    fw = get_framework("jax")
    conf = TonyConfig({"tony.ps.instances": "2", "tony.worker.instances": "2"})
    with pytest.raises(ValueError, match="SPMD"):
        fw.am_adapter().validate_and_update_config(conf)


def test_jax_multislice_megascale_env():
    """tony.jax.slices>1 splits the rendezvous world into contiguous
    equal slices and exports the megascale DCN coordination env: slice id
    from global rank, coordinator on the rank-0 host, conf-keyed port."""
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 1,           # global rank 2 → slice 1
                conf_extra={"tony.jax.slices": "2"}))
    assert env[constants.ENV_MEGASCALE_NUM_SLICES] == "2"
    assert env[constants.ENV_MEGASCALE_SLICE_ID] == "1"
    assert env[constants.ENV_MEGASCALE_COORDINATOR_ADDRESS] == "h0:8537"
    assert env[constants.ENV_MEGASCALE_PORT] == "8537"
    # Slice 0 (global rank 0 = chief).
    env0 = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "chief", 0, conf_extra={"tony.jax.slices": "2"}))
    assert env0[constants.ENV_MEGASCALE_SLICE_ID] == "0"


def test_jax_single_slice_no_megascale_env():
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0))
    assert constants.ENV_MEGASCALE_NUM_SLICES not in env
    assert constants.ENV_MEGASCALE_COORDINATOR_ADDRESS not in env


def test_jax_multislice_adds_dcn_xla_flags():
    """Multi-slice TPU tasks get the DCN overlap flag set on top of the
    single-slice overlap knobs; single-slice tasks must not (fewer flags
    = fewer compiler-version hazards)."""
    multi = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0,
                conf_extra={"tony.worker.tpus": "2",
                            "tony.jax.slices": "2"}))
    assert "--xla_tpu_data_parallel_opt_different_sized_ops=true" \
        in multi[constants.ENV_XLA_FLAGS]
    assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
        in multi[constants.ENV_XLA_FLAGS]
    single = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0, conf_extra={"tony.worker.tpus": "2"}))
    assert "--xla_tpu_data_parallel_opt_different_sized_ops" \
        not in single[constants.ENV_XLA_FLAGS]


def test_jax_slices_must_divide_world():
    fw = get_framework("jax")
    conf = TonyConfig({"tony.chief.instances": "1",
                       "tony.worker.instances": "2",
                       "tony.application.framework": "jax",
                       "tony.jax.slices": "2"})
    with pytest.raises(ValueError, match="slices"):
        fw.am_adapter().validate_and_update_config(conf)
    # Sidecars don't count toward the sliced world.
    ok = TonyConfig({"tony.worker.instances": "4",
                     "tony.tensorboard.instances": "1",
                     "tony.application.framework": "jax",
                     "tony.jax.slices": "2"})
    fw.am_adapter().validate_and_update_config(ok)


def test_mxnet_env():
    spec = {"scheduler": ["h0:9100"], "server": ["h0:9101"],
            "worker": ["h1:9102", "h1:9103"]}
    env = get_framework("mxnet").task_adapter().build_task_env(
        ctx_for("mxnet", "worker", 0, spec=spec,
                conf_extra={"tony.scheduler.instances": "1",
                            "tony.server.instances": "1",
                            "tony.worker.instances": "2"}))
    assert env[constants.ENV_DMLC_PS_ROOT_URI] == "h0"
    assert env[constants.ENV_DMLC_PS_ROOT_PORT] == "9100"
    assert env[constants.ENV_DMLC_ROLE] == "worker"
    assert env[constants.ENV_DMLC_NUM_SERVER] == "1"
    assert env[constants.ENV_DMLC_NUM_WORKER] == "2"


def test_horovod_slot_math():
    slots = compute_slots(["h0", "h0", "h1", "h1", "h1"])
    assert [s["rank"] for s in slots] == [0, 1, 2, 3, 4]
    assert [s["local_rank"] for s in slots] == [0, 1, 0, 1, 2]
    assert [s["cross_rank"] for s in slots] == [0, 0, 1, 1, 1]
    assert slots[0]["local_size"] == 2 and slots[4]["local_size"] == 3
    assert all(s["size"] == 5 and s["cross_size"] == 2 for s in slots)


def test_horovod_env_and_driver_roundtrip():
    driver = HorovodDriver()
    try:
        payload = fetch_slots(driver.address)
        assert payload["ready"] is False
        driver.set_hosts(["h0", "h0", "h1", "h1"])
        payload = fetch_slots(driver.address)
        assert payload["ready"] and len(payload["slots"]) == 4

        env = get_framework("horovod").task_adapter().build_task_env(
            ctx_for("horovod", "worker", 1,
                    callback={CALLBACK_RENDEZVOUS_ADDR: driver.address}))
        assert env[constants.ENV_HOROVOD_RANK] == "2"
        assert env[constants.ENV_HOROVOD_SIZE] == "4"
        assert env[constants.ENV_HOROVOD_LOCAL_RANK] == "0"
        assert env[constants.ENV_HOROVOD_CROSS_RANK] == "1"
        assert env[constants.ENV_HOROVOD_RENDEZVOUS_PORT] == str(driver.port)
        # NCCL→ICI bridge: coordinator triple present for the JAX data plane.
        assert env[constants.ENV_COORDINATOR_ADDRESS] == "h0:4000"
    finally:
        driver.stop()


def test_tb_port_reservation_policy():
    ad = get_framework("jax").task_adapter()
    assert ad.need_reserve_tb_port(ctx_for("jax", "chief", 0))
    assert not ad.need_reserve_tb_port(ctx_for("jax", "worker", 0))
    # With a dedicated tensorboard task, the chief does not reserve.
    spec = dict(SPEC, tensorboard=["h9:5000"])
    assert not ad.need_reserve_tb_port(
        ctx_for("jax", "chief", 0, spec=spec,
                conf_extra={"tony.tensorboard.instances": "1"}))


# --- sidecar-exclusion semantics (round-2 fixes) ---------------------------

SIDECAR_SPEC = {
    "chief": ["h0:4000"],
    "worker": ["h0:4001", "h1:4002"],
    "tensorboard": ["h1:5000"],
}
SIDECAR_CONF = {"tony.chief.instances": "1", "tony.worker.instances": "2",
                "tony.tensorboard.instances": "1"}


def sidecar_ctx(framework, job_type, index):
    return ctx_for(framework, job_type, index, spec=SIDECAR_SPEC,
                   conf_extra=SIDECAR_CONF)


def test_jax_world_excludes_sidecars():
    env = get_framework("jax").task_adapter().build_task_env(
        sidecar_ctx("jax", "worker", 1))
    # 3 rendezvous tasks, not 4: the tensorboard sidecar is not in the world.
    assert env[constants.ENV_NUM_PROCESSES] == "3"
    assert env[constants.ENV_PROCESS_ID] == "2"
    assert env[constants.ENV_COORDINATOR_ADDRESS] == "h0:4000"
    assert env[constants.ENV_TPU_WORKER_HOSTNAMES] == "h0,h1"


def test_sidecar_task_gets_no_rendezvous_env():
    for fw in ("jax", "pytorch", "horovod", "mxnet"):
        env = get_framework(fw).task_adapter().build_task_env(
            sidecar_ctx(fw, "tensorboard", 0))
        for key in (constants.ENV_COORDINATOR_ADDRESS, constants.ENV_RANK,
                    constants.ENV_HOROVOD_RANK, constants.ENV_DMLC_ROLE):
            assert key not in env, (fw, key)
        # Common env still present so the sidecar knows who it is.
        assert env[constants.ENV_JOB_TYPE] == "tensorboard"


def test_pytorch_world_excludes_sidecars():
    env = get_framework("pytorch").task_adapter().build_task_env(
        sidecar_ctx("pytorch", "worker", 1))
    assert env[constants.ENV_WORLD_SIZE] == "3"
    assert env[constants.ENV_RANK] == "2"
    # LOCAL_RANK counts only rendezvous tasks on h1 (tb excluded).
    assert env[constants.ENV_LOCAL_RANK] == "0"


def test_jax_chip_pinning_mixed_tpus():
    # chief (tpus=4) and worker:0 (tpus=2) share h0; worker:0's chips start
    # after the chief's four, not at local_rank*2.
    conf_extra = {"tony.chief.tpus": "4", "tony.worker.tpus": "2"}
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "worker", 0, conf_extra=conf_extra))
    assert env[constants.ENV_TPU_VISIBLE_DEVICES] == "4,5"
    env = get_framework("jax").task_adapter().build_task_env(
        ctx_for("jax", "chief", 0, conf_extra=conf_extra))
    assert env[constants.ENV_TPU_VISIBLE_DEVICES] == "0,1,2,3"


def test_global_rank_out_of_range_raises():
    ctx = ctx_for("jax", "worker", 9)
    with pytest.raises(KeyError):
        ctx.global_rank()


def test_horovod_validate_idempotent():
    fw = get_framework("horovod")
    am = fw.am_adapter()
    conf = TonyConfig({"tony.worker.instances": "2",
                       "tony.application.framework": "horovod"})
    try:
        am.validate_and_update_config(conf)
        first = am.driver
        am.validate_and_update_config(conf)
        assert am.driver is first
    finally:
        am.stop()


def test_jax_am_adapter_collects_profiler_callbacks():
    from tony_tpu.runtime.jax_runtime import JAXAMAdapter

    a = JAXAMAdapter()
    a.receive_task_callback_info("worker:1", '{"profiler": "h1:9432"}')
    a.receive_task_callback_info("worker:0", '{"profiler": "h0:9431"}')
    a.receive_task_callback_info("worker:2", "not json")     # ignored
    a.receive_task_callback_info("worker:3", '{"other": 1}')  # ignored
    assert a.profiler_endpoints == {"worker:0": "h0:9431",
                                    "worker:1": "h1:9432"}
