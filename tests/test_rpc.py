"""Control-plane RPC tests (reference tier: rpc/ unit tests, SURVEY.md §4):
server+client roundtrip, gang barrier over the wire, token auth, error
transport, reconnection."""

import threading

import pytest

from tony_tpu.conf import TonyConfig
from tony_tpu.rpc import ApplicationRpcHandler, RpcClient, RpcError, RpcServer
from tony_tpu.session import JobStatus, TonySession


@pytest.fixture
def server_and_session():
    conf = TonyConfig({"tony.worker.instances": "2"})
    session = TonySession(conf, app_id="app_rpc_0001")
    handler = ApplicationRpcHandler(session)
    server = RpcServer(handler, host="127.0.0.1").start()
    yield server, handler, session
    server.stop()


def test_register_and_gang_barrier(server_and_session):
    server, handler, session = server_and_session
    with RpcClient(server.address, timeout=5) as c:
        spec = c.call("get_cluster_spec")
        assert spec == {"complete": False, "spec": {}, "callback_info": {}}
        c.call("register_worker_spec", job_type="worker", index=0,
               host="127.0.0.1", port=4000)
        assert not c.call("get_cluster_spec")["complete"]
        c.call("register_worker_spec", job_type="worker", index=1,
               host="127.0.0.1", port=4001)
        spec = c.call("get_cluster_spec")
        assert spec["complete"]
        assert spec["spec"] == {"worker": ["127.0.0.1:4000", "127.0.0.1:4001"]}
        # Barrier passed -> tasks RUNNING.
        infos = c.call("get_task_infos")
        assert all(i["status"] == "RUNNING" for i in infos)


def test_all_registered_fires_once(server_and_session):
    server, handler, session = server_and_session
    fired = []
    handler.on_all_registered = lambda: fired.append(1)
    with RpcClient(server.address, timeout=5) as c:
        c.call("register_worker_spec", job_type="worker", index=0,
               host="h", port=1)
        c.call("register_worker_spec", job_type="worker", index=1,
               host="h", port=2)
        # Re-registration (executor restart) must not re-fire the barrier.
        c.call("register_worker_spec", job_type="worker", index=1,
               host="h", port=2)
    assert fired == [1]


def test_result_heartbeat_metrics_and_status(server_and_session):
    server, handler, session = server_and_session
    with RpcClient(server.address, timeout=5) as c:
        c.call("register_worker_spec", job_type="worker", index=0, host="h", port=1)
        c.call("register_worker_spec", job_type="worker", index=1, host="h", port=2)
        assert c.call("heartbeat", job_type="worker", index=0) is True
        c.call("metrics_report", job_type="worker", index=0,
               metrics={"cpu_pct": 12.5, "rss_mb": 100})
        assert session.task("worker", 0).metrics["cpu_pct"] == 12.5
        c.call("register_execution_result", job_type="worker", index=0,
               exit_code=0)
        c.call("register_execution_result", job_type="worker", index=1,
               exit_code=0)
        status = c.call("get_job_status")
        assert status["status"] == "SUCCEEDED"


def test_heartbeat_carries_committed_ckpt_step(server_and_session):
    """Checkpoint-plane wiring: an executor that sees a tony.ckpt.dir
    piggybacks the last committed step; older executors omit the param and
    nothing changes (the optional-kwarg back-compat contract)."""
    server, handler, session = server_and_session
    with RpcClient(server.address, timeout=5) as c:
        c.call("register_worker_spec", job_type="worker", index=0,
               host="h", port=1)
        c.call("register_worker_spec", job_type="worker", index=1,
               host="h", port=2)
        assert session.last_committed_step() is None
        c.call("heartbeat", job_type="worker", index=0)       # legacy form
        assert session.last_committed_step() is None
        c.call("heartbeat", job_type="worker", index=0, ckpt_step=7)
        c.call("heartbeat", job_type="worker", index=1, ckpt_step=6)
        assert session.task("worker", 0).ckpt_step == 7
        assert session.last_committed_step() == 7
        # Surfaced to the client through get_task_infos.
        infos = {i["index"]: i for i in c.call("get_task_infos")}
        assert infos[0]["ckpt_step"] == 7 and infos[1]["ckpt_step"] == 6
        # A later heartbeat WITHOUT the param must not erase progress.
        c.call("heartbeat", job_type="worker", index=0)
        assert session.last_committed_step() == 7


def test_error_transport(server_and_session):
    server, _, _ = server_and_session
    with RpcClient(server.address, timeout=5) as c:
        with pytest.raises(RpcError, match="unknown RPC method"):
            c.call("no_such_method")
        with pytest.raises(RpcError, match="KeyError"):
            c.call("heartbeat", job_type="worker", index=99)


def test_token_auth():
    conf = TonyConfig({"tony.worker.instances": "1"})
    session = TonySession(conf, app_id="app_tok_0001")
    server = RpcServer(ApplicationRpcHandler(session), host="127.0.0.1",
                       token="s3cret").start()
    try:
        with RpcClient(server.address, token="wrong", timeout=5) as c:
            with pytest.raises(RpcError, match="token"):
                c.call("get_cluster_spec")
        with RpcClient(server.address, token="s3cret", timeout=5) as c:
            assert c.call("get_cluster_spec")["complete"] is False
    finally:
        server.stop()


def test_client_retries_until_server_up():
    conf = TonyConfig({"tony.worker.instances": "1"})
    session = TonySession(conf, app_id="app_retry_0001")
    handler = ApplicationRpcHandler(session)
    # Pre-bind to learn the port, start serving shortly after the first call.
    server = RpcServer(handler, host="127.0.0.1")
    t = threading.Timer(0.4, server.start)
    t.start()
    try:
        with RpcClient(server.address, timeout=10) as c:
            assert c.call("get_cluster_spec")["complete"] is False
    finally:
        t.join()
        server.stop()


def test_finish_application_kills(server_and_session):
    server, _, session = server_and_session
    with RpcClient(server.address, timeout=5) as c:
        c.call("finish_application", reason="user ctrl-c")
    assert session.job_status is JobStatus.KILLED
    assert all(t.status.value == "KILLED" for t in session.tasks())


def test_call_timeout_override(server_and_session):
    """Per-call _timeout clamps the retry window AND the in-flight socket
    ops — deadline-driven loops (the executor gang barrier) must not block
    a full default window past their own deadline."""
    import time

    server, handler, session = server_and_session
    with RpcClient(server.address, timeout=60.0) as c:
        assert c.call("get_cluster_spec", _timeout=5.0)["complete"] is False
    # Unreachable address: the override bounds the total wall time.
    dead = RpcClient("127.0.0.1:1", timeout=60.0)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        dead.call("heartbeat", _timeout=0.5, job_type="w", index=0)
    assert time.monotonic() - t0 < 5.0
