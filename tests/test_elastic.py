"""Elastic gang resize (PR 19): the drain → commit → re-gang → restore
machine, the TONY_CHAOS_* fault harness, and the planes it touches.

Groups, cheapest first:

* chaos harness unit pins — env parsing, "first n" counters, hooks;
* ResizeController driven by a fake clock — phase order, per-phase
  deadlines, the retryable split (drain failures are NOT), abandon;
* train_loop's drain-file exit — EXIT_DRAINED only over a durable
  manifest, data cursor committed in the same step;
* RPC client backoff — bounded exponential with jitter, capped, never
  past the deadline; plus the chaos RPC-delay injection end to end;
* history rotation crash sweep — kill -9 at every stage-and-rename
  boundary leaves old-or-new, never a torn file;
* per-tenant SLO-target autoscaling — worst-ratio rule, the PR 18
  single-target and queue-depth matrices pinned unchanged, replay;
* billing rollup + resize timeline rendering in `tony history`;
* THE HEADLINE PIN (slow): >=3 injected preemptions across changing
  host counts reproduce the undisturbed run's example-id stream
  exactly — zero examples lost or duplicated — and the final params
  bitwise equal;
* MiniPod e2e (slow): operator `tony resize N` and a real preemption
  each walk a live gang through drain → re-gang; a gang that cannot
  drain degrades to the full-restart verdict.
"""

import collections
import dataclasses
import json
import os
import socket
import subprocess
import sys
import types
from pathlib import Path

import numpy as np
import pytest

from tony_tpu import chaos, constants
from tony_tpu import events as ev
from tony_tpu import history
from tony_tpu.am.resize import (ResizeController, ResizeError, ResizePhase,
                                ResizeSpec, ResizeTimeouts)
from tony_tpu.conf import (SERVE_QOS_TENANTS, SERVE_SLO_TARGETS, TonyConfig)
from tony_tpu.serve.scaling import ScalingPolicy, decide, replay_decisions

pytestmark = pytest.mark.elastic

WORKLOADS = Path(__file__).parent / "workloads"


@pytest.fixture(autouse=True)
def chaos_clean(monkeypatch):
    """Every test starts and ends with an unarmed chaos harness."""
    for name in (chaos.ENV_KILL_STEP, chaos.ENV_HB_DROP,
                 chaos.ENV_RPC_DELAY_S, chaos.ENV_RPC_DELAY_CALLS,
                 chaos.ENV_CRASH):
        monkeypatch.delenv(name, raising=False)
    monkeypatch.setattr(chaos, "KILL_HOOK", None)
    monkeypatch.setattr(chaos, "CRASH_HOOK", None)
    monkeypatch.setattr(chaos, "SLEEP_HOOK", None)
    chaos.reset()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------

def test_kill_point_unarmed_noop():
    chaos.kill_point(1)  # no env, no hook, no SIGKILL


def test_kill_point_fires_hook_at_exact_step(monkeypatch):
    fired = []
    monkeypatch.setenv(chaos.ENV_KILL_STEP, "3")
    monkeypatch.setattr(chaos, "KILL_HOOK", fired.append)
    chaos.kill_point(1)
    chaos.kill_point(2)
    assert fired == []
    chaos.kill_point(3)
    assert fired == [3]
    chaos.kill_point(4)
    assert fired == [3]


def test_malformed_kill_step_raises(monkeypatch):
    monkeypatch.setenv(chaos.ENV_KILL_STEP, "soon")
    with pytest.raises(ValueError, match="not an integer"):
        chaos.kill_point(1)


def test_negative_rpc_delay_raises(monkeypatch):
    monkeypatch.setenv(chaos.ENV_RPC_DELAY_S, "-1")
    with pytest.raises(ValueError, match="must be >= 0"):
        chaos.rpc_delay()


def test_drop_heartbeat_first_n(monkeypatch):
    assert not chaos.drop_heartbeat()          # unarmed
    monkeypatch.setenv(chaos.ENV_HB_DROP, "2")
    chaos.reset()
    assert chaos.drop_heartbeat()
    assert chaos.drop_heartbeat()
    assert not chaos.drop_heartbeat()          # schedule exhausted
    chaos.reset()
    assert chaos.drop_heartbeat()              # reset re-arms


def test_rpc_delay_counts_calls(monkeypatch):
    slept = []
    monkeypatch.setattr(chaos, "SLEEP_HOOK", slept.append)
    monkeypatch.setenv(chaos.ENV_RPC_DELAY_S, "0.25")
    chaos.rpc_delay()
    chaos.rpc_delay()                          # default: first call only
    assert slept == [0.25]
    chaos.reset()
    monkeypatch.setenv(chaos.ENV_RPC_DELAY_CALLS, "2")
    chaos.rpc_delay()
    chaos.rpc_delay()
    chaos.rpc_delay()
    assert slept == [0.25, 0.25, 0.25]


def test_crash_point_site_match(monkeypatch):
    fired = []
    monkeypatch.setattr(chaos, "CRASH_HOOK", fired.append)
    chaos.crash_point("rotate_after_stage")    # unarmed: no-op
    monkeypatch.setenv(chaos.ENV_CRASH, "rotate_after_stage")
    chaos.crash_point("rotate_before_stage")   # wrong site
    assert fired == []
    chaos.crash_point("rotate_after_stage")
    assert fired == ["rotate_after_stage"]


# ---------------------------------------------------------------------------
# ResizeController (fake clock — the never-hang guarantee is pinned here)
# ---------------------------------------------------------------------------

SPEC = ResizeSpec(trigger="preempted", job_type="worker",
                  old_workers=3, new_workers=2)


def make_controller(flags, clock, **kw):
    """Controller whose phase predicates read mutable ``flags``."""
    return ResizeController(
        poll={ResizePhase.DRAINING: lambda: flags["drain"],
              ResizePhase.REGANG: lambda: flags["regang"],
              ResizePhase.RESTORING: lambda: flags["restore"]},
        clock=lambda: clock[0], **kw)


def test_resize_happy_path_walls_and_observer():
    clock = [0.0]
    flags = {"drain": False, "regang": False, "restore": False}
    seen = []
    c = make_controller(
        flags, clock,
        on_phase=lambda s, p, w, ok, d: seen.append((p, w, ok)))
    assert not c.active and c.tick() is None
    c.start(SPEC)
    assert c.active and c.phase is ResizePhase.DRAINING
    clock[0] = 5.0
    assert c.tick() is None                    # still draining
    flags["drain"] = True
    clock[0] = 10.0
    assert c.tick() is None                    # drain done -> REGANG begins
    assert c.phase is ResizePhase.REGANG
    flags["regang"] = True
    clock[0] = 12.0
    assert c.tick() is None
    assert c.phase is ResizePhase.RESTORING
    flags["restore"] = True
    clock[0] = 15.0
    result = c.tick()
    assert result is not None and result.ok and not result.degraded
    assert result.phase_walls == {"DRAINING": 10.0, "RE-GANG": 2.0,
                                  "RESTORING": 3.0}
    assert [(p.value, ok) for p, _, ok in seen] == [
        ("DRAINING", True), ("RE-GANG", True), ("RESTORING", True)]
    assert not c.active and c.tick() is None   # terminal: inert


def test_drain_timeout_degrades_not_retryable():
    clock = [0.0]
    flags = {"drain": False, "regang": True, "restore": True}
    c = make_controller(flags, clock,
                        timeouts=ResizeTimeouts(drain_s=30.0))
    c.start(SPEC)
    clock[0] = 30.0
    assert c.tick() is None                    # at the budget: not past it
    clock[0] = 30.1
    result = c.tick()
    assert result.degraded and result.failed_phase is ResizePhase.DRAINING
    assert not result.retryable                # commit may predate the drain
    assert "timed out" in result.reason


def test_regang_timeout_degrades_retryable():
    clock = [0.0]
    flags = {"drain": True, "regang": False, "restore": True}
    c = make_controller(flags, clock,
                        timeouts=ResizeTimeouts(regang_s=60.0))
    c.start(SPEC)
    assert c.tick() is None                    # DRAINING done instantly
    clock[0] = 61.0
    result = c.tick()
    assert result.degraded and result.failed_phase is ResizePhase.REGANG
    assert result.retryable                    # a later resize is sound
    assert result.phase_walls["DRAINING"] == 0.0


def test_predicate_exception_fails_that_phase():
    clock = [0.0]

    def boom():
        raise OSError("conf rewrite failed")

    c = ResizeController(
        poll={ResizePhase.DRAINING: lambda: True,
              ResizePhase.REGANG: boom,
              ResizePhase.RESTORING: lambda: True},
        clock=lambda: clock[0])
    c.start(SPEC)
    assert c.tick() is None
    result = c.tick()
    assert result.degraded and result.failed_phase is ResizePhase.REGANG
    assert result.retryable and "OSError" in result.reason


def test_draining_predicate_exception_not_retryable():
    def boom():
        raise RuntimeError("session gone")

    c = ResizeController(
        poll={ResizePhase.DRAINING: boom,
              ResizePhase.REGANG: lambda: True,
              ResizePhase.RESTORING: lambda: True})
    c.start(SPEC)
    result = c.tick()
    assert result.degraded and not result.retryable


def test_start_guards():
    flags = {"drain": False, "regang": False, "restore": False}
    c = make_controller(flags, [0.0])
    c.start(SPEC)
    with pytest.raises(ResizeError, match="already in flight"):
        c.start(SPEC)
    with pytest.raises(ValueError, match="missing phases"):
        ResizeController(poll={ResizePhase.DRAINING: lambda: True})
    c2 = make_controller(flags, [0.0])
    with pytest.raises(ValueError, match="at least 1"):
        c2.start(dataclasses.replace(SPEC, new_workers=0))


def test_abandon_terminal_and_idempotent():
    flags = {"drain": False, "regang": False, "restore": False}
    c = make_controller(flags, [0.0])
    assert c.abandon("no resize in flight") is None
    c.start(SPEC)
    result = c.abandon("AM shutting down")
    assert result.degraded and "abandoned" in result.reason
    assert not c.active and c.abandon("again") is None


# ---------------------------------------------------------------------------
# train_loop: the drain-file exit (EXIT_DRAINED only over a durable commit)
# ---------------------------------------------------------------------------

@pytest.fixture
def clean_train_env(monkeypatch):
    for name in (constants.ENV_CKPT_DIR, constants.ENV_CKPT_EVERY,
                 constants.ENV_CKPT_KEEP, constants.ENV_DRAIN_FILE):
        monkeypatch.delenv(name, raising=False)


def test_train_loop_drain_commits_model_and_cursor(tmp_path,
                                                   clean_train_env):
    from tony_tpu import ckpt as ckpt_mod
    from tony_tpu import train as tr
    from tony_tpu.data import Dataset, ShardSpec, ckptio

    ds = Dataset.from_arrays(
        {"x": np.arange(16, dtype=np.float32)},
        seed=3).repeat(2).batch(4).with_ids()
    undisturbed = [b["id"].tolist() for b in ds.iterator(ShardSpec(0, 1))]
    assert len(undisturbed) == 8

    root = tmp_path / "ckpt"
    drain = tmp_path / "drain"
    seen = []

    def step_fn(state, batch):
        seen.append(batch["id"].tolist())
        return state, {}

    def on_step(step, metrics):
        if step == 2:
            drain.touch()              # the executor's drain directive

    with pytest.raises(SystemExit) as exc:
        tr.train_loop({"w": np.zeros(2, np.float32)}, step_fn,
                      data=ds.iterator(ShardSpec(0, 1)),
                      ckpt_dir=str(root), on_step=on_step,
                      drain_file=str(drain))
    assert exc.value.code == constants.EXIT_DRAINED
    assert seen == undisturbed[:2]
    # EXIT_DRAINED was reported over a DURABLE manifest: model + cursor
    # at exactly the drained step.
    assert ckpt_mod.latest_step(root) == 2
    assert ckptio.has_iter_state(root, 2)
    resumed = ds.iterator(ShardSpec(0, 1))
    resumed.restore(ckptio.load_iter_state(root, 2))
    assert [b["id"].tolist() for b in resumed] == undisturbed[2:]


def test_train_loop_consults_kill_point(monkeypatch, clean_train_env):
    from tony_tpu import train as tr

    class _Killed(Exception):
        pass

    def hook(step):
        raise _Killed(step)

    monkeypatch.setenv(chaos.ENV_KILL_STEP, "2")
    monkeypatch.setattr(chaos, "KILL_HOOK", hook)
    seen = []
    batches = [{"i": i} for i in range(5)]
    with pytest.raises(_Killed):
        tr.train_loop({"w": 0}, lambda s, b: (s, {}), batches,
                      on_step=lambda step, m: seen.append(step))
    # The kill lands as step 2 COMPLETES — after step 1's on_step, before
    # step 2's (no step-2 examples reach the caller's bookkeeping).
    assert seen == [1]


# ---------------------------------------------------------------------------
# RPC client backoff + chaos delay injection
# ---------------------------------------------------------------------------

def _refused_address():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


def test_rpc_retry_backoff_doubles_and_caps(monkeypatch):
    import tony_tpu.rpc as rpc_mod

    slept = []
    fake_now = [0.0]

    def fake_sleep(d):
        slept.append(d)
        fake_now[0] += d

    fake_time = types.SimpleNamespace(monotonic=lambda: fake_now[0],
                                      sleep=fake_sleep)
    monkeypatch.setattr(rpc_mod, "time", fake_time)
    monkeypatch.setattr(rpc_mod, "random",
                        types.SimpleNamespace(random=lambda: 0.5))  # x1.0
    c = rpc_mod.RpcClient(_refused_address(), timeout=10.0,
                          retry_interval=0.2)
    with pytest.raises(ConnectionError, match="failed after"):
        c.call("heartbeat", job_type="worker", index=0)
    c.close()
    # Exponential from retry_interval, capped at BACKOFF_CAP_S, and the
    # final sleep clamped to the remaining deadline — never past it.
    assert slept[:4] == pytest.approx([0.2, 0.4, 0.8, 1.6])
    assert max(slept) == pytest.approx(rpc_mod.RpcClient.BACKOFF_CAP_S)
    assert all(d >= 0 for d in slept)
    assert sum(slept) <= 10.0 + 1e-9


def test_chaos_rpc_delay_injected_heartbeat_still_lands(monkeypatch):
    from tony_tpu.rpc import ApplicationRpcHandler, RpcClient, RpcServer
    from tony_tpu.session import TonySession

    conf = TonyConfig({"tony.worker.instances": "1"})
    session = TonySession(conf, app_id="app_chaos_rpc")
    server = RpcServer(ApplicationRpcHandler(session),
                       host="127.0.0.1").start()
    slept = []
    monkeypatch.setattr(chaos, "SLEEP_HOOK", slept.append)
    monkeypatch.setenv(chaos.ENV_RPC_DELAY_S, "0.5")
    try:
        with RpcClient(server.address, timeout=5) as c:
            c.call("register_worker_spec", job_type="worker", index=0,
                   host="h", port=1)
            assert c.call("heartbeat", job_type="worker", index=0) is True
        # The delay stalled the first logical call, then the RPCs landed.
        assert slept == [0.5]
    finally:
        server.stop()


def test_heartbeat_carries_drain_directive():
    from tony_tpu.rpc import ApplicationRpcHandler, RpcClient, RpcServer
    from tony_tpu.session import TonySession

    conf = TonyConfig({"tony.worker.instances": "1"})
    session = TonySession(conf, app_id="app_drain_rpc")
    server = RpcServer(ApplicationRpcHandler(session),
                       host="127.0.0.1").start()
    try:
        with RpcClient(server.address, timeout=5) as c:
            c.call("register_worker_spec", job_type="worker", index=0,
                   host="h", port=1)
            assert c.call("heartbeat", job_type="worker", index=0) is True
            session.request_drain()
            resp = c.call("heartbeat", job_type="worker", index=0)
            assert resp == {"ok": True, "drain": True}
            session.clear_drain()
            assert c.call("heartbeat", job_type="worker", index=0) is True
            # Resize RPC is rejected until the AM arms the callback slot.
            with pytest.raises(Exception, match="not enabled"):
                c.call("resize", num_workers=1)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# history rotation crash sweep (old log or new log, never a torn file)
# ---------------------------------------------------------------------------

ROTATE_SITES = ("rotate_before_stage", "rotate_after_stage",
                "rotate_after_replace")


@pytest.mark.parametrize("site", ROTATE_SITES)
def test_rotation_crash_leaves_parseable_log(tmp_path, monkeypatch, site):
    class _Crashed(Exception):
        pass

    def hook(where):
        raise _Crashed(where)

    monkeypatch.setattr(chaos, "CRASH_HOOK", hook)
    monkeypatch.setenv(chaos.ENV_CRASH, site)
    handler = ev.EventHandler(tmp_path, "app_rotcrash", max_bytes=700)
    try:
        handler.task_started("worker", 0, "host0")
        with pytest.raises(_Crashed):
            for i in range(500):
                handler.task_metrics("worker", 0, {"step": i})
    finally:
        handler._closed = True         # the crash left the writer dead
    records = ev._parse_file(handler.inprogress_path)
    assert records, f"crash at {site} left an unreadable log"
    assert records[0]["type"] == "METADATA"
    # Lifecycle events survive compaction whole — old file or new.
    assert any(r["type"] == ev.TASK_STARTED for r in records)
    # Every line parsed back — never a torn half-written record.
    assert all("timestamp" in r for r in records)


@pytest.mark.slow
@pytest.mark.parametrize("site", ROTATE_SITES)
def test_rotation_crash_sweep_real_sigkill(tmp_path, site):
    """The same sweep with a REAL kill -9 mid-rotation in a child
    process — the invariant the in-process hook variant models."""
    child = (
        "import sys\n"
        "from tony_tpu.events import EventHandler\n"
        "h = EventHandler(sys.argv[1], 'app_kill9', max_bytes=700)\n"
        "h.task_started('worker', 0, 'host0')\n"
        "for i in range(2000):\n"
        "    h.task_metrics('worker', 0, {'step': i})\n"
        "print('survived')\n"
    )
    env = dict(os.environ, PYTHONPATH=str(Path(__file__).parent.parent))
    env[chaos.ENV_CRASH] = site
    proc = subprocess.run([sys.executable, "-c", child, str(tmp_path)],
                          env=env, capture_output=True, text=True,
                          timeout=60)
    assert proc.returncode == -9, (proc.returncode, proc.stdout,
                                   proc.stderr)
    path = (tmp_path / constants.EVENTS_DIR_INTERMEDIATE
            / ("app_kill9" + constants.JHIST_INPROGRESS_SUFFIX))
    records = ev._parse_file(path)
    assert records and records[0]["type"] == "METADATA"
    assert any(r["type"] == ev.TASK_STARTED for r in records)


# ---------------------------------------------------------------------------
# per-tenant SLO-target autoscaling
# ---------------------------------------------------------------------------

def _pol(**kw):
    base = dict(min_replicas=1, max_replicas=4, queue_high=8.0,
                queue_low=1.0, cooldown_s=0.0)
    base.update(kw)
    return ScalingPolicy(**base)


def _sample(qd=0.0, p99=0.0, tenants=None):
    s = {"qps": 1.0, "p99_ms": float(p99), "queue_depth": float(qd)}
    if tenants is not None:
        s["tenants"] = tenants
    return s


def test_tenant_slo_hot_and_cold():
    pol = _pol(slo_targets={"gold": 200.0})
    hot = [_sample(tenants={"gold": {"p99_ms": 250.0}})]
    assert decide(pol, 2, hot, now=100.0) == 1
    cold = [_sample(qd=0.2, tenants={"gold": {"p99_ms": 50.0}})]
    assert decide(pol, 2, cold, now=100.0) == -1
    held = [_sample(tenants={"gold": {"p99_ms": 150.0}})]  # 0.75: in band
    assert decide(pol, 2, held, now=100.0) == 0


def test_worst_ratio_rules_across_fleet_and_tenants():
    pol = _pol(slo_target_ms=1000.0, slo_targets={"gold": 200.0,
                                                  "bulk": 5000.0})
    # Fleet p99 comfortable, bulk comfortable — but gold misses ITS slo.
    samples = [_sample(p99=300.0, tenants={
        "gold": {"p99_ms": 260.0}, "bulk": {"p99_ms": 300.0}})]
    assert decide(pol, 2, samples, now=0.0) == 1
    # Every armed promise under half its target and the queue idle: shrink.
    samples = [_sample(qd=0.1, p99=400.0, tenants={
        "gold": {"p99_ms": 90.0}, "bulk": {"p99_ms": 400.0}})]
    assert decide(pol, 2, samples, now=0.0) == -1
    # Gold fine but the FLEET target misses: still hot.
    samples = [_sample(p99=1200.0, tenants={"gold": {"p99_ms": 100.0}})]
    assert decide(pol, 2, samples, now=0.0) == 1
    # Latency headroom everywhere but a deep queue is not idleness.
    samples = [_sample(qd=5.0, p99=100.0,
                       tenants={"gold": {"p99_ms": 50.0}})]
    assert decide(pol, 2, samples, now=0.0) == 0


def test_tenant_worst_across_replicas():
    pol = _pol(slo_targets={"gold": 200.0})
    # Fleet-worst per tenant: one replica's gold overage is enough.
    samples = [_sample(tenants={"gold": {"p99_ms": 50.0}}),
               _sample(tenants={"gold": {"p99_ms": 230.0}})]
    assert decide(pol, 2, samples, now=0.0) == 1


def test_single_target_behavior_pinned_unchanged():
    """slo_targets={} must leave the PR 18 single-target mode verbatim."""
    for n, qd, p99 in [(2, 0.0, 250.0), (2, 0.2, 40.0), (2, 0.2, 150.0),
                       (4, 0.0, 900.0), (1, 0.0, 10.0), (2, 6.0, 40.0)]:
        old = decide(_pol(slo_target_ms=200.0), n,
                     [_sample(qd=qd, p99=p99)], now=0.0)
        new = decide(_pol(slo_target_ms=200.0, slo_targets={}), n,
                     [_sample(qd=qd, p99=p99)], now=0.0)
        assert new == old, (n, qd, p99)


def test_queue_depth_matrix_pinned_unchanged():
    pol = _pol()                       # no SLO mode at all
    assert decide(pol, 2, [_sample(qd=10.0)], now=0.0) == 1
    assert decide(pol, 2, [_sample(qd=0.5)], now=0.0) == -1
    assert decide(pol, 2, [_sample(qd=4.0)], now=0.0) == 0
    assert decide(pol, 4, [_sample(qd=10.0)], now=0.0) == 0   # at ceiling
    assert decide(pol, 1, [_sample(qd=0.0)], now=0.0) == 0    # at floor
    assert decide(pol, 0, [], now=0.0) == 1                   # repair


def test_slo_targets_from_conf_and_validation():
    conf = TonyConfig({SERVE_SLO_TARGETS: "gold:200,silver:800",
                       "tony.serve.replicas.max": "4"})
    pol = ScalingPolicy.from_conf(conf, 1)
    assert pol.slo_targets == {"gold": 200.0, "silver": 800.0}
    assert ScalingPolicy.from_conf(TonyConfig({}), 1).slo_targets == {}
    with pytest.raises(ValueError, match="must be > 0"):
        _pol(slo_targets={"gold": 0.0})
    with pytest.raises(ValueError, match="must be > 0"):
        _pol(slo_targets={"gold": -5.0})


def test_slo_targets_decision_replays_from_log():
    pol = _pol(slo_targets={"gold": 200.0})
    samples = [_sample(qd=2.0, tenants={"gold": {"p99_ms": 250.0}})]
    delta = decide(pol, 2, samples, now=50.0, last_action=None)
    rec = json.loads(json.dumps({          # the jhist round trip
        "job_type": "worker", "delta": delta, "n_active": 2,
        "samples": samples, "now": 50.0, "last_action": None,
        "policy": dataclasses.asdict(pol)}))
    verdicts = replay_decisions([rec])
    assert verdicts == [{"job_type": "worker", "logged": 1,
                         "replayed": 1, "match": True}]


# ---------------------------------------------------------------------------
# billing rollup + resize timeline in `tony history`
# ---------------------------------------------------------------------------

def _serve_window_record(ts, index, tenants):
    return {"type": ev.SERVE_WINDOW, "timestamp": float(ts),
            "payload": {"job_type": "server", "index": index,
                        "stats": {"tenants": tenants}}}


def test_billing_rollup_integrates_rates():
    records = [
        _serve_window_record(100.0, 0, {"gold": {"tokens_per_s": 100.0}}),
        _serve_window_record(110.0, 0, {"gold": {"tokens_per_s": 7.0},
                                        "free": {"tokens_per_s": 3.0}}),
        _serve_window_record(115.0, 0, {"gold": {"tokens_per_s": 0.0},
                                        "free": {"tokens_per_s": 0.0}}),
        # A second task's windows integrate independently and sum.
        _serve_window_record(100.0, 1, {"gold": {"tokens_per_s": 10.0}}),
        _serve_window_record(101.0, 1, {"gold": {"tokens_per_s": 0.0}}),
    ]
    out = history.billing_rollup(records, {SERVE_QOS_TENANTS: "gold:2"})
    # gold: 100*10 + 7*5 (task 0) + 10*1 (task 1) = 1045, weight 2.
    assert out["gold"] == {"tokens": pytest.approx(1045.0), "weight": 2.0,
                           "billed": pytest.approx(2090.0)}
    # Unlisted tenants bill at weight 1.
    assert out["free"]["weight"] == 1.0
    assert out["free"]["billed"] == pytest.approx(15.0)
    # Malformed snapshot: weight 1, never a crash. No windows: empty.
    assert history.billing_rollup(
        records, {SERVE_QOS_TENANTS: "::bad::"})["gold"]["weight"] == 1.0
    assert history.billing_rollup([], None) == {}


@pytest.fixture
def resize_jhist(tmp_path, monkeypatch):
    """A finished job log carrying RESIZE + SERVE_WINDOW records with
    controlled timestamps."""
    clock = {"t": 1000.0}
    monkeypatch.setattr(ev, "time",
                        types.SimpleNamespace(time=lambda: clock["t"]))
    handler = ev.EventHandler(
        tmp_path, "app_resize_hist",
        conf_snapshot={SERVE_QOS_TENANTS: "gold:2,free:1"})
    handler.task_started("server", 0, "host0")
    clock["t"] = 1010.0
    handler.serve_window("server", 0,
                         {"tenants": {"gold": {"tokens_per_s": 50.0}}})
    clock["t"] = 1020.0
    handler.serve_window("server", 0,
                         {"tenants": {"gold": {"tokens_per_s": 0.0}}})
    handler.resize("DRAINING", "preempted", "worker", 3, 2, 1.5, True)
    handler.resize("RE-GANG", "preempted", "worker", 3, 2, 4.0, True)
    handler.resize("RESTORING", "preempted", "worker", 3, 2, 2.0, False,
                   detail="timed out after 2.0s")
    handler.application_finished("FAILED", "resize degraded")
    handler.close()
    return tmp_path


def test_history_resize_timeline_and_billing(resize_jhist):
    jobs = history.gather_jobs(resize_jhist)
    assert len(jobs) == 1
    detail = history.job_detail(jobs[0])
    assert [r["phase"] for r in detail["resizes"]] == [
        "DRAINING", "RE-GANG", "RESTORING"]
    assert detail["resizes"][0]["old_workers"] == 3
    assert detail["billing"]["gold"]["tokens"] == pytest.approx(500.0)
    assert detail["billing"]["gold"]["billed"] == pytest.approx(1000.0)
    text = history.render_show(detail)
    assert "resize timeline:" in text
    assert "RE-GANG" in text and "[preempted]" in text
    assert "3→2" in text and "FAILED" in text
    assert "billing (tokens × weight" in text
    assert "gold: tokens=500 weight=2 billed=1000" in text
    page = history._job_page(detail)
    assert "Resize timeline" in page and "Billing" in page


def test_history_bill_action(resize_jhist, capsys):
    args = types.SimpleNamespace(action="bill", app_id=None,
                                 history_dir=str(resize_jhist))
    assert history.main(args) == 0
    out = capsys.readouterr().out
    assert "gold" in out and "TOTAL" in out and "1000" in out
    # Tenant filter: an unknown tenant bills nothing.
    args = types.SimpleNamespace(action="bill", app_id="nobody",
                                 history_dir=str(resize_jhist))
    assert history.main(args) == 0
    assert "no serve-window ledgers found for nobody" in \
        capsys.readouterr().out


# ---------------------------------------------------------------------------
# THE HEADLINE PIN: >=3 injected preemptions across changing host counts
# reproduce the undisturbed example-id stream exactly, zero examples lost
# or duplicated, final params bitwise equal.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_elastic_resize_pins_example_stream_and_params(tmp_path,
                                                       monkeypatch):
    import jax
    import optax
    from flax import linen as nn

    from tony_tpu import ckpt as ckpt_mod
    from tony_tpu import train as tr
    from tony_tpu.data import Dataset, ShardSpec, ckptio

    N, BATCH, EPOCHS = 48, 12, 3
    X = np.arange(N * 8, dtype=np.float32).reshape(N, 8) / (N * 8)
    Y = (np.arange(N) % 4).astype(np.int32)
    ds = Dataset.from_arrays({"x": X, "y": Y}, seed=7) \
        .shuffle().repeat(EPOCHS).batch(BATCH).with_ids()
    total_steps = N * EPOCHS // BATCH          # 12 global steps

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    def fresh_state():
        return tr.create_train_state(
            Tiny(), optax.sgd(0.1, momentum=0.9),
            np.zeros((BATCH, 8), np.float32), jax.random.PRNGKey(0))

    step = tr.make_train_step(donate=False)

    def apply(state, batch):
        new_state, _ = step(state, {"x": batch["x"], "y": batch["y"]})
        return new_state

    # ---- undisturbed run: the reference stream and reference params ----
    state = fresh_state()
    it = ds.iterator(ShardSpec(0, 1))
    ids_ref = []
    for _ in range(total_steps):
        b = next(it)
        ids_ref.append(np.asarray(b["id"]))
        state = apply(state, b)
    params_ref = jax.device_get(state.params)

    # ---- chaotic run: 3 re-gangs across changing host counts, plus one
    # scripted hard kill (SIGKILL analogue) that discards uncommitted
    # work and replays from the last durable commit ----
    class _Preempted(Exception):
        pass

    def kill_hook(at):
        raise _Preempted(at)

    monkeypatch.setattr(chaos, "KILL_HOOK", kill_hook)
    monkeypatch.setenv(chaos.ENV_KILL_STEP, "5")   # mid-segment 2

    root = str(tmp_path / "ckpt")
    ck = ckpt_mod.AsyncCheckpointer(root, keep=8)
    template = ckpt_mod.encode_portable(fresh_state())
    segments = [(2, 3), (3, 3), (1, 2), (2, 4)]    # (world, steps)
    assert sum(k for _, k in segments) == total_steps

    state = fresh_state()
    cursor = None                      # global data cursor of last commit
    committed_ids = []
    gstep = 0
    restores = 0
    try:
        for world, nsteps in segments:
            while True:                # replay the segment if preempted
                its = [ds.iterator(ShardSpec(i, world))
                       for i in range(world)]
                if cursor is not None:
                    for shard_it in its:
                        shard_it.restore(cursor)
                pending = []
                try:
                    for local in range(nsteps):
                        shards = [next(shard_it) for shard_it in its]
                        gb = {leaf: np.concatenate(
                            [np.asarray(s[leaf]) for s in shards], axis=0)
                            for leaf in shards[0]}
                        pending.append(gb["id"])
                        state = apply(state, gb)
                        chaos.kill_point(gstep + local + 1)
                except _Preempted:
                    # kill -9 mid-segment: every uncommitted example is
                    # discarded with the process; disarm (one-shot) and
                    # restore from the last durable commit.
                    monkeypatch.setenv(chaos.ENV_KILL_STEP, "")
                    restored = ckpt_mod.restore_pytree(
                        root, {ckptio.MODEL_KEY: template}, step=gstep)
                    state = ckpt_mod.decode_portable(
                        restored[ckptio.MODEL_KEY])
                    cursor = ckptio.load_iter_state(root, gstep)
                    restores += 1
                    continue
                break
            gstep += nsteps
            committed_ids.extend(pending)
            # Drain commit: model + global cursor in ONE durable step
            # (any survivor's cursor is the global one).
            ck.save(ckptio.wrap_for_save(
                ckpt_mod.encode_portable(state), its[0].state()),
                step=gstep, block=True)
            # Re-gang: the next segment's processes restore from the
            # manifest at the NEW world size.
            restored = ckpt_mod.restore_pytree(
                root, {ckptio.MODEL_KEY: template}, step=gstep)
            state = ckpt_mod.decode_portable(restored[ckptio.MODEL_KEY])
            cursor = ckptio.load_iter_state(root, gstep)
            restores += 1
    finally:
        ck.close()

    # >=3 preemptions across changing host counts (2 -> 3 -> 1 -> 2),
    # plus the scripted SIGKILL: every re-gang restored from a commit.
    assert restores >= 4

    # The example-id stream is EXACTLY the undisturbed run's.
    assert len(committed_ids) == len(ids_ref)
    for got, want in zip(committed_ids, ids_ref):
        assert np.array_equal(got, want)

    # Zero examples lost or duplicated across the whole run.
    counts = collections.Counter(
        int(i) for arr in committed_ids for i in arr)
    assert counts == {i: EPOCHS for i in range(N)}

    # Final params bitwise equal to the undisturbed run.
    params_got = jax.device_get(state.params)
    flat_got = jax.tree.leaves(params_got)
    flat_ref = jax.tree.leaves(params_ref)
    assert len(flat_got) == len(flat_ref)
    for a, b in zip(flat_got, flat_ref):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# MiniPod e2e: live AM, real executor processes
# ---------------------------------------------------------------------------

from tony_tpu.minipod import MiniPod          # noqa: E402
from tony_tpu.session import TaskStatus       # noqa: E402


@pytest.fixture
def pod(tmp_path):
    return MiniPod(tmp_path)


def _resize_props(**over):
    base = {
        "tony.application.framework": "standalone",
        "tony.application.executes": "python drain_aware.py",
        "tony.worker.instances": "2",
        "tony.resize.enabled": "true",
        "tony.resize.drain-timeout-ms": "20000",
        "tony.resize.regang-timeout-ms": "60000",
        "tony.resize.restore-timeout-ms": "60000",
    }
    base.update({k: str(v) for k, v in over.items()})
    return base


def _workers(session):
    return [t for t in session.tasks() if t.job_type == "worker"]


def _resized_to(job, n):
    def check():
        s = job.session
        if s is None or s.draining:
            return False
        if job.am._resize is not None and job.am._resize.active:
            return False
        live = [t for t in _workers(s) if t.status is TaskStatus.RUNNING]
        return len(live) == n and len(_workers(s)) == n
    return check


@pytest.mark.slow
@pytest.mark.e2e
def test_e2e_operator_resize_drains_and_regangs(pod):
    job = pod.submit(_resize_props(), src_dir=WORKLOADS)
    try:
        job.wait_for(
            lambda: job.session is not None
            and len([t for t in _workers(job.session)
                     if t.status is TaskStatus.RUNNING]) == 2,
            timeout=90, what="initial 2-worker gang running")
        # The operator verb arrives over the real RPC surface.
        assert job.am.handler.rpc_resize(1) is True
        job.wait_for(_resized_to(job, 1), timeout=120,
                     what="gang re-ganged at 1 worker")
        assert job.am.conf.get("tony.worker.instances") == "1"
        # The drained attempt's workers went DRAINED/terminal, not FAILED.
        assert job.session.job_status.name == "RUNNING"
    finally:
        job.kill()
        job.wait(60)


@pytest.mark.slow
@pytest.mark.e2e
def test_e2e_preemption_triggers_elastic_resize(pod):
    job = pod.submit(_resize_props(), src_dir=WORKLOADS)
    try:
        victim = job.wait_for(
            lambda: next(
                (t for t in _workers(job.session)
                 if t.index == 1 and t.status is TaskStatus.RUNNING
                 and t.container_id), None)
            if job.session is not None else None,
            timeout=90, what="worker 1 running")
        all_up = job.wait_for(
            lambda: all(t.status is TaskStatus.RUNNING
                        for t in _workers(job.session)),
            timeout=90, what="both workers running")
        assert all_up
        assert job.scheduler.preempt(victim.container_id)
        job.wait_for(_resized_to(job, 1), timeout=120,
                     what="preemption re-ganged at 1 worker")
        assert job.am.conf.get("tony.worker.instances") == "1"
    finally:
        job.kill()
        job.wait(60)


@pytest.mark.slow
@pytest.mark.e2e
def test_e2e_undrainable_gang_degrades(pod):
    """A workload that ignores the drain directive forces the DRAINING
    deadline; the resize degrades to the full-restart verdict instead of
    hanging."""
    job = pod.submit(_resize_props(**{
        "tony.application.executes": "python forever.py",
        "tony.resize.drain-timeout-ms": "1500",
        "tony.am.retry-count": "0",
    }), src_dir=WORKLOADS)
    try:
        job.wait_for(
            lambda: job.session is not None
            and len(_workers(job.session)) == 2
            and all(t.status is TaskStatus.RUNNING
                    for t in _workers(job.session)),
            timeout=90, what="gang running")
        job.am.handler.rpc_resize(1)
        code = job.wait(120)
        assert code != 0
        assert "resize degraded" in (job.session.final_message or "")
    finally:
        if job.exit_code is None:
            job.kill()
            job.wait(60)
