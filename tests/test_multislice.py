"""Multi-slice tier (host-simulated 2-slice mesh on the virtual 8-device
CPU backend): the hierarchical ICI/DCN bucketed reduce and its ZeRO-3
combination — the `make tier1` multislice leg (`-m multislice`) gates these
paths explicitly. On one host both levels ride the same transport, so these
are NUMERICS pins (hierarchical == flat == monolithic); the DCN timing
story needs a real multi-slice pod (ROADMAP)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu import parallel as par
from tony_tpu import profiler, train
from tony_tpu.benchmark import fsdp_shard_state
from tony_tpu.models import get_model
from tony_tpu.parallel import overlap

pytestmark = pytest.mark.multislice


def _mnist_setup(batch=32, hidden=64):
    model = get_model("mnist-mlp", hidden=hidden)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, 784))
    y = jax.random.randint(ky, (batch,), 0, 10)
    state = train.create_train_state(model, optax.sgd(0.1), x, kr)
    return state, {"x": x, "y": y}


def test_two_slice_mesh_shape_and_batch_placement():
    mesh = par.make_mesh(slices=2)
    assert mesh.shape["slice"] == 2 and mesh.shape["data"] == 4
    spec = par.batch_sharding(mesh).spec
    assert spec == jax.sharding.PartitionSpec(("slice", "data", "fsdp"))
    assert overlap.dcn_axis(mesh) == "slice"
    assert overlap.ici_axes(mesh) == ("data", "fsdp")


def test_hierarchical_accum_matches_flat_and_monolithic():
    """THE multi-slice acceptance pin: per-bucket psum_scatter over ICI +
    DCN allreduce inside the scan == flat single-level reduce == the
    monolithic GSPMD step, within 1e-5."""
    mesh = par.make_mesh(slices=2)
    state, batch = _mnist_setup()
    mono = train.make_train_step(mesh=mesh, donate=False)
    hier = train.make_accum_train_step(
        mesh=mesh, microbatches=4, bucket_bytes=32 * 1024, donate=False)
    flat = train.make_accum_train_step(
        mesh=mesh, microbatches=4, bucket_bytes=32 * 1024,
        hierarchy="flat", donate=False)
    s1, m1 = mono(state, batch)
    s2, m2 = hier(state, batch)
    s3, m3 = flat(state, batch)
    for m in (m2, m3):
        assert abs(float(m1["loss"]) - float(m["loss"])) < 1e-5
        assert abs(float(m1["grad_norm"]) - float(m["grad_norm"])) < 1e-5
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_hierarchical_profiler_level_records():
    """Per-level bucket plan records: the ICI level carries the full
    bucket bytes (psum_scatter input), the DCN level the scattered-chunk
    bytes — what actually crosses slices per bucket."""
    profiler.reset_overlap_records()
    mesh = par.make_mesh(slices=2)
    state, batch = _mnist_setup()
    step = train.make_accum_train_step(
        mesh=mesh, microbatches=4, bucket_bytes=32 * 1024, donate=False)
    step(state, batch)
    rec = profiler.overlap_report()["accum_step"]
    assert rec["hierarchy"] == "hierarchical"
    by_level = {l["level"]: l for l in rec["levels"]}
    assert by_level["ici"]["op"] == "psum_scatter"
    assert by_level["ici"]["axes"] == ["data", "fsdp"]
    assert by_level["dcn"]["op"] == "all_reduce"
    assert by_level["dcn"]["axes"] == ["slice"]
    ici_group = 4   # data=4 x fsdp=1
    for full, chunk in zip(by_level["ici"]["bucket_nbytes"],
                           by_level["dcn"]["bucket_nbytes"]):
        assert 0 < chunk <= -(-full // ici_group) + 4 * ici_group
    assert sum(by_level["ici"]["bucket_nbytes"]) == sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(state.params))


def test_zero3_on_two_slice_mesh():
    """ZeRO-3 x multi-slice: grads psum_scatter over fsdp, psum over the
    intra-slice data axis, DCN allreduce over slice — all inside the scan
    — and the result still matches the monolithic step, with updates in
    the shard layout."""
    mesh = par.make_mesh(slices=2, fsdp=2)    # slice=2 x data=2 x fsdp=2
    state, batch = _mnist_setup()
    mono = train.make_train_step(mesh=mesh, donate=False)
    s1, m1 = mono(state, batch)
    zstate = fsdp_shard_state(state, mesh)
    profiler.reset_overlap_records()
    for hierarchy in ("auto", "flat"):
        step = train.make_accum_train_step(
            mesh=mesh, microbatches=4, bucket_bytes=32 * 1024,
            hierarchy=hierarchy, donate=False)
        s2, m2 = step(zstate, batch)
        assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
        assert abs(float(m1["grad_norm"]) - float(m2["grad_norm"])) < 1e-5
        for a, b in zip(jax.tree.leaves(s1.params),
                        jax.tree.leaves(s2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)
        assert sum("fsdp" in str(leaf.sharding.spec)
                   for leaf in jax.tree.leaves(s2.params)) >= 4
    rec = profiler.overlap_report()["accum_step"]
    assert rec["zero3"] is True and rec["n_scatter_buckets"] >= 1


def test_zero3_multislice_grad_shardings():
    mesh = par.make_mesh(slices=2, fsdp=2)
    state, batch = _mnist_setup()
    zstate = fsdp_shard_state(state, mesh)
    specs = overlap.fsdp_param_specs(zstate.params, mesh)

    def loss_fn(params, mb):
        logits = zstate.apply_fn({"params": params}, mb["x"])
        return train.cross_entropy_loss(logits, mb["y"])

    with jax.sharding.Mesh(mesh.devices, mesh.axis_names):
        _, grads = jax.jit(lambda p, b: overlap.microbatch_grads(
            loss_fn, p, b, mesh, microbatches=4, bucket_bytes=32 * 1024,
            param_specs=specs))(zstate.params, batch)
    assert sum("fsdp" in str(g.sharding.spec)
               for g in jax.tree.leaves(grads)) >= 4


def test_create_train_state_fsdp_autodetects():
    """A transformer state created through the logical rules on an fsdp
    mesh (embed→fsdp) opts into the ZeRO-3 path with no flag."""
    mesh = par.make_mesh(fsdp=4)
    model = get_model("llama-tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 256)
    state = train.create_train_state(
        model, optax.adam(1e-2), tokens, jax.random.PRNGKey(0), mesh=mesh)
    specs = overlap.fsdp_param_specs(state.params, mesh)
    assert specs is not None
    flat = jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    assert any("fsdp" in str(s) for s in flat)


def test_overlap_bench_hier_and_zero3_legs():
    """Acceptance: the bench leg reports both modes with numerics intact
    and per-level plans attached."""
    import os

    from tony_tpu.benchmark import run_overlap_bench

    os.environ["BENCH_WINDOWS"] = "1"
    try:
        hier = run_overlap_bench(batch=64, hidden=64, steps=1,
                                 bucket_bytes=32 * 1024, slices=2)
        z = run_overlap_bench(batch=64, hidden=64, steps=1,
                              bucket_bytes=32 * 1024, fsdp=4, zero3=True)
    finally:
        del os.environ["BENCH_WINDOWS"]
    assert hier["numerics_ok"] and hier["hierarchy"] == "hierarchical"
    assert [l["level"] for l in
            hier["overlap_records"]["accum_step"]["levels"]].count("dcn") == 1
    assert z["numerics_ok"] and z["zero3"] and z["n_scatter_buckets"] >= 1
    assert z["accum_step_s"] > 0 and hier["accum_step_s"] > 0
