"""Collective-scheduler tier (tony_tpu.parallel.sched): bucketed +
prefetched ZeRO-3 forward gathers pinned bit-exact against the per-leaf
path, the static gather schedule (the hoisted spec test), MoE explicit
per-capacity-chunk all_to_all vs the GSPMD einsum path, pipeline-edge
registration, and the unified collective_report schema — on the virtual
8-device CPU mesh. `make tier1-sched` runs this file by marker."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tony_tpu import parallel as par
from tony_tpu import profiler, train
from tony_tpu.benchmark import fsdp_shard_state
from tony_tpu.compat import shard_map
from tony_tpu.models import get_model
from tony_tpu.models.moe import MoEMLP
from tony_tpu.parallel import overlap, sched
from tony_tpu.parallel.overlap import GradBuckets
from tony_tpu.parallel.sched import GatherPlan, moe_dispatch_ffn_combine

pytestmark = pytest.mark.sched


def _mixed_tree():
    """Sharded + uneven-sharded + replicated + scalar leaves — the full
    menu the gather schedule must sort statically."""
    k = jax.random.split(jax.random.PRNGKey(7), 4)
    params = {"w": jax.random.normal(k[0], (8, 16)),    # even: 8 % 4 == 0
              "u": jax.random.normal(k[1], (6, 16)),    # uneven: 6 % 4
              "b": jax.random.normal(k[2], (16,)),      # replicated
              "s": jnp.float32(0.5)}                    # scalar
    specs = {"w": P("fsdp"), "u": P("fsdp"), "b": P(), "s": P()}
    return params, specs


class TestGatherPlan:
    def test_static_schedule_from_mixed_tree(self):
        """Satellite pin (gather_params hoist): which leaves gather, on
        which dim, in which bucket is resolved at BUILD time — scalars,
        replicated, and uneven leaves land in the static passthrough
        list, never in the traced branch."""
        params, specs = _mixed_tree()
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=1 << 20)
        gp = GatherPlan.from_buckets(plan, prefetch=1)
        leaves = jax.tree.leaves(params)
        names = sorted(params)                    # flatten order: b,s,u,w
        i_w = names.index("w")
        assert gp.gather_leaves == ((i_w, 0),)
        assert sorted(gp.passthrough) == [i for i in range(len(leaves))
                                          if i != i_w]
        # Only even scatter buckets are gatherable; the padded (uneven)
        # bucket is not.
        assert all(plan._is_scatter(b) and not plan._is_padded(b)
                   for b in gp.gather_buckets)
        assert gp.n_gather_buckets == 1
        assert gp.gather_nbytes == (8 * 16 * 4,)

    def test_rejects_negative_prefetch(self):
        plan = GradBuckets.plan({"w": jnp.zeros((8, 4))}, 1 << 20)
        with pytest.raises(ValueError, match="prefetch"):
            GatherPlan.from_buckets(plan, prefetch=-1)

    def test_plain_plan_has_no_gather_buckets(self):
        plan = GradBuckets.plan({"w": jnp.zeros((8, 4))}, 1 << 20)
        gp = GatherPlan.from_buckets(plan)
        assert gp.n_gather_buckets == 0 and gp.gather_leaves == ()

    @pytest.mark.parametrize("prefetch", [0, 1, 2])
    def test_gather_bitexact_vs_per_leaf(self, prefetch):
        """THE data-movement pin: bucketed gathers (any prefetch depth)
        reproduce every sharded leaf bit-exactly."""
        mesh = par.make_mesh(fsdp=4)
        k = jax.random.split(jax.random.PRNGKey(0), 6)
        params = {f"w{i}": jax.random.normal(k[i], (8, 4 + i))
                  for i in range(6)}
        specs = jax.tree.map(lambda _: P("fsdp"), params)
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=512)
        assert plan.n_scatter_buckets > 1      # several gather buckets
        gp = GatherPlan.from_buckets(plan, prefetch=prefetch)
        region = jax.tree.map(lambda _: P("fsdp"), params)

        def spmd(p):
            return gp.gather(jax.tree.leaves(p))

        out = shard_map(spmd, mesh, in_specs=(region,),
                        out_specs=[P()] * 6)(params)
        for a, b in zip(out, jax.tree.leaves(params)):
            np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                          np.asarray(b))


class TestZero3ForwardGathers:
    def _setup(self, hidden=64):
        mesh = par.make_mesh(fsdp=4)
        model = get_model("mnist-mlp", hidden=hidden)
        kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
        x = jax.random.normal(kx, (32, 784))
        y = jax.random.randint(ky, (32,), 0, 10)
        state = fsdp_shard_state(
            train.create_train_state(model, optax.sgd(0.1), x, kr), mesh)
        return mesh, state, {"x": x, "y": y}

    def test_bucketed_bitexact_vs_per_leaf(self):
        """THE acceptance pin: ZeRO-3 train-step numerics with bucketed +
        prefetched gathers are BIT-exact against the pre-refactor per-leaf
        path (bucketing is pure data movement)."""
        mesh, state, batch = self._setup()
        specs = overlap.fsdp_param_specs(state.params, mesh)

        def loss_fn(p, mb):
            logits = state.apply_fn({"params": p}, mb["x"])
            return train.cross_entropy_loss(logits, mb["y"])

        def run(mode, prefetch=1):
            return overlap.microbatch_grads(
                loss_fn, state.params, batch, mesh, microbatches=4,
                bucket_bytes=32 * 1024, param_specs=specs, gather=mode,
                prefetch=prefetch)

        l_p, g_p = run("per_leaf")
        for prefetch in (0, 1, 2):
            l_b, g_b = run("bucketed", prefetch)
            assert float(l_b) == float(l_p)
            for a, b in zip(jax.tree.leaves(g_b), jax.tree.leaves(g_p)):
                np.testing.assert_array_equal(
                    np.asarray(jax.device_get(a)),
                    np.asarray(jax.device_get(b)))

    def test_accum_step_gather_modes_match_monolithic(self):
        mesh, state, batch = self._setup()
        mono = train.make_train_step(mesh=mesh, donate=False)
        s1, m1 = mono(state, batch)
        for mode in ("bucketed", "per_leaf"):
            step = train.make_accum_train_step(
                mesh=mesh, microbatches=4, bucket_bytes=32 * 1024,
                gather=mode, donate=False)
            s2, m2 = step(state, batch)
            assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
            for a, b in zip(jax.tree.leaves(s1.params),
                            jax.tree.leaves(s2.params)):
                np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                           np.asarray(jax.device_get(b)),
                                           atol=1e-5)

    def test_rejects_unknown_gather_mode(self):
        mesh, state, batch = self._setup()
        step = train.make_accum_train_step(
            mesh=mesh, microbatches=4, gather="bogus", donate=False)
        with pytest.raises(ValueError, match="gather"):
            step(state, batch)

    def test_mixed_tree_regression(self):
        """Satellite pin (gather_params fix): a params tree mixing
        sharded, uneven-sharded, replicated, and SCALAR leaves goes
        through the ZeRO-3 path and matches full-batch jax.grad."""
        params, specs = _mixed_tree()
        mesh = par.make_mesh(fsdp=4)
        kb = jax.random.split(jax.random.PRNGKey(8), 2)
        batch = {"x": jax.random.normal(kb[0], (32, 16)),
                 "y": jax.random.normal(kb[1], (32, 6))}

        def loss_fn(p, mb):
            out = mb["x"] @ (p["w"].T @ jnp.ones((8, 6)) @ p["u"]
                             + jnp.diag(p["b"])) * p["s"]
            return jnp.mean((out[:, :6] - mb["y"]) ** 2)

        for mode in ("bucketed", "per_leaf"):
            loss, grads = overlap.microbatch_grads(
                loss_fn, params, batch, mesh, microbatches=4,
                bucket_bytes=1 << 20, param_specs=specs, gather=mode)
            ref_loss, ref = jax.value_and_grad(
                lambda p: loss_fn(p, batch))(params)
            # Loss runs ~2e2 here: scale the tolerance (fp reassociation
            # of the microbatch sum), ~1e-7 relative.
            assert abs(float(loss) - float(ref_loss)) \
                < 1e-5 * max(1.0, abs(float(ref_loss)))
            assert np.ndim(jax.device_get(grads["s"])) == 0
            for k in ("w", "u", "b", "s"):
                np.testing.assert_allclose(
                    np.asarray(jax.device_get(grads[k])),
                    np.asarray(ref[k]), atol=1e-4)

    def test_fwd_gather_recorded(self):
        mesh, state, batch = self._setup()
        step = train.make_accum_train_step(
            mesh=mesh, microbatches=4, bucket_bytes=32 * 1024,
            prefetch=2, donate=False)
        profiler.reset_collective_records()
        step(state, batch)
        rec = profiler.collective_report()["accum.fwd_gather"]
        assert rec["kind"] == "all_gather"
        assert rec["plane"] == "fwd_gather"
        assert rec["axes"] == ["fsdp"]
        assert rec["gather"] == "bucketed" and rec["prefetch"] == 2
        assert sum(rec["nbytes"]) > 0


class TestPlanShardedEdgeCases:
    """Satellite pins on the bucket planner itself."""

    def test_single_leaf_larger_than_bucket_bytes(self):
        """One leaf bigger than the threshold gets a scatter bucket of its
        own (nowhere smaller to go) and still round-trips shard-major."""
        params = {"big": jnp.arange(64 * 16, dtype=jnp.float32
                                    ).reshape(64, 16),
                  "small": jnp.ones((8, 4))}
        specs = {"big": P("fsdp"), "small": P("fsdp")}
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=1024)
        assert plan.n_buckets == 2
        [b_big] = [b for b in range(plan.n_buckets)
                   if plan.bucket_nbytes[b] > plan.threshold]
        assert plan.buckets[b_big] == (0,)         # flatten: big, small
        bufs = plan.pack(params)
        out = plan.leaf_buffers(b_big, bufs[b_big], layout="gathered")
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(params["big"]))

    def test_pure_replicated_tree_falls_back_to_unsharded_plan(self):
        """Zero fsdp-sharded leaves: plan_sharded must degrade to the
        plain plan (no scatter buckets), not crash — and the accum engine
        must run it end to end."""
        k = jax.random.split(jax.random.PRNGKey(1), 2)
        params = {"a": jax.random.normal(k[0], (8, 4)),
                  "b": jax.random.normal(k[1], (16,))}
        specs = jax.tree.map(lambda _: P(), params)
        plan = GradBuckets.plan_sharded(params, specs, shard_size=4,
                                        bucket_bytes=1 << 20)
        base = GradBuckets.plan(params, 1 << 20)
        assert plan.n_scatter_buckets == 0
        assert plan.buckets == base.buckets
        assert plan.bucket_nbytes == base.bucket_nbytes
        assert GatherPlan.from_buckets(plan).n_gather_buckets == 0

        mesh = par.make_mesh(fsdp=4)
        kb = jax.random.split(jax.random.PRNGKey(2), 2)
        batch = {"x": jax.random.normal(kb[0], (32, 8)),
                 "y": jax.random.normal(kb[1], (32, 4))}

        def loss_fn(p, mb):
            return jnp.mean((mb["x"] @ p["a"] + p["b"][:4]
                             - mb["y"]) ** 2)

        loss, grads = overlap.microbatch_grads(
            loss_fn, params, batch, mesh, microbatches=4,
            bucket_bytes=1 << 20, param_specs=specs)
        ref_loss, ref = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(params)
        assert abs(float(loss) - float(ref_loss)) < 1e-5
        for a, b in zip(jax.tree.leaves(grads), jax.tree.leaves(ref)):
            np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                       np.asarray(b), atol=1e-5)


class TestReportAliasing:
    """Satellite pin: every profiler report is a deep copy behind one
    shared snapshot helper — mutating a returned report (including its
    nested lists/dicts) must not poison the live store."""

    @pytest.mark.parametrize("kind,report,reset", [
        ("overlap", profiler.overlap_report,
         profiler.reset_overlap_records),
        ("ckpt", profiler.ckpt_report, profiler.reset_ckpt_records),
        ("input", profiler.input_report, profiler.reset_input_records),
        ("collective", profiler.collective_report,
         profiler.reset_collective_records),
        ("update", profiler.update_report, profiler.reset_update_records),
        ("quant", profiler.quant_report, profiler.reset_quant_records),
        ("serve", profiler.serve_report, profiler.reset_serve_records),
        ("analysis", profiler.analysis_report,
         profiler.reset_analysis_records),
        ("locks", profiler.lock_report, profiler.reset_lock_records),
    ])
    def test_mutating_report_does_not_poison_store(self, kind, report,
                                                   reset):
        reset()
        profiler.safe_record(kind, "t", nested={"deep": [1, 2]},
                             nbytes=[10, 20])
        snap = report()
        snap["t"]["nested"]["deep"].append(99)
        snap["t"]["nbytes"][0] = -1
        snap["t"]["new_key"] = "poison"
        snap["injected"] = {}
        clean = report()
        assert clean == {"t": {"nested": {"deep": [1, 2]},
                               "nbytes": [10, 20]}}
        reset()


class TestMoEExplicitA2A:
    def _layer_and_vars(self, e=4, d=32, f=64, dtype=jnp.float32):
        import flax.linen as nn

        x = jax.random.normal(jax.random.PRNGKey(2), (8, 16, d), dtype)
        layer = MoEMLP(dim=d, ffn_hidden=f, n_experts=e, top_k=2,
                       dtype=dtype)
        variables = {"params": nn.unbox(
            layer.init(jax.random.PRNGKey(3), x))["params"]}
        return layer, variables, x

    @pytest.mark.parametrize("chunks", [1, 2, 7])
    def test_matches_gspmd_einsum_path(self, chunks):
        """The explicit per-capacity-chunk a2a path must reproduce the
        GSPMD dispatch-einsum path (chunked combine-sum reassociation
        aside) — including chunks > capacity, which clamps."""
        mesh = par.make_mesh(ep=2)
        layer, variables, x = self._layer_and_vars()
        y_ref = layer.apply(variables, x)
        layer_s = MoEMLP(dim=32, ffn_hidden=64, n_experts=4, top_k=2,
                         dtype=jnp.float32, explicit_a2a=True, mesh=mesh,
                         a2a_chunks=chunks)
        profiler.reset_collective_records()
        y = layer_s.apply(variables, x)
        np.testing.assert_allclose(np.asarray(jax.device_get(y)),
                                   np.asarray(jax.device_get(y_ref)),
                                   atol=1e-5)
        rec = profiler.collective_report()
        # Per-issue PER-CHIP payload (same semantics as pipeline edges):
        # [E, B/dp, Cc, D] f32 summed over chunks = E * B/dp * C * D * 4.
        capacity = rec["moe.dispatch"]["capacity"]
        dp = mesh.shape["data"]
        want_total = 4 * (8 // dp) * capacity * 32 * 4
        for tag in ("moe.dispatch", "moe.combine"):
            assert rec[tag]["kind"] == "all_to_all"
            assert rec[tag]["plane"] == "moe"
            assert rec[tag]["axes"] == ["expert"]
            assert len(rec[tag]["nbytes"]) == rec[tag]["chunks"]
            assert sum(rec[tag]["nbytes"]) == want_total

    def test_trains_under_jit_on_ep_mesh(self):
        """The explicit path composes with jit + sharded weights on the
        EP mesh (the make_train_step context it is meant for)."""
        from jax.sharding import NamedSharding

        mesh = par.make_mesh(ep=2)
        layer, variables, x = self._layer_and_vars()
        layer_s = MoEMLP(dim=32, ffn_hidden=64, n_experts=4, top_k=2,
                         dtype=jnp.float32, explicit_a2a=True, mesh=mesh,
                         a2a_chunks=2)
        shard = {"params": {
            k: NamedSharding(mesh, P("expert"))
            if k.startswith("w_") and k != "w_router"
            else NamedSharding(mesh, P())
            for k in variables["params"]}}
        v_sh = jax.device_put(variables, shard)
        x_sh = jax.device_put(x, par.batch_sharding(mesh))
        y_ref = layer.apply(variables, x)

        def f(v, xx):
            return layer_s.apply(v, xx)

        y = jax.jit(f)(v_sh, x_sh)
        np.testing.assert_allclose(np.asarray(jax.device_get(y)),
                                   np.asarray(jax.device_get(y_ref)),
                                   atol=1e-5)

    def test_requires_mesh(self):
        layer, variables, x = self._layer_and_vars()
        bad = MoEMLP(dim=32, ffn_hidden=64, n_experts=4, top_k=2,
                     dtype=jnp.float32, explicit_a2a=True)
        with pytest.raises(ValueError, match="mesh"):
            bad.apply(variables, x)

    def test_rejects_tp_sharded_mesh(self):
        mesh = par.make_mesh(ep=2, tp=2)
        w = jnp.zeros((4, 8, 16))
        with pytest.raises(ValueError, match="model"):
            moe_dispatch_ffn_combine(
                jnp.zeros((4, 4, 8)), jnp.zeros((4, 4, 4, 2)),
                jnp.zeros((4, 4, 4, 2)), (w, w, jnp.zeros((4, 16, 8))),
                mesh)

    def test_rejects_indivisible_experts(self):
        mesh = par.make_mesh(ep=2)
        w = jnp.zeros((3, 8, 16))
        with pytest.raises(ValueError, match="divisible"):
            moe_dispatch_ffn_combine(
                jnp.zeros((4, 4, 8)), jnp.zeros((4, 4, 3, 2)),
                jnp.zeros((4, 4, 3, 2)), (w, w, jnp.zeros((3, 16, 8))),
                mesh)


def test_pipeline_edges_registered():
    """gpipe/gpipe_1f1b register their ppermute ring edges with the
    scheduler: per-tick bytes, forward-only vs forward+reverse."""
    from tony_tpu.parallel import gpipe, gpipe_1f1b, stage_split

    mesh = par.make_mesh(pp=4)
    w = jax.random.normal(jax.random.PRNGKey(4), (4, 8, 8)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(5), (16, 8))

    def stage_fn(p, mb):
        return jnp.tanh(mb @ p["w"][0])

    profiler.reset_collective_records()
    y1 = gpipe(stage_fn, stage_split({"w": w}, 4), x, mesh,
               microbatches=4)
    y2 = gpipe_1f1b(stage_fn, stage_split({"w": w}, 4), x, mesh,
                    microbatches=4)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-6)
    rec = profiler.collective_report()
    fwd, fb = rec["gpipe.ppermute"], rec["gpipe_1f1b.ppermute"]
    # pp=4 mesh keeps data=2: each DP group's pipeline moves 16/2/4-row
    # microbatches of [*, 8] f32 per edge tick.
    mb_bytes = (16 // 2 // 4) * 8 * 4
    for r in (fwd, fb):
        assert r["kind"] == "ppermute" and r["plane"] == "pipeline"
        assert r["axes"] == ["pipe"]
        assert set(r["nbytes"]) == {mb_bytes}
    assert fwd["directions"] == 1 and fb["directions"] == 2
    assert len(fb["nbytes"]) == 2 * (4 + 4 - 1)


def test_collective_report_covers_all_planes():
    """ACCEPTANCE: every collective a ZeRO-3 + MoE + pipeline step issues
    shows up in one collective_report() — forward gathers, gradient
    scatter/reduce buckets, expert a2a, and pipeline edges."""
    profiler.reset_collective_records()

    # ZeRO-3 accum step (fwd all_gather + grad psum_scatter/all_reduce).
    mesh = par.make_mesh(fsdp=4)
    model = get_model("mnist-mlp", hidden=64)
    kx, ky, kr = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (32, 784))
    y = jax.random.randint(ky, (32,), 0, 10)
    state = fsdp_shard_state(
        train.create_train_state(model, optax.sgd(0.1), x, kr), mesh)
    step = train.make_accum_train_step(mesh=mesh, microbatches=4,
                                       bucket_bytes=32 * 1024,
                                       donate=False)
    step(state, {"x": x, "y": y})

    # MoE explicit a2a.
    import flax.linen as nn
    mesh_e = par.make_mesh(ep=2)
    xk = jax.random.normal(jax.random.PRNGKey(2), (8, 16, 32),
                           jnp.float32)
    layer = MoEMLP(dim=32, ffn_hidden=64, n_experts=4, top_k=2,
                   dtype=jnp.float32, explicit_a2a=True, mesh=mesh_e)
    variables = {"params": nn.unbox(
        layer.init(jax.random.PRNGKey(3), xk))["params"]}
    layer.apply(variables, xk)

    # Pipeline edges.
    from tony_tpu.parallel import gpipe_1f1b, stage_split
    mesh_p = par.make_mesh(pp=4)
    w = jax.random.normal(jax.random.PRNGKey(4), (4, 8, 8)) * 0.1
    gpipe_1f1b(lambda p, mb: jnp.tanh(mb @ p[0]), stage_split(w, 4),
               jax.random.normal(jax.random.PRNGKey(5), (16, 8)),
               mesh_p, microbatches=4)

    rec = profiler.collective_report()
    kinds = {r["kind"] for r in rec.values()}
    assert {"all_gather", "psum_scatter", "all_to_all",
            "ppermute"} <= kinds
    planes = {r["plane"] for r in rec.values() if "plane" in r}
    assert {"fwd_gather", "grad_reduce", "moe", "pipeline"} <= planes
    # Schema: every record carries kind/axes/nbytes.
    for tag, r in rec.items():
        assert {"kind", "axes", "nbytes"} <= set(r), tag


def test_run_sched_bench_smoke(monkeypatch):
    """The bench leg runs on the CPU mesh and reports bit-exact numerics
    plus the unified records (the speedup itself is hardware-dependent
    and not asserted here)."""
    from tony_tpu.benchmark import run_sched_bench

    monkeypatch.setenv("BENCH_WINDOWS", "1")
    r = run_sched_bench(leaves=12, leaf_rows=8, leaf_cols=16,
                        bucket_bytes=1024, steps=1)
    assert r["gather_bitexact"] and r["zero3_bitexact"]
    assert r["gather_per_leaf_s"] > 0 and r["gather_bucketed_s"] > 0
    assert r["n_gather_buckets"] >= 1
    assert r.get("moe_numerics_ok", True)
    kinds = {rec.get("kind") for rec in r["collective_records"].values()}
    assert "all_gather" in kinds
