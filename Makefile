# Developer/CI entry points. `make tier1` is THE gate: the exact ROADMAP.md
# tier-1 verify command (timeout, marker filter, dot accounting included) —
# run it before every push so CI never learns something you didn't.

SHELL := /bin/bash

.PHONY: tier1 tier1-slow quick test

# Exact ROADMAP.md "Tier-1 verify" command, verbatim.
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# The tests tier-1 excludes to stay inside its timeout (heavy multi-device
# compiles): run them standalone, no timeout.
tier1-slow:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m slow --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Fast pure-logic tier (~35s): the inner-loop smoke run.
quick:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m quick -p no:cacheprovider

test: tier1
