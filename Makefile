# Developer/CI entry points. `make tier1` is THE gate: the exact ROADMAP.md
# tier-1 verify command (timeout, marker filter, dot accounting included) —
# run it before every push so CI never learns something you didn't.

SHELL := /bin/bash

.PHONY: tier1 tier1-verify tier1-multislice tier1-ckpt tier1-data tier1-sched tier1-optim tier1-quant tier1-analysis tier1-serve tier1-spec tier1-route tier1-conc tier1-disagg tier1-kvtier tier1-aot tier1-qos tier1-elastic tier1-publish tier1-slow quick test lint

# THE gate: the verbatim ROADMAP command, then the explicit multislice leg
# (hierarchical ICI/DCN + ZeRO-3 paths on the simulated 2-slice mesh), the
# checkpoint leg (crash consistency / async overlap / elastic restore),
# the data-plane leg (deterministic sharding / prefetch / iterator-state
# resume) and the collective-scheduler leg (bucketed+prefetched forward
# gathers / explicit MoE a2a / unified collective records) so a
# regression there fails the make target by name, not just as one more
# dot. Legs run SEQUENTIALLY (the no-concurrent-pytest rule: e2e timing
# tests flake under CPU contention).
tier1: tier1-verify tier1-multislice tier1-ckpt tier1-data tier1-sched tier1-optim tier1-quant tier1-analysis tier1-serve tier1-spec tier1-route tier1-conc tier1-disagg tier1-kvtier tier1-aot tier1-qos tier1-elastic tier1-publish

# Exact ROADMAP.md "Tier-1 verify" command, verbatim.
tier1-verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# Multi-slice marker leg (also inside tier1-verify's 'not slow' selection;
# standalone so the hierarchical/ZeRO-3 gate is visible and can be run
# alone while iterating on the overlap engine).
tier1-multislice:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m multislice -p no:cacheprovider -p no:xdist -p no:randomly

# Checkpoint-plane marker leg (fast, tmpdir-backed; also inside
# tier1-verify's selection) — the slow large-state async-save test rides
# tier1-slow instead.
tier1-ckpt:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'ckpt and not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# Input-data-plane marker leg (tmpdir/array-backed; also inside
# tier1-verify's selection) — deterministic sharding, shuffle RNG,
# prefetch overlap, checkpointable iterator resume.
tier1-data:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'data and not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# Collective-scheduler marker leg (also inside tier1-verify's selection) —
# forward-gather bucketing/prefetch bit-exactness, MoE explicit a2a vs
# GSPMD, pipeline-edge records, unified collective_report schema.
tier1-sched:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'sched and not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# Fused-optimizer marker leg (also inside tier1-verify's selection) —
# bucket-major update kernels pinned vs optax, padded uneven shards,
# bucket-major grad norm/clip, leaf-major ckpt portability across
# changed fsdp topologies.
tier1-optim:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'optim and not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# Quantized-lane marker leg — int8 matmul kernel vs XLA fallback
# bit-exactness, per-channel scales, delayed-scaling windows,
# quantize-on-gather exactness + pad inertness, the LOSS-PIN gate, and
# the scale-state ckpt round-trip. Runs the FULL quant selection (slow
# included): the model loss pins and the cross-topology ckpt round-trip
# are slow-marked to keep tier1-verify inside its timeout, but this
# named leg is the lane's gate and must see all of them.
tier1-quant:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m quant -p no:cacheprovider -p no:xdist -p no:randomly

# Static-analysis marker leg (also inside tier1-verify's selection) — the
# jaxpr invariant analyzer: shipped configs clean, every rule fires on a
# seeded violation, committed step-signature pins, source lint.
tier1-analysis:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'analysis and not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# Serving-plane marker leg — paged KV cache invariants, flash-decoding
# kernel-vs-fallback bit pin, the continuous-batching BITWISE
# decode-vs-full-prefill pin, bf16 restore dtype policy, serve
# heartbeat/autoscale control plane. Runs the FULL serve selection
# (slow included): the train→ckpt→replica e2e is slow-marked to keep
# tier1-verify inside its timeout, but this named leg is the lane's
# gate and must see it.
tier1-serve:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serve -p no:cacheprovider -p no:xdist -p no:randomly

# Speculative-decoding marker leg — paged-cache spec_reserve/commit/
# rollback invariants + leak-free randomized accept/reject, the BITWISE
# greedy-parity pin vs the non-speculative engine (n-gram and model
# draft lanes, all draft depths), the effective-throughput heartbeat
# round trip, and the seventh analyze config. Runs the FULL spec
# selection (slow included): the train→replica spec e2e is slow-marked
# to keep tier1-verify inside its (already tight — ROADMAP) 870 s
# budget, but this named leg is the lane's gate and must see it.
tier1-spec:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m spec -p no:cacheprovider -p no:xdist -p no:randomly

# Routed-serving marker leg — prefix-cache sharing invariants (refcount/
# COW/LRU partition under a randomized interleave), the BITWISE pins of
# prefix-cached and chunked-prefill admissions vs the unrouted engine,
# the cross-replica router (overlap scoring, sticky affinity, failover),
# the widened heartbeat schema, and the eighth analyze config. Runs the
# FULL route selection (slow included): the multi-replica e2e and
# long-prompt chunking tests are slow-marked to keep tier1-verify inside
# its (tight — ROADMAP) 870 s budget, but this named leg is the lane's
# gate and must see them.
tier1-route:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m route -p no:cacheprovider -p no:xdist -p no:randomly

# Concurrency-plane marker leg — the lock-discipline lint + lock-order
# witness + thread-hygiene audit: seeded violations per rule, the
# package tree clean at HEAD, the witness catching a seeded lock-order
# inversion, and the genuinely multi-threaded randomized kvcache
# interleave (N threads of admit/fork/write/spec/evict with the
# refcount/free/LRU partition pinned at every quiescent point). Runs the
# FULL conc selection (slow included): the threaded stress tests are
# slow-marked to keep tier1-verify inside its (tight — ROADMAP) 870 s
# budget, but this named leg is the lane's gate and must see them.
tier1-conc:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m conc -p no:cacheprovider -p no:xdist -p no:randomly

# Disaggregated-serving marker leg — the KV-block wire tier (export/
# import with per-block CRC, adoption of shipped shared-prefix stems,
# state-unchanged typed rejections), the prefill-only engine mode, the
# BITWISE disagg-vs-colocated pins (ragged lengths, hit/miss
# admissions, spec lane on the decode side), bounded retry/backoff with
# the router's colocated fallback and the OSError-vs-request-error
# failover split, the widened role+handoff heartbeat schema, and the
# ninth analyze config. Runs the FULL disagg selection (slow included):
# the RPC fleet e2e and long-prompt handoff tests are slow-marked to
# keep tier1-verify inside its (tight — ROADMAP) 870 s budget, but this
# named leg is the lane's gate and must see them.
tier1-disagg:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m disagg -p no:cacheprovider -p no:xdist -p no:randomly

# KV-memory-hierarchy marker leg — the host-offload tier (demote/promote
# with bytes verbatim, CRC-guarded host payloads, the extended
# free/LRU/host partition), conversation parking pinned BITWISE vs a
# never-parked engine (ragged lengths, prefix-cache/spec/disagg
# composition), typed pool-pressure degrades, and the persistent prefix
# store's stage-and-rename round trip + replica adoption. Runs the FULL
# kvtier selection (slow included): the heavier parity sweeps are
# slow-marked to keep tier1-verify inside its (tight — ROADMAP) 870 s
# budget, but this named leg is the lane's gate and must see them.
tier1-kvtier:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m kvtier -p no:cacheprovider -p no:xdist -p no:randomly

# Replica cold-start marker leg (tony_tpu.ckpt.aot PR 17) — persisted
# AOT compile cache, warm-standby pool policy, demotion daemon; the
# heavier family sweeps are slow-marked to keep tier1-verify inside its
# timeout, but this named leg is the lane's full gate (slow included).
tier1-aot:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m aot -p no:cacheprovider -p no:xdist -p no:randomly

# History-plane + multi-tenant QoS marker leg (tony_tpu.serve.qos
# PR 18) — weighted-fair budgets + tenant-isolation bitwise pins, the
# widened jhist vocabulary with bounded rotation and the rename-race
# fix, SLO-mode autoscaling + exact decision replay, the tony history
# conf fix + dashboards; the engine-compile isolation pins and the
# threaded reader race are slow-marked to keep tier1-verify inside its
# timeout, but this named leg is the lane's full gate (slow included).
tier1-qos:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m qos -p no:cacheprovider -p no:xdist -p no:randomly

# Elastic-resize marker leg (tony_tpu.am.resize PR 19) — the resize
# state machine's phase/timeout/degrade pins, the chaos-injection
# harness, the drain→commit train-loop exit, the heartbeat-backoff
# regression, the rotation crash sweep, and the headline pin: a run
# with >=3 injected preemptions across changing host counts reproduces
# the undisturbed run's example-id stream exactly with final params
# within tolerance. The chaos/e2e segments are slow-marked to keep
# tier1-verify inside its (tight — ROADMAP) 870 s budget, but this
# named leg is the lane's full gate (slow included).
tier1-elastic:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m elastic -p no:cacheprovider -p no:xdist -p no:randomly

# Continuous-publication marker leg (tony_tpu.publish + tony_tpu.serve.
# swap PR 20) — the published.json pointer's stage-and-rename crash
# sweep (old pointer or new, never torn), resolve_target's pointer/pin/
# race rules, the FleetSwapController rolling-swap policy, the in-place
# hot weight swap pinned BITWISE vs a fresh replica restored from the
# same manifest with ZERO dropped requests under concurrent traffic,
# the four-site swap chaos sweep (exactly one weight version per
# replica), the routed 2-replica rolling-fleet headline, history
# billing windows, and tony aot gc. The replica hot-swap and
# rolling-fleet legs are slow-marked to keep tier1-verify inside its
# (tight — ROADMAP) 870 s budget, but this named leg is the lane's
# full gate (slow included).
tier1-publish:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m publish -p no:cacheprovider -p no:xdist -p no:randomly

# Source lints, machine-checked: (1) the jnp.concatenate/stack pack-site
# lint (the jax-0.4 GSPMD concat-reshard footgun) — every call site
# outside the approved pack planes must carry an audited
# 'packsite: region-local' pragma; (2) the concurrency plane — lock
# discipline (guarded-elsewhere mutations need the lock or an audited
# '# lockfree:' pragma), lock-order cycles over the static nested-with
# graph, and the thread-hygiene audit (daemon or joined), diffed against
# the committed blessings baseline.
lint:
	python -m tony_tpu.analysis.srclint tony_tpu
	python -m tony_tpu.analysis.concurrency tony_tpu --baseline tests/signatures/concurrency.json

# The tests tier-1 excludes to stay inside its timeout (heavy multi-device
# compiles): run them standalone, no timeout.
tier1-slow:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m slow --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Fast pure-logic tier (~35s): the inner-loop smoke run.
quick:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m quick -p no:cacheprovider

test: tier1
