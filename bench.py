"""Benchmark: ResNet-50 data-parallel train step on the real TPU chip.

North star (BASELINE.md): ≥55% MFU, images/sec/chip primary. This bench
runs the full training step (forward + backward + SGD update + BatchNorm
stats) on synthetic ImageNet-shaped data in bf16 and prints ONE JSON line::

    {"metric": "resnet50_mfu", "value": ..., "unit": ..., "vs_baseline": ...}

``vs_baseline`` is MFU / 0.55 (≥1.0 beats the target). Peak-FLOPs table per
chip generation; generation from PALLAS_AXON_TPU_GEN / TPU_ACCELERATOR_TYPE.
"""

from __future__ import annotations

import functools
import json
import os
import sys

import jax

from tony_tpu.benchmark import (PEAK_BF16, best_window_time,
                                chip_generation, peak_flops,
                                run_resnet_bench)


def main() -> int:
    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    # Batch 384: peak of the r3 sweep on v5e (128→0.247, 256→0.266,
    # 384→0.295, 512→0.292, 640→0.281, 768→0.275 MFU). The step profile
    # says why bigger stops helping: ~51% of step time is BatchNorm
    # statistics/backward reductions (bandwidth-bound, linear in batch),
    # ~45% conv fusions, ~2% maxpool backward — past the MXU's saturation
    # point extra batch just adds HBM traffic.
    batch = int(os.environ.get("BENCH_BATCH", "384" if on_tpu else "8"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "64"))
    # 20 steps/window: the device→host fence costs ~80 ms per window over
    # the relay; longer windows shrink its share of the measurement.
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "4"))

    # Fused pallas BN(+add)(+ReLU) epilogues (VERDICT r3 #1). Tried and
    # measured SLOWER than XLA's fusions — see ROOFLINE.md: XLA already
    # runs the BN reductions at/below the standalone-kernel HBM-pass
    # lower bound, so the fused path stays flag-gated off.
    fused_bn = os.environ.get("BENCH_FUSED_BN", "0") == "1"
    # MLPerf-standard space-to-depth stem (r5): mathematically equivalent
    # 4x4/s1 stem on the 112²x12 packing. Measured on v5e at batch 384:
    # see exp/s2d_results.txt and README round-5 notes.
    s2d = os.environ.get("BENCH_S2D", "1") == "1"
    # The step construction, scanned-window protocol, fencing, and MFU
    # accounting live in tony_tpu.benchmark so the tony-submitted bench
    # job (examples/resnet_bench_job) measures the IDENTICAL thing.
    result = run_resnet_bench(batch, image, steps, s2d=s2d,
                              fused_bn=fused_bn, on_tpu=on_tpu)
    peak = peak_flops(on_tpu)
    # One cumulative JSON line per completed leg (the driver/judge read the
    # LAST line): the 7B leg alone compiles for minutes, and a harness
    # timeout mid-leg must not cost the already-measured numbers.
    print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_OVERLAP", "1") != "0":
        # Comm/compute overlap leg: monolithic vs bucketed-accum step on
        # the DP mesh (runs on CPU too — numerics pin; the speedup only
        # means something on hardware with async collectives).
        try:
            from tony_tpu.benchmark import run_overlap_bench
            ov = run_overlap_bench(on_tpu=on_tpu)
            result["overlap_mono_step_s"] = ov["mono_step_s"]
            result["overlap_accum_step_s"] = ov["accum_step_s"]
            result["overlap_speedup"] = ov["speedup"]
            result["overlap_n_buckets"] = ov["n_buckets"]
            result["overlap_bucket_nbytes"] = ov["bucket_nbytes"]
            result["overlap_numerics_ok"] = ov["numerics_ok"]
        except Exception as e:  # secondary metric must not sink the bench
            result["overlap_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    n_dev = len(jax.devices())
    if os.environ.get("BENCH_OVERLAP_HIER", "1") != "0" and n_dev % 2 == 0 \
            and n_dev >= 2:
        # Hierarchical ICI/DCN leg on a (simulated) 2-slice mesh: the
        # per-bucket psum_scatter-over-ICI + DCN-allreduce schedule vs the
        # same accum step with the flat single-level reduce. On one host
        # both axes are ICI — the numerics pin is real, the DCN timing
        # story needs a real multi-slice pod.
        try:
            from tony_tpu.benchmark import run_overlap_bench
            hier = run_overlap_bench(slices=2, on_tpu=on_tpu)
            flat = run_overlap_bench(slices=2, hierarchy="flat",
                                     on_tpu=on_tpu)
            result["overlap_hier_step_s"] = hier["accum_step_s"]
            result["overlap_hier_flat_step_s"] = flat["accum_step_s"]
            result["overlap_hier_numerics_ok"] = (
                hier["numerics_ok"] and flat["numerics_ok"])
            result["overlap_hier_levels"] = hier["overlap_records"][
                "accum_step"]["levels"]
        except Exception as e:
            result["overlap_hier_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    # Largest power-of-two fsdp degree (<=4) the device count divides —
    # min(4, n_dev) broke on counts like 6.
    zero3_fsdp = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    if os.environ.get("BENCH_OVERLAP_ZERO3", "1") != "0" and zero3_fsdp > 1:
        # ZeRO-3 leg: fsdp-sharded params, grads psum_scatter-ed straight
        # into the shard layout inside the accum scan.
        try:
            from tony_tpu.benchmark import run_overlap_bench
            z = run_overlap_bench(fsdp=zero3_fsdp, zero3=True,
                                  on_tpu=on_tpu)
            result["overlap_zero3_step_s"] = z["accum_step_s"]
            result["overlap_zero3_mono_step_s"] = z["mono_step_s"]
            result["overlap_zero3_numerics_ok"] = z["numerics_ok"]
            result["overlap_zero3_scatter_buckets"] = z["n_scatter_buckets"]
        except Exception as e:
            result["overlap_zero3_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    sweep_env = os.environ.get("BENCH_OVERLAP_SWEEP", "")
    if sweep_env:
        # csv of bucket-bytes thresholds, e.g. "65536,1048576,4194304" —
        # prints its own JSON line (the sweep is a tuning curve, not a
        # headline key).
        try:
            from tony_tpu.benchmark import run_overlap_sweep
            sw = run_overlap_sweep(
                tuple(int(s) for s in sweep_env.split(",") if s),
                on_tpu=on_tpu)
            print(json.dumps(sw), flush=True)
        except Exception as e:
            result["overlap_sweep_error"] = f"{type(e).__name__}: {e}"
            print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_CKPT", "1") != "0":
        # Checkpoint-plane leg (tony_tpu.ckpt): blocking save wall time vs
        # the stall an async save charges the train loop, plus the
        # bit-exact restore pin. Runs on CPU too — unlike the overlap
        # legs, the I/O-vs-compute overlap is real on any backend.
        try:
            from tony_tpu.benchmark import run_ckpt_bench
            zero3_ckpt = 2 if n_dev % 2 == 0 else 1
            ck = run_ckpt_bench(fsdp=zero3_ckpt)
            result["ckpt_state_mb"] = ck["state_mb"]
            result["ckpt_blocking_save_s"] = ck["blocking_save_s"]
            result["ckpt_async_stall_s"] = ck["async_stall_s"]
            result["ckpt_stall_vs_blocking"] = ck["stall_vs_blocking"]
            result["ckpt_overlap_ok"] = ck["overlap_ok"]
            result["ckpt_restore_exact"] = ck["restore_exact"]
        except Exception as e:  # secondary metric must not sink the bench
            result["ckpt_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_INPUT", "1") != "0":
        # Input-plane leg (tony_tpu.data): per-step wait-on-data with the
        # prefetching device iterator at depth 0/1/2 over a feed with
        # simulated I/O latency. Runs on CPU too — like the ckpt leg, the
        # feed-vs-compute overlap is real on any backend.
        try:
            from tony_tpu.benchmark import run_input_bench
            di = run_input_bench()
            result["input_stall_ms_depth0"] = di["input_stall_ms_depth0"]
            result["input_stall_ms_depth1"] = di["input_stall_ms_depth1"]
            result["input_stall_ms_depth2"] = di["input_stall_ms_depth2"]
            result["input_stall_hidden"] = di["stall_hidden"]
            result["input_per_depth"] = di["per_depth"]
        except Exception as e:  # secondary metric must not sink the bench
            result["input_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_SCHED", "1") != "0" and n_dev % 2 == 0:
        # Collective-scheduler leg (tony_tpu.parallel.sched): per-leaf vs
        # bucketed+prefetched ZeRO-3 forward gathers (exposed gather
        # time), bit-exact step numerics, and MoE a2a-under-scan vs the
        # GSPMD default. Runs on CPU too — the gather coalescing win
        # (fewer, size-targeted collectives) is real on any backend; the
        # prefetch-overlap share of it needs hardware async collectives.
        try:
            from tony_tpu.benchmark import run_sched_bench
            sc = run_sched_bench(on_tpu=on_tpu)
            result["sched_gather_per_leaf_s"] = sc["gather_per_leaf_s"]
            result["sched_gather_bucketed_s"] = sc["gather_bucketed_s"]
            result["sched_gather_speedup"] = sc["gather_speedup"]
            result["sched_gather_2x_ok"] = sc["gather_2x_ok"]
            result["sched_gather_bitexact"] = sc["gather_bitexact"]
            result["sched_zero3_bitexact"] = sc["zero3_bitexact"]
            result["sched_n_gather_buckets"] = sc["n_gather_buckets"]
            result["sched_moe_numerics_ok"] = sc.get("moe_numerics_ok")
            result["sched_moe_gspmd_s"] = sc.get("moe_gspmd_s")
            result["sched_moe_sched_s"] = sc.get("moe_sched_s")
            result["sched_collective_kinds"] = sorted(
                {r.get("kind") for r in
                 sc["collective_records"].values()})
        except Exception as e:  # secondary metric must not sink the bench
            result["sched_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_OPTIM", "1") != "0" and n_dev % 2 == 0:
        # Fused-optimizer leg (tony_tpu.ops.fused_optim): per-leaf optax
        # update vs the bucket-major fused update on the simulated
        # fsdp mesh — wall time, jaxpr op counts (O(n_leaves) vs
        # O(n_buckets) update chains), f32 bit-exact pin. Runs on CPU too:
        # the dispatch-count win is real on any backend; the HBM
        # bytes-bound floor (ROOFLINE.md) needs metal.
        try:
            from tony_tpu.benchmark import run_optim_bench
            ob = run_optim_bench(on_tpu=on_tpu)
            result["optim_optax_update_s"] = ob["optax_update_s"]
            result["optim_fused_update_s"] = ob["fused_update_s"]
            result["optim_speedup"] = ob["speedup"]
            result["optim_n_leaves"] = ob["n_leaves"]
            result["optim_n_buckets"] = ob["n_buckets"]
            result["optim_optax_jaxpr_eqns"] = ob["optax_jaxpr_eqns"]
            result["optim_fused_jaxpr_eqns"] = ob["fused_jaxpr_eqns"]
            result["optim_numerics_ok"] = ob["numerics_ok"]
        except Exception as e:  # secondary metric must not sink the bench
            result["optim_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_QUANT", "1") != "0":
        # Quantized-lane leg (tony_tpu.ops.quant): int8 matmul vs bf16
        # wall time (on CPU the MXU win can't show — the leg documents
        # that and the metal run rides the hardware debt list), int8
        # gather bytes vs the BENCH_r09 bucketed path (4x for f32
        # params, bit-exact dequant pin), and the quantized-gather loss
        # pin gating both claims.
        try:
            from tony_tpu.benchmark import run_quant_bench
            qb = run_quant_bench(on_tpu=on_tpu)
            result["quant_bf16_matmul_s"] = qb["bf16_matmul_s"]
            result["quant_matmul_s"] = qb["quant_matmul_s"]
            result["quant_matmul_speedup"] = qb["quant_matmul_speedup"]
            result["quant_kernel_bitexact"] = qb["quant_kernel_bitexact"]
            if "quant_matmul_sim_note" in qb:
                result["quant_matmul_sim_note"] = qb["quant_matmul_sim_note"]
            result["quant_gather_raw_nbytes"] = qb.get("gather_raw_nbytes")
            result["quant_gather_int8_nbytes"] = qb.get(
                "gather_int8_nbytes")
            result["quant_gather_bytes_ratio"] = qb.get(
                "gather_bytes_ratio")
            result["quant_gather_2x_fewer_ok"] = qb.get(
                "gather_2x_fewer_ok")
            result["quant_gather_roundtrip_bitexact"] = qb.get(
                "gather_roundtrip_bitexact")
            result["quant_losspin_ok"] = qb.get("losspin_ok")
            result["quant_losspin_rel"] = qb.get("losspin_rel")
        except Exception as e:  # secondary metric must not sink the bench
            result["quant_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_SERVE", "1") != "0":
        # Serving-plane leg (tony_tpu.serve): continuous vs static
        # batching under one Poisson arrival trace — tokens/s, p50/p99
        # request latency, and the token-identity gate (continuous
        # batching must be bit-transparent). CPU numbers measure engine
        # scheduling, not TPU decode (serve_sim_note); BENCH_r12.
        try:
            from tony_tpu.benchmark import run_serve_bench
            result.update(run_serve_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["serve_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_SPEC", "1") != "0":
        # Speculative-decoding leg (tony_tpu.serve.spec): draft-and-
        # verify vs the plain engine on the SAME Poisson trace as the
        # serve leg — tokens per target forward, acceptance rate by
        # draft depth k, p50/p99, and the bitwise token-identity gate.
        # CPU wall numbers measure scheduling (spec_sim_note); the
        # forward-count ratios are the machine-independent claim;
        # BENCH_r13.
        try:
            from tony_tpu.benchmark import run_spec_bench
            result.update(run_spec_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["spec_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)

    if os.environ.get("BENCH_ROUTE", "1") != "0":
        # Routed-serving leg (tony_tpu.serve PR 13): block-level prefix
        # caching + chunked prefill + the 2-replica routed fleet on a
        # shared-prefix workload mix — prefill launch/row reduction and
        # cache hit rate (the machine-independent claims), chunked
        # on/off p50/p99, routed vs single-replica throughput, and the
        # token-identity gate in every configuration. CPU wall numbers
        # measure scheduling (route_sim_note); BENCH_r14.
        try:
            from tony_tpu.benchmark import run_route_bench
            result.update(run_route_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["route_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)

    if os.environ.get("BENCH_DISAGG", "1") != "0":
        # Disaggregated prefill/decode leg (tony_tpu.serve.disagg,
        # PR 15): a decode floor absorbing a prefill burst, colocated
        # chunked vs the split gang with KV-block handoff — decode p99
        # isolation is the headline, the decode side's ZERO prefill
        # launches and the launch split are the machine-independent
        # claims, token identity gated in both configurations. CPU wall
        # numbers measure scheduling (disagg_sim_note); BENCH_r15.
        try:
            from tony_tpu.benchmark import run_disagg_bench
            result.update(run_disagg_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["disagg_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)

    if os.environ.get("BENCH_KVTIER", "1") != "0":
        # KV-memory-hierarchy leg (tony_tpu.serve PR 16): multi-turn
        # conversations on the host-offload engine (park between
        # turns, resume through the atomic import path) vs the
        # recompute engine — turn-resume latency is the headline; the
        # machine-independent claims are the prefill-row ledger (zero
        # rows for the parked-covered extent), the park hit rate, and
        # the bitwise token-identity gate. CPU wall numbers measure
        # scheduling plus saved prefill compute (kvtier_sim_note);
        # BENCH_r16.
        try:
            from tony_tpu.benchmark import run_kvtier_bench
            result.update(run_kvtier_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["kvtier_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)

    if os.environ.get("BENCH_COLDSTART", "1") != "0":
        # Replica cold-start leg (tony_tpu.ckpt.aot, PR 17): grant→
        # first-token for a cold replica (trace+compile, cache
        # populate) vs a cache-hit replica (deserialize-only — ZERO
        # fresh compiles, counter-pinned) vs a warm standby (promote +
        # first request), with the build/warm/first-token wall split
        # broken out and token identity gated bitwise across all three
        # starts. CPU compile walls understate the TPU win
        # (coldstart_sim_note); BENCH_r17.
        try:
            from tony_tpu.benchmark import run_coldstart_bench
            result.update(run_coldstart_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["coldstart_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)

    if os.environ.get("BENCH_QOS", "1") != "0":
        # Multi-tenant QoS leg (tony_tpu.serve.qos, PR 18): a victim
        # tenant's decode floor absorbing an aggressor tenant's
        # long-prompt burst, weighted-fair block budgets on vs off —
        # victim p99 under the burst is the headline; the machine-
        # independent claims are the deferral ledger (back-pressure on
        # the aggressor, zero drops, zero deferrals unbudgeted) and the
        # bitwise victim-stream gate vs an unloaded engine. CPU wall
        # numbers measure scheduling (qos_sim_note); BENCH_r18.
        try:
            from tony_tpu.benchmark import run_qos_bench
            result.update(run_qos_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["qos_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)

    if os.environ.get("BENCH_RESIZE", "1") != "0":
        # Elastic-resize leg (tony_tpu.am.resize, PR 19): the drain →
        # commit → re-gang → restore lifecycle's data-plane walls — a
        # run interrupted mid-schedule by a synchronous drain-commit
        # and an elastic restore vs the same schedule undisturbed. The
        # headline is resize_overhead_s (decomposed into commit +
        # restore); the machine-independent claim is the bitwise
        # final-state gate (resize_numerics_ok). BENCH_r19.
        try:
            from tony_tpu.benchmark import run_resize_bench
            result.update(run_resize_bench(on_tpu=on_tpu))
        except Exception as e:  # secondary metric must not sink the bench
            result["resize_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if on_tpu and os.environ.get("BENCH_LLM", "1") != "0":
        try:
            result.update(bench_llm(peak))
        except Exception as e:  # secondary metric must not sink the bench
            result["llm_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if on_tpu and os.environ.get("BENCH_LLM_GQA", "1") != "0":
        # Zero-copy GQA leg (r5): same proxy shapes, kv_heads = heads/4.
        # MFU accounting counts the SMALLER kv projections, so the delta
        # is genuine kernel efficiency, not bookkeeping (r5 measured:
        # 0.585 MHA → 0.612 GQA, +13% tokens/sec).
        prior = os.environ.get("BENCH_LLM_KV_HEADS")
        try:
            os.environ["BENCH_LLM_KV_HEADS"] = str(
                max(1, int(os.environ.get("BENCH_LLM_HEADS", "8")) // 4))
            gqa = bench_llm(peak)
            result["llm_gqa_mfu"] = gqa["llm_mfu"]
            result["llm_gqa_tokens_per_sec"] = gqa["tokens_per_sec_per_chip"]
        except Exception as e:
            result["llm_gqa_error"] = f"{type(e).__name__}: {e}"
        finally:
            if prior is None:
                os.environ.pop("BENCH_LLM_KV_HEADS", None)
            else:
                os.environ["BENCH_LLM_KV_HEADS"] = prior
        print(json.dumps(result), flush=True)
    if on_tpu and os.environ.get("BENCH_LLM_7B", "1") != "0":
        try:
            result.update(bench_llm_7b(peak))
        except Exception as e:
            result["llm_7b_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(result), flush=True)
    if on_tpu and os.environ.get("BENCH_LLM_MOE", "1") != "0":
        # Mixtral-proxy sparse-MoE leg: 8 experts / top-2 / GQA kv=heads/4
        # at the proxy decoder shapes — measures the GShard static-capacity
        # dispatch path's single-chip efficiency.
        saved = {k: os.environ.get(k) for k in
                 ("BENCH_LLM_KV_HEADS", "BENCH_LLM_LAYERS",
                  "BENCH_LLM_SCAN", "BENCH_LLM_BATCH", "BENCH_LLM_REMAT")}
        try:
            os.environ["BENCH_LLM_KV_HEADS"] = str(
                max(1, int(os.environ.get("BENCH_LLM_HEADS", "8")) // 4))
            # 6 layers, scanned: 8 experts at the proxy dims are ~104M
            # params/layer — 12 layers of f32 adamw state exceed HBM, and
            # the 12-layer UNROLLED graph kills the AOT compile helper.
            os.environ["BENCH_LLM_LAYERS"] = \
                os.environ.get("BENCH_LLM_MOE_LAYERS", "6")
            os.environ["BENCH_LLM_SCAN"] = "1"
            # b16: the scanned layer stack keeps whole-stack bf16 copies
            # of the 8-expert weights as temps; b32 activations on top of
            # those tip 16 GB HBM.
            os.environ["BENCH_LLM_BATCH"] = \
                os.environ.get("BENCH_LLM_MOE_BATCH", "16")
            # Remat: without it the layer scan saves every layer's MoE
            # dispatch/combine tensors — gigabytes of f32 — and OOMs.
            os.environ["BENCH_LLM_REMAT"] = "1"
            moe = bench_llm(
                peak,
                moe_experts=int(os.environ.get("BENCH_LLM_MOE_EXPERTS",
                                               "8")),
                moe_top_k=int(os.environ.get("BENCH_LLM_MOE_TOPK", "2")))
            result["llm_moe_mfu"] = moe["llm_mfu"]
            result["llm_moe_tokens_per_sec"] = moe["tokens_per_sec_per_chip"]
        except Exception as e:
            result["llm_moe_error"] = f"{type(e).__name__}: {e}"
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        print(json.dumps(result), flush=True)
    return 0


def bench_llm_7b(peak: float) -> dict:
    """True Llama-2-7B LAYER shapes (SURVEY.md §6 config ⑤: dim 4096,
    32 heads, ffn 11008, vocab 32000), measured honestly under the 1-chip
    16 GB HBM constraint: f32 adamw state for 32 such layers needs ~100 GB
    (that is what fsdp shards on a pod), so the chip fits 2–3 layers and a
    small-L proxy over-weights the lm head ~12× vs the real model (24.5%
    of FLOPs at L=2 vs 2% at L=32).

    Protocol: run L=2 and L=3 at identical batch/seq/remat, difference the
    step times → the MARGINAL per-layer time (head/embed/overhead cancel),
    then report (a) the marginal per-layer MFU — the efficiency a 32-layer
    stack's bulk runs at — and (b) the 32-layer extrapolation
    t(32) = fixed + 32·marginal with full-model FLOPs. Round-5 measured:
    82 ms marginal layer, 61% marginal MFU, vs 51.5% raw at L=3.
    """
    import functools as _f

    import optax

    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    batch = int(os.environ.get("BENCH_LLM_7B_BATCH", "16"))
    seq = int(os.environ.get("BENCH_LLM_7B_SEQ", "512"))
    dim, heads, ffn, vocab = 4096, 32, 11008, 32000
    steps = int(os.environ.get("BENCH_LLM_7B_STEPS", "10"))
    times = {}
    for layers in (2, 3):
        model = get_model(
            "llama2-7b", dim=dim, n_layers=layers, n_heads=heads,
            n_kv_heads=heads, ffn_hidden=ffn, vocab=vocab, max_seq=seq,
            attention="flash", scan_layers=False, remat=True,
            xent_chunk=1024)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (batch, seq), 0, vocab)
        state = tr.create_train_state(
            model, optax.adamw(1e-4), tokens, jax.random.PRNGKey(1))
        step = tr.make_train_step(
            loss_of=lambda out, b: out,
            apply_kwargs_of=lambda b: {"targets": b["x"]})

        def scan_step(state, _):
            state, metrics = step(state, {"x": tokens})
            return state, metrics["loss"]

        @_f.partial(jax.jit, donate_argnums=(0,))
        def window(state):
            state, losses = jax.lax.scan(scan_step, state, None,
                                         length=steps)
            return state, losses[-1]

        best, state, _ = best_window_time(window, state,
                                          params_of=lambda s: s.params,
                                          default_windows=2)
        times[layers] = best / steps
        del state

    marginal_s = times[3] - times[2]
    fixed_s = times[2] - 2 * marginal_s
    tokens_per_step = batch * seq
    # Per-layer matmul FLOPs (fwd+bwd = 6·params + attention seq term).
    layer_flops = (6 * (dim * dim * 4 + 3 * dim * ffn)
                   + 12 * dim * seq) * tokens_per_step
    marginal_mfu = layer_flops / marginal_s / peak
    full_layers = 32
    t32 = fixed_s + full_layers * marginal_s
    flops32 = (full_layers * layer_flops
               + 6 * vocab * dim * tokens_per_step)
    return {
        "llm_7b_marginal_layer_mfu": round(marginal_mfu, 4),
        "llm_7b_extrapolated_32l_mfu": round(flops32 / t32 / peak, 4),
        "llm_7b_raw_3l_mfu_note":
            "see README r5: small-L proxies over-weight the lm head",
        "llm_7b_batch": batch,
        "llm_7b_seq": seq,
        "llm_7b_marginal_layer_ms": round(marginal_s * 1e3, 2),
    }


def bench_llm(peak: float, moe_experts: int = 0,
              moe_top_k: int = 2) -> dict:
    """Secondary metric: a matmul-dominated Llama-style train step (the
    GSPMD graduation config ⑤'s single-chip core), same fencing rules.
    ``moe_experts`` is an explicit PARAMETER, not env: the MoE leg must
    not be able to silently convert the dense headline legs."""
    import optax

    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    # r3 sweep on v5e (dim 1024, 12 layers, adamw, bf16): head_dim 64→128
    # was the big win (MXU contraction depth), 0.375→0.480 MFU; unrolling
    # the layer scan +5.6pt; batch 16 × seq 512 +4.7pt → 0.583; batch 32
    # +3.9pt → 0.622 (b64 OOMs on the f32-logits temp); flash block size
    # 128→256 +5pt → 0.673. An FFN-heavy variant (ffn 8192, BENCH_LLM_FFN)
    # measured 0.659 pre-block-win — reported via env knob, not defaulted:
    # the headline stays Llama-proportioned. heads=16 (head_dim 64) drops
    # to 0.474; seq 1024 at b8 to 0.551.
    batch = int(os.environ.get("BENCH_LLM_BATCH", "32"))
    seq = int(os.environ.get("BENCH_LLM_SEQ", "512"))
    heads = int(os.environ.get("BENCH_LLM_HEADS", "8"))
    # GQA (zero-copy through the flash kernels' index maps — r5):
    # n_kv_heads < n_heads shrinks K/V projections and kernel KV traffic.
    kv_heads = int(os.environ.get("BENCH_LLM_KV_HEADS", str(heads)))
    dim = int(os.environ.get("BENCH_LLM_DIM", "1024"))
    ffn = int(os.environ.get("BENCH_LLM_FFN", "4096"))
    layers = int(os.environ.get("BENCH_LLM_LAYERS", "12"))
    vocab = int(os.environ.get("BENCH_LLM_VOCAB", "32768"))
    remat = os.environ.get("BENCH_LLM_REMAT", "0") == "1"
    remat_policy = os.environ.get("BENCH_LLM_REMAT_POLICY") or None
    scan_layers = os.environ.get("BENCH_LLM_SCAN", "0") == "1"
    # Row-chunked fused head+CE (train.chunked_next_token_xent): the
    # [B,T,V] logits never materialize, lifting the f32-logits HBM cap
    # that limited batch to 32. 0 = plain head + next_token_loss.
    xent_chunk = int(os.environ.get("BENCH_LLM_XENT_CHUNK", "0"))
    model = get_model(
        "llama2-7b", dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=kv_heads, ffn_hidden=ffn, vocab=vocab, max_seq=seq,
        attention=os.environ.get("BENCH_LLM_ATTN", "flash"),
        scan_layers=scan_layers, remat=remat, remat_policy=remat_policy,
        xent_chunk=xent_chunk, moe_experts=moe_experts,
        moe_top_k=moe_top_k)
    cfg = model.cfg
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (batch, seq), 0, cfg.vocab)
    state = tr.create_train_state(
        model, optax.adamw(1e-4), tokens, jax.random.PRNGKey(1))
    if xent_chunk:
        step = tr.make_train_step(
            loss_of=lambda out, b: out,
            apply_kwargs_of=lambda b: {"targets": b["x"]})
    else:
        step = tr.make_train_step(
            loss_of=lambda logits, b: tr.next_token_loss(logits, b["x"]))

    steps = int(os.environ.get("BENCH_LLM_STEPS", "20"))
    # One dispatch per timed window (see the resnet window comment).
    def scan_step(state, _):
        state, metrics = step(state, {"x": tokens})
        return state, metrics["loss"]

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window(state):
        state, losses = jax.lax.scan(scan_step, state, None, length=steps)
        return state, losses[-1]

    best, state, loss = best_window_time(
        window, state, params_of=lambda s: s.params)
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / best
    mfu = cfg.flops_per_token() * tokens_per_sec / peak
    return {
        "llm_mfu": round(mfu, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "llm_batch": batch,
        "llm_seq": seq,
        "llm_loss": float(loss),
    }


if __name__ == "__main__":
    sys.exit(main())
