"""Benchmark: ResNet-50 data-parallel train step on the real TPU chip.

North star (BASELINE.md): ≥55% MFU, images/sec/chip primary. This bench
runs the full training step (forward + backward + SGD update + BatchNorm
stats) on synthetic ImageNet-shaped data in bf16 and prints ONE JSON line::

    {"metric": "resnet50_mfu", "value": ..., "unit": ..., "vs_baseline": ...}

``vs_baseline`` is MFU / 0.55 (≥1.0 beats the target). Peak-FLOPs table per
chip generation; generation from PALLAS_AXON_TPU_GEN / TPU_ACCELERATOR_TYPE.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

# Peak bf16 matmul FLOP/s per chip by generation (public spec sheets).
PEAK_BF16 = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def best_window_time(window, carry, params_of, default_windows=4):
    """Shared measurement protocol for both benches: run
    ``window(carry) -> (carry, loss)`` twice as warmup (compile + steady
    state), then best-of-N timed runs. Each run is fenced via host readback
    of the loss AND a param leaf — through the remote PJRT relay,
    ``block_until_ready`` returns before execution finishes, so a
    device→host transfer is the only reliable fence, and the last optimizer
    update is not a dependency of its own step's loss. Best window wins:
    the relay path has heavy run-to-run jitter (67–266 ms spread measured
    on one step) and the fastest window best estimates device throughput.

    Returns ``(best_seconds, carry, loss)``.
    """
    carry, loss = window(carry)
    float(loss)
    carry, loss = window(carry)
    float(loss)
    best = float("inf")
    for _ in range(int(os.environ.get("BENCH_WINDOWS",
                                      str(default_windows)))):
        t0 = time.perf_counter()
        carry, loss = window(carry)
        float(loss)
        float(jax.tree_util.tree_leaves(params_of(carry))[0].ravel()[0])
        best = min(best, time.perf_counter() - t0)
    return best, carry, loss


def chip_generation() -> str:
    gen = os.environ.get("PALLAS_AXON_TPU_GEN") or os.environ.get(
        "TPU_ACCELERATOR_TYPE", "v5e")
    return gen.split("-")[0].lower()


def main() -> int:
    import optax
    import flax.linen as nn

    from tony_tpu.models import get_model
    from tony_tpu.models.resnet import resnet50_flops
    from tony_tpu import train as tr

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    # Batch 384: peak of the r3 sweep on v5e (128→0.247, 256→0.266,
    # 384→0.295, 512→0.292, 640→0.281, 768→0.275 MFU). The step profile
    # says why bigger stops helping: ~51% of step time is BatchNorm
    # statistics/backward reductions (bandwidth-bound, linear in batch),
    # ~45% conv fusions, ~2% maxpool backward — past the MXU's saturation
    # point extra batch just adds HBM traffic.
    batch = int(os.environ.get("BENCH_BATCH", "384" if on_tpu else "8"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "64"))
    # 20 steps/window: the device→host fence costs ~80 ms per window over
    # the relay; longer windows shrink its share of the measurement.
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "4"))

    # Fused pallas BN(+add)(+ReLU) epilogues (VERDICT r3 #1). Tried and
    # measured SLOWER than XLA's fusions — see ROOFLINE.md: XLA already
    # runs the BN reductions at/below the standalone-kernel HBM-pass
    # lower bound, so the fused path stays flag-gated off.
    fused_bn = os.environ.get("BENCH_FUSED_BN", "0") == "1"
    # MLPerf-standard space-to-depth stem (r5): mathematically equivalent
    # 4x4/s1 stem on the 112²x12 packing. Measured on v5e at batch 384:
    # see exp/s2d_results.txt and README round-5 notes.
    s2d = os.environ.get("BENCH_S2D", "1") == "1"
    model = get_model("resnet50", fused_bn=fused_bn, s2d_stem=s2d)
    kx, ky, kinit = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (batch, image, image, 3), jnp.bfloat16)
    y = jax.random.randint(ky, (batch,), 0, 1000)
    variables = jax.jit(lambda: model.init(kinit, x, train=False))()
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)

    def step(carry, _):
        params, opt_state, batch_stats = carry

        def loss_fn(p):
            logits, updates = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return tr.cross_entropy_loss(logits, y), updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, new_stats), loss

    # The whole timed window is ONE jitted lax.scan over `steps` train
    # steps: through the remote PJRT relay each dispatch costs ~5 ms, so a
    # per-step host loop would tax every step; one dispatch per window
    # amortizes it to noise.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def window(carry):
        carry, losses = jax.lax.scan(step, carry, None, length=steps)
        return carry, losses[-1]

    elapsed, (params, opt_state, batch_stats), loss = best_window_time(
        window, (params, opt_state, batch_stats), params_of=lambda c: c[0])

    images_per_sec = batch * steps / elapsed
    # fwd ≈ 8.2 GFLOP/image @224² (MACs×2); training ≈ 3× forward.
    train_flops_per_step = 3 * resnet50_flops(batch, image)
    gen = chip_generation()
    peak = PEAK_BF16.get(gen, PEAK_BF16["v5e"]) if on_tpu else 1e12
    mfu = train_flops_per_step * steps / elapsed / peak

    result = {
        "metric": "resnet50_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_bf16_peak",
        "vs_baseline": round(mfu / 0.55, 4),
        "images_per_sec_per_chip": round(images_per_sec, 1),
        "batch": batch,
        "image": image,
        "backend": backend,
        "chip": gen,
        "fused_bn": fused_bn,
        "loss": float(loss),
    }
    if on_tpu and os.environ.get("BENCH_LLM", "1") != "0":
        try:
            result.update(bench_llm(peak))
        except Exception as e:  # secondary metric must not sink the bench
            result["llm_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result))
    return 0


def bench_llm(peak: float) -> dict:
    """Secondary metric: a matmul-dominated Llama-style train step (the
    GSPMD graduation config ⑤'s single-chip core), same fencing rules."""
    import optax

    from tony_tpu import train as tr
    from tony_tpu.models import get_model

    # r3 sweep on v5e (dim 1024, 12 layers, adamw, bf16): head_dim 64→128
    # was the big win (MXU contraction depth), 0.375→0.480 MFU; unrolling
    # the layer scan +5.6pt; batch 16 × seq 512 +4.7pt → 0.583; batch 32
    # +3.9pt → 0.622 (b64 OOMs on the f32-logits temp); flash block size
    # 128→256 +5pt → 0.673. An FFN-heavy variant (ffn 8192, BENCH_LLM_FFN)
    # measured 0.659 pre-block-win — reported via env knob, not defaulted:
    # the headline stays Llama-proportioned. heads=16 (head_dim 64) drops
    # to 0.474; seq 1024 at b8 to 0.551.
    batch = int(os.environ.get("BENCH_LLM_BATCH", "32"))
    seq = int(os.environ.get("BENCH_LLM_SEQ", "512"))
    heads = int(os.environ.get("BENCH_LLM_HEADS", "8"))
    # GQA (zero-copy through the flash kernels' index maps — r5):
    # n_kv_heads < n_heads shrinks K/V projections and kernel KV traffic.
    kv_heads = int(os.environ.get("BENCH_LLM_KV_HEADS", str(heads)))
    dim = int(os.environ.get("BENCH_LLM_DIM", "1024"))
    ffn = int(os.environ.get("BENCH_LLM_FFN", "4096"))
    layers = int(os.environ.get("BENCH_LLM_LAYERS", "12"))
    vocab = int(os.environ.get("BENCH_LLM_VOCAB", "32768"))
    remat = os.environ.get("BENCH_LLM_REMAT", "0") == "1"
    scan_layers = os.environ.get("BENCH_LLM_SCAN", "0") == "1"
    # Row-chunked fused head+CE (train.chunked_next_token_xent): the
    # [B,T,V] logits never materialize, lifting the f32-logits HBM cap
    # that limited batch to 32. 0 = plain head + next_token_loss.
    xent_chunk = int(os.environ.get("BENCH_LLM_XENT_CHUNK", "0"))
    model = get_model(
        "llama2-7b", dim=dim, n_layers=layers, n_heads=heads,
        n_kv_heads=kv_heads, ffn_hidden=ffn, vocab=vocab, max_seq=seq,
        attention=os.environ.get("BENCH_LLM_ATTN", "flash"),
        scan_layers=scan_layers, remat=remat, xent_chunk=xent_chunk)
    cfg = model.cfg
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (batch, seq), 0, cfg.vocab)
    state = tr.create_train_state(
        model, optax.adamw(1e-4), tokens, jax.random.PRNGKey(1))
    if xent_chunk:
        step = tr.make_train_step(
            loss_of=lambda out, b: out,
            apply_kwargs_of=lambda b: {"targets": b["x"]})
    else:
        step = tr.make_train_step(
            loss_of=lambda logits, b: tr.next_token_loss(logits, b["x"]))

    steps = int(os.environ.get("BENCH_LLM_STEPS", "20"))
    # One dispatch per timed window (see the resnet window comment).
    def scan_step(state, _):
        state, metrics = step(state, {"x": tokens})
        return state, metrics["loss"]

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window(state):
        state, losses = jax.lax.scan(scan_step, state, None, length=steps)
        return state, losses[-1]

    best, state, loss = best_window_time(
        window, state, params_of=lambda s: s.params)
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / best
    mfu = cfg.flops_per_token() * tokens_per_sec / peak
    return {
        "llm_mfu": round(mfu, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "llm_batch": batch,
        "llm_seq": seq,
        "llm_loss": float(loss),
    }


if __name__ == "__main__":
    sys.exit(main())
