"""PyTorch DDP MNIST over the PyTorchRuntime rendezvous.

Reference analogue: ``tony-examples/mnist-pytorch`` (SURVEY.md §2.2). The
PyTorchRuntime exports MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE/LOCAL_RANK;
this script hands them to ``torch.distributed`` (gloo — CPU containers; on
GPU clusters the reference used NCCL, which TonY-TPU does not ship: TPU
training belongs to the JAXRuntime).

Submit::

    tony submit --framework pytorch --src_dir examples \\
        --executes "python pytorch_mnist_ddp.py" \\
        --conf tony.worker.instances=2
"""

import json
import os
from pathlib import Path

import torch
import torch.distributed as td
import torch.nn as nn


def main():
    world = int(os.environ.get("WORLD_SIZE", "1"))
    if world > 1:
        td.init_process_group("gloo")
    rank = td.get_rank() if world > 1 else 0

    torch.manual_seed(rank)
    model = nn.Sequential(nn.Linear(784, 128), nn.ReLU(), nn.Linear(128, 10))
    if world > 1:
        model = nn.parallel.DistributedDataParallel(model)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    loss_fn = nn.CrossEntropyLoss()

    x = torch.randn(256, 784)
    y = torch.randint(0, 10, (256,))
    losses = []
    for step in range(20):
        opt.zero_grad()
        loss = loss_fn(model(x), y)
        loss.backward()        # DDP allreduces grads here
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    if rank == 0:
        Path("result.json").write_text(json.dumps(
            {"final_loss": losses[-1], "world_size": world}))
        print(f"done: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
              f"(world={world})")
    if world > 1:
        td.destroy_process_group()


if __name__ == "__main__":
    main()
