"""Expert-parallel mixture-of-experts training (SURVEY.md §2.3 EP — a
TPU-build capability the reference never had).

One jitted train step over a dp×ep×tp mesh: expert FFN weights shard over
the ``expert`` axis (GSPMD turns the dispatch einsums into all_to_all over
ICI), the Switch load-balancing aux loss flows through the train harness's
``losses`` collection automatically.

Submit (2 hosts)::

    tony submit --framework jax --src_dir examples \\
        --executes "python jax_moe_ep.py" \\
        --conf tony.worker.instances=2 --conf tony.worker.tpus=4

Env knobs: MODEL (llama-moe-tiny|mixtral-8x7b), MESH_EP/MESH_TP, STEPS.
"""

import json
import os
from pathlib import Path

import jax

import tony_tpu.distributed as dist

dist.initialize()

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model


def main():
    ep = int(os.environ.get("MESH_EP", str(min(2, jax.device_count()))))
    tp = int(os.environ.get("MESH_TP", "1"))
    mesh = par.MeshSpec(ep=ep, tp=tp).build()

    model = get_model(os.environ.get("MODEL", "llama-moe-tiny"))
    cfg = model.cfg
    # BATCH is the GLOBAL batch; each process contributes its local shard
    # through train.global_batch (cf. jax_llama_sharded.py).
    batch = int(os.environ.get("BATCH", str(2 * mesh.shape["data"])))
    local = batch // max(1, jax.process_count())
    seq = min(cfg.max_seq, int(os.environ.get("SEQ", "64")))

    sample = jnp.zeros((batch, seq), jnp.int32)
    state = train.create_train_state(
        model, optax.adamw(3e-4), sample, jax.random.PRNGKey(0), mesh=mesh)
    step = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
        mesh=mesh)

    losses, aux = [], []
    for i in range(int(os.environ.get("STEPS", "5"))):
        tokens = jax.random.randint(
            jax.random.PRNGKey(1000 * jax.process_index() + i),
            (local, seq), 0, cfg.vocab)
        state, metrics = step(state, train.global_batch(mesh, {"x": tokens}))
        losses.append(float(metrics["loss"]))
        aux.append(float(metrics["aux_loss"]))
        if jax.process_index() == 0:
            print(f"step {i} loss {losses[-1]:.4f} aux {aux[-1]:.4f}")

    if jax.process_index() == 0:
        Path("moe_losses.json").write_text(json.dumps({
            "mesh": dict(mesh.shape), "losses": losses, "aux": aux}))


if __name__ == "__main__":
    main()
