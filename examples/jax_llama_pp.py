"""Pipeline-parallel Llama training (SURVEY.md §2.3 PP — a TPU-build
capability the reference never had).

The transformer's scanned block stack runs as a GPipe over the ``pipe``
mesh axis (microbatches rotating via ppermute), composed with data
parallelism; embedding/head stay outside the pipeline. The whole schedule
— forward, reverse-pipeline backward, optimizer update — is one jitted
program.

Submit (2 hosts)::

    tony submit --framework jax --src_dir examples \\
        --executes "python jax_llama_pp.py" \\
        --conf tony.worker.instances=2 --conf tony.worker.tpus=4

Env knobs: MODEL, MESH_PP, MICROBATCHES, STEPS.
"""

import json
import os
from pathlib import Path

import jax

import tony_tpu.distributed as dist

dist.initialize()

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model
from tony_tpu.parallel import pipelined_lm_logits


def main():
    pp = int(os.environ.get("MESH_PP", str(min(2, jax.device_count()))))
    mesh = par.MeshSpec(pp=pp).build()
    microbatches = int(os.environ.get("MICROBATCHES", str(2 * pp)))

    model = get_model(os.environ.get("MODEL", "llama-tiny"))
    cfg = model.cfg
    dp = mesh.shape["data"]
    # BATCH is the GLOBAL batch; each process feeds its local shard via
    # train.global_batch (cf. jax_llama_sharded.py).
    batch = int(os.environ.get("BATCH", str(microbatches * dp)))
    local = batch // max(1, jax.process_count())
    seq = min(cfg.max_seq, int(os.environ.get("SEQ", "64")))

    sample = jnp.zeros((batch, seq), jnp.int32)
    state = train.create_train_state(
        model, optax.adamw(3e-4), sample, jax.random.PRNGKey(1), mesh=mesh)

    def loss_fn(params, tokens):
        logits = pipelined_lm_logits(params, tokens, cfg, mesh,
                                     n_stages=pp, microbatches=microbatches)
        return train.next_token_loss(logits, tokens)

    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        return state.apply_gradients(grads=grads), loss

    losses = []
    for i in range(int(os.environ.get("STEPS", "5"))):
        local_tokens = jax.random.randint(
            jax.random.PRNGKey(1000 * jax.process_index() + i),
            (local, seq), 0, cfg.vocab)
        tokens = train.global_batch(mesh, {"x": local_tokens})["x"]
        state, loss = step(state, tokens)
        losses.append(float(loss))
        if jax.process_index() == 0:
            print(f"step {i} loss {losses[-1]:.4f}")

    if jax.process_index() == 0:
        Path("pp_losses.json").write_text(json.dumps({
            "mesh": dict(mesh.shape), "microbatches": microbatches,
            "losses": losses}))


if __name__ == "__main__":
    main()
