"""Data-parallel MNIST on JAX — the canonical TonY-TPU job.

Reference analogue: ``tony-examples/mnist-tensorflow`` /
``mnist-distributed`` (SURVEY.md §2.2), re-designed for the JAXRuntime: the
rendezvous is ``tony_tpu.distributed.initialize()`` (wired from the env the
JAXRuntime adapter built), the data plane is the GSPMD gradient psum over
the device mesh — no parameter server, no NCCL.

Submit::

    tony submit --framework jax --src_dir examples \\
        --executes "python jax_mnist_dp.py" \\
        --conf tony.worker.instances=2

Uses synthetic MNIST-shaped data unless ``MNIST_NPZ`` points at the real
arrays (keeps the example hermetic: the image has no dataset downloads).
"""

import json
import os
from pathlib import Path

import jax

import tony_tpu.distributed as dist

dist.initialize()          # no-op single-process; rendezvous under TonY

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.checkpoint import Checkpointer
from tony_tpu.models import get_model


def load_data(rng, n=512):
    npz = os.environ.get("MNIST_NPZ")
    if npz and Path(npz).is_file():
        import numpy as np
        with np.load(npz) as d:
            return (jnp.asarray(d["x_train"][:n]).reshape(n, -1) / 255.0,
                    jnp.asarray(d["y_train"][:n]))
    x = jax.random.normal(rng, (n, 784))
    y = jax.random.randint(rng, (n,), 0, 10)
    return x, y


def main():
    mesh = par.MeshSpec(dp=jax.device_count()).build()
    model = get_model("mnist-mlp")
    x, y = load_data(jax.random.PRNGKey(jax.process_index()))

    state = train.create_train_state(
        model, optax.adam(1e-3), jnp.zeros((1, 784)), jax.random.PRNGKey(0),
        mesh=mesh)
    # Checkpoint dir must be shared + stable across gang restarts (the
    # per-container sandbox is replaced on restart); every process calls
    # save/restore — tony_tpu.ckpt coordinates the per-process shard
    # writes through the shared directory (process 0 commits).
    ckpt_dir = os.environ.get("CKPT_DIR") or (
        Path.home() / ".tony-tpu" / "ckpt"
        / os.environ.get("TONY_APP_ID", "local-mnist"))
    ckpt = Checkpointer(ckpt_dir)
    state = ckpt.restore_or(state)
    step_fn = train.make_train_step(mesh=mesh)

    steps = int(os.environ.get("TRAIN_STEPS", "30"))
    per = x.shape[0] // max(1, steps)
    start = int(state.step)
    loss = None
    for i in range(start, steps):
        lo = (i * per) % (x.shape[0] - per + 1)
        batch = train.global_batch(mesh, {"x": x[lo:lo + per],
                                          "y": y[lo:lo + per]})
        state, metrics = step_fn(state, batch)
        loss = float(metrics["loss"])
        if i % 10 == 0:
            if jax.process_index() == 0:
                print(f"step {i}: loss {loss:.4f}", flush=True)
            ckpt.save(state)
    ckpt.save(state)
    if jax.process_index() == 0:
        Path("result.json").write_text(json.dumps({"final_loss": loss}))
        print("done:", "already complete (resumed past TRAIN_STEPS)"
              if loss is None else f"final loss {loss:.4f}")


if __name__ == "__main__":
    main()
