"""ResNet-50 DP bench INSIDE a tony job (BASELINE.md: the north star is
measured "via tony-submit", not via a bare script — VERDICT r4 next-step
#2). Runs the IDENTICAL step/protocol as bench.py via tony_tpu.benchmark,
prints the one-line JSON, and writes it to ./bench_result.json for the
client/test to collect."""
import json
import os
import sys

from tony_tpu.benchmark import run_resnet_bench

batch = int(os.environ.get("BENCH_BATCH", "384"))
image = int(os.environ.get("BENCH_IMAGE", "224"))
steps = int(os.environ.get("BENCH_STEPS", "20"))
result = run_resnet_bench(batch, image, steps)
result["task"] = "{}:{}".format(os.environ.get("TONY_JOB_NAME", "?"),
                                os.environ.get("TONY_TASK_INDEX", "?"))
print(json.dumps(result))
with open("bench_result.json", "w") as f:
    json.dump(result, f)
sys.exit(0)
