"""Sharded Llama-style training — the GSPMD graduation config (SURVEY.md §6
config ⑤) at example scale.

One jitted train step over a dp×fsdp×tp(×sp) mesh: params shard per the
logical rules, XLA inserts the TP collectives and DP gradient psum, ring
attention activates when ``MESH_SP > 1``. On a pod slice, submit with one
worker per host and the JAXRuntime wires the multi-host mesh; single-host it
uses every local chip.

Submit (2 hosts)::

    tony submit --framework jax --src_dir examples \\
        --executes "python jax_llama_sharded.py" \\
        --conf tony.worker.instances=2 --conf tony.worker.tpus=4

Env knobs: MODEL (llama-tiny|llama2-7b), MESH_TP/MESH_SP/MESH_FSDP, STEPS.
"""

import json
import os
from pathlib import Path

import jax

import tony_tpu.distributed as dist

dist.initialize()

import jax.numpy as jnp
import optax

from tony_tpu import parallel as par
from tony_tpu import train
from tony_tpu.models import get_model


def main():
    tp = int(os.environ.get("MESH_TP", "1"))
    sp = int(os.environ.get("MESH_SP", "1"))
    fsdp = int(os.environ.get("MESH_FSDP", "1"))
    mesh = par.MeshSpec(fsdp=fsdp, sp=sp, tp=tp).build()

    name = os.environ.get("MODEL", "llama-tiny")
    model = get_model(name, attention="ring" if sp > 1 else "flash",
                      mesh=mesh if sp > 1 else None)
    cfg = model.cfg
    batch = int(os.environ.get("BATCH", str(2 * mesh.shape["data"])))
    seq = min(cfg.max_seq, int(os.environ.get("SEQ", "64")))

    rng = jax.random.PRNGKey(jax.process_index())
    local = batch // max(1, jax.process_count())
    tokens_local = jax.random.randint(rng, (local, seq), 0, cfg.vocab)

    state = train.create_train_state(
        model, optax.adamw(3e-4),
        jnp.zeros((batch, seq), jnp.int32), jax.random.PRNGKey(0), mesh=mesh)
    step_fn = train.make_train_step(
        loss_of=lambda logits, b: train.next_token_loss(logits, b["x"]),
        mesh=mesh)

    losses = []
    for i in range(int(os.environ.get("STEPS", "10"))):
        batch_arrays = train.global_batch(mesh, {"x": tokens_local})
        state, metrics = step_fn(state, batch_arrays)
        losses.append(float(metrics["loss"]))
        if jax.process_index() == 0:
            print(f"step {i}: loss {losses[-1]:.4f} "
                  f"grad_norm {float(metrics['grad_norm']):.3f}", flush=True)
    if jax.process_index() == 0:
        Path("result.json").write_text(json.dumps({
            "model": name, "mesh": dict(mesh.shape), "losses": losses}))


if __name__ == "__main__":
    main()
