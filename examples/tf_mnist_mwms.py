"""Multi-worker TensorFlow MNIST — the reference's flagship example shape.

Reference analogue: ``tony-examples/mnist-tensorflow`` (SURVEY.md §2.2,
graduation configs ①/②): an actually-training TF job whose only wiring is
the ``TF_CONFIG`` the TFRuntime injected. MultiWorkerMirroredStrategy
forms its collective ring from that cluster spec; a custom ``strategy.run``
loop (keras-3 ``fit`` no longer supports MWMS) trains a small conv net on
MNIST-shaped data with the gradient allreduce crossing containers.

Submit::

    tony submit --framework tensorflow --src_dir examples \\
        --executes "python tf_mnist_mwms.py" \\
        --conf tony.worker.instances=2

Uses synthetic MNIST-shaped data unless ``MNIST_NPZ`` points at the real
arrays (keeps the example hermetic: the image has no dataset downloads).
"""

import json
import os

os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

import numpy as np
import tensorflow as tf


def load_data(n=512):
    path = os.environ.get("MNIST_NPZ")
    if path and os.path.exists(path):
        with np.load(path) as d:
            return (d["x_train"][:n].reshape(-1, 28, 28, 1)
                    .astype("float32") / 255.0,
                    d["y_train"][:n].astype("int32"))
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(n, 28, 28, 1)).astype("float32")
    ys = rng.integers(0, 10, size=(n,)).astype("int32")
    return xs, ys


def main():
    tfc = json.loads(os.environ["TF_CONFIG"])
    rank = tfc["task"]["index"]
    n_workers = len(tfc["cluster"]["worker"])
    strategy = tf.distribute.MultiWorkerMirroredStrategy()
    assert strategy.num_replicas_in_sync == n_workers

    xs, ys = load_data()
    shard_x = tf.constant(xs[rank::n_workers])
    shard_y = tf.constant(ys[rank::n_workers])

    with strategy.scope():
        model = tf.keras.Sequential([
            tf.keras.layers.Conv2D(8, 3, activation="relu",
                                   input_shape=(28, 28, 1)),
            tf.keras.layers.MaxPooling2D(),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(10),
        ])
        opt = tf.keras.optimizers.SGD(0.05)
        loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
            from_logits=True)

    @tf.function
    def step():
        def replica_step():
            with tf.GradientTape() as tape:
                loss = loss_fn(shard_y, model(shard_x, training=True))
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            return loss

        per_replica = strategy.run(replica_step)
        return strategy.reduce(tf.distribute.ReduceOp.MEAN, per_replica,
                               axis=None)

    losses = [float(step()) for _ in range(20)]
    assert losses[-1] < losses[0], losses
    print(f"worker {rank}/{n_workers}: loss {losses[0]:.4f} -> "
          f"{losses[-1]:.4f}")
    if rank == 0:
        with open("tf_mnist_result.json", "w") as f:
            json.dump({"losses": losses, "n_workers": n_workers}, f)


if __name__ == "__main__":
    main()
