"""Gang-identity input sharding: which slice of every global batch is MINE.

The reference leaves input sharding to user scripts (each worker builds its
own ``tf.data`` pipeline from ``TASK_INDEX`` by hand — SURVEY.md §1 L7);
TF-Replicator's lesson (PAPERS 1902.00465) is that the framework must own
this or determinism and resume semantics become every user's bug. A
:class:`ShardSpec` is derived once from the executor env the runtimes
already export and threaded through the data plane:

* the **global** example stream (order, shuffling, batching) is computed
  identically on every host from the seed + iterator state alone — no
  host-count dependence anywhere in the index math;
* the ShardSpec then selects this host's CONTIGUOUS block of each global
  batch (block h of ``world_size`` equal blocks). ``train.global_batch``
  reassembles the blocks in task order, so the device-resident global
  batch — and therefore the training trajectory — is identical for ANY
  (host-count, shard) layout over the same world. That invariance is what
  makes elastic restore across a changed host count exact rather than
  approximate.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Mapping, Optional, Sequence, TypeVar

from tony_tpu import constants

_T = TypeVar("_T")


@dataclass(frozen=True)
class ShardSpec:
    """This process's position in the input gang: ``task_index`` of
    ``world_size``. Standalone (no TonY env) is ``ShardSpec(0, 1)``."""

    task_index: int = 0
    world_size: int = 1

    def __post_init__(self) -> None:
        if self.world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {self.world_size}")
        if not 0 <= self.task_index < self.world_size:
            raise ValueError(
                f"task_index {self.task_index} out of range for "
                f"world_size {self.world_size}")

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "ShardSpec":
        """Derive the shard from the executor env. The JAX rendezvous pair
        (``TONY_PROCESS_ID``/``TONY_NUM_PROCESSES``, exported by the
        JAXRuntime) wins over the generic executor pair
        (``TONY_TASK_INDEX``/``TONY_NUM_TASKS``): the rendezvous index is
        the GLOBAL rank across job types, which is what ``global_batch``'s
        process ordering uses — the per-jobtype task index only coincides
        with it in single-jobtype gangs. No env at all → standalone."""
        env = os.environ if env is None else env
        for idx_key, n_key in (
                (constants.ENV_PROCESS_ID, constants.ENV_NUM_PROCESSES),
                (constants.ENV_TASK_INDEX, constants.ENV_TASK_NUM)):
            idx, n = env.get(idx_key), env.get(n_key)
            if idx is not None and n is not None:
                return cls(int(idx), int(n))
        return cls(0, 1)

    def local_count(self, global_batch: int) -> int:
        """Examples of each global batch this host materializes."""
        if global_batch % self.world_size:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"world_size {self.world_size}")
        return global_batch // self.world_size

    def local_slice(self, global_batch: int) -> slice:
        """This host's contiguous block of a ``global_batch``-sized id
        vector — block ``task_index`` of ``world_size`` equal blocks, so
        concatenating the blocks in task order reproduces the global
        batch (the ``make_array_from_process_local_data`` contract)."""
        local = self.local_count(global_batch)
        return slice(self.task_index * local, (self.task_index + 1) * local)

    def shard_files(self, files: Sequence[_T], *,
                    pad: bool = False) -> List[_T]:
        """Static per-host FILE assignment (round-robin) for pipelines that
        shard at file granularity instead of example granularity — e.g.
        feeding :class:`~tony_tpu.data.pipeline.FileListSource` a per-host
        subset. Note this trades away host-count elasticity: a file-sharded
        stream is only reproducible across runs with the SAME world size
        (example-granularity sharding — the default — has no such caveat).

        A file count that does not divide ``world_size`` is rejected:
        hosts would build sources of DIFFERENT lengths, so the gang
        desyncs at epoch end (the short host raises ``StopIteration``
        while the rest block in the collective) and the single saved
        gang cursor fails every other host's ``restore()`` source-length
        pin. ``pad=True`` wrap-pads the assignment with files from the
        front of the list to equal per-host counts (duplicating up to
        ``world_size - 1`` files per epoch) instead of raising.
        """
        files = list(files)
        short = (-len(files)) % self.world_size
        if short:
            if not pad:
                raise ValueError(
                    f"{len(files)} files not divisible by world_size "
                    f"{self.world_size}: hosts would see different source "
                    f"lengths, breaking gang epoch sync and checkpoint "
                    f"resume — drop the remainder, or pass pad=True to "
                    f"wrap-pad to equal per-host counts")
            files = files + files[:short]
        return files[self.task_index::self.world_size]
