"""Deterministic sharded input-data plane.

The reference delegates the input pipeline entirely to user scripts (each
worker hand-rolls ``tf.data`` from ``TASK_INDEX`` — SURVEY.md §1 L7); this
package is the framework-owned replacement the TPU rebuild needs once the
train loop, checkpoint plane, and overlap engine are all framework-owned
too. Four pieces:

* **deterministic sharding** (:mod:`~tony_tpu.data.sharding`) — a
  :class:`ShardSpec` derived from the executor's gang identity
  (``TONY_PROCESS_ID``/``TONY_NUM_PROCESSES`` env on real gangs,
  standalone fallback); all index math is computed GLOBALLY on every host
  and the shard selects a contiguous block of each global batch, so any
  (host-count, shard) layout yields the same global example order;
* **a composable pipeline** (:mod:`~tony_tpu.data.pipeline`) —
  array/memmap/file :class:`Source`\\ s → shuffle (per-epoch Philox
  permutation or counter-based shuffle buffer) → repeat → batch → map,
  with the whole cursor exposed as a small JSON-able ``state()``;
* **double-buffered device prefetch** (:mod:`~tony_tpu.data.prefetch`) —
  a background thread stages the next K batches host→device through
  ``train.global_batch`` so the step never blocks on the feed; the stall
  it does pay is recorded per step in
  :func:`tony_tpu.profiler.input_report` (``run_input_bench`` measures);
* **checkpointable iterator state** (:mod:`~tony_tpu.data.ckptio`) — the
  cursor rides the PR 3 ``ckpt`` manifest in the same atomic commit as
  the train state (``train_loop(data=...)``), and restores elastically
  across a CHANGED host count: the state is global, the new gang's
  ShardSpecs just re-slice it.
"""

from __future__ import annotations

from tony_tpu.data.ckptio import (DATA_ITER_KEY, MODEL_KEY, decode_state,
                                  encode_state, has_iter_state,
                                  load_iter_state, wrap_for_save)
from tony_tpu.data.pipeline import (ArraySource, Dataset, FileListSource,
                                    MemmapSource, PipelineIterator, Source)
from tony_tpu.data.prefetch import DeviceIterator
from tony_tpu.data.sharding import ShardSpec

__all__ = [
    "ArraySource", "DATA_ITER_KEY", "Dataset", "DeviceIterator",
    "FileListSource", "MODEL_KEY", "MemmapSource", "PipelineIterator",
    "ShardSpec", "Source", "decode_state", "encode_state", "has_iter_state",
    "load_iter_state", "wrap_for_save",
]
