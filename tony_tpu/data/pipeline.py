"""Composable deterministic pipeline: sources → shuffle/repeat/batch/map.

Design rules that everything here follows:

* **All index math is global.** The iterator computes the global id stream
  (epoch orders, shuffle-buffer draws, batch boundaries) identically on
  every host; the :class:`~tony_tpu.data.sharding.ShardSpec` only selects
  which contiguous block of each global batch this host fetches. Any
  (host-count, shard) layout therefore yields the same global example
  order — the invariant the elastic-resume pin tests.
* **Counter-based RNG only.** Epoch orders come from
  ``Philox(key=(seed, epoch))`` permutations and shuffle-buffer draws from
  ``Philox(key=(seed', draw_counter))`` — both regenerable from a handful
  of integers, so :meth:`PipelineIterator.state` is a small JSON-able dict
  (epoch, cursor, draw counter, buffered ids), not a pickled generator.
* **Stages expose state()/restore().** The whole pipeline's cursor rides
  the PR 3 checkpoint manifest next to the train state
  (:mod:`tony_tpu.data.ckptio`), so an interrupted run's example stream is
  element-identical to an uninterrupted one — including across a changed
  host count.

This module is jax-free: sources hand back host numpy batches; device
placement (and the prefetch thread that hides it) lives in
:mod:`tony_tpu.data.prefetch`.
"""

from __future__ import annotations

import copy
import os
from pathlib import Path
from typing import (Any, Callable, Dict, List, Mapping, Optional, Sequence,
                    Union)

import numpy as np

from tony_tpu import constants
from tony_tpu.data.sharding import ShardSpec

STATE_VERSION = 1
# Domain separation between the two counter-based streams: the epoch
# permutation keys on (seed, epoch), buffer draws on (seed ^ SALT, block).
_BUFFER_SALT = 0x5D41402A
# Buffer draws are generated this many words at a time — a fresh
# Generator per example costs ~µs of construction on the producer path,
# the same order as the feed latency the prefetcher exists to hide.
_DRAW_BLOCK = 256

Batch = Dict[str, np.ndarray]


def _philox(*key: int) -> np.random.Generator:
    return np.random.Generator(
        np.random.Philox(key=np.array(key, dtype=np.uint64)))


# ---------------------------------------------------------------------------
# Sources: __len__ + fetch(global ids) -> dict of host arrays
# ---------------------------------------------------------------------------

class Source:
    """An indexable example store. Subclasses implement ``__len__`` and
    ``fetch(ids) -> {leaf: np.ndarray}`` (leading dim = ``len(ids)``);
    fetch must be a pure function of ``ids`` — all randomness lives in the
    iterator's index stream so the fetch side never carries RNG state."""

    def __len__(self) -> int:
        raise NotImplementedError

    def fetch(self, ids: np.ndarray) -> Batch:
        raise NotImplementedError


class ArraySource(Source):
    """In-memory dict-of-arrays source (the bench/test workhorse)."""

    def __init__(self, arrays: Mapping[str, Any]):
        if not arrays:
            raise ValueError("ArraySource needs at least one leaf")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        lengths = {k: v.shape[0] if v.ndim else None
                   for k, v in self.arrays.items()}
        sizes = set(lengths.values())
        if None in sizes or len(sizes) != 1:
            raise ValueError(
                f"ArraySource leaves must share a leading example dim, "
                f"got {lengths}")
        self._n = sizes.pop()

    def __len__(self) -> int:
        return self._n

    def fetch(self, ids: np.ndarray) -> Batch:
        return {k: v[ids] for k, v in self.arrays.items()}


class MemmapSource(Source):
    """``.npy``-backed source opened with ``mmap_mode="r"``: fetch reads
    only the pages the requested ids touch — datasets larger than host RAM
    stream without a loader process."""

    def __init__(self, paths: Mapping[str, Union[str, Path]]):
        if not paths:
            raise ValueError("MemmapSource needs at least one leaf")
        self.arrays = {k: np.load(p, mmap_mode="r")
                       for k, p in paths.items()}
        lengths = {k: v.shape[0] for k, v in self.arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(
                f"MemmapSource leaves must share a leading example dim, "
                f"got {lengths}")
        self._n = next(iter(lengths.values()))

    def __len__(self) -> int:
        return self._n

    def fetch(self, ids: np.ndarray) -> Batch:
        # Fancy indexing on a memmap materializes a real ndarray (a copy),
        # so the returned batch never aliases the mapped file.
        return {k: v[ids] for k, v in self.arrays.items()}


class FileListSource(Source):
    """One example per file: ``loader(path) -> {leaf: array}``; fetch
    loads the id-indexed files and stacks them. The id space is the FILE
    list, so the deterministic global order is over files — the per-host
    file assignment the tentpole names falls out of the same contiguous
    block selection every other source uses."""

    def __init__(self, files: Sequence[Union[str, Path]],
                 loader: Callable[[Union[str, Path]], Mapping[str, Any]]):
        if not files:
            raise ValueError("FileListSource needs at least one file")
        self.files = list(files)
        self.loader = loader

    def __len__(self) -> int:
        return len(self.files)

    def fetch(self, ids: np.ndarray) -> Batch:
        examples = [self.loader(self.files[int(i)]) for i in ids]
        keys = list(examples[0])
        for i, ex in zip(ids, examples):
            if set(ex) != set(keys):
                raise ValueError(
                    f"FileListSource: file {self.files[int(i)]} produced "
                    f"leaves {sorted(ex)} != {sorted(keys)}")
        return {k: np.stack([np.asarray(ex[k]) for ex in examples])
                for k in keys}


# ---------------------------------------------------------------------------
# Dataset builder
# ---------------------------------------------------------------------------

class Dataset:
    """Declarative pipeline spec; chain stages, then ``iterator()`` /
    ``device_iterator()`` instantiate it for a shard::

        ds = (Dataset.from_arrays({"x": X, "y": Y})
                .shuffle()            # per-epoch Philox permutation
                .repeat()             # epochs forever (or repeat(3))
                .batch(64)            # GLOBAL batch size
                .map(augment)
                .with_ids())          # attach the global example ids
        it = ds.device_iterator(mesh, prefetch=2)

    Builder methods return a copy — a Dataset can be re-instantiated (the
    resume tests rebuild the identical stream from the same spec). The
    default seed comes from ``TONY_DATA_SEED`` (``tony.data.seed`` through
    the JAXRuntime) so a tony-submitted gang agrees on the stream without
    the script threading a seed through."""

    def __init__(self, source: Source, *, seed: Optional[int] = None):
        self.source = source
        if seed is None:
            seed = int(os.environ.get(constants.ENV_DATA_SEED, "0") or 0)
        if seed < 0:
            raise ValueError(f"seed must be >= 0 (Philox key), got {seed}")
        self.seed = seed
        self._shuffle = False
        self._buffer_size = 0
        self._epochs: Optional[int] = 1
        self._global_batch: Optional[int] = None
        self._map_fn: Optional[Callable[[Batch], Batch]] = None
        self._id_leaf: Optional[str] = None

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_arrays(cls, arrays: Mapping[str, Any], *,
                    seed: Optional[int] = None) -> "Dataset":
        return cls(ArraySource(arrays), seed=seed)

    @classmethod
    def from_memmap(cls, paths: Mapping[str, Union[str, Path]], *,
                    seed: Optional[int] = None) -> "Dataset":
        return cls(MemmapSource(paths), seed=seed)

    @classmethod
    def from_files(cls, files: Sequence[Union[str, Path]],
                   loader: Callable[[Union[str, Path]], Mapping[str, Any]],
                   *, seed: Optional[int] = None) -> "Dataset":
        return cls(FileListSource(files, loader), seed=seed)

    # -- stages ------------------------------------------------------------
    def _copy(self) -> "Dataset":
        return copy.copy(self)

    def shuffle(self, buffer_size: Optional[int] = None) -> "Dataset":
        """No argument: full per-epoch permutation (counter-based, zero
        state beyond the cursor). ``buffer_size=k``: streaming k-deep
        shuffle buffer over the id stream — for sources too big to permute
        whole epochs of, at the cost of ``k`` ids in the iterator state."""
        ds = self._copy()
        if buffer_size is None:
            ds._shuffle = True
        else:
            if buffer_size < 2:
                raise ValueError(
                    f"shuffle buffer_size must be >= 2, got {buffer_size}")
            ds._buffer_size = buffer_size
        return ds

    def repeat(self, epochs: Optional[int] = None) -> "Dataset":
        """``None`` = forever. Each epoch gets its own permutation
        (``Philox(seed, epoch)``); batches may span epoch boundaries."""
        if epochs is not None and epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {epochs}")
        ds = self._copy()
        ds._epochs = epochs
        return ds

    def batch(self, global_batch: int) -> "Dataset":
        """GLOBAL batch size — the whole gang's, not this host's. A final
        partial batch is dropped (a ragged global batch has no stable
        sharding across world sizes)."""
        if global_batch < 1:
            raise ValueError(f"global_batch must be >= 1, got {global_batch}")
        ds = self._copy()
        ds._global_batch = global_batch
        return ds

    def map(self, fn: Callable[[Batch], Batch]) -> "Dataset":
        """Host-side per-LOCAL-batch transform (decode, augment, cast).
        Must be deterministic per batch — randomness belongs in the index
        stream, where it is counter-based and checkpointable."""
        ds = self._copy()
        ds._map_fn = fn
        return ds

    def with_ids(self, leaf: str = "id") -> "Dataset":
        """Attach each example's GLOBAL id as an extra int64 leaf (added
        after ``map``) — the observable the deterministic-resume pin
        asserts on, and a join key for eval bookkeeping."""
        ds = self._copy()
        ds._id_leaf = leaf
        return ds

    # -- instantiation -----------------------------------------------------
    def iterator(self, shard: Optional[ShardSpec] = None
                 ) -> "PipelineIterator":
        return PipelineIterator(
            self, ShardSpec.from_env() if shard is None else shard)

    def device_iterator(self, mesh=None, *, shard: Optional[ShardSpec] = None,
                        prefetch: int = 2, seq_axis: bool = False,
                        tag: str = "input"):
        from tony_tpu.data.prefetch import DeviceIterator
        return DeviceIterator(self.iterator(shard), mesh,
                              depth=prefetch, seq_axis=seq_axis, tag=tag)


# ---------------------------------------------------------------------------
# The iterator: global index stream + shard-local fetch
# ---------------------------------------------------------------------------

class PipelineIterator:
    """Yields this shard's block of each global batch; ``state()`` /
    ``restore()`` round-trip the cursor exactly (and host-count
    independently — the state carries no shard identity)."""

    def __init__(self, ds: Dataset, shard: ShardSpec):
        if ds._global_batch is None:
            raise ValueError(
                "Dataset has no batch size: call .batch(global_batch) "
                "before building an iterator")
        if len(ds.source) == 0:
            # With repeat(), a zero-length epoch would spin the index
            # stream forever instead of raising — fail at construction.
            raise ValueError("Dataset source is empty")
        self._ds = ds
        self.shard = shard
        self.global_batch = ds._global_batch
        self._local_slice = shard.local_slice(self.global_batch)
        # Cursor state (the whole of it — everything else above is spec).
        self._epoch = 0
        self._pos = 0                 # ids consumed from the current epoch
        self._draws = 0               # shuffle-buffer draw counter
        self._buffer: List[int] = []  # shuffle-buffer contents (global ids)
        self._batches = 0             # global batches emitted
        # Cursor as of BEFORE the last emitted batch (the retained
        # rollback snapshot): lets a consumer holding that batch
        # undelivered (depth-0 DeviceIterator retry window) checkpoint
        # without the pipeline paying a second per-step state copy.
        self._committed_snap: Optional[tuple] = None
        self._order_cache: tuple = (-1, None)
        self._draw_cache: tuple = (-1, None)

    # -- global index stream ----------------------------------------------
    def _epoch_order(self, epoch: int) -> np.ndarray:
        if self._order_cache[0] == epoch:
            return self._order_cache[1]
        n = len(self._ds.source)
        if self._ds._shuffle:
            order = _philox(self._ds.seed, epoch).permutation(n)
        else:
            order = np.arange(n)
        self._order_cache = (epoch, order)
        return order

    def _stream_next(self, k: int) -> List[int]:
        """Up to ``k`` ids from the epoch-concatenated stream, advancing
        (epoch, pos)."""
        out: List[int] = []
        epochs = self._ds._epochs
        while len(out) < k:
            if epochs is not None and self._epoch >= epochs:
                break
            order = self._epoch_order(self._epoch)
            take = min(k - len(out), len(order) - self._pos)
            out.extend(int(i) for i in order[self._pos:self._pos + take])
            self._pos += take
            if self._pos >= len(order):
                self._epoch += 1
                self._pos = 0
        return out

    def _draw(self, n: int) -> int:
        """Word ``draws`` of the Philox word stream, reduced mod ``n``
        (bias < n/2**62 — immaterial for any realistic buffer). The block
        cache is derived state: a restore just regenerates it from the
        draw counter."""
        blk, off = divmod(self._draws, _DRAW_BLOCK)
        if self._draw_cache[0] != blk:
            words = _philox(self._ds.seed ^ _BUFFER_SALT, blk).integers(
                0, 1 << 62, size=_DRAW_BLOCK, dtype=np.int64)
            self._draw_cache = (blk, words)
        self._draws += 1
        return int(self._draw_cache[1][off]) % n

    def _next_ids(self) -> np.ndarray:
        """The next GLOBAL batch's example ids — identical on every host."""
        b = self.global_batch
        if not self._ds._buffer_size:
            ids = self._stream_next(b)
            if len(ids) < b:
                raise StopIteration
            return np.asarray(ids, np.int64)
        out: List[int] = []
        while len(out) < b:
            want = self._ds._buffer_size - len(self._buffer)
            if want > 0:
                self._buffer.extend(self._stream_next(want))
            if not self._buffer:
                break                        # stream dry AND buffer drained
            j = self._draw(len(self._buffer))
            # Swap-pop: O(1) removal keeps the buffer a plain id list the
            # state dict can carry verbatim.
            self._buffer[j], self._buffer[-1] = \
                self._buffer[-1], self._buffer[j]
            out.append(self._buffer.pop())
        if len(out) < b:
            raise StopIteration
        return np.asarray(out, np.int64)

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> "PipelineIterator":
        return self

    def _snapshot(self) -> tuple:
        return (self._epoch, self._pos, self._draws,
                list(self._buffer), self._batches)

    def _rollback(self, snap: tuple) -> None:
        (self._epoch, self._pos, self._draws,
         self._buffer, self._batches) = snap

    def __next__(self) -> Batch:
        # Snapshot → advance → fetch → commit: a fetch/map failure rolls
        # the cursor back, so a caught-and-retried transient I/O error
        # re-reads the SAME global batch instead of silently skipping it —
        # and a state() taken after the failure doesn't bake the skip in.
        snap = self._snapshot()
        try:
            ids = self._next_ids()
        except StopIteration:
            # Exhaustion consumes (and drops) the final partial batch's
            # ids before raising; roll those back too, or a state() taken
            # after the end — restored into a pipeline with more epochs —
            # would silently skip them.
            self._rollback(snap)
            raise
        self._batches += 1
        local_ids = ids[self._local_slice]
        try:
            batch = dict(self._ds.source.fetch(local_ids))
            if self._ds._map_fn is not None:
                batch = self._ds._map_fn(batch)
        except StopIteration as e:
            # PEP-479 hazard: a StopIteration leaking out of a user map_fn
            # (e.g. next() on an exhausted side iterator) re-raised from
            # __next__ reads as clean end-of-stream and silently truncates
            # the run — surface it as an error instead.
            self._rollback(snap)
            raise RuntimeError(
                "Source.fetch/map_fn raised StopIteration — refusing to "
                "treat it as end-of-stream") from e
        except Exception:
            self._rollback(snap)
            raise
        if self._ds._id_leaf is not None:
            if self._ds._id_leaf in batch:
                self._rollback(snap)
                raise ValueError(
                    f"with_ids() leaf {self._ds._id_leaf!r} already exists "
                    f"in the batch (from the source or map_fn) and would be "
                    f"silently overwritten — pick another name via "
                    f"with_ids(leaf=...)")
            batch[self._ds._id_leaf] = local_ids
        self._committed_snap = snap
        return batch

    @property
    def batches_emitted(self) -> int:
        return self._batches

    # -- checkpointable state ----------------------------------------------
    def state(self) -> Dict[str, Any]:
        """JSON-able cursor: everything needed to resume the GLOBAL stream
        bit-exactly on any world size. The stream-defining spec
        (``seed``/``global_batch``/``source_len``/shuffle config) is
        pinned inside so a restore against a different spec — including a
        source that grew or shrank since the save — fails loudly instead
        of silently forking the stream."""
        return self._state_dict(self._epoch, self._pos, self._draws,
                                list(self._buffer), self._batches)

    def state_before_last(self) -> Dict[str, Any]:
        """Cursor as of BEFORE the last batch ``__next__`` emitted — what a
        consumer still holding that batch undelivered must save so a
        resume replays it. Equals :meth:`state` when nothing was emitted
        since construction/restore."""
        if self._committed_snap is None:
            return self.state()
        epoch, pos, draws, buffer, batches = self._committed_snap
        return self._state_dict(epoch, pos, draws, list(buffer), batches)

    def _state_dict(self, epoch: int, pos: int, draws: int,
                    buffer: List[int], batches: int) -> Dict[str, Any]:
        return {
            "version": STATE_VERSION,
            "seed": self._ds.seed,
            "global_batch": self.global_batch,
            "source_len": len(self._ds.source),
            "shuffle": int(bool(self._ds._shuffle)),
            "buffer_size": int(self._ds._buffer_size),
            "epoch": epoch,
            "pos": pos,
            "draws": draws,
            "buffer": buffer,
            "batches": batches,
        }

    def restore(self, state: Mapping[str, Any]) -> None:
        if state.get("version") != STATE_VERSION:
            raise ValueError(
                f"iterator state version {state.get('version')!r} != "
                f"{STATE_VERSION} — written by an incompatible data plane")
        for key, mine in (("seed", self._ds.seed),
                          ("global_batch", self.global_batch),
                          ("source_len", len(self._ds.source)),
                          ("shuffle", int(bool(self._ds._shuffle))),
                          ("buffer_size", int(self._ds._buffer_size))):
            if int(state[key]) != mine:
                raise ValueError(
                    f"iterator state {key}={state[key]} != this pipeline's "
                    f"{key}={mine} — restoring it would fork the example "
                    f"stream")
        self._epoch = int(state["epoch"])
        self._pos = int(state["pos"])
        self._draws = int(state["draws"])
        self._buffer = [int(i) for i in state["buffer"]]
        self._batches = int(state["batches"])
        self._committed_snap = None
        self._order_cache = (-1, None)
        self._draw_cache = (-1, None)
