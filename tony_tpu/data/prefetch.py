"""Double-buffered device prefetch: the host→device feed off the step path.

T3's case (PAPERS 2401.16677) is that transfers must be *tracked and
triggered* so they hide under compute; the overlap engine (PR 1/2) did
that for gradient traffic, this does it for the one transfer the train
loop still paid in the open — the input feed. A daemon thread runs the
host pipeline (fetch + map) and stages the next ``depth`` batches onto
the devices via :func:`tony_tpu.train.global_batch`, so ``next()`` in the
train loop returns a device-resident global batch immediately whenever
the producer is keeping up. The time ``next()`` DOES block — the input
stall the step actually pays — is recorded per step in the profiler
(:func:`tony_tpu.profiler.input_report`), next to the overlap and ckpt
records, so "the feed is hidden" is a measured number (``run_input_bench``
serializes it; BENCH_r08).

Checkpoint correctness under prefetch: each staged batch carries the
pipeline state taken AFTER producing it; :meth:`DeviceIterator.state`
returns the state of the last batch DELIVERED to the caller, never the
producer's read-ahead position — a checkpoint taken between steps resumes
exactly at the next undelivered example, regardless of depth.

Thread hygiene (audited by ``tony_tpu.analysis.concurrency``): the
producer is daemon AND joined — daemon so an abandoned iterator can
never pin the interpreter, joined (``close()``, bounded) so the normal
teardown path is deterministic rather than relying on interpreter exit;
the weakref dance below covers the abandoned case in between.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
import weakref
from typing import Any, Dict, Mapping, Optional

from tony_tpu._trace import trace_record
from tony_tpu.data.pipeline import PipelineIterator

_record = functools.partial(trace_record, "input")


class _Stop:
    """End-of-stream sentinel (a class, not object(): survives queue
    identity checks across threads unambiguously)."""


def _q_put(q: "queue.Queue", stop: threading.Event,
           ref: "weakref.ref", item: Any) -> bool:
    """Put that keeps polling ``stop`` AND the iterator's liveness: a
    producer parked on a full queue must exit both on close() and when
    the consumer dropped the iterator without closing it."""
    while not stop.is_set():
        if ref() is None:
            return False
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _producer(ref: "weakref.ref", q: "queue.Queue",
              stop: threading.Event) -> None:
    """Prefetch loop, deliberately a module function over a WEAK
    reference: a bound-method target would make the running thread a GC
    root for the iterator, so a DeviceIterator dropped without close()
    could never be collected and its producer would spin for the process
    lifetime. Holding the iterator only within one loop iteration —
    never across a blocking put — lets the drop be observed and the
    thread exit within one put timeout."""
    while True:
        it = ref()
        if it is None or stop.is_set():
            return
        try:
            try:
                batch = it._next_host_batch()
            except StopIteration:
                del it
                break
            item = (batch, it._it.state())
        except BaseException as e:  # noqa: BLE001 — surfaced on next()
            it._err = e
            del it
            break
        del it, batch
        if not _q_put(q, stop, ref, item):
            return
        del item
    _q_put(q, stop, ref, _Stop)


class DeviceIterator:
    """Prefetching device-placement wrapper over a
    :class:`~tony_tpu.data.pipeline.PipelineIterator`.

    * ``depth >= 1``: a background thread fetches, maps, and stages the
      next ``depth`` batches host→device; ``next()`` only blocks when the
      producer falls behind (the measured input stall).
    * ``depth == 0``: fully synchronous — the comparison leg the input
      bench measures the stall of.
    * ``mesh=None``: batches stay host-side (single-process loops, tests);
      with a mesh each batch is assembled into the logically-global array
      via :func:`tony_tpu.train.global_batch` (sharded over the DP axes,
      every process contributing its ShardSpec block).
    """

    def __init__(self, it: PipelineIterator, mesh=None, *, depth: int = 2,
                 seq_axis: bool = False, tag: str = "input"):
        if depth < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {depth}")
        self._it = it
        self._mesh = mesh
        self.depth = depth
        self._seq_axis = seq_axis
        self._tag = tag
        # depth 0 never runs ahead of the consumer, so state() reads the
        # pipeline lazily instead of materializing the cursor (a full
        # shuffle-buffer copy) on every synchronous next(); depth >= 1
        # tracks the last-DELIVERED state eagerly because the producer
        # thread owns (and advances) the pipeline.
        self._state: Optional[Dict[str, Any]] = it.state() if depth else None
        self._started = False
        self._closed = False
        self._placed_once = False
        self._err: Optional[BaseException] = None
        # Running totals, not per-step lists: bookkeeping on the step path
        # must stay O(1) in steps for million-step runs.
        self.stats: Dict[str, Any] = {"steps": 0, "wait_s_last": 0.0,
                                      "wait_s_total": 0.0, "place_n": 0,
                                      "place_s_total": 0.0}
        self._pending: Optional[Any] = None
        if depth > 0:
            self._q: "queue.Queue" = queue.Queue(maxsize=depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=_producer,
                args=(weakref.ref(self), self._q, self._stop),
                daemon=True, name="tony-data-prefetch")

    # -- producer side -----------------------------------------------------
    def _place(self, batch: Mapping[str, Any]) -> Any:
        t0 = time.perf_counter()
        if self._mesh is not None:
            from tony_tpu import train
            # The shape contract is invariant per pipeline: pre-flight it
            # (leaf-naming ValueError) on the first batch only, then skip
            # the per-step re-validation on the feed path.
            batch = train.global_batch(self._mesh, dict(batch),
                                       seq_axis=self._seq_axis,
                                       check=not self._placed_once)
            self._placed_once = True
        self.stats["place_n"] += 1
        self.stats["place_s_total"] += time.perf_counter() - t0
        return batch

    def _next_host_batch(self) -> Any:
        return self._place(next(self._it))

    # -- consumer side -----------------------------------------------------
    def __iter__(self) -> "DeviceIterator":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise RuntimeError("DeviceIterator is closed")
        t0 = time.perf_counter()
        if self.depth == 0:
            # A _place() failure (transient device transfer error) keeps
            # the already-pulled batch pending, so a caught-and-retried
            # next() re-places the SAME batch — the synchronous twin of
            # the pipeline's cursor rollback, for the stage past the
            # cursor's reach.
            if self._pending is None:
                self._pending = next(self._it)
            placed = self._place(self._pending)
            self._pending = None
        else:
            if not self._started:
                self._started = True
                self._thread.start()
            item = self._q.get()
            if item is _Stop:
                # Leave a sentinel behind: repeated next() after
                # exhaustion must keep raising, not deadlock on get().
                self._q.put(_Stop)
                if self._err is not None:
                    # Stays latched: every subsequent next() must keep
                    # raising, or a caught-and-retried error turns into a
                    # clean StopIteration and the run silently truncates.
                    raise RuntimeError("data prefetch thread failed") \
                        from self._err
                raise StopIteration
            placed, self._state = item
        wait_s = time.perf_counter() - t0
        self.stats["steps"] += 1
        self.stats["wait_s_last"] = wait_s
        self.stats["wait_s_total"] += wait_s
        _record(self._tag, depth=self.depth, steps=self.stats["steps"],
                wait_s_last=wait_s,
                wait_s_total=float(self.stats["wait_s_total"]),
                wait_ms_mean=1e3 * self.stats["wait_s_total"]
                / self.stats["steps"],
                place_ms_mean=1e3 * self.stats["place_s_total"]
                / max(1, self.stats["place_n"]))
        return placed

    # -- checkpointable state ----------------------------------------------
    def state(self) -> Dict[str, Any]:
        """Pipeline cursor as of the last batch DELIVERED through
        ``next()`` (prefetched-but-undelivered batches are not consumed:
        a resume from this state replays them)."""
        if self.depth == 0:
            # A place-failed batch left pending was pulled but never
            # delivered — its pre-pull cursor is the delivered position.
            if self._pending is not None:
                return self._it.state_before_last()
            return self._it.state()
        return dict(self._state)

    def restore(self, state: Mapping[str, Any]) -> None:
        """Restore the underlying pipeline. Must happen before the first
        ``next()`` — the producer thread latches the cursor once started."""
        if self._started or self.stats["steps"]:
            raise RuntimeError(
                "DeviceIterator.restore() after iteration started: the "
                "prefetch thread has already advanced the pipeline")
        # A depth-0 next() that failed in _place() leaves its batch
        # pending for retry; that batch predates the restored cursor and
        # must not be delivered against it.
        self._pending = None
        self._it.restore(state)
        if self.depth:
            self._state = self._it.state()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.depth > 0 and self._started:
            self._stop.set()
            # Unblock a producer parked on a full queue.
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            self._thread.join(timeout=30.0)

    def __enter__(self) -> "DeviceIterator":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()
