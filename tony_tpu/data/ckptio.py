"""Iterator state ↔ checkpoint manifest glue (the PR 3 ``ckpt`` plane).

The pipeline cursor is a small JSON dict; it rides the SAME committed step
as the train state by being encoded into a uint8 leaf of the saved pytree::

    {"model": <TrainState>, "data_iter": <uint8 json blob>}

so one atomic directory rename commits model and stream position together —
there is no window where the model resumed at step N but the data stream at
step N−1 (the silent repeat/skip PR 3 left open). The blob is written by
process 0 only (host leaves follow the snapshot engine's replicated-leaf
rule) and is byte-identical across processes anyway: the cursor is GLOBAL
by construction (:mod:`tony_tpu.data.pipeline`).

Reading back is manifest-direct (:func:`load_iter_state`): the blob's
length is only known from the manifest, so it cannot be expressed as a
``restore_pytree`` target leaf — and staying on the jax-free
:mod:`~tony_tpu.ckpt.format` path means control-plane code can inspect a
checkpoint's stream position without the compute stack.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Mapping, Optional, Union

import numpy as np

from tony_tpu.ckpt import format as fmt

# Leaf names inside the wrapped save tree, and the keystr paths they get
# from jax.tree_util (the manifest's join key).
MODEL_KEY = "model"
DATA_ITER_KEY = "data_iter"
DATA_ITER_PATH = f"['{DATA_ITER_KEY}']"


def encode_state(state: Mapping[str, Any]) -> np.ndarray:
    """Iterator-state dict → uint8 leaf (UTF-8 JSON, sorted keys)."""
    return np.frombuffer(
        json.dumps(dict(state), sort_keys=True).encode("utf-8"),
        dtype=np.uint8).copy()


def decode_state(blob: np.ndarray) -> Dict[str, Any]:
    return json.loads(np.asarray(blob, dtype=np.uint8).tobytes()
                      .decode("utf-8"))


def wrap_for_save(train_state: Any,
                  iter_state: Mapping[str, Any]) -> Dict[str, Any]:
    """The pytree ``train_loop`` hands the checkpointer when a data
    iterator is attached."""
    return {MODEL_KEY: train_state, DATA_ITER_KEY: encode_state(iter_state)}


def has_iter_state(root: Union[str, Path], step: int) -> bool:
    """Does the committed step carry a data-plane cursor (i.e. was it
    written by a wrapped save)? Distinguishes PR 3-era bare-state
    checkpoints, which restore fine but carry no stream position."""
    manifest = fmt.read_manifest(root, step)
    return any(m["path"] == DATA_ITER_PATH for m in manifest["leaves"])


def load_iter_state(root: Union[str, Path],
                    step: Optional[int] = None) -> Dict[str, Any]:
    """Read the iterator state out of a committed checkpoint (newest step
    by default). jax-free: manifest + seek-read of the one uint8 leaf."""
    if step is None:
        step = fmt.latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no committed checkpoint under {root}")
    manifest = fmt.read_manifest(root, step)
    idx = next((i for i, m in enumerate(manifest["leaves"])
                if m["path"] == DATA_ITER_PATH), None)
    if idx is None:
        raise KeyError(
            f"checkpoint step {step} under {root} carries no "
            f"{DATA_ITER_PATH} leaf — saved without a data iterator "
            f"attached")
    meta = manifest["leaves"][idx]
    out = np.empty(tuple(meta["shape"]), dtype=np.uint8)
    filled = 0
    with fmt.ChunkReader(root, step, manifest) as reader:
        for chunk in reader.chunks_for_leaf(idx):
            start = int(chunk["start"][0])
            data = reader.read(chunk, np.uint8)
            out[start:start + data.shape[0]] = data
            filled += data.shape[0]
    if filled != out.shape[0]:
        raise IOError(
            f"checkpoint step {step}: {DATA_ITER_PATH} chunks cover "
            f"{filled} of {out.shape[0]} bytes — incomplete payload")
    return decode_state(out)
