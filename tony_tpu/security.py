"""Credential-provider SPI (reference: the Kerberos login + HDFS/RM
delegation-token plumbing scattered through ``TonyClient`` /
``TonyApplicationMaster`` / ``Utils`` — SURVEY.md §2.1 "Security", ≈300 LoC).

The reference's *shape*, kept; its Hadoop substance, replaced by a
pluggable hook:

* **acquire at submit** — the client calls :meth:`CredentialProvider.acquire`
  and writes the credential map to ``<job>/credentials.json`` (mode 0600),
  the moral equivalent of the delegation tokens packed into the AM launch
  context;
* **ship** — the AM loads that file (or acquires itself when launched
  without a client, e.g. MiniPod), authenticates its RPC surface with the
  ``token`` entry, and injects :meth:`CredentialProvider.executor_env` into
  every container (the ``HADOOP_TOKEN_FILE_LOCATION`` analogue);
* **refresh** — for long jobs the AM periodically calls
  :meth:`CredentialProvider.refresh` so providers can renew *external*
  credentials (files, tickets). The wire-auth ``token`` itself is
  job-lifetime: executors bake it into their env at launch, exactly like
  the reference's static ClientToAM token.

The default provider is the round-3 job token, unchanged on the wire; a
deployment plugs its own with
``tony.security.credential-provider = my_pkg.my_mod:MyProvider``.
"""

from __future__ import annotations

import importlib
import json
import secrets
from pathlib import Path
from typing import Dict, Optional

CREDENTIALS_FILE = "credentials.json"

# Conf keys (registered here, not conf/__init__.py, to keep the security
# surface in one file; conf docs point here).
CREDENTIAL_PROVIDER = "tony.security.credential-provider"
CREDENTIAL_REFRESH_INTERVAL_MS = "tony.security.credential-refresh-interval-ms"


class CredentialProvider:
    """SPI base. Subclass and point ``tony.security.credential-provider``
    at ``module:Class``. All methods run with the job conf and job dir —
    providers needing state should keep it under the job dir so it ships
    with the job and dies with it."""

    name = "base"

    def acquire(self, conf, job_dir: Path) -> Dict[str, str]:
        """Called ONCE at submit, client side (AM side only when no client
        staged credentials — dev harnesses). Returns the credential map;
        the ``token`` entry, if present, becomes the RPC auth token."""
        raise NotImplementedError

    def refresh(self, conf, job_dir: Path,
                current: Dict[str, str]) -> Optional[Dict[str, str]]:
        """Periodic AM-side renewal hook; return a replacement map to
        rewrite ``credentials.json`` (and future container launches), or
        None to keep the current one. The in-flight RPC token is NOT
        re-keyed: launched executors hold the env they were born with."""
        return None

    def executor_env(self, creds: Dict[str, str]) -> Dict[str, str]:
        """Env injected into every container for this credential map."""
        from tony_tpu.rpc import ENV_JOB_TOKEN

        return {ENV_JOB_TOKEN: creds["token"]} if "token" in creds else {}


class TokenCredentialProvider(CredentialProvider):
    """Default: a per-job random shared secret (the reference's
    ClientToAM-token analogue, exactly round 3's wire behavior)."""

    name = "token"

    def acquire(self, conf, job_dir: Path) -> Dict[str, str]:
        return {"token": secrets.token_hex(16)}


def provider_for(conf) -> CredentialProvider:
    """Resolve ``tony.security.credential-provider``: the built-in name
    ``token`` (default) or a ``module:Class`` dotted path."""
    spec = conf.get(CREDENTIAL_PROVIDER, "token")
    if spec == "token":
        return TokenCredentialProvider()
    mod_name, sep, cls_name = spec.partition(":")
    if not sep:
        raise ValueError(
            f"{CREDENTIAL_PROVIDER}={spec!r}: expected 'token' or "
            f"'module:Class'")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    provider = cls()
    if not isinstance(provider, CredentialProvider):
        raise TypeError(f"{spec} is not a CredentialProvider")
    return provider


def write_credentials(job_dir: Path, creds: Dict[str, str]) -> Path:
    import os

    path = Path(job_dir) / CREDENTIALS_FILE
    # 0600 from birth — a write-then-chmod leaves a window where other
    # local users can read the token on a shared submit host.
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(json.dumps(creds))
    os.chmod(path, 0o600)   # refresh rewrites reuse the existing inode
    return path


def read_credentials(job_dir: Path) -> Optional[Dict[str, str]]:
    path = Path(job_dir) / CREDENTIALS_FILE
    if not path.is_file():
        return None
    return {str(k): str(v) for k, v in json.loads(path.read_text()).items()}
