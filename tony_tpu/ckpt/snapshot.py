"""Async snapshot engine: device→host shard extraction + background writer.

The save path is split at the device/host boundary the way Horovod splits
gradient exchange from compute (PAPERS: 1802.05799) — the part that must
fence the accelerator is made as small as possible, everything else rides a
background thread:

* **extract** (synchronous, inside :meth:`AsyncCheckpointer.save`): each
  process walks its addressable shards, keeps exactly the chunks it owns
  (``replica_id == 0`` — one copy of every distinct chunk globally, shard-
  local writes under ZeRO-3), and pulls them to host in ONE batched
  ``jax.device_get`` (a single transfer program, not per-leaf round trips).
  Once this returns, the train loop may donate/overwrite the state buffers.
* **write + commit** (asynchronous): a daemon writer thread serializes the
  host snapshot through :mod:`tony_tpu.ckpt.format` and commits the step.
  Two snapshot slots are kept (double buffering): a save issued while one
  write is still in flight proceeds immediately into the second slot; only
  a THIRD save stalls until a slot frees. The stall time (slot wait +
  extract) is what the train loop actually pays — the profiler records it
  next to the blocking write time so the overlap is measurable
  (:func:`tony_tpu.profiler.ckpt_report`, ``run_ckpt_bench``).

Writer errors never vanish: they surface on the next ``save``/``wait``.
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from tony_tpu._trace import trace_record
from tony_tpu.ckpt import format as fmt


# Trace-side channel into the profiler registry (shared shim: lazy
# import + swallow-all, log-once lives in profiler.safe_record).
_record = functools.partial(trace_record, "ckpt")


def _is_saveable(leaf: Any) -> bool:
    """Array-like leaves (jax/np arrays, np scalars, Python scalars) are
    checkpointed; everything else passes through restore untouched."""
    if isinstance(leaf, (bool, int, float, complex)):
        return True
    return hasattr(leaf, "shape") and hasattr(leaf, "dtype")


def leaf_paths(tree: Any) -> Tuple[List[str], List[Any], Any]:
    """Stable leaf addressing: ``jax.tree_util.keystr`` paths in flatten
    order — the join key between a manifest and any same-structured tree.
    Returns ``(paths, leaves, treedef)`` from ONE traversal."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return ([jax.tree_util.keystr(path) for path, _ in flat],
            [leaf for _, leaf in flat], treedef)


def _leaf_meta(path: str, leaf: Any) -> Dict[str, Any]:
    arr_like = np.asarray(leaf) if isinstance(
        leaf, (bool, int, float, complex)) else leaf
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return {
        "path": path,
        "shape": [int(s) for s in arr_like.shape],
        "dtype": fmt.dtype_name(arr_like.dtype),
        "spec": fmt.spec_to_json(spec),
    }


def _mesh_meta(leaves: Sequence[Any]) -> Optional[Dict[str, Any]]:
    for leaf in leaves:
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "axis_names", None):
            return {"axis_names": list(mesh.axis_names),
                    "shape": {str(a): int(mesh.shape[a])
                              for a in mesh.axis_names}}
    return None


@dataclass
class Snapshot:
    """One step's host-side copy of this process's owned chunks."""
    step: int
    leaves: List[Dict[str, Any]]                 # manifest leaf metadata
    chunks: List[Tuple[int, List[int], np.ndarray]]
    mesh: Optional[Dict[str, Any]]
    nbytes: int = 0
    extract_s: float = 0.0
    stall_s: float = 0.0
    done: threading.Event = field(default_factory=threading.Event)


def extract_snapshot(tree: Any, step: int) -> Snapshot:
    """Device→host extraction of this process's owned chunks (see module
    docstring for the ownership rule). Returns once every chunk is resident
    on host — the caller may mutate/donate the device buffers after."""
    t0 = time.perf_counter()
    paths, leaves, _ = leaf_paths(tree)
    metas: List[Dict[str, Any]] = []
    # (leaf, start, device-or-host ref, aliases-live-memory)
    pending: List[Tuple[int, List[int], Any, bool]] = []
    proc = jax.process_index()
    for i, (path, leaf) in enumerate(zip(paths, leaves)):
        if not _is_saveable(leaf):
            continue
        metas.append(_leaf_meta(path, leaf))
        li = len(metas) - 1
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            # Host array / scalar: replicated by construction; process 0
            # writes the single global copy. ALWAYS copied below — it
            # aliases a buffer the train loop may mutate in place.
            if proc == 0:
                pending.append((li, [0] * np.ndim(leaf),
                                np.asarray(leaf), True))
            continue
        for shard in shards:
            if shard.replica_id != 0:
                continue
            start = [int(s.start or 0) for s in shard.index]
            pending.append((li, start, shard.data, False))
    # One batched transfer for everything device-side, then copy ONLY
    # what still aliases live memory: host leaves (the caller's arrays),
    # and zero-copy views the CPU backend's device_get hands back (a later
    # donated step rewrites the underlying buffer while the writer thread
    # serializes). TPU device_get returns fresh owned host buffers —
    # re-copying those would double the snapshot's memcpy and its
    # transient memory for nothing.
    datas = jax.device_get([d for _, _, d, _ in pending])

    def _own(data: np.ndarray, aliased: bool) -> np.ndarray:
        data = np.asarray(data)
        if aliased or data.base is not None or not data.flags["OWNDATA"]:
            return np.array(data, copy=True)
        return data

    chunks = [(li, start, _own(data, aliased))
              for (li, start, _, aliased), data in zip(pending, datas)]
    snap = Snapshot(step=int(step), leaves=metas, chunks=chunks,
                    mesh=_mesh_meta(leaves),
                    nbytes=sum(int(a.nbytes) for _, _, a in chunks))
    snap.extract_s = time.perf_counter() - t0
    return snap


def write_snapshot(root: str | Path, snap: Snapshot, *,
                   process_index: Optional[int] = None,
                   num_processes: Optional[int] = None,
                   keep: int = 0,
                   barrier_timeout_s: float = 300.0) -> Optional[Path]:
    """Serialize + commit one snapshot (blocking). Every process writes its
    shard file; process 0 additionally merges the sidecars into the
    manifest and atomically commits the step, then prunes old steps."""
    proc = jax.process_index() if process_index is None else process_index
    n = jax.process_count() if num_processes is None else num_processes
    staging = fmt.tmp_dir(root, snap.step)
    fmt.write_process_file(staging, proc, snap.chunks)
    if proc != 0:
        # Block until process 0's manifest rename lands: a blocking save
        # (and wait()/restore_or's drain) must mean GLOBALLY committed on
        # every process, or latest_step diverges across the gang.
        fmt.wait_committed(root, snap.step, barrier_timeout_s)
        return None
    path = fmt.commit(root, snap.step, leaves=snap.leaves, mesh=snap.mesh,
                      num_processes=n, barrier_timeout_s=barrier_timeout_s)
    if keep:
        fmt.prune(root, keep)
    return path


class AsyncCheckpointer:
    """Double-buffered async checkpoint writer bound to one directory.

    ``save(state, step)`` stalls the caller only for slot acquisition plus
    the device→host extract; serialization, fsync, and the atomic commit
    run on the writer thread so subsequent train steps overlap the I/O.
    ``save(..., block=True)`` degrades to a blocking save (the comparison
    leg ``run_ckpt_bench`` measures).

    One live instance per process per directory: construction sweeps torn
    staging dirs from crashed predecessors, so a second concurrent
    instance on the same directory could reclaim this one's in-flight
    save (use one manager — ``train_loop`` owns its own, user code holding
    a ``Checkpointer`` should not save through both at once).
    """

    def __init__(self, directory: str | Path, *, keep: int = 3,
                 buffers: int = 2, process_index: Optional[int] = None,
                 num_processes: Optional[int] = None,
                 barrier_timeout_s: float = 300.0):
        self.directory = Path(directory)
        self.keep = keep
        self.process_index = jax.process_index() if process_index is None \
            else process_index
        self.num_processes = jax.process_count() if num_processes is None \
            else num_processes
        self.barrier_timeout_s = barrier_timeout_s
        self._slots = threading.BoundedSemaphore(max(1, buffers))
        self._q: "queue.Queue[Optional[Snapshot]]" = queue.Queue()
        self._err_lock = threading.Lock()    # guards _err (writer/caller)
        self._err: Optional[BaseException] = None
        self._closed = False
        self.stats: Dict[str, Any] = {
            "saves": 0, "stall_s": [], "extract_s": [], "write_s": [],
            "nbytes": 0}
        # Reclaim torn staging dirs from a previous (crashed) incarnation —
        # process 0 only: a sibling process may already be staging shard
        # files for a new step, and its tmp dir must not be swept.
        if self.process_index == 0:
            fmt.clean_stale(self.directory)
        self._writer = threading.Thread(target=self._run, daemon=True,
                                        name="ckpt-writer")
        self._writer.start()

    # -- background side ---------------------------------------------------
    def _run(self) -> None:
        while True:
            snap = self._q.get()
            if snap is None:
                self._q.task_done()
                return
            t0 = time.perf_counter()
            try:
                write_snapshot(
                    self.directory, snap,
                    process_index=self.process_index,
                    num_processes=self.num_processes, keep=self.keep,
                    barrier_timeout_s=self.barrier_timeout_s)
                write_s = time.perf_counter() - t0
                self.stats["write_s"].append(write_s)
                _record("async_save", step=snap.step, stall_s=snap.stall_s,
                        extract_s=snap.extract_s, write_s=write_s,
                        nbytes=snap.nbytes, n_chunks=len(snap.chunks),
                        keep=self.keep)
            except BaseException as e:  # noqa: BLE001 — surfaced on save/wait
                with self._err_lock:
                    self._err = e
            finally:
                snap.done.set()
                self._slots.release()
                self._q.task_done()

    def _raise_pending(self) -> None:
        # Swap under the lock: an unlocked read-then-clear could
        # overwrite (and lose) an error the writer banked between the
        # two — the concurrency audit's torn read-modify-write case.
        with self._err_lock:
            err, self._err = self._err, None
        if err is not None:
            raise RuntimeError("checkpoint writer failed") from err

    # -- caller side -------------------------------------------------------
    def save(self, state: Any, step: Optional[int] = None,
             block: bool = False) -> Snapshot:
        """Snapshot ``state`` and enqueue the write. Returns once the host
        copy is complete (state buffers are free to be donated); the commit
        itself lands asynchronously unless ``block``."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        if step is None:
            step_leaf = getattr(state, "step", None)
            step = int(jax.device_get(step_leaf)) if step_leaf is not None \
                else 0
        t0 = time.perf_counter()
        self._slots.acquire()          # stalls only when both slots busy
        try:
            snap = extract_snapshot(state, step)
        except BaseException:
            self._slots.release()
            raise
        snap.stall_s = time.perf_counter() - t0
        self.stats["saves"] += 1
        self.stats["stall_s"].append(snap.stall_s)
        self.stats["extract_s"].append(snap.extract_s)
        self.stats["nbytes"] = snap.nbytes
        self._q.put(snap)
        if block:
            snap.done.wait()
            self._raise_pending()
        return snap

    def wait(self) -> None:
        """Block until every enqueued save has committed (or failed)."""
        self._q.join()
        self._raise_pending()

    def latest_step(self) -> Optional[int]:
        return fmt.latest_step(self.directory)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._writer.join(timeout=self.barrier_timeout_s + 60.0)
        self._raise_pending()
