"""Persisted AOT compile cache: own the executable like PR 3 owns the
checkpoint.

At heavy traffic, autoscale reaction time IS the product: today every
scale-up grant — and every gang restart or elastic resize on the
training side — pays a full trace + XLA compile before producing a
token. This module decouples replica startup from accelerator
compilation (the runtime-decoupling move Arax argues for, PAPERS
2305.01291): a step program compiled once anywhere persists next to the
ckpt manifest, and every later replica of the same (topology, config,
jax/XLA) family deserializes it in milliseconds instead of re-tracing.

One cache entry is one directory::

    <root>/aot_<key>/
        payload.bin     # the serialized executable, chunked
        entry.json      # format tag + FULL fingerprint + chunk table
                        # + the pickled call trees (base64, CRC'd)

committed with the ckpt plane's stage-``.tmp``-then-rename discipline
(:mod:`tony_tpu.ckpt.format`): payload and entry are written (fsynced)
into a per-writer staging dir and ``os.replace``d into place — a
crashed writer leaves a ``.tmp`` orphan, never a half entry, and a
concurrent populate of one key is first-writer-wins (the second rename
fails against the committed directory and its staging is discarded).

``<key>`` is a digest of the fingerprint, but the name is only an
address: ``entry.json`` stores the FULL fingerprint dict and
:meth:`AOTCache.get` requires an exact match — a digest collision, a
hand-edited entry, or any key drift (changed geometry, changed jax
version) rejects to a counted miss. Every payload chunk carries a
CRC32 verified on read (the ChunkReader discipline); corruption of any
byte returns ``None``. The cache may cost a recompile, never a wrong
program.

Jax-free at import by the ckpt package's layering rule (the fingerprint
helpers and the serialize/deserialize shims import lazily): the AM can
name a cache dir in a grant without dragging the compute stack in.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import pickle
import shutil
import threading
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional

from tony_tpu.ckpt.format import TMP_SUFFIX, _atomic_write_json, _fsync_dir

_PREFIX = "aot_"
FORMAT = "tony-aot-v1"

# Payload chunking: per-chunk CRC32 bounds what one flipped bit costs to
# detect (the sidecar idiom) without hashing multi-MB artifacts twice.
CHUNK_BYTES = 1 << 20


# ---------------------------------------------------------------------------
# Fingerprinting: what makes two compiles THE SAME program
# ---------------------------------------------------------------------------

def runtime_fingerprint() -> Dict[str, Any]:
    """The jax/XLA half of a fingerprint: versions, backend platform,
    device kind/count, and the XLA flags env — a serialized executable
    is only valid against the toolchain and device family that built
    it, and any of these changing must be a miss, not a wrong load."""
    import jax
    try:
        import jaxlib
        jaxlib_v = jaxlib.version.__version__
    except Exception:
        jaxlib_v = ""
    devs = jax.devices()
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_v,
        "backend": jax.default_backend(),
        "device_kind": str(devs[0].device_kind) if devs else "",
        "n_devices": len(devs),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }


def mesh_descriptor(mesh: Any) -> Optional[Dict[str, Any]]:
    """Topology half: axis names/sizes plus the device kind the mesh is
    laid over. ``None`` for meshless (single-device) callers."""
    if mesh is None:
        return None
    axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    kinds = sorted({str(getattr(d, "device_kind", d))
                    for d in mesh.devices.flat})
    return {"axes": axes, "device_kinds": kinds}


def tree_digest(tree: Any) -> str:
    """Digest of a pytree's SHAPE: treedef + per-leaf shape/dtype/
    sharding. Params/state enter the fingerprint through this — the
    compiled program depends on avals and layouts, not on values, so
    restored weights of the same family hit while a changed model
    geometry (or a resharded state) misses."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    h = hashlib.sha256(str(treedef).encode())
    for leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        shard = str(getattr(leaf, "sharding", None))
        h.update(f"{shape}|{dtype}|{shard};".encode())
    return h.hexdigest()


def make_fingerprint(kind: str, *, mesh: Any = None,
                     geometry: Optional[Dict[str, Any]] = None,
                     model: Any = None, tree: Any = None,
                     batch: Any = None,
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Assemble one step family's full fingerprint: runtime + topology
    + step geometry + model config + state-shape digests. JSON-
    canonicalized so the dict a fresh process derives compares equal to
    the dict :meth:`AOTCache.get` reads back from ``entry.json``."""
    fp: Dict[str, Any] = {"format": FORMAT, "kind": str(kind)}
    fp.update(runtime_fingerprint())
    fp["mesh"] = mesh_descriptor(mesh)
    fp["geometry"] = dict(geometry or {})
    fp["model"] = "" if model is None else str(model)
    if tree is not None:
        fp["tree"] = tree_digest(tree)
    if batch is not None:
        fp["batch"] = tree_digest(batch)
    if extra:
        fp["extra"] = dict(extra)
    # Round-trip through JSON so tuples/np ints normalize to exactly
    # what a later get() will load and compare against.
    return json.loads(json.dumps(fp, sort_keys=True))


def fingerprint_key(fp: Dict[str, Any]) -> str:
    """The entry's directory name stem — an ADDRESS, not the identity:
    ``get`` always re-verifies the stored full fingerprint."""
    blob = json.dumps(fp, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:20]


# ---------------------------------------------------------------------------
# The cache
# ---------------------------------------------------------------------------

class AOTCache:
    """One directory of persisted compiled executables (module
    docstring). ``put`` serializes a ``jax.stages.Compiled``; ``get``
    returns a loaded, callable one — or ``None`` on any corruption,
    key drift, or an unsupported backend (counted; callers re-trace).

    Counters are lifetime and cross-consumer (the serve engine and the
    train stepper each also keep their own): ``hits``/``misses`` per
    ``get``, ``puts`` committed, ``put_races`` lost to a concurrent
    first writer, ``unsupported`` serialize declines."""

    def __init__(self, root: str) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.put_races = 0
        self.unsupported = 0

    def _dir(self, fp: Dict[str, Any]) -> Path:
        return self.root / f"{_PREFIX}{fingerprint_key(fp)}"

    def entries(self) -> List[str]:
        """Committed entry keys, sorted (staging orphans excluded)."""
        out = []
        for entry in sorted(os.listdir(self.root)):
            if entry.startswith(_PREFIX) and TMP_SUFFIX not in entry:
                out.append(entry[len(_PREFIX):])
        return out

    # -- read --------------------------------------------------------------
    def get(self, fp: Dict[str, Any], *, in_tree: Any = None,
            out_tree: Any = None) -> Optional[Any]:
        """The loaded ``jax.stages.Compiled`` for ``fp``, or ``None``
        (counted miss) on: no entry, format/fingerprint drift, any
        chunk CRC mismatch, a truncated payload, or a backend that
        cannot deserialize. Never raises, never mutates the store —
        a poison entry costs a recompile on every consult, not a
        crash (and never a wrong program: the payload only loads
        after the FULL fingerprint matched byte for byte).

        ``in_tree``/``out_tree`` are the caller's own call-tree defs,
        used when the entry carries none (``put`` met an unpicklable
        treedef — e.g. a train state whose static aux data holds local
        functions; the caller derives them from its args and
        ``Lowered.out_info``). An entry without stored trees AND no
        caller trees is a counted miss."""
        d = self._dir(fp)
        try:
            with open(d / "entry.json") as f:
                entry = json.load(f)
            if entry.get("format") != FORMAT:
                raise ValueError("format drift")
            if entry.get("fingerprint") != fp:
                raise ValueError("fingerprint drift")
            payload = bytearray()
            with open(d / "payload.bin", "rb") as f:
                for chunk in entry["chunks"]:
                    f.seek(int(chunk["offset"]))
                    raw = f.read(int(chunk["nbytes"]))
                    if len(raw) != int(chunk["nbytes"]) or \
                            (zlib.crc32(raw) & 0xFFFFFFFF) \
                            != int(chunk["crc32"]):
                        raise ValueError("payload chunk CRC mismatch")
                    payload += raw
            if entry["trees_b64"] is not None:
                trees_raw = base64.b64decode(entry["trees_b64"])
                if (zlib.crc32(trees_raw) & 0xFFFFFFFF) \
                        != int(entry["trees_crc32"]):
                    raise ValueError("call-tree CRC mismatch")
                in_tree, out_tree = pickle.loads(trees_raw)
            elif in_tree is None or out_tree is None:
                raise ValueError("entry has no call trees and the "
                                 "caller supplied none")
        except (OSError, ValueError, KeyError, TypeError,
                pickle.UnpicklingError, EOFError):
            self.misses += 1
            return None
        from tony_tpu.compat import deserialize_compiled
        compiled = deserialize_compiled(bytes(payload), in_tree, out_tree)
        if compiled is None:
            self.misses += 1
            return None
        self.hits += 1
        return compiled

    # -- write -------------------------------------------------------------
    def put(self, fp: Dict[str, Any], compiled: Any) -> bool:
        """Persist one compiled executable under ``fp``. Returns True
        only when THIS call committed the entry; False when the key was
        already committed (idempotent / lost a concurrent race — both
        counted in ``put_races``) or the backend cannot serialize
        (``unsupported``). Commit is stage-then-rename: a crash leaves
        a ``.tmp`` orphan, never a half entry."""
        final = self._dir(fp)
        if final.exists():
            self.put_races += 1
            return False
        from tony_tpu.compat import serialize_compiled
        triple = serialize_compiled(compiled)
        if triple is None:
            self.unsupported += 1
            return False
        payload, in_tree, out_tree = triple
        payload = bytes(payload)
        try:
            trees_raw = pickle.dumps((in_tree, out_tree))
        except (pickle.PicklingError, AttributeError, TypeError):
            # Treedefs whose static aux data holds local objects (a
            # train state's optax tx) don't pickle; the entry commits
            # payload-only and ``get`` requires caller-derived trees.
            trees_raw = None
        table: List[Dict[str, int]] = []
        for off in range(0, max(1, len(payload)), CHUNK_BYTES):
            raw = payload[off:off + CHUNK_BYTES]
            table.append({"offset": off, "nbytes": len(raw),
                          "crc32": zlib.crc32(raw) & 0xFFFFFFFF})
        # Per-writer staging name: two concurrent populates of ONE key
        # must not tear each other's staging dir — each stages alone,
        # and the os.replace onto an already-committed entry fails
        # (first-writer-wins) with the loser's staging discarded.
        staging = Path(f"{final}{TMP_SUFFIX}.{os.getpid()}"
                       f".{threading.get_ident()}")
        staging.mkdir(parents=True, exist_ok=True)
        with open(staging / "payload.bin", "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        _atomic_write_json(staging / "entry.json", {
            "format": FORMAT, "fingerprint": fp, "chunks": table,
            "trees_b64": None if trees_raw is None
            else base64.b64encode(trees_raw).decode("ascii"),
            "trees_crc32": None if trees_raw is None
            else zlib.crc32(trees_raw) & 0xFFFFFFFF})
        try:
            os.replace(staging, final)
        except OSError:
            shutil.rmtree(staging, ignore_errors=True)
            self.put_races += 1
            return False
        _fsync_dir(self.root)
        self.puts += 1
        return True

    # -- maintenance (tony aot gc) -----------------------------------------
    def gc(self, *, dry_run: bool = False,
           runtime: Optional[Dict[str, Any]] = None) -> tuple:
        """Drop entries no live config can produce. The criterion is the
        RUNTIME half of the fingerprint (:func:`runtime_fingerprint`):
        an entry whose stored jax/jaxlib/backend/device/XLA-flags tuple
        differs from this process's can never hit again — ``get``
        compares the full fingerprint and the runtime fields come from
        the environment, not the caller — so it is stranded disk, not a
        cache. Geometry/model variation is NOT a drop criterion: other
        topologies of the live runtime are exactly what the cache is
        for. Unreadable entries (torn by an unclean kill before the
        rename discipline, or hand-damaged) are stranded the same way
        and drop too. Staging ``.tmp`` orphans are always reclaimed.

        Returns ``(dropped, kept, freed_bytes)``. ``dry_run`` reports
        without deleting; ``runtime`` overrides the live fingerprint
        (tests)."""
        if runtime is None:
            runtime = runtime_fingerprint()   # lazy jax import
        rt_keys = sorted(runtime)

        def _size(d: Path) -> int:
            try:
                return sum(f.stat().st_size for f in d.rglob("*")
                           if f.is_file())
            except OSError:
                return 0

        dropped, kept, freed = 0, 0, 0
        for name in sorted(os.listdir(self.root)):
            if not name.startswith(_PREFIX):
                continue
            d = self.root / name
            if TMP_SUFFIX in name:
                # A crashed writer's staging dir: never addressable.
                freed += _size(d)
                dropped += 1
                if not dry_run:
                    shutil.rmtree(d, ignore_errors=True)
                continue
            try:
                with open(d / "entry.json") as f:
                    fp = json.load(f).get("fingerprint") or {}
                stale = any(fp.get(k) != runtime[k] for k in rt_keys)
            except (OSError, ValueError):
                stale = True          # unreadable = unhittable
            if stale:
                freed += _size(d)
                dropped += 1
                if not dry_run:
                    shutil.rmtree(d, ignore_errors=True)
            else:
                kept += 1
        if dropped and not dry_run:
            _fsync_dir(self.root)
        return dropped, kept, freed
