"""Elastic restore: rebuild a pytree from a committed checkpoint, onto a
possibly DIFFERENT topology than the one that wrote it.

The degraded-topology resume the multi-slice work needs (a ZeRO-3 state
written on an ``S×fsdp`` mesh restored onto fewer slices or a different
fsdp degree) falls out of the format: the manifest records every leaf's
global shape + PartitionSpec and every chunk's global extent, so restore is
pure geometry —

1. resolve each leaf's TARGET sharding: the target tree's own committed
   sharding when it has one, else the manifest's PartitionSpec mapped onto
   the new mesh (axes the new mesh lacks — or whose new size no longer
   divides the dim — degrade to replicated for that dim), else host numpy;
2. for every local device shard the target sharding asks for, assemble its
   slice of the global array from the covering file chunks (seek-read only
   what overlaps — a 1-slice restore of a 2-slice checkpoint reads each
   byte once, not the whole payload per device);
3. ``jax.make_array_from_single_device_arrays`` stitches the per-device
   buffers into the global array — multi-host safe, no cross-process
   traffic (every process reads only its own shards from the shared dir).

Leaves absent from the manifest (``apply_fn``-style statics) pass through
from the target; dtype changes cast; shape changes raise.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.ckpt import format as fmt
from tony_tpu.ckpt.snapshot import _is_saveable, leaf_paths


def adapt_spec(spec: Optional[P], shape: tuple, mesh: Mesh) -> P:
    """Map a manifest PartitionSpec onto a (possibly different) mesh: keep
    each dim's axes only when the new mesh has them ALL and their combined
    size still divides the dim — otherwise that dim degrades to replicated
    (correct, just less sharded; the resharding IS the elasticity)."""
    if spec is None:
        return P()
    entries = []
    for d, entry in enumerate(tuple(spec)):
        names = entry if isinstance(entry, tuple) else (
            (entry,) if entry is not None else ())
        size = 1
        ok = bool(names)
        for a in names:
            if a not in mesh.axis_names:
                ok = False
                break
            size *= mesh.shape[a]
        if not ok or d >= len(shape) or size == 0 or shape[d] % size:
            entries.append(None)
        else:
            entries.append(entry)
    return P(*entries)


def _assemble(reader: fmt.ChunkReader, leaf_idx: int, dtype: np.dtype,
              index: tuple, global_shape: tuple,
              chunk_cache: Optional[Dict[Any, np.ndarray]] = None
              ) -> np.ndarray:
    """Build the sub-array ``global[index]`` from the covering chunks.
    ``chunk_cache`` (keyed by file+offset, scoped to one leaf) avoids
    re-reading/re-verifying a chunk that covers several target shards."""
    start = [int(s.start or 0) for s in index]
    stop = [int(s.stop if s.stop is not None else n)
            for s, n in zip(index, global_shape)]
    out_shape = [b - a for a, b in zip(start, stop)]
    out = np.empty(out_shape, dtype=dtype)
    filled = 0
    for chunk in reader.chunks_for_leaf(leaf_idx):
        c_start = chunk["start"]
        c_stop = [a + s for a, s in zip(c_start, chunk["shape"])]
        lo = [max(a, b) for a, b in zip(start, c_start)]
        hi = [min(a, b) for a, b in zip(stop, c_stop)]
        if any(a >= b for a, b in zip(lo, hi)):
            continue
        key = (chunk["file"], chunk["offset"])
        data = chunk_cache.get(key) if chunk_cache is not None else None
        if data is None:
            data = reader.read(chunk, dtype)
            if chunk_cache is not None:
                chunk_cache[key] = data
        src = tuple(slice(a - cs, b - cs)
                    for a, b, cs in zip(lo, hi, c_start))
        dst = tuple(slice(a - os_, b - os_)
                    for a, b, os_ in zip(lo, hi, start))
        out[dst] = data[src]
        filled += int(np.prod([b - a for a, b in zip(lo, hi)],
                              dtype=np.int64))
    if filled != out.size:
        raise IOError(
            f"checkpoint leaf {leaf_idx}: chunks cover {filled} of "
            f"{out.size} elements for shard {index} — incomplete payload "
            f"(replica-0 chunks must partition every leaf)")
    return out


# Restore-time dtype policies (f32 master → serving dtype, applied
# during shard assembly so the wide master copy never reaches a device):
# policy name → the dtype float leaves cast to.
DTYPE_POLICIES: Dict[str, str] = {"bf16": "bfloat16", "f32": "float32"}

# Leaves the policy NEVER touches: optimizer slots (optax state and the
# fused plane's portable leaf-major form both live under .opt_state) and
# the quant lane's delayed-scaling state — numerically load-bearing f32
# that a serving cast would silently corrupt on the next fine-tune.
POLICY_EXEMPT_MARKERS: tuple = (".opt_state", ".quant_state")


def _apply_dtype_policy(policy: Optional[str], path: str,
                        dtype: np.dtype) -> np.dtype:
    """The dtype a leaf at ``path`` assembles into under ``policy``:
    float leaves cast to the policy dtype, optimizer/scale state and
    non-float leaves (tokens, counters, bools) keep their own."""
    if policy is None:
        return dtype
    if policy not in DTYPE_POLICIES:
        raise ValueError(f"unknown dtype_policy {policy!r} "
                         f"(one of {sorted(DTYPE_POLICIES)})")
    if any(m in path for m in POLICY_EXEMPT_MARKERS):
        return dtype
    import jax.numpy as jnp
    if not jnp.issubdtype(dtype, jnp.floating):
        return dtype
    return fmt.dtype_from_name(DTYPE_POLICIES[policy])


def _restore_leaf(reader: fmt.ChunkReader, leaf_idx: int,
                  meta: Dict[str, Any], target: Any,
                  mesh: Optional[Mesh],
                  dtype_policy: Optional[str] = None) -> Any:
    global_shape = tuple(meta["shape"])
    saved_dtype = fmt.dtype_from_name(meta["dtype"])
    t_shape = tuple(np.shape(target)) if not isinstance(
        target, (bool, int, float, complex)) else ()
    if hasattr(target, "shape") and t_shape != global_shape:
        raise ValueError(
            f"checkpoint leaf {meta['path']}: saved shape "
            f"{global_shape} != target shape {t_shape} — the checkpoint "
            f"was written for a different model")
    dtype = np.dtype(getattr(target, "dtype", saved_dtype))
    if hasattr(dtype, "name"):
        dtype = fmt.dtype_from_name(dtype.name)   # normalize ml_dtypes
    dtype = _apply_dtype_policy(dtype_policy, meta["path"], dtype)

    sharding = getattr(target, "sharding", None)
    if sharding is None and mesh is not None:
        sharding = NamedSharding(
            mesh, adapt_spec(fmt.spec_from_json(meta["spec"]),
                             global_shape, mesh))
    if sharding is None:
        full = _assemble(reader, leaf_idx, saved_dtype,
                         tuple(slice(0, n) for n in global_shape),
                         global_shape)
        return full.astype(dtype, copy=False)

    # Device path: one host assembly per DISTINCT shard extent (chunks
    # read/verified once even when they span extents), then a device_put
    # per local device; the global array is stitched without any
    # cross-process traffic.
    index_map = sharding.devices_indices_map(global_shape)
    cache: Dict[Any, np.ndarray] = {}
    chunk_cache: Dict[Any, np.ndarray] = {}
    arrays = []
    for device in sharding.addressable_devices:
        index = index_map[device]
        key = tuple((s.start, s.stop) for s in index)
        buf = cache.get(key)
        if buf is None:
            buf = _assemble(reader, leaf_idx, saved_dtype, index,
                            global_shape,
                            chunk_cache).astype(dtype, copy=False)
            cache[key] = buf
        arrays.append(jax.device_put(buf, device))
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrays)


def restore_pytree(root: str | Path, target: Any, *,
                   step: Optional[int] = None, mesh: Optional[Mesh] = None,
                   verify: bool = True, strict: bool = True,
                   dtype_policy: Optional[str] = None,
                   path_prefix: str = "") -> Any:
    """Restore ``target``'s array leaves from the committed checkpoint at
    ``step`` (default: newest). ``target`` supplies structure, statics,
    dtypes, and — when its leaves carry committed shardings — the exact
    output layout; ``mesh`` supplies the layout for shardingless targets
    (manifest specs mapped through :func:`adapt_spec`). ``strict`` raises
    when an array leaf has no manifest entry (else it passes through).

    ``dtype_policy`` is the serving plane's restore-time cast
    (``"bf16"``: f32 master → bf16, applied per-shard DURING assembly so
    the wide copy never reaches a device; optimizer/scale state is never
    cast — see :data:`POLICY_EXEMPT_MARKERS`). ``path_prefix`` restores
    a SUBTREE of a larger manifest: target leaf paths are looked up as
    ``path_prefix + path`` (e.g. ``".params"`` pulls just the params out
    of a full-TrainState checkpoint — the replica's restore, which wants
    no optimizer slots resurrected at all). Use
    :func:`find_path_prefix` to locate the prefix in a manifest whose
    wrapping (bare state vs train_loop's ``{"model": ...}``) is
    unknown."""
    if step is None:
        step = fmt.latest_step(root)
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {root}")
    manifest = fmt.read_manifest(root, step)
    by_path = {m["path"]: (i, m) for i, m in enumerate(manifest["leaves"])}
    paths, leaves, treedef = leaf_paths(target)
    out = []
    with fmt.ChunkReader(root, step, manifest, verify=verify) as reader:
        for path, leaf in zip(paths, leaves):
            path = path_prefix + path
            if path not in by_path:
                if strict and _is_saveable(leaf) and np.ndim(leaf) > 0:
                    raise KeyError(
                        f"target leaf {path} has no entry in checkpoint "
                        f"step {step} (pass strict=False to keep the "
                        f"target's value)")
                out.append(leaf)
                continue
            idx, meta = by_path[path]
            out.append(_restore_leaf(reader, idx, meta, leaf, mesh,
                                     dtype_policy))
    return jax.tree_util.tree_unflatten(treedef, out)


def find_path_prefix(root: str | Path, target: Any, *,
                     step: Optional[int] = None) -> str:
    """The ``path_prefix`` under which ``target``'s leaves live in the
    committed manifest — resolves a bare params tree against whatever
    wrapping wrote the checkpoint (a raw params save → ``""``, a
    TrainState → ``".params"``, train_loop's wrapped payload →
    ``"['model'].params"``). Raises ``KeyError`` when no prefix covers
    every array leaf."""
    if step is None:
        step = fmt.latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no committed checkpoint under {root}")
    manifest = fmt.read_manifest(root, step)
    mpaths = {m["path"] for m in manifest["leaves"]}
    paths, leaves, _ = leaf_paths(target)
    needed = [p for p, l in zip(paths, leaves)
              if _is_saveable(l) and np.ndim(l) > 0]
    if not needed:
        return ""
    probe = needed[0]
    candidates = []
    for mp in sorted(mpaths):
        if not mp.endswith(probe):
            continue
        prefix = mp[:len(mp) - len(probe)]
        if all(prefix + p in mpaths for p in needed):
            candidates.append(prefix)
    if not candidates:
        raise KeyError(
            f"no manifest path prefix covers the target's leaves (probe "
            f"{probe!r}; manifest has {len(mpaths)} leaves) — is this "
            f"checkpoint for a different model?")
    # Ambiguity is real: adamw's mu/nu trees mirror the params' leaf
    # paths exactly, so ".opt_state[0].mu" covers a bare params target
    # too. Prefer prefixes OUTSIDE the derived-state subtrees (optimizer
    # slots / quant scale state are never the tree a restore should seed
    # from), shortest first.
    primary = [c for c in candidates
               if not any(m in c for m in POLICY_EXEMPT_MARKERS)]
    return min(primary or candidates, key=len)


def restore_latest(root: str | Path, target: Any, *,
                   mesh: Optional[Mesh] = None, verify: bool = True,
                   dtype_policy: Optional[str] = None) -> Any:
    """``restore_pytree`` when a committed step exists, else ``target``
    unchanged — the first-attempt no-op the gang-restart contract needs."""
    if fmt.latest_step(root) is None:
        return target
    return restore_pytree(root, target, mesh=mesh, verify=verify,
                          dtype_policy=dtype_policy)
