"""Crash-consistent on-disk checkpoint format (the ckpt subsystem's wire).

One checkpoint step is one directory::

    <root>/step_00000042/
        shards_00000.bin    # proc 0's chunk payload (raw concatenated blobs)
        shards_00000.json   # proc 0's sidecar: chunk table + checksums
        shards_00001.bin    # ... one pair per process
        manifest.json       # written LAST, by process 0 only

and is written under ``<root>/step_00000042.tmp`` until process 0 commits it
with ONE atomic ``os.replace`` of the directory. The invariants that make a
``kill -9`` at any instant recoverable:

* a step directory without the ``.tmp`` suffix always holds a complete,
  checksummed checkpoint (the rename is the commit point — POSIX renames
  are atomic, and the payload/manifest are fsynced before it);
* :func:`latest_step` only ever looks at committed directories, so a crash
  mid-write leaves the previous step exactly restorable and the torn
  ``.tmp`` dir inert (reclaimed by the next save);
* the manifest is itself written via tmp-file + rename inside the staging
  dir, so even the commit's final rename never exposes a torn JSON.

The payload is dtype-transparent raw bytes (``ndarray.tobytes`` little-
endian blobs, offsets in the sidecar) rather than ``.npz``: bf16 and the
other ``ml_dtypes`` round-trip without pickle, and elastic restore can
``seek``/read exactly the chunks that cover a new topology's shard instead
of decompressing whole archives. Every chunk carries a CRC32; restore
verifies the chunks it actually reads.

Fault injection for the crash-consistency tests: :data:`CRASH_HOOK` (or the
``TONY_CKPT_CRASH`` env var naming a phase) fires at the phases marked by
:func:`_crash_point` — the test hook SIGKILLs the writer mid-save and the
previous step must restore bit-exact.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# No jax import here (and none at module level below): the executor's
# heartbeat loop calls latest_step() from a process that never touches the
# compute plane — listing committed steps must not drag the jax stack in.
import numpy as np

MANIFEST_NAME = "manifest.json"
FORMAT_VERSION = "tony-ckpt-v1"
TMP_SUFFIX = ".tmp"
ENV_CRASH = "TONY_CKPT_CRASH"

_STEP_RE = re.compile(r"^step_(\d+)$")

# Test seam: a callable ``(phase) -> None`` invoked at the marked phases of
# a save ("after_shards" — payload written, manifest not; "before_commit" —
# manifest staged, directory rename not yet issued). The env var variant
# SIGKILLs the process outright so subprocess tests exercise a true kill -9.
CRASH_HOOK: Optional[Callable[[str], None]] = None


def _crash_point(phase: str) -> None:
    if CRASH_HOOK is not None:
        CRASH_HOOK(phase)
    if os.environ.get(ENV_CRASH) == phase:
        os.kill(os.getpid(), signal.SIGKILL)


# ---------------------------------------------------------------------------
# Naming / discovery
# ---------------------------------------------------------------------------

def step_dir(root: str | Path, step: int) -> Path:
    return Path(root) / f"step_{step:08d}"


def tmp_dir(root: str | Path, step: int) -> Path:
    return Path(root) / f"step_{step:08d}{TMP_SUFFIX}"


def shard_file_name(proc: int) -> str:
    return f"shards_{proc:05d}.bin"


def sidecar_name(proc: int) -> str:
    return f"shards_{proc:05d}.json"


def committed_steps(root: str | Path) -> List[int]:
    """All committed step numbers under ``root``, ascending. A directory
    counts only if the commit rename happened AND the manifest is inside —
    ``.tmp`` staging dirs and torn leftovers never appear here."""
    root = Path(root)
    if not root.is_dir():
        return []
    out = []
    for entry in root.iterdir():
        m = _STEP_RE.match(entry.name)
        if m and (entry / MANIFEST_NAME).is_file():
            out.append(int(m.group(1)))
    return sorted(out)


def latest_step(root: str | Path) -> Optional[int]:
    steps = committed_steps(root)
    return steps[-1] if steps else None


# ---------------------------------------------------------------------------
# Dtype / PartitionSpec serialization
# ---------------------------------------------------------------------------

def dtype_name(dt: Any) -> str:
    return np.dtype(dt).name


def dtype_from_name(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes family (bfloat16,
    float8_*) numpy itself doesn't know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def spec_to_json(spec: Any) -> Optional[List[Any]]:
    """PartitionSpec → JSON (None when the array carried no named spec).
    Each dim entry is ``None`` | ``"axis"`` | ``["axis", ...]``."""
    if spec is None:
        return None
    out: List[Any] = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, tuple):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    return out


def spec_from_json(entries: Optional[Sequence[Any]]) -> Optional[Any]:
    if entries is None:
        return None
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


# ---------------------------------------------------------------------------
# Write side
# ---------------------------------------------------------------------------

def _fsync_dir(path: Path) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write_json(path: Path, payload: Dict[str, Any]) -> None:
    tmp = path.with_suffix(path.suffix + ".part")
    with open(tmp, "w") as f:
        json.dump(payload, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_process_file(staging: str | Path, proc: int,
                       chunks: Sequence[Tuple[int, Sequence[int],
                                              np.ndarray]]) -> Dict[str, Any]:
    """Write this process's chunk payload + sidecar into the staging dir.

    ``chunks`` is ``[(leaf_index, start_offsets, host_array), ...]``. The
    sidecar (written tmp+rename AFTER the payload is fsynced — its presence
    is the per-process completion signal the committer waits on) records
    every chunk's byte offset, extent, and CRC32.
    """
    staging = Path(staging)
    staging.mkdir(parents=True, exist_ok=True)
    fname = shard_file_name(proc)
    table: List[Dict[str, Any]] = []
    offset = 0
    file_crc = 0
    with open(staging / fname, "wb") as f:
        for leaf, start, arr in chunks:
            # NOT ascontiguousarray: it promotes 0-d scalars to 1-d, and
            # the recorded chunk shape must match the leaf geometry.
            arr = np.asarray(arr, order="C")
            blob = arr.tobytes()
            f.write(blob)
            table.append({
                "leaf": int(leaf),
                "start": [int(s) for s in start],
                "shape": [int(s) for s in arr.shape],
                "offset": offset,
                "nbytes": len(blob),
                "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
            })
            file_crc = zlib.crc32(blob, file_crc) & 0xFFFFFFFF
            offset += len(blob)
        f.flush()
        os.fsync(f.fileno())
    sidecar = {"file": fname, "process": int(proc), "nbytes": offset,
               "crc32": file_crc, "chunks": table}
    _atomic_write_json(staging / sidecar_name(proc), sidecar)
    return sidecar


def commit(root: str | Path, step: int, *, leaves: List[Dict[str, Any]],
           mesh: Optional[Dict[str, Any]], num_processes: int,
           barrier_timeout_s: float = 300.0) -> Path:
    """Process-0 commit: wait for every process's sidecar, merge them into
    the single manifest, then atomically rename the staging dir into place.
    The filesystem IS the barrier (the root is the durable shared dir the
    TonY contract already assumes for checkpoints)."""
    staging = tmp_dir(root, step)
    deadline = time.monotonic() + barrier_timeout_s
    sidecars: List[Dict[str, Any]] = []
    for proc in range(num_processes):
        path = staging / sidecar_name(proc)
        while not path.is_file():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"checkpoint step {step}: process {proc} did not finish "
                    f"its shard file within {barrier_timeout_s:.0f}s")
            time.sleep(0.05)
        sidecars.append(json.loads(path.read_text()))
    _crash_point("after_shards")
    manifest = {
        "format": FORMAT_VERSION,
        "step": int(step),
        "num_processes": int(num_processes),
        "created": time.time(),
        "mesh": mesh,
        "leaves": leaves,
        "files": [{"file": s["file"], "nbytes": s["nbytes"],
                   "crc32": s["crc32"]} for s in sidecars],
        "chunks": [dict(c, file=s["file"])
                   for s in sidecars for c in s["chunks"]],
    }
    _atomic_write_json(staging / MANIFEST_NAME, manifest)
    _fsync_dir(staging)
    _crash_point("before_commit")
    final = step_dir(root, step)
    old: Optional[Path] = None
    if final.exists():
        # Re-saving an already-committed step (same-step retry after a
        # restart): move the old copy ASIDE (atomic rename, invisible to
        # committed_steps) rather than rmtree-then-replace — a kill
        # between delete and rename would otherwise lose the only
        # committed copy of this step. Deleted only after the new commit.
        old = final.with_name(final.name + ".old")
        if old.exists():
            shutil.rmtree(old)
        os.replace(final, old)
    os.replace(staging, final)
    _fsync_dir(Path(root))
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    return final


def wait_committed(root: str | Path, step: int,
                   timeout_s: float = 300.0) -> Path:
    """Block until ``step`` is committed (the manifest is visible at the
    final path) — the non-zero-process half of the commit barrier: every
    process's blocking save must mean GLOBALLY durable, not just "my
    shards landed", or a gang-wide save-then-restore diverges across
    processes."""
    final = step_dir(root, step)
    deadline = time.monotonic() + timeout_s
    while not (final / MANIFEST_NAME).is_file():
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"checkpoint step {step}: process 0 did not commit the "
                f"manifest within {timeout_s:.0f}s")
        time.sleep(0.05)
    return final


def clean_stale(root: str | Path) -> None:
    """Remove torn ``.tmp`` staging dirs left by crashed writers and
    ``.old`` dirs left by a same-step recommit killed mid-swap. Caller
    contract (AsyncCheckpointer): at most ONE live writer instance per
    process per directory — a sweep concurrent with another instance's
    in-flight save would reclaim its staging dir."""
    root = Path(root)
    if not root.is_dir():
        return
    for entry in root.iterdir():
        if entry.name.endswith(".old") \
                and _STEP_RE.match(entry.name[:-len(".old")]):
            shutil.rmtree(entry, ignore_errors=True)
        elif entry.name.endswith(TMP_SUFFIX) \
                and _STEP_RE.match(entry.name[: -len(TMP_SUFFIX)]):
            shutil.rmtree(entry, ignore_errors=True)


def prune(root: str | Path, keep: int) -> List[int]:
    """Delete committed steps beyond the newest ``keep`` (0/negative keeps
    everything). Returns the pruned step numbers."""
    if keep <= 0:
        return []
    steps = committed_steps(root)
    victims = steps[:-keep] if len(steps) > keep else []
    for s in victims:
        shutil.rmtree(step_dir(root, s), ignore_errors=True)
    return victims


# ---------------------------------------------------------------------------
# Read side
# ---------------------------------------------------------------------------

def read_manifest(root: str | Path, step: int) -> Dict[str, Any]:
    path = step_dir(root, step) / MANIFEST_NAME
    manifest = json.loads(path.read_text())
    if manifest.get("format") != FORMAT_VERSION:
        raise ValueError(
            f"{path}: unknown checkpoint format "
            f"{manifest.get('format')!r} (expected {FORMAT_VERSION})")
    return manifest


class ChunkReader:
    """Random-access reader over one committed step's chunk payload:
    ``read(chunk)`` seeks into the owning shard file, verifies the chunk's
    CRC32, and returns the ndarray. File handles are cached per file."""

    def __init__(self, root: str | Path, step: int,
                 manifest: Optional[Dict[str, Any]] = None,
                 verify: bool = True):
        self.dir = step_dir(root, step)
        self.manifest = manifest if manifest is not None \
            else read_manifest(root, step)
        self.verify = verify
        self._files: Dict[str, Any] = {}
        # Indexed once: restore assembles per leaf per shard extent, and a
        # linear manifest scan per call would be O(leaves x extents x
        # chunks).
        self._by_leaf: Dict[int, List[Dict[str, Any]]] = {}
        for c in self.manifest["chunks"]:
            self._by_leaf.setdefault(int(c["leaf"]), []).append(c)

    def chunks_for_leaf(self, leaf: int) -> List[Dict[str, Any]]:
        return self._by_leaf.get(leaf, [])

    def read(self, chunk: Dict[str, Any], dtype: np.dtype) -> np.ndarray:
        f = self._files.get(chunk["file"])
        if f is None:
            f = open(self.dir / chunk["file"], "rb")
            self._files[chunk["file"]] = f
        f.seek(chunk["offset"])
        blob = f.read(chunk["nbytes"])
        if len(blob) != chunk["nbytes"]:
            raise IOError(
                f"{self.dir / chunk['file']}: short read at offset "
                f"{chunk['offset']} (wanted {chunk['nbytes']}, got "
                f"{len(blob)}) — truncated shard file")
        if self.verify and (zlib.crc32(blob) & 0xFFFFFFFF) != chunk["crc32"]:
            raise IOError(
                f"{self.dir / chunk['file']}: CRC mismatch for leaf "
                f"{chunk['leaf']} chunk at offset {chunk['offset']} — "
                f"corrupt checkpoint payload")
        return np.frombuffer(blob, dtype=dtype).reshape(chunk["shape"])

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()

    def __enter__(self) -> "ChunkReader":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()
