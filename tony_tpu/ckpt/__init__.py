"""Native async sharded checkpoint & elastic-restore plane.

The reference delegates checkpointing entirely to user code (HDFS dirs that
survive AM restarts; TonY restarts the gang and the script restores —
PAPER §5.4/§7). This package is the framework-owned replacement the TPU
rebuild needs once ZeRO-3 states live permanently sharded across an
ICI×DCN mesh (TF-Replicator's argument, PAPERS 1902.00465: a distributed
runtime must own state management, not delegate it):

* :class:`AsyncCheckpointer` (:mod:`~tony_tpu.ckpt.snapshot`) — double-
  buffered device→host snapshot + background writer, so saves overlap the
  train loop the way the overlap engine hides gradient sync;
* the crash-consistent on-disk format (:mod:`~tony_tpu.ckpt.format`) —
  per-process shard files + ONE manifest (pytree structure, global shapes,
  dtypes, mesh, per-leaf PartitionSpecs, CRC32s), committed atomically via
  directory rename: a ``kill -9`` mid-save always leaves the previous step
  restorable;
* elastic restore (:mod:`~tony_tpu.ckpt.restore`) — a checkpoint written
  on one mesh restores onto a different slice count / fsdp degree by
  mapping the manifest specs onto the new mesh and assembling each
  process's shards from the covering file chunks.

Control-plane wiring: ``tony.ckpt.dir/every/keep`` flow to user code via
``TONY_CKPT_*`` env (JAXRuntime), :func:`tony_tpu.train.train_loop` drives
``save_every``/``restore_on_start``, and the executor reports the last
COMMITTED step over the heartbeat RPC so the AM logs what a gang restart
will resume from. ``tony_tpu.checkpoint.Checkpointer`` is the thin compat
shim over this package (orbax no longer required).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from tony_tpu.ckpt.format import (FORMAT_VERSION, ChunkReader,
                                  committed_steps, latest_step, prune,
                                  read_manifest, step_dir)

# ---------------------------------------------------------------------------
# Portable-form codecs: a plane whose LIVE state layout is topology-bound
# (e.g. the fused optimizer's bucket-resident moment buffers — bucket
# partitioning depends on the fsdp degree and bucket_bytes) registers an
# encode/decode pair here so what the manifest records is the PORTABLE
# form (topology-independent leaf paths/shapes). ``train_loop`` encodes
# every payload before save and decodes after restore; trees no codec
# claims pass through untouched, so pre-codec checkpoints and plain optax
# states behave exactly as before.
# ---------------------------------------------------------------------------

PORTABLE_CODECS: List[Tuple[str, Callable[[Any], bool],
                            Callable[[Any], Any],
                            Callable[[Any, Any], Any]]] = []


def register_portable_codec(name: str, predicate: Callable[[Any], bool],
                            encode: Callable[[Any], Any],
                            decode: Callable[[Any, Any], Any],
                            prepend: bool = False) -> None:
    """Register ``(predicate, encode, decode)`` under ``name`` (replacing
    an earlier registration of the same name — planes re-import under
    pytest). ``encode(tree) -> portable tree``; ``decode(tree, mesh) ->
    live tree`` re-bound to the CURRENT topology. First matching codec
    wins; ``prepend`` registers ahead of the existing entries — for a
    codec whose predicate SUBSUMES an earlier one's (the quant-gather
    codec composes the fused-optimizer codec and must match first)."""
    PORTABLE_CODECS[:] = [c for c in PORTABLE_CODECS if c[0] != name]
    entry = (name, predicate, encode, decode)
    if prepend:
        PORTABLE_CODECS.insert(0, entry)
    else:
        PORTABLE_CODECS.append(entry)


def encode_portable(tree: Any) -> Any:
    """Apply the first matching codec's encode; identity otherwise."""
    for _, predicate, encode, _ in PORTABLE_CODECS:
        if predicate(tree):
            return encode(tree)
    return tree


def decode_portable(tree: Any, mesh: Optional[Any] = None) -> Any:
    """Apply the first matching codec's decode; identity otherwise."""
    for _, predicate, _, decode in PORTABLE_CODECS:
        if predicate(tree):
            return decode(tree, mesh)
    return tree

# snapshot/restore re-exports are LAZY (PEP 562): format is jax-free so
# the executor's heartbeat can list committed steps without importing the
# compute stack, and `import tony_tpu.ckpt` must keep that property.
_LAZY = {
    "adapt_spec": "restore", "restore_latest": "restore",
    "restore_pytree": "restore", "find_path_prefix": "restore",
    "AsyncCheckpointer": "snapshot", "Snapshot": "snapshot",
    "extract_snapshot": "snapshot", "write_snapshot": "snapshot",
    # AOT compile cache (tony_tpu.ckpt.aot): jax-free at import like
    # format, but re-exported lazily by the same rule — the cache's
    # fingerprint helpers import jax on first use.
    "AOTCache": "aot", "make_fingerprint": "aot",
    "fingerprint_key": "aot",
}

__all__ = [
    "FORMAT_VERSION", "ChunkReader", "committed_steps", "latest_step",
    "prune", "read_manifest", "step_dir", "register_portable_codec",
    "encode_portable", "decode_portable", *sorted(_LAZY),
]


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f"tony_tpu.ckpt.{_LAZY[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
