"""Layered job configuration with open per-jobtype templating.

Mirrors ``com.linkedin.tony.TonyConfigurationKeys`` +
``tony-core/src/main/resources/tony-default.xml`` (upstream paths, unverified —
SURVEY.md §0).  The single most load-bearing idea preserved from the reference
(SURVEY.md §5.6) is the *open* per-jobtype key template::

    tony.<jobtype>.instances / .memory / .vcores / .gpus / .tpus / .command

so that ``ps``/``worker``/``chief``/``evaluator``/``tensorboard``/``notebook``
— or any user-invented job type — work without code changes.

Layering (lowest to highest precedence), as in Hadoop ``Configuration``:

1. built-in defaults (:data:`DEFAULTS`, the ``tony-default.xml`` analogue)
2. a user config file — Hadoop-style ``tony.xml`` or JSON — via :meth:`TonyConfig.load`
3. explicit ``-D key=value`` overrides via :meth:`TonyConfig.set`
"""

from __future__ import annotations

import json
import re
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

from tony_tpu import constants

# --------------------------------------------------------------------------
# Key names (reference: TonyConfigurationKeys.*)
# --------------------------------------------------------------------------
TONY_PREFIX = "tony."

APPLICATION_NAME = "tony.application.name"
APPLICATION_FRAMEWORK = "tony.application.framework"          # jax|tensorflow|pytorch|horovod|mxnet|standalone
APPLICATION_UNTRACKED = "tony.application.untracked.jobtypes" # csv of untracked types
APPLICATION_STOP_ON_FAILURE = "tony.application.fail-fast"    # fail job on first task failure
APPLICATION_TIMEOUT = "tony.application.timeout-ms"           # 0 = no timeout
APPLICATION_NODE_BLACKLIST = "tony.application.node-blacklist"
# CSV of extra files/dirs/archives to localize into every container's cwd
# (reference: LocalizableResource / Utils.uploadFileAndSetConfResources —
# datasets, tokenizer files, certs). An entry suffixed "#archive" is
# unpacked in the container cwd instead of copied.
CONTAINERS_RESOURCES = "tony.containers.resources"
SECURITY_ENABLED = "tony.security.enabled"
DOCKER_ENABLED = "tony.docker.enabled"
DOCKER_IMAGE = "tony.docker.containers.image"

TASK_HEARTBEAT_INTERVAL_MS = "tony.task.heartbeat-interval-ms"
TASK_MAX_MISSED_HEARTBEATS = "tony.task.max-missed-heartbeats"
TASK_METRICS_INTERVAL_MS = "tony.task.metrics-interval-ms"
TASK_EXECUTOR_EXECUTION_TIMEOUT_MS = "tony.task.executor.execution-timeout-ms"

AM_RETRY_COUNT = "tony.am.retry-count"                        # gang-restart attempts
AM_MAX_ATTEMPTS = "tony.am.max-attempts"                      # AM-process relaunches (reference: yarn am max-attempts)
AM_MEMORY = "tony.am.memory"
AM_VCORES = "tony.am.vcores"
AM_GANG_TIMEOUT_MS = "tony.am.gang-allocation-timeout-ms"     # all-registered barrier timeout

PREEMPTION_MAX_RETRIES = "tony.container.preemption.max-retries"

HISTORY_LOCATION = "tony.history.location"                    # event-log root dir
SCHEDULER_TOTAL_TPUS = "tony.scheduler.total-tpus"            # chip-census override
PYTHON_VENV = "tony.application.python-venv"                  # venv dir/archive to ship
PYTHON_BINARY = "tony.application.python-binary"              # interpreter path (in venv)
# Base port for TPU_PROCESS_ADDRESSES/TPU_PROCESS_PORT when tasks subdivide
# a host (port = base + global_rank): all processes must know every peer's
# libtpu address BEFORE launch, so these can't be executor-reserved
# ephemerals. Conf-keyed so concurrent jobs sharing hosts stay apart.
LIBTPU_PORT_BASE = "tony.task.libtpu.port-base"
# JAXRuntime injects the comm/compute-overlap XLA flags (latency-hiding
# scheduler, async collective fusion — tony_tpu.parallel.overlap) into a jax
# task's XLA_FLAGS, merged under any flags from tony.<jobtype>.env (user-set
# flag names win). Unset: injected iff the task requests TPUs
# (tony.<jobtype>.tpus > 0 — the xla_tpu_* set aborts non-TPU XLA builds).
# Explicit true/false forces it on (whole-host TPU jobs) / off.
JAX_OVERLAP_XLA_FLAGS = "tony.jax.overlap-xla-flags"
# Number of DCN-connected TPU slices the jax gang spans (>1 = multi-slice).
# The rendezvous world is split contiguously into this many equal slices:
# JAXRuntime derives each task's MEGASCALE_SLICE_ID from its global rank,
# exports the megascale coordination env, and adds the DCN XLA flag set
# (overlap.MULTISLICE_XLA_FLAGS) so the hierarchical per-bucket DCN
# allreduces overlap. Must divide the rendezvous task count.
JAX_SLICES = "tony.jax.slices"
# Port for the megascale DCN transport/coordinator (same on every host;
# conf-keyed like the libtpu base so concurrent jobs sharing hosts can be
# kept apart). The coordinator is the global-rank-0 task's host.
MEGASCALE_PORT = "tony.jax.megascale.port"
# Checkpoint plane (tony_tpu.ckpt). tony.ckpt.dir names the DURABLE shared
# directory (the HDFS-dir analogue that survives gang restarts) the async
# checkpointer commits steps into; setting it turns on the whole wiring:
# JAXRuntime exports TONY_CKPT_DIR/EVERY/KEEP to jax tasks (train_loop's
# defaults), and the executor reports the last committed step found there
# over the heartbeat RPC so the AM logs what a gang restart resumes from.
CKPT_DIR = "tony.ckpt.dir"
CKPT_EVERY = "tony.ckpt.every"            # save every N steps (0 = final only)
CKPT_KEEP = "tony.ckpt.keep"              # committed steps retained (def. 3)
# Input-data plane (tony_tpu.data): seed of the deterministic global
# example stream. Exported to jax tasks as TONY_DATA_SEED (Dataset's
# default seed) so every process in the gang — and every RESTART of the
# gang — derives the identical stream; the per-host shard comes from the
# rendezvous identity, not from conf.
DATA_SEED = "tony.data.seed"

# -- serving plane (tony_tpu.serve; the `tony serve` CLI writes these,
# the replica process and the AM's replica autoscaler read them) --------
SERVE_MODEL = "tony.serve.model"                # registered model name
SERVE_MODEL_KWARGS = "tony.serve.model-kwargs"  # JSON dict of model kwargs
SERVE_CKPT_DIR = "tony.serve.ckpt-dir"          # training ckpt to serve
SERVE_DTYPE_POLICY = "tony.serve.dtype-policy"  # bf16 (default) | f32
SERVE_CTX_MAX = "tony.serve.ctx-max"            # max positions per sequence
SERVE_BLOCK_SIZE = "tony.serve.block-size"      # KV pool block size
SERVE_MAX_RUNNING = "tony.serve.max-running"    # max joined batch
SERVE_MESH = "tony.serve.mesh"                  # JSON MeshSpec kwargs
SERVE_PORT = "tony.serve.port"                  # replica RPC port (0=any)
SERVE_REPLICAS_MIN = "tony.serve.replicas.min"  # autoscale floor
SERVE_REPLICAS_MAX = "tony.serve.replicas.max"  # autoscale ceiling
SERVE_QUEUE_HIGH = "tony.serve.scale.queue-high"
SERVE_QUEUE_LOW = "tony.serve.scale.queue-low"
SERVE_P99_HIGH_MS = "tony.serve.scale.p99-high-ms"
SERVE_COOLDOWN_S = "tony.serve.scale.cooldown-s"
# Speculative decoding lane (tony_tpu.serve.spec): spec-k > 0 turns the
# replica's engine into the draft-and-verify SpecEngine. With a draft
# model name it restores a second (smaller, optionally quant=-laned)
# transformer through the same elastic-restore path; without one the
# self-drafting n-gram fallback runs — no second checkpoint needed.
SERVE_SPEC_K = "tony.serve.spec-k"              # draft depth (0 = off)
# Prefix caching + chunked prefill + cross-replica routing (PR 13): the
# engine's prefix tier shares block-hashed KV across admissions; chunked
# prefill interleaves long prompts with decode; the route weights feed
# the gateway router's replica scoring (prefix-digest overlap vs load).
SERVE_PREFIX_CACHE = "tony.serve.prefix-cache"  # true arms block sharing
SERVE_PREFILL_CHUNK = "tony.serve.prefill-chunk"  # rows/chunk (0 = mono)
SERVE_ROUTE_CACHE_WEIGHT = "tony.serve.route.cache-weight"
SERVE_ROUTE_QUEUE_WEIGHT = "tony.serve.route.queue-weight"
SERVE_ROUTE_P99_WEIGHT = "tony.serve.route.p99-weight"
SERVE_DRAFT_MODEL = "tony.serve.draft.model"    # registered draft model
SERVE_DRAFT_MODEL_KWARGS = "tony.serve.draft.model-kwargs"  # JSON kwargs
SERVE_DRAFT_CKPT_DIR = "tony.serve.draft.ckpt-dir"  # draft training ckpt
SERVE_DRAFT_NGRAM_MAX = "tony.serve.draft.ngram-max"  # fallback n-gram n
# Disaggregated prefill/decode (PR 15): serve-role jobtypes. A jobtype
# carrying tony.serve.role.<jobtype> = prefill|decode|colocated is a
# serving gang of that role — the first heterogeneous-gang wiring: ONE
# job runs a prefill gang and a decode gang as separate jobtypes, each
# with its own instance count and autoscale floor, sharing the serve.*
# engine config. The AM's autoscaler and the serve_endpoints verb treat
# every role-keyed jobtype (plus the classic "serve") as serving.
SERVE_ROLE_PREFIX = "tony.serve.role."
# KV memory hierarchy (PR 16): host-blocks > 0 arms the pool's host-
# offload tier (cold published stems demote to host RAM, finished
# conversation turns PARK there and resume without re-prefill); the
# prefix store names an on-disk directory of persisted hot stems —
# replicas load it at startup and scale-up grants inherit it, so a
# fresh replica warms its prefix tier from disk instead of recompute.
SERVE_HOST_BLOCKS = "tony.serve.host-blocks"    # host tier size (0 = off)
SERVE_PREFIX_STORE = "tony.serve.prefix-store"  # stem store dir ("" = off)
# Replica cold-start plane (PR 17): the AOT cache dir persists compiled
# step executables next to the ckpt manifest (tony_tpu.ckpt.aot) so a
# scale-up grant deserializes instead of re-tracing; warm-standby > 0
# holds that many compiled-and-idle replicas per serve jobtype ahead of
# the traffic curve (the AM promotes one on scale-up instead of a cold
# grant); the demote watermark arms the engine-loop demotion daemon
# that pre-drains the device pool into the PR 16 host tier.
SERVE_AOT_CACHE = "tony.serve.aot-cache"        # AOT cache dir ("" = off)
SERVE_WARM_STANDBY = "tony.serve.warm-standby"  # standby pool size (0=off)
SERVE_DEMOTE_WATERMARK = "tony.serve.demote-watermark"  # pool frac (0=off)
SERVE_DEMOTE_BATCH = "tony.serve.demote-batch"  # blocks/sweep (0=nb_max)
# Multi-tenant QoS + SLO autoscaling (PR 18): the tenants CSV declares
# the gang's QoS classes as "name:weight,..." — requests tagged with a
# tenant get a weighted-fair share of the paged KV pool at admission
# (work-conserving: an idle tenant's share redistributes), so one
# tenant's prefill burst queues behind its own budget instead of
# starving another tenant's decode floor. Untagged requests bypass
# budgets entirely; with the CSV empty the engine is byte-identical to
# an un-QoS'd one. The SLO target switches the autoscaler from raw
# queue depth to p99-vs-target per gang, computed from the same latency
# windows the history plane logs — a replayed event log reproduces the
# live scale decisions exactly.
SERVE_QOS_TENANTS = "tony.serve.qos.tenants"    # "name:weight,.." ("" = off)
SERVE_QOS_MAX_QUEUE = "tony.serve.qos.max-queue"  # per-tenant cap (0 = inf)
SERVE_SLO_TARGET_MS = "tony.serve.scale.slo-target-ms"  # p99 target (0=off)
# Per-tenant p99 targets ("gold:200,silver:800", same grammar as the QoS
# tenants CSV): SLO mode scales on the WORST tenant's p99-vs-target,
# read from the tenants breakdown riding every SERVE_WINDOW record.
# Composes with the single gang-wide target; "" = per-tenant mode off.
SERVE_SLO_TARGETS = "tony.serve.scale.slo-targets"

# Elastic gang resize (tony_tpu.am.resize): on worker preemption / lost
# heartbeat (or `tony resize N`), drain survivors through an atomic
# commit, re-gang at the new host count, and restore elastically —
# instead of the full gang restart. Off by default: the historical
# preemption-retry + gang-restart behavior is byte-unchanged unless
# armed.
RESIZE_ENABLED = "tony.resize.enabled"
RESIZE_JOB_TYPE = "tony.resize.job-type"            # the elastic train gang
RESIZE_MIN_WORKERS = "tony.resize.min-workers"      # floor after shrink
RESIZE_MAX_RESIZES = "tony.resize.max-resizes"      # per-job resize budget
RESIZE_DRAIN_TIMEOUT_MS = "tony.resize.drain-timeout-ms"
RESIZE_REGANG_TIMEOUT_MS = "tony.resize.regang-timeout-ms"
RESIZE_RESTORE_TIMEOUT_MS = "tony.resize.restore-timeout-ms"

# Continuous weight publication (tony_tpu.publish / serve.swap): with
# publish.every > 0, JAXRuntime exports TONY_PUBLISH_EVERY and the train
# loop advances the ckpt root's published.json pointer every N committed
# saves (stage-and-rename, announced on the heartbeat). publish.follow
# = true arms the AM's rolling fleet swap: when a newer pointer version
# appears (heartbeat or a direct ckpt-dir read), serve replicas hot-swap
# to it one at a time, down-marked in the router for their swap window.
PUBLISH_EVERY = "tony.publish.every"                # saves/publication (0=off)
PUBLISH_FOLLOW = "tony.publish.follow"              # AM swaps the fleet
PUBLISH_SWAP_TIMEOUT_MS = "tony.publish.swap-timeout-ms"  # per-replica window
# Shared per-gang train-side AOT cache dir (the serve cold-start plane's
# train half): one worker pays the accum-step trace+compile per (mesh,
# geometry) fingerprint, the rest of the gang — and every post-resize
# re-gang — deserializes. Exported to jax tasks as TONY_TRAIN_AOT_CACHE.
TRAIN_AOT_CACHE = "tony.train.aot-cache"            # cache dir ("" = off)
# link (default): per-container venv localization hardlinks file content —
# metadata-only, but containers ALIAS the staged inodes, so a job that
# rewrites venv files IN PLACE (r+ open, forced reinstall reusing inodes)
# would mutate every sibling container's view. Such jobs set "copy".
VENV_LOCALIZATION = "tony.task.venv-localization"             # link|copy

# Per-jobtype templates (reference: tony.{jobtype}.{instances,memory,vcores,gpus})
def instances_key(job_type: str) -> str:
    return f"tony.{job_type}.instances"

def memory_key(job_type: str) -> str:
    return f"tony.{job_type}.memory"

def vcores_key(job_type: str) -> str:
    return f"tony.{job_type}.vcores"

def gpus_key(job_type: str) -> str:
    return f"tony.{job_type}.gpus"

def tpus_key(job_type: str) -> str:
    return f"tony.{job_type}.tpus"          # TPU-native addition: chips per task

def command_key(job_type: str) -> str:
    return f"tony.{job_type}.command"       # per-jobtype command override

def serve_role_key(job_type: str) -> str:
    """Per-jobtype serving role (tony_tpu.serve.disagg):
    ``tony.serve.role.<jobtype>`` = prefill|decode|colocated."""
    return f"{SERVE_ROLE_PREFIX}{job_type}"

def serve_replicas_max_key(job_type: str) -> str:
    """Per-GANG autoscale ceiling override for a split fleet:
    ``tony.serve.replicas.max.<jobtype>``. Without it, the global
    ``tony.serve.replicas.max`` is a FLEET ceiling that the AM
    apportions across the serve jobtypes (scaling.apportion_fleet_max)
    — two gangs must not each inflate to the whole budget."""
    return f"{SERVE_REPLICAS_MAX}.{job_type}"

def serve_warm_standby_key(job_type: str) -> str:
    """Per-jobtype warm-standby pool override for a split fleet:
    ``tony.serve.warm-standby.<jobtype>``. Without it the global
    ``tony.serve.warm-standby`` applies to every serve jobtype —
    a prefill gang and a decode gang usually want different pools
    (prefill compiles one chunk program; decode compiles a bucket
    ladder), so the per-gang key mirrors the replicas.max override."""
    return f"{SERVE_WARM_STANDBY}.{job_type}"

def env_key(job_type: str) -> str:
    return f"tony.{job_type}.env"           # csv KEY=VALUE extra env

_INSTANCES_RE = re.compile(r"^tony\.([A-Za-z0-9_\-]+)\.instances$")
# Keys of the form tony.<word>.instances that are NOT job types.
_RESERVED_SEGMENTS = {"application", "task", "am", "container", "history",
                      "docker", "security", "keytab"}

DEFAULTS: Dict[str, str] = {
    APPLICATION_NAME: "tony-tpu-job",
    APPLICATION_FRAMEWORK: "jax",
    APPLICATION_UNTRACKED: f"{constants.PS},{constants.TENSORBOARD},{constants.NOTEBOOK},{constants.DRIVER},{constants.SCHEDULER}",
    APPLICATION_STOP_ON_FAILURE: "true",
    APPLICATION_TIMEOUT: "0",
    SECURITY_ENABLED: "false",
    DOCKER_ENABLED: "false",
    TASK_HEARTBEAT_INTERVAL_MS: "1000",
    TASK_MAX_MISSED_HEARTBEATS: "25",
    TASK_METRICS_INTERVAL_MS: "5000",
    TASK_EXECUTOR_EXECUTION_TIMEOUT_MS: "0",
    AM_RETRY_COUNT: "0",
    AM_MAX_ATTEMPTS: "1",
    AM_MEMORY: "2g",
    AM_VCORES: "1",
    AM_GANG_TIMEOUT_MS: "120000",
    PREEMPTION_MAX_RETRIES: "3",
    HISTORY_LOCATION: "",
    RESIZE_ENABLED: "false",
    RESIZE_JOB_TYPE: constants.WORKER,
    RESIZE_MIN_WORKERS: "1",
    RESIZE_MAX_RESIZES: "8",
    RESIZE_DRAIN_TIMEOUT_MS: "60000",
    RESIZE_REGANG_TIMEOUT_MS: "120000",
    RESIZE_RESTORE_TIMEOUT_MS: "120000",
    PUBLISH_EVERY: "0",
    PUBLISH_FOLLOW: "false",
    PUBLISH_SWAP_TIMEOUT_MS: "120000",
    TRAIN_AOT_CACHE: "",
}


def _parse_memory(value: str) -> int:
    """Parse '2g'/'512m'/'1024' (MiB) into MiB, as the reference's resource parser does."""
    v = value.strip().lower()
    if v.endswith("g"):
        return int(float(v[:-1]) * 1024)
    if v.endswith("m"):
        return int(float(v[:-1]))
    return int(v)


class TonyConfig:
    """Layered string-keyed configuration (Hadoop ``Configuration`` analogue)."""

    def __init__(self, initial: Optional[Dict[str, str]] = None):
        self._props: Dict[str, str] = dict(DEFAULTS)
        if initial:
            for k, v in initial.items():
                self._props[k] = str(v)

    # -- loading ------------------------------------------------------------
    @classmethod
    def load(cls, path: str | Path) -> "TonyConfig":
        """Load a config file on top of defaults. ``.xml`` is parsed as a
        Hadoop-style ``<configuration><property><name>..<value>..`` document
        (``tony.xml`` compatibility); anything else is parsed as JSON."""
        cfg = cls()
        cfg.merge_file(path)
        return cfg

    def merge_file(self, path: str | Path) -> None:
        path = Path(path)
        if path.suffix == ".xml":
            root = ET.parse(path).getroot()
            for prop in root.iter("property"):
                name = prop.findtext("name")
                value = prop.findtext("value")
                if name is not None and value is not None:
                    self._props[name.strip()] = value.strip()
        else:
            data = json.loads(path.read_text())
            if not isinstance(data, dict):
                raise ValueError(f"config file {path} must hold a JSON object")
            for k, v in data.items():
                self._props[str(k)] = str(v)

    def merge_overrides(self, overrides: Dict[str, str]) -> None:
        """Apply ``-D key=value`` style overrides (highest precedence)."""
        for k, v in overrides.items():
            self._props[str(k)] = str(v)

    # -- typed getters ------------------------------------------------------
    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._props.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._props.get(key)
        return int(v) if v not in (None, "") else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._props.get(key)
        return float(v) if v not in (None, "") else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._props.get(key)
        if v is None or v == "":
            return default
        return v.strip().lower() in ("true", "1", "yes", "on")

    def get_list(self, key: str, default: Tuple[str, ...] = ()) -> List[str]:
        v = self._props.get(key)
        if not v:
            return list(default)
        return [item.strip() for item in v.split(",") if item.strip()]

    def get_memory_mb(self, key: str, default: str = "1g") -> int:
        return _parse_memory(self._props.get(key) or default)

    def set(self, key: str, value: Any) -> None:
        self._props[key] = str(value)

    def unset(self, key: str) -> None:
        self._props.pop(key, None)

    def __contains__(self, key: str) -> bool:
        return key in self._props

    def items(self) -> Iterator[Tuple[str, str]]:
        return iter(sorted(self._props.items()))

    # -- job-type discovery (the open templating) ---------------------------
    def job_types(self) -> List[str]:
        """All configured job types: every ``tony.<type>.instances`` key with a
        positive count, excluding reserved segments. Order is deterministic:
        chief-like first, then alphabetical (matches the reference's stable
        cluster-spec assembly)."""
        found = []
        for key in self._props:
            m = _INSTANCES_RE.match(key)
            if not m:
                continue
            jt = m.group(1)
            if jt in _RESERVED_SEGMENTS:
                continue
            if self.get_int(key, 0) > 0:
                found.append(jt)
        # Canonical chief-like order (CHIEF_LIKE_JOB_TYPES order, NOT dict
        # insertion order) so the AM and every executor — which load the
        # config from different serializations — agree on rank 0.
        chief_like = [t for t in constants.CHIEF_LIKE_JOB_TYPES if t in found]
        rest = sorted(t for t in found if t not in constants.CHIEF_LIKE_JOB_TYPES)
        return chief_like + rest

    def instances(self, job_type: str) -> int:
        return self.get_int(instances_key(job_type), 0)

    def total_tasks(self) -> int:
        return sum(self.instances(t) for t in self.job_types())

    def untracked_job_types(self) -> List[str]:
        return self.get_list(APPLICATION_UNTRACKED)

    def is_tracked(self, job_type: str) -> bool:
        return job_type not in self.untracked_job_types()

    def task_env(self, job_type: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for pair in self.get_list(env_key(job_type)):
            if "=" in pair:
                k, _, v = pair.partition("=")
                out[k] = v
        return out

    def container_request(self, job_type: str) -> "ContainerRequest":
        return ContainerRequest(
            job_type=job_type,
            instances=self.instances(job_type),
            memory_mb=self.get_memory_mb(memory_key(job_type), "1g"),
            vcores=self.get_int(vcores_key(job_type), 1),
            gpus=self.get_int(gpus_key(job_type), 0),
            tpus=self.get_int(tpus_key(job_type), 0),
        )

    # -- validation (reference: TonyClient#init sanity checks) -------------
    def validate(self) -> None:
        if not self.job_types():
            raise ValueError(
                "no job types configured: set at least one tony.<jobtype>.instances > 0")
        for jt in self.job_types():
            if self.get_int(vcores_key(jt), 1) <= 0:
                raise ValueError(f"{vcores_key(jt)} must be > 0")
            # This is a TPU substrate: a GPU ask that scheduled in the
            # reference would otherwise silently no-op here (VERDICT r4
            # missing #5) — fail loudly at submit instead.
            if self.get_int(gpus_key(jt), 0) > 0:
                raise ValueError(
                    f"{gpus_key(jt)}: GPUs cannot be scheduled on the TPU "
                    f"substrate; ask for chips with {tpus_key(jt)} instead")
        framework = self.get(APPLICATION_FRAMEWORK, "jax")
        from tony_tpu.runtime import FRAMEWORKS  # late import: avoid cycle
        if framework not in FRAMEWORKS:
            raise ValueError(
                f"unknown {APPLICATION_FRAMEWORK}={framework!r}; "
                f"known: {sorted(FRAMEWORKS)}")

    # -- serialization (ship effective conf to AM / executors) -------------
    def to_json(self) -> str:
        return json.dumps(self._props, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "TonyConfig":
        cfg = cls()
        cfg._props.update({str(k): str(v) for k, v in json.loads(text).items()})
        return cfg

    def save(self, path: str | Path) -> None:
        Path(path).write_text(self.to_json())


class ContainerRequest:
    """Resource ask for one job type (reference: ``JobContainerRequest``)."""

    __slots__ = ("job_type", "instances", "memory_mb", "vcores", "gpus", "tpus")

    def __init__(self, job_type: str, instances: int, memory_mb: int,
                 vcores: int, gpus: int, tpus: int):
        self.job_type = job_type
        self.instances = instances
        self.memory_mb = memory_mb
        self.vcores = vcores
        self.gpus = gpus
        self.tpus = tpus

    def __repr__(self) -> str:
        return (f"ContainerRequest({self.job_type}x{self.instances}, "
                f"{self.memory_mb}MiB, {self.vcores}c, gpus={self.gpus}, tpus={self.tpus})")

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ContainerRequest) and all(
            getattr(self, f) == getattr(other, f) for f in self.__slots__)
