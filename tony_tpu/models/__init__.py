"""Model zoo for the TPU compute plane.

The reference ships models only as *examples* (``tony-examples/``: TF MNIST,
Keras MNIST, PyTorch MNIST — SURVEY.md §2.2); the orchestrator itself has no
model code. The TPU rebuild's north star (BASELINE.json via SURVEY.md §6)
adds two first-class model families this package owns:

* :mod:`~tony_tpu.models.resnet` — ResNet-50 for the ImageNet DP target;
* :mod:`~tony_tpu.models.transformer` — a Llama-style decoder for the
  ``pjit``/GSPMD graduation config (SURVEY.md §6 config ⑤), with logical
  sharding axes wired for dp/fsdp/tp/sp meshes;
* :mod:`~tony_tpu.models.mnist` — the small nets the examples train.

All models are flax ``linen`` modules: params in f32, compute in bf16 by
default (MXU-native), logical axis metadata resolved through
:data:`tony_tpu.parallel.RULES`.
"""

from typing import Any, Callable, Dict

_REGISTRY: Dict[str, Callable[..., Any]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def get_model(name: str, **kw):
    """Build a registered model by name (``resnet50``, ``llama2-7b``,
    ``llama-tiny``, ``mnist-mlp``, ``mnist-cnn``)."""
    # Import for registration side effects.
    from tony_tpu.models import mnist, resnet, transformer  # noqa: F401
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kw)
