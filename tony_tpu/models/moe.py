"""Mixture-of-experts layer with expert parallelism (SURVEY.md §2.3 "Expert
parallel (EP/MoE)" — absent from the reference, a first-class TPU-build
equivalent here).

TPU-first design — the GShard/Switch dispatch formulation, not a torch-style
gather/scatter loop:

* routing uses a **static expert capacity** ``C`` so every shape is known at
  trace time (XLA requirement); over-capacity tokens are dropped (their
  residual path still carries them);
* dispatch/combine are dense one-hot einsums — they lower to MXU matmuls and
  give GSPMD a clean pattern to turn into ``all_to_all`` over the ``expert``
  mesh axis;
* expert weights are stacked on a leading ``expert`` axis with logical names
  ``("expert", "embed", "ffn")`` so :data:`tony_tpu.parallel.RULES` shards
  each expert's FFN over the EP axis (and its hidden dim over TP);
* the Switch load-balancing auxiliary loss is sown into a ``losses``
  collection; :func:`tony_tpu.train.make_train_step` adds any sown losses to
  the objective.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def router_assignment(gates: jax.Array, top_k: int, capacity: int):
    """Top-k expert assignment with per-expert capacity.

    Args:
      gates: [G, S, E] f32 router probabilities (softmax over E).
      top_k: experts per token.
      capacity: max tokens an expert accepts per group (static).

    Returns:
      dispatch: [G, S, E, C] one-hot f32 — token s of group g occupies
        capacity slot c of expert e.
      combine: [G, S, E, C] f32 — dispatch weighted by the (renormalized)
        router probability.
      aux: scalar Switch load-balancing loss (un-scaled).
    """
    g, s, e = gates.shape
    if top_k > e:
        raise ValueError(f"top_k={top_k} exceeds n_experts={e}")
    remaining = gates
    dispatch = jnp.zeros((g, s, e, capacity), gates.dtype)
    combine = jnp.zeros((g, s, e, capacity), gates.dtype)
    for _ in range(top_k):  # static, tiny (k ≤ 2 in practice)
        choice = jnp.argmax(remaining, axis=-1)                # [G, S]
        onehot = jax.nn.one_hot(choice, e, dtype=gates.dtype)  # [G, S, E]
        # Position of this token within its chosen expert's queue, counting
        # earlier tokens (in sequence order) AND slots taken in earlier
        # top-k rounds.
        taken = dispatch.sum(axis=(1, 3))                      # [G, E]
        pos = (jnp.cumsum(onehot, axis=1) - onehot             # [G, S, E]
               + taken[:, None, :])
        pos = (pos * onehot).sum(axis=-1).astype(jnp.int32)    # [G, S]
        fits = (pos < capacity).astype(gates.dtype)            # [G, S]
        slot = jax.nn.one_hot(pos, capacity, dtype=gates.dtype)  # [G, S, C]
        hot = (onehot * fits[..., None])[..., None] * slot[:, :, None, :]
        dispatch = dispatch + hot
        gate = (gates * onehot).sum(-1)                        # [G, S]
        combine = combine + gate[..., None, None] * hot
        # Exclude chosen experts with -inf, not by multiplying to zero: if
        # a token's remaining probabilities all underflowed to 0, argmax
        # would tie-break to expert 0 and could re-select an already-chosen
        # expert (double-booking its capacity). -inf can never win argmax
        # while any un-chosen expert remains.
        remaining = jnp.where(onehot > 0, -jnp.inf, remaining)
    # Renormalize combine weights over the k selected experts so the output
    # is a convex mixture (dropped tokens keep weight 0 → pure residual).
    total = combine.sum(axis=(2, 3), keepdims=True)
    combine = jnp.where(total > 0, combine / jnp.maximum(total, 1e-9), 0.0)
    # Switch aux loss: E · Σ_e fraction_routed(e) · mean_prob(e), averaged
    # over groups — minimized (=1) when routing is perfectly balanced; the
    # mean-prob factor is what gradients flow through.
    first = jax.nn.one_hot(jnp.argmax(gates, -1), e, dtype=gates.dtype)
    frac = first.mean(axis=1)        # [G, E] fraction of tokens → expert
    prob = gates.mean(axis=1)        # [G, E] mean router probability
    aux = e * (frac * prob).sum(axis=-1).mean()
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Expert-parallel SwiGLU FFN: drop-in for the dense MLP block.

    Input [B, T, D]; groups = batch rows (already sharded over the DP axes),
    experts sharded over the ``expert`` mesh axis — the dispatch einsum is
    where GSPMD inserts the EP ``all_to_all``.

    ``explicit_a2a=True`` (with ``mesh=``) routes dispatch/FFN/combine
    through the collective scheduler instead
    (:func:`tony_tpu.parallel.sched.moe_dispatch_ffn_combine`): the EP
    ``all_to_all`` is issued explicitly per capacity chunk
    (``a2a_chunks``) inside the layer so chunk *c+1*'s a2a rides under
    chunk *c*'s expert FFN compute, rather than whatever one-shot
    schedule GSPMD picks for the einsum. Same math (per-chunk combine-sum
    reassociation aside); owns only the expert axis, so it needs
    ``tp=sp=pp=1`` and must not run inside another manual region (the
    accum engine's) — the einsum path stays the default and the GSPMD
    numerics pin.
    """
    dim: int
    ffn_hidden: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    dtype: object = jnp.bfloat16
    explicit_a2a: bool = False
    mesh: Any = None
    a2a_chunks: int = 2

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        e, f = self.n_experts, self.ffn_hidden
        capacity = max(1, int(self.capacity_factor * t * self.top_k / e))

        wr = self.param("w_router", nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), ("embed", "expert_dim")),
            (d, e), jnp.float32)
        # Router in f32: softmax over few logits, numerics matter more
        # than MXU throughput here.
        gates = jax.nn.softmax(x.astype(jnp.float32) @ wr, axis=-1)
        dispatch, combine, aux = router_assignment(
            gates, self.top_k, capacity)
        self.sow("losses", "moe_aux", self.aux_coef * aux,
                 reduce_fn=lambda a, c: a + c,
                 init_fn=lambda: jnp.float32(0.0))

        stacked = lambda name, shape, logical: self.param(
            name, nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), logical), shape, jnp.float32)
        w_gate = stacked("w_gate", (e, d, f), ("expert", "embed", "ffn"))
        w_up = stacked("w_up", (e, d, f), ("expert", "embed", "ffn"))
        w_down = stacked("w_down", (e, f, d), ("expert", "ffn", "embed"))

        if self.explicit_a2a:
            if self.mesh is None:
                raise ValueError(
                    "MoEMLP(explicit_a2a=True) needs mesh=: the scheduler "
                    "issues the a2a over the mesh's expert axis itself")
            from tony_tpu.parallel import sched  # lazy: models stay light
            y = sched.moe_dispatch_ffn_combine(
                x, dispatch, combine, (w_gate, w_up, w_down), self.mesh,
                chunks=self.a2a_chunks, dtype=self.dtype)
            return nn.with_logical_constraint(
                y, ("batch", "act_seq", "act_embed"))

        # Dispatch: [B,S,E,C] × [B,S,D] → [E,B,C,D] (the EP all_to_all).
        xin = jnp.einsum("gsec,gsd->egcd", dispatch.astype(self.dtype),
                         x, precision=jax.lax.Precision.DEFAULT)
        xin = nn.with_logical_constraint(
            xin, ("expert", "batch", None, "act_embed"))
        h = nn.silu(jnp.einsum("egcd,edf->egcf", xin,
                               w_gate.astype(self.dtype)))
        h = h * jnp.einsum("egcd,edf->egcf", xin, w_up.astype(self.dtype))
        out = jnp.einsum("egcf,efd->egcd", h, w_down.astype(self.dtype))
        out = nn.with_logical_constraint(
            out, ("expert", "batch", None, "act_embed"))
        # Combine back to token order: [B,S,E,C] × [E,B,C,D] → [B,S,D].
        y = jnp.einsum("gsec,egcd->gsd", combine.astype(self.dtype), out)
        return nn.with_logical_constraint(
            y, ("batch", "act_seq", "act_embed"))
